"""Speculative decoding tier (PR 10): draft-verified multi-token
generation over the paged engine.

The load-bearing properties, per the subsystem contract:

- **lossless greedy**: speculative greedy output is token-identical to
  plain greedy decode — float and int8, tp=1 and tp=2, any k, any
  admission order, whatever the draft model proposes;
- the rejection sampler (``ops.sampling.speculative_sample``) exact-
  matches its pure-numpy oracle per step, over accept, reject-residual,
  and full-acceptance-bonus branches;
- sampled speculative streams are deterministic across runs, admission
  orderings, and schedulers (draws are keyed by (request, output
  position), never by step — acceptance-length variance cannot desync a
  stream), and ``static_generate(speculate=...)`` emits the engine's
  exact streams;
- the draft/verify/prefill/chunk kernels each compile exactly once
  across a mixed workload (acceptance lengths are data, not shapes);
- the draft and target lanes live side by side in ONE ``PagePool`` with
  owner-tagged reservations, and both drain to zero on every path —
  retirement, cancel mid-flight, close(drain=False), injected faults.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import faults
from bigdl_tpu.core.rng import threefry_key_data
from bigdl_tpu.faults import InjectedFault
from bigdl_tpu.nn.layers.attention import Transformer
from bigdl_tpu.ops.sampling import (
    draft_sample,
    filtered_probs,
    numpy_reference_draft,
    numpy_reference_filtered,
    numpy_reference_speculative,
    speculative_sample,
)
from bigdl_tpu.serving import (
    GenerationEngine,
    PagePool,
    SpeculativeKernels,
    StreamCancelled,
    static_generate,
)

SLOTS, MAXLEN = 4, 48


@pytest.fixture(scope="module")
def lm():
    model = Transformer(vocab_size=64, hidden_size=32, num_heads=4,
                        filter_size=64, num_hidden_layers=2)
    params, _ = model.init(jax.random.key(0))
    draft = Transformer(vocab_size=64, hidden_size=16, num_heads=2,
                        filter_size=32, num_hidden_layers=1)
    dparams, _ = draft.init(jax.random.key(1))
    # one kernel set for the whole module: the jit cache persists across
    # engines (each distinct k retraces the verify width once)
    kernels = SpeculativeKernels(model, draft)
    return model, params, draft, dparams, kernels


def make_engine(lm, k=2, shared=True, **kw):
    model, params, draft, dparams, kernels = lm
    kw.setdefault("max_slots", SLOTS)
    kw.setdefault("max_len", MAXLEN)
    kw.setdefault("page_size", 4)
    if shared:
        kw.setdefault("kernels", kernels)
    return GenerationEngine(model, params,
                            speculate=(draft, dparams, k), **kw)


def plain_engine(lm, **kw):
    model, params, _, _, _ = lm
    kw.setdefault("max_slots", SLOTS)
    kw.setdefault("max_len", MAXLEN)
    kw.setdefault("page_size", 4)
    return GenerationEngine(model, params, **kw)


def ref_greedy(model, params, prompt, n):
    ids = [int(t) for t in prompt]
    out = []
    for _ in range(n):
        logits, _ = model.apply(params, jnp.asarray([ids]))
        tok = int(np.asarray(logits)[0, -1].argmax())
        ids.append(tok)
        out.append(tok)
    return out


PROMPTS = [[1, 5, 9], [2, 4], [7, 3, 11, 13, 2], [6, 2, 2, 8]]
LENS = [6, 9, 4, 11]


# ------------------------------------------------------------- sampler ----


class TestSpeculativeSampler:
    def test_filtered_probs_matches_oracle(self):
        """Vocab-order filtered distributions: sampled rows match the
        numpy mirror within float tolerance; greedy rows are EXACT
        one-hot argmax deltas (the lossless-greedy foundation)."""
        rng = np.random.RandomState(0)
        temps = np.asarray([0.0, 0.7, 1.3, 0.0], np.float32)
        tks = np.asarray([0, 5, 0, 3], np.int32)
        tps = np.asarray([1.0, 1.0, 0.85, 0.9], np.float32)
        logits = (rng.randn(4, 40) * 2).astype(np.float32)
        got = np.asarray(filtered_probs(jnp.asarray(logits),
                                        jnp.asarray(temps),
                                        jnp.asarray(tks),
                                        jnp.asarray(tps)))
        for s in range(4):
            want = numpy_reference_filtered(logits[s], float(temps[s]),
                                            int(tks[s]), float(tps[s]))
            if temps[s] <= 0:
                assert np.array_equal(got[s], want)   # exact delta
            else:
                np.testing.assert_allclose(got[s], want, atol=1e-6)
                np.testing.assert_allclose(got[s].sum(), 1.0, atol=1e-5)

    def test_speculative_sample_matches_numpy_oracle_per_step(self):
        """The acceptance anchor: 15 steps x 4 slots (greedy + sampled
        rows mixed) of drafts proposed by ``draft_sample`` on random
        draft logits, verified against random target logits — the
        jitted sampler must pick the SAME accepted count and the SAME
        emitted tokens as the oracle at every step, across accept,
        reject-residual, and full-acceptance branches."""
        rng = np.random.RandomState(0)
        s_, k, vocab = 4, 3, 50
        temps = np.asarray([0.0, 0.8, 1.3, 0.0], np.float32)
        tks = np.asarray([0, 6, 0, 0], np.int32)
        tps = np.asarray([1.0, 0.9, 0.85, 1.0], np.float32)
        keys = np.stack([threefry_key_data(100 + s) for s in range(s_)])
        fspec = jax.jit(speculative_sample)
        fdraft = jax.jit(draft_sample)
        branch_seen = set()
        for step in range(15):
            out_base = rng.randint(0, 40, (s_,)).astype(np.int32)
            d_toks, d_dists = [], []
            # bias the target toward the draft every other step so the
            # accept branch is exercised, not just immediate rejection
            tlog = (rng.randn(s_, k + 1, vocab) * 2).astype(np.float32)
            for i in range(k):
                if step % 2:
                    dlog = tlog[:, i] + rng.randn(
                        s_, vocab).astype(np.float32) * 0.05
                else:
                    dlog = (rng.randn(s_, vocab) * 2).astype(np.float32)
                t_, di_ = fdraft(jnp.asarray(dlog), jnp.asarray(temps),
                                 jnp.asarray(tks), jnp.asarray(tps),
                                 jnp.asarray(keys),
                                 jnp.asarray(out_base + i))
                t_, di_ = np.asarray(t_), np.asarray(di_)
                for s in range(s_):
                    wt, wd = numpy_reference_draft(
                        dlog[s], float(temps[s]), int(tks[s]),
                        float(tps[s]), keys[s], int(out_base[s]) + i)
                    assert int(t_[s]) == wt
                    np.testing.assert_allclose(di_[s], wd, atol=1e-6)
                d_toks.append(t_)
                d_dists.append(di_)
            d_toks = np.stack(d_toks, 1)
            d_dists = np.stack(d_dists, 1)
            n_, toks_ = fspec(jnp.asarray(tlog), jnp.asarray(d_toks),
                              jnp.asarray(d_dists), jnp.asarray(temps),
                              jnp.asarray(tks), jnp.asarray(tps),
                              jnp.asarray(keys), jnp.asarray(out_base))
            n_, toks_ = np.asarray(n_), np.asarray(toks_)
            for s in range(s_):
                wn, wtoks = numpy_reference_speculative(
                    tlog[s], d_toks[s], d_dists[s], float(temps[s]),
                    int(tks[s]), float(tps[s]), keys[s],
                    int(out_base[s]))
                assert int(n_[s]) == wn
                assert [int(t) for t in toks_[s, :wn + 1]] == wtoks
                branch_seen.add("full" if wn == k
                                else "reject" if wn < k else "?")
        assert branch_seen >= {"full", "reject"}, branch_seen

    def test_all_greedy_batch_is_exact_prefix_match(self):
        """The greedy fast path: accepted = longest prefix where the
        draft equals the target argmax; every emitted token is a target
        argmax."""
        rng = np.random.RandomState(1)
        s_, k, vocab = 3, 3, 30
        tlog = (rng.randn(s_, k + 1, vocab)).astype(np.float32)
        am = tlog.argmax(-1)
        d_toks = am[:, :k].copy().astype(np.int32)
        d_toks[0, 1] = (d_toks[0, 1] + 1) % vocab    # mismatch at i=1
        d_toks[2, 0] = (d_toks[2, 0] + 1) % vocab    # mismatch at i=0
        dd = np.zeros((s_, k, vocab), np.float32)
        n_, toks_ = speculative_sample(
            jnp.asarray(tlog), jnp.asarray(d_toks), jnp.asarray(dd),
            jnp.zeros(s_, jnp.float32), jnp.zeros(s_, jnp.int32),
            jnp.ones(s_, jnp.float32), jnp.zeros((s_, 2), jnp.uint32),
            jnp.zeros(s_, jnp.int32))
        n_, toks_ = np.asarray(n_), np.asarray(toks_)
        assert list(n_) == [1, 3, 0]
        for s in range(s_):
            n = int(n_[s])
            assert np.array_equal(toks_[s, :n], am[s, :n])
            assert toks_[s, n] == am[s, n]

    def test_identical_distributions_accept_everything(self):
        """When the draft IS the target (same filtered distribution and
        it proposed a kept token), the accept ratio is 1 and u < 1
        always — full acceptance, the E[speedup] upper bound."""
        rng = np.random.RandomState(2)
        s_, k, vocab = 2, 4, 40
        temps = np.asarray([0.9, 0.0], np.float32)
        tks = np.zeros(2, np.int32)
        tps = np.ones(2, np.float32)
        keys = np.stack([threefry_key_data(s) for s in range(2)])
        row = (rng.randn(s_, vocab)).astype(np.float32)
        tlog = np.repeat(row[:, None], k + 1, axis=1)
        fp = np.asarray(filtered_probs(jnp.asarray(row),
                                       jnp.asarray(temps),
                                       jnp.asarray(tks),
                                       jnp.asarray(tps)))
        d_dists = np.repeat(fp[:, None], k, axis=1)
        d_toks = fp.argmax(-1)[:, None].repeat(k, 1).astype(np.int32)
        n_, _ = speculative_sample(
            jnp.asarray(tlog), jnp.asarray(d_toks), jnp.asarray(d_dists),
            jnp.asarray(temps), jnp.asarray(tks), jnp.asarray(tps),
            jnp.asarray(keys), jnp.zeros(s_, jnp.int32))
        assert list(np.asarray(n_)) == [k, k]


# --------------------------------------------------------- model level ----


def test_verify_step_scores_like_sequential_decode(lm):
    """``decode_verify_paged`` row i == the logits a sequential
    ``decode_step_paged`` chain produces at the same position: argmax
    chains identical, logits within float tolerance (multi-row vs
    single-row reassociation only)."""
    model, params, _, _, _ = lm
    ps = 4
    ppn = MAXLEN // ps
    trash = 2 * ppn
    prompt = np.array([5, 11, 2, 29, 7], np.int32)
    rng = np.random.RandomState(3)
    pages = rng.choice(2 * ppn, ppn, replace=False).astype(np.int32)
    pm = np.full((2, ppn), trash, np.int32)
    pm[1] = pages

    def prefilled():
        cache = model.init_paged_cache(2 * ppn + 1, ps)
        logits, cache = model.prefill_paged(
            params, cache, jnp.asarray(pages), jnp.asarray(prompt), 0, 5,
            trash)
        return int(np.asarray(logits).argmax()), cache

    t0, cache = prefilled()
    seq_logits = []
    feed, pos = t0, 5
    for _ in range(4):
        tok = np.zeros(2, np.int32)
        posv = np.zeros(2, np.int32)
        tok[1], posv[1] = feed, pos
        lg, cache = model.decode_step_paged(
            params, cache, jnp.asarray(tok), jnp.asarray(posv),
            jnp.asarray(pm))
        seq_logits.append(np.asarray(lg)[1])
        feed = int(seq_logits[-1].argmax())
        pos += 1
    chain = [int(l.argmax()) for l in seq_logits]

    _, cache2 = prefilled()
    vt = np.zeros((2, 4), np.int32)
    vt[1] = [t0] + chain[:3]
    vp = np.zeros(2, np.int32)
    vp[1] = 5
    vlog, _ = model.decode_verify_paged(
        params, cache2, jnp.asarray(vt), jnp.asarray(vp),
        jnp.asarray(pm), trash)
    vlog = np.asarray(vlog)[1]
    assert [int(vlog[i].argmax()) for i in range(4)] == chain
    np.testing.assert_allclose(vlog, np.stack(seq_logits), atol=1e-5)


# -------------------------------------------------------- engine level ----


class TestSpeculativeEngine:
    @pytest.mark.parametrize("k", [1, 2, 4])
    def test_greedy_identity_any_k_any_order(self, lm, k):
        """THE acceptance assertion: speculative greedy == plain greedy
        token for token, for any k and either admission order, and both
        match the full-forward reference."""
        model, params, _, _, _ = lm
        peng = plain_engine(lm, max_slots=2)
        want = [peng.submit(PROMPTS[i], max_new_tokens=LENS[i])
                .result(timeout=60) for i in range(4)]
        peng.close()
        for order in (range(4), reversed(range(4))):
            eng = make_engine(lm, k=k, max_slots=2)
            streams = {i: eng.submit(PROMPTS[i], max_new_tokens=LENS[i])
                       for i in order}
            outs = {i: s.result(timeout=120) for i, s in streams.items()}
            eng.close()
            assert [outs[i] for i in range(4)] == want
        assert want[0] == ref_greedy(model, params, PROMPTS[0], LENS[0])

    def test_self_draft_accepts_most_tokens(self, lm):
        """Draft == target is the acceptance upper bound: greedy
        proposals match the verify argmax almost always (only budget
        truncation at stream ends loses a few), and output stays
        identical — speculation is lossless even at 100% acceptance."""
        model, params, _, _, _ = lm
        eng = GenerationEngine(model, params, max_slots=2, max_len=MAXLEN,
                               page_size=4, speculate=(model, params, 3))
        outs = [eng.submit(p, max_new_tokens=m).result(timeout=120)
                for p, m in zip(PROMPTS, LENS)]
        snap = eng.metrics.snapshot()
        eng.close()
        peng = plain_engine(lm, max_slots=2)
        want = [peng.submit(p, max_new_tokens=m).result(timeout=60)
                for p, m in zip(PROMPTS, LENS)]
        peng.close()
        assert outs == want
        assert snap["acceptance_rate"] >= 0.5, snap["acceptance_rate"]
        assert snap["verify_steps"] > 0
        # amortization: far fewer verify forwards than emitted tokens
        assert snap["verify_steps"] < snap["tokens_out"]

    def test_chunked_prompt_and_max_len_wall_identity(self, lm):
        """A chunked long prompt and a generation that runs into the
        max_len wall both stay token-identical to the plain engine."""
        model, params, _, _, _ = lm
        long_prompt = list(np.random.RandomState(0).randint(
            1, 60, MAXLEN - 8))
        peng = plain_engine(lm, max_slots=2, prefill_chunk=8)
        want_long = peng.generate(long_prompt, max_new_tokens=4,
                                  timeout=60)
        want_wall = peng.generate([1, 2, 3], max_new_tokens=200,
                                  timeout=120)
        peng.close()
        eng = make_engine(lm, k=4, max_slots=2, prefill_chunk=8,
                          shared=False, kernels=None)
        assert eng.generate(long_prompt, max_new_tokens=4,
                            timeout=120) == want_long
        got_wall = eng.generate([1, 2, 3], max_new_tokens=200,
                                timeout=120)
        eng.close()
        assert got_wall == want_wall and len(got_wall) == MAXLEN - 3

    def test_eos_truncation_identity(self, lm):
        """An EOS inside an accepted run truncates the stream exactly
        where plain decode stops — tokens past it are never emitted."""
        model, params, _, _, _ = lm
        ref = ref_greedy(model, params, [6, 2, 2, 8], 12)
        eos = ref[min(2, len(ref) - 1)]
        peng = plain_engine(lm, max_slots=2, eos_id=eos)
        want = peng.generate([6, 2, 2, 8], max_new_tokens=12, timeout=60)
        peng.close()
        for k in (1, 3):
            eng = make_engine(lm, k=k, max_slots=2, eos_id=eos)
            got = eng.generate([6, 2, 2, 8], max_new_tokens=12,
                               timeout=120)
            eng.close()
            assert got == want, (k, got, want)

    def test_sampled_deterministic_across_runs_and_orderings(self, lm):
        """Per-(request, output-position) keys: fixed engine seed =>
        identical sampled streams across fresh engines AND reversed
        admission order (acceptance-length variance cannot desync);
        distinct explicit seeds diverge."""
        prompts = [[3, 1, 4], [1, 5], [9, 2, 6, 5]]
        spec = dict(temperature=0.9, top_k=20, top_p=0.95)

        def run(order):
            eng = make_engine(lm, k=2, max_slots=2, seed=42)
            streams = {i: eng.submit(prompts[i], max_new_tokens=8, **spec)
                       for i in order}
            outs = {i: s.result(timeout=120) for i, s in streams.items()}
            eng.close()
            return outs

        a = run(range(3))
        b = run(reversed(range(3)))
        assert a == b
        eng = make_engine(lm, k=2, max_slots=2, seed=42)
        s1 = eng.generate(prompts[0], max_new_tokens=8, seed=1,
                          timeout=120, **spec)
        s2 = eng.generate(prompts[0], max_new_tokens=8, seed=2,
                          timeout=120, **spec)
        snap = eng.metrics.snapshot()
        eng.close()
        assert s1 != s2
        assert snap["sampled_tokens"] == 16

    def test_static_generate_speculative_matches_engine(self, lm):
        """``static_generate(speculate=...)`` over the SAME kernels
        emits the engine's exact streams — greedy and sampled (the
        schedule-invariance gate the speculative bench runs)."""
        model, params, draft, dparams, kernels = lm
        requests = [([1 + i, 3, 7], 3 if i % 2 else 9) for i in range(6)]

        eng = make_engine(lm, k=2)
        greedy_eng = [eng.submit(p, max_new_tokens=m).result(timeout=120)
                      for p, m in requests]
        eng.close()
        greedy_static, rounds = static_generate(
            model, params, requests, max_slots=SLOTS, max_len=MAXLEN,
            page_size=4, kernels=kernels,
            speculate=(draft, dparams, 2))
        assert greedy_static == greedy_eng and rounds > 0

        spec = dict(temperature=1.1, top_k=16, top_p=0.9)
        eng = make_engine(lm, k=2, seed=7)
        sampled_eng = [eng.submit(p, max_new_tokens=m, **spec)
                       .result(timeout=120) for p, m in requests]
        eng.close()
        sampled_static, _ = static_generate(
            model, params, requests, max_slots=SLOTS, max_len=MAXLEN,
            page_size=4, kernels=kernels, seed=7,
            speculate=(draft, dparams, 2),
            sampling=[spec] * len(requests))
        assert sampled_static == sampled_eng
        assert sampled_eng != greedy_eng

    def test_compile_once_across_mixed_speculative_workload(self, lm):
        """Warmup traces draft once, verify once, chunk once, prefill /
        draft_write once per bucket; a mixed workload (greedy + sampled,
        short + chunked-long, staggered admissions, every acceptance
        length) traces NOTHING further — acceptance is data, not
        shape."""
        model, params, draft, dparams, _ = lm
        kernels = SpeculativeKernels(model, draft)  # private counters
        eng = GenerationEngine(model, params, max_slots=SLOTS,
                               max_len=MAXLEN, kernels=kernels,
                               page_size=4, prefill_chunk=8,
                               max_queue=64,
                               speculate=(draft, dparams, 2))
        eng.warmup()
        n_buckets = len(eng.prompt_buckets)
        # draft_write serves chunk AND final-bucket shapes through one
        # jit: a prefill_chunk equal to a bucket width shares its trace
        n_dw = len(set(eng.prompt_buckets) | {eng.prefill_chunk})
        assert kernels.draft_traces == 1
        assert kernels.verify_traces == 1
        assert kernels.chunk_traces == 1
        assert kernels.prefill_traces == n_buckets
        assert kernels.draft_write_traces == n_dw

        streams = []
        rng = np.random.RandomState(0)
        for i in range(10):
            plen = 1 + (i * 7) % (MAXLEN - 9)
            prompt = [int(t) for t in rng.randint(1, 60, plen)]
            kw = {}
            if i % 3 == 0:
                kw = dict(temperature=0.8, top_k=10, top_p=0.9)
            streams.append(eng.submit(prompt,
                                      max_new_tokens=2 + (i * 5) % 9,
                                      **kw))
            if i % 4 == 0:
                time.sleep(0.002)
        for s in streams:
            s.result(timeout=240)
        eng.close()

        assert kernels.draft_traces == 1, "draft step recompiled"
        assert kernels.verify_traces == 1, "verify step recompiled"
        assert kernels.chunk_traces == 1
        assert kernels.prefill_traces == n_buckets
        assert kernels.draft_write_traces == n_dw
        assert kernels._draft._cache_size() == 1
        assert kernels._verify._cache_size() == 1
        assert kernels._prefill._cache_size() == n_buckets

    def test_int8_speculative_identity(self, lm):
        """The quantized tier composes: int8 GEMMs + int8 KV pages on
        BOTH models, speculative output == plain int8 output."""
        model, params, draft, dparams, _ = lm
        e1 = plain_engine(lm, max_slots=2, cache_dtype="int8",
                          quantize="int8", kernels=None)
        want = [e1.submit(p, max_new_tokens=m).result(timeout=120)
                for p, m in zip(PROMPTS[:3], LENS[:3])]
        e1.close()
        e2 = GenerationEngine(model, params, max_slots=2, max_len=MAXLEN,
                              page_size=4, cache_dtype="int8",
                              quantize="int8",
                              speculate=(draft, dparams, 2))
        got = [e2.submit(p, max_new_tokens=m).result(timeout=120)
               for p, m in zip(PROMPTS[:3], LENS[:3])]
        e2.close()
        assert got == want

    @pytest.mark.slow  # tp2 mesh leg (~27 s) — same tier as the other
    # sharded identity legs (async/int8 tp2 are slow-marked too)
    def test_tp2_token_identity(self, lm):
        """tp=2 over the speculative tier: both models shard on the
        serving mesh, greedy decode equals the single-device engine
        token for token, and the verify step compiles once."""
        from bigdl_tpu.parallel import serving_meshes

        model, params, draft, dparams, _ = lm
        peng = plain_engine(lm, max_slots=2)
        want = [peng.submit(p, max_new_tokens=m).result(timeout=60)
                for p, m in zip(PROMPTS[:3], LENS[:3])]
        peng.close()
        mesh = serving_meshes(1, 2)[0]
        eng = GenerationEngine(model, params, max_slots=2, max_len=MAXLEN,
                               page_size=4, mesh=mesh,
                               speculate=(draft, dparams, 2))
        eng.warmup()
        outs = [eng.submit(p, max_new_tokens=m).result(timeout=240)
                for p, m in zip(PROMPTS[:3], LENS[:3])]
        assert eng.kernels.verify_traces == 1
        eng.close()
        assert outs == want

    def test_submit_rejects_unreservable_double_lane_budget(self, lm):
        """The two-lane reservation doubles the page budget: a request
        whose TARGET lane alone would fit must still be rejected at
        submit when target + draft cannot ever fit the pool."""
        eng = make_engine(lm, k=2, max_slots=2, page_size=16,
                          num_pages=3, shared=False, kernels=None)
        with pytest.raises(ValueError, match="KV pages"):
            eng.submit([1, 2], max_new_tokens=30)   # 2 x 2 = 4 of 3
        assert len(eng.generate([1, 2], max_new_tokens=8,
                                timeout=120)) == 8
        eng.close()

    def test_pool_owner_tags_drain_on_cancel_and_failure(self, lm):
        """Both lanes of every slot return to the pool when a stream is
        cancelled mid-flight and when close(drain=False) fails the
        rest — per-owner gauges drain to zero, not just the total."""
        eng = make_engine(lm, k=2, max_slots=1)
        s1 = eng.submit([1, 2], max_new_tokens=40)
        deadline = time.monotonic() + 10
        while len(s1.tokens) < 2 and time.monotonic() < deadline:
            time.sleep(0.001)
        assert len(s1.tokens) >= 2
        assert eng._pool.in_use_by("target") > 0
        assert eng._pool.in_use_by("draft") > 0
        s1.cancel()
        with pytest.raises(StreamCancelled):
            s1.result(timeout=30)
        assert eng._pool.in_use_by("target") == 0
        assert eng._pool.in_use_by("draft") == 0
        streams = [eng.submit([3 + i], max_new_tokens=30)
                   for i in range(3)]
        eng.close(drain=False)
        failed = 0
        for s in streams:
            try:
                s.result(timeout=10)
            except RuntimeError:
                failed += 1
        assert failed >= 1
        assert eng.pages_in_use == 0
        assert eng._pool.in_use_by("target") == 0
        assert eng._pool.in_use_by("draft") == 0
        assert eng.metrics.snapshot()["pages_in_use"] == 0

    @pytest.mark.parametrize("site", ["engine.draft", "engine.verify"])
    def test_fault_site_fails_streams_and_releases_both_lanes(self, lm,
                                                             site):
        """The new fault sites: an armed draft/verify fault fails the
        in-flight streams with the injected error (the engine's step
        contract — a consumed donated cache cannot be retried) and BOTH
        models' pages return to the pool."""
        eng = make_engine(lm, k=2, max_slots=2)
        with faults.armed(site, nth=2, only=lambda engine=None, **_:
                          engine is eng):
            streams = [eng.submit([1 + i, 4], max_new_tokens=20)
                       for i in range(2)]
            errors = 0
            for s in streams:
                try:
                    s.result(timeout=60)
                except InjectedFault:
                    errors += 1
            assert errors == 2
        assert eng.pages_in_use == 0
        assert eng._pool.in_use_by("target") == 0
        assert eng._pool.in_use_by("draft") == 0
        with pytest.raises(RuntimeError, match="step failure"):
            eng.submit([1])
        eng.close()

    def test_speculate_knob_validation(self, lm):
        model, params, draft, dparams, kernels = lm
        with pytest.raises(ValueError, match="triple"):
            GenerationEngine(model, params, speculate=(dparams, 2))
        with pytest.raises(ValueError, match="k must be"):
            GenerationEngine(model, params,
                             speculate=(draft, dparams, 0))
        with pytest.raises(ValueError, match="go together"):
            GenerationEngine(model, params, kernels=kernels,
                             max_len=MAXLEN)
        with pytest.raises(ValueError, match="vocab"):
            bad = Transformer(vocab_size=32, hidden_size=16, num_heads=2,
                              filter_size=32, num_hidden_layers=1)
            SpeculativeKernels(model, bad)

    def test_speculative_engine_behind_router_and_replicaset(self, lm):
        """The model-family wiring: a draft+target pair serves behind
        the ModelRouter, and a LIST of speculative engines registers as
        a ReplicaSet — outputs through the front door equal plain
        greedy decode."""
        from bigdl_tpu.serving import ModelRouter

        model, params, _, _, _ = lm
        peng = plain_engine(lm, max_slots=2)
        want = [peng.submit(p, max_new_tokens=m).result(timeout=60)
                for p, m in zip(PROMPTS[:3], LENS[:3])]
        peng.close()
        router = ModelRouter()
        router.register("lm", make_engine(lm, k=2, max_slots=2))
        router.register("lm-fleet", [make_engine(lm, k=2, max_slots=2)
                                     for _ in range(2)])
        outs = [router.submit("lm", p, max_new_tokens=m)
                .result(timeout=120)
                for p, m in zip(PROMPTS[:3], LENS[:3])]
        fleet = [router.submit("lm-fleet", p, max_new_tokens=m)
                 .result(timeout=120)
                 for p, m in zip(PROMPTS[:3], LENS[:3])]
        router.close()
        assert outs == want
        assert fleet == want


# -------------------------------------------------------------- metrics ----


def test_speculative_metrics_rows_append_after_golden_order():
    """PR-10 golden contract: speculative rows render strictly AFTER
    the PR-9 quantized block, which renders after the PR-7 replica
    block — append-only, never reordered."""
    from bigdl_tpu.serving import ServingMetrics

    m = ServingMetrics()
    m.record_batch(3, 4)
    m.record_served(0.010, 0.004)
    m.record_prefill(5, 8, 0.002)
    m.record_decode_step(3, 4)
    m.record_chunk(8, 8)
    m.set_pages(5, 32)
    m.record_reload()
    m.set_replicas(2, 2, {"r0": 1})
    m.set_kv_cache(4096, "int8")
    m.set_quantized_gemms(13)
    pre_lines = m.format_table().splitlines()

    m.record_verify_step(8, 5, 5)
    full_lines = m.format_table().splitlines()
    assert ([ln.split()[0] for ln in full_lines[:len(pre_lines)]]
            == [ln.split()[0] for ln in pre_lines])
    extra = [ln.split()[0] for ln in full_lines[len(pre_lines):]]
    assert extra == ["draft_tokens", "accepted_tokens", "acceptance_rate",
                     "verify_steps"]
    snap = m.snapshot()
    assert snap["draft_tokens"] == 8
    assert snap["accepted_tokens"] == 5
    assert snap["acceptance_rate"] == pytest.approx(5 / 8)
    assert snap["verify_steps"] == 1
    # extra emitted tokens folded into tokens_out (prefill 1 + decode 3
    # + 5 speculative extras)
    assert snap["tokens_out"] == 9
    keys = list(snap)
    # the PR-10 block sits immediately before the PR-11 step-timeline,
    # PR-12 prefix-cache, PR-18 KV-tier, PR-19 async-scheduling, and
    # PR-20 structured-generation keys (append-only: each PR's rows
    # land AFTER every earlier block)
    assert keys[-31:-27] == ["draft_tokens", "accepted_tokens",
                            "acceptance_rate", "verify_steps"]


def test_page_pool_owner_tagging_unit():
    """PagePool owner accounting: tags ride alloc/release by page id,
    untagged allocs stay anonymous, totals always reconcile."""
    pool = PagePool(8, 4, 16)
    a = pool.alloc(2, owner="target")
    b = pool.alloc(3, owner="draft")
    c = pool.alloc(1)
    assert pool.in_use == 6
    assert pool.in_use_by("target") == 2
    assert pool.in_use_by("draft") == 3
    assert pool.in_use_by("nobody") == 0
    pool.release(b)
    assert pool.in_use_by("draft") == 0 and pool.in_use == 3
    pool.release(a)
    pool.release(c)
    assert pool.in_use == 0 and pool.in_use_by("target") == 0
    # recycled pages take fresh tags
    d = pool.alloc(4, owner="draft")
    assert pool.in_use_by("draft") == 4
    pool.release(d)
    assert pool.in_use_by("draft") == 0
