"""Inference-tier tests (reference: ``Predictor``/``Evaluator``/
``PredictionService`` behavior, ``DL/optim/Predictor.scala:92`` splitBatch,
``Evaluator.scala:51`` reduce)."""

import threading

import jax
import numpy as np
import pytest

from bigdl_tpu.dataset.dataset import DataSet
from bigdl_tpu.dataset.sample import Sample
from bigdl_tpu.nn import (
    ClassNLLCriterion, Linear, LogSoftMax, ReLU, Sequential,
)
from bigdl_tpu.optim.predictor import Evaluator, PredictionService, Predictor
from bigdl_tpu.optim.validation import Loss, Top1Accuracy


@pytest.fixture(scope="module")
def setup():
    model = Sequential().add(Linear(8, 16)).add(ReLU()).add(Linear(16, 4)).add(LogSoftMax())
    params, state = model.init(jax.random.key(0))
    rs = np.random.RandomState(0)
    x = rs.rand(37, 8).astype("float32")
    y = rs.randint(0, 4, 37)
    return model, params, state, x, y


def test_predict_splits_per_sample(setup):
    model, params, state, x, _ = setup
    p = Predictor(model, params, state)
    outs = p.predict(x)
    assert len(outs) == 37 and outs[0].shape == (4,)
    # per-sample outputs must equal the full-batch forward rows
    full, _ = model.apply(params, x, state=state)
    np.testing.assert_allclose(np.asarray(outs[3]), np.asarray(full)[3], rtol=1e-5)


def test_predict_class(setup):
    model, params, state, x, _ = setup
    p = Predictor(model, params, state)
    cls = p.predict_class(x)
    full, _ = model.apply(params, x, state=state)
    np.testing.assert_array_equal(cls, np.argmax(np.asarray(full), axis=-1))


def test_predict_on_samples_list(setup):
    model, params, state, x, y = setup
    p = Predictor(model, params, state)
    samples = [Sample.of(x[i], y[i]) for i in range(10)]
    assert len(p.predict(samples)) == 10


def test_evaluator_counts_all_records(setup):
    model, params, state, x, y = setup
    ev = Evaluator(model, params, state, batch_size=8)  # 37 -> partial batch
    res = ev.test(DataSet.tensors(x, y), [Top1Accuracy(), Loss(ClassNLLCriterion())])
    for r in res:
        v, n = r.result()
        assert n == 37
    acc, _ = res[0].result()
    full, _ = model.apply(params, x, state=state)
    expected = float(np.mean(np.argmax(np.asarray(full), -1) == y))
    assert abs(acc - expected) < 1e-6


def test_evaluator_runs_host_side_metrics(setup):
    """PRAUC / MAP run host-side numpy in .batch — Evaluator must apply
    them outside the jitted eval step (ADVICE round 1: calling them inside
    jit raised TracerArrayConversionError)."""
    from bigdl_tpu.optim.validation import MeanAveragePrecision, PrecisionRecallAUC

    model, params, state, x, y = setup
    ev = Evaluator(model, params, state, batch_size=8)
    res = ev.test(
        DataSet.tensors(x, y),
        [Top1Accuracy(), MeanAveragePrecision(4)],
    )
    assert [r.name for r in res] == ["Top1Accuracy", "MAP@4"]
    for r in res:
        v, n = r.result()
        assert n == 37 and np.isfinite(v)

    # PRAUC is binary: one score per sample
    bin_model = Sequential().add(Linear(8, 1))
    bp, bs = bin_model.init(jax.random.key(1))
    yb = (y % 2).astype("float32")
    bev = Evaluator(bin_model, bp, bs, batch_size=8)
    (prauc,) = bev.test(DataSet.tensors(x, yb), [PrecisionRecallAUC()])
    v, n = prauc.result()
    assert n == 37 and 0.0 <= v <= 1.0


def test_keras_evaluate_host_side_metric():
    from bigdl_tpu import keras
    from bigdl_tpu.optim.validation import MeanAveragePrecision

    m = keras.Sequential()
    m.add(keras.Dense(8, input_shape=(6,), activation="relu"))
    m.add(keras.Dense(3, activation="log_softmax"))
    m.compile(optimizer="sgd", loss="sparse_categorical_crossentropy",
              metrics=[MeanAveragePrecision(3)])
    rs = np.random.RandomState(1)
    x = rs.rand(20, 6).astype("float32")
    y = rs.randint(0, 3, 20)
    out = m.evaluate(x, y, batch_size=8)
    names = [n for n, _ in out]
    assert "MAP@3" in names
    assert all(np.isfinite(v) for _, v in out)


def test_optimizer_validation_with_host_side_metric(setup):
    """Host-side metrics must also work in training-time validation
    (Optimizer._build_eval_step), not just Evaluator/evaluate."""
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.optim.trigger import Trigger
    from bigdl_tpu.optim.validation import MeanAveragePrecision

    from bigdl_tpu.dataset.transformer import SampleToMiniBatch

    model, _, _, x, y = setup
    ds = DataSet.tensors(x, y) >> SampleToMiniBatch(8)
    opt = LocalOptimizer(model, ds, ClassNLLCriterion(), batch_size=8)
    opt.set_optim_method(SGD(learning_rate=0.05))
    opt.set_validation(Trigger.several_iteration(1), DataSet.tensors(x, y),
                       [Top1Accuracy(), MeanAveragePrecision(4)])
    opt.set_end_when(Trigger.max_iteration(2))
    opt.optimize()
    results = opt._run_validation()
    assert [r.name for r in results] == ["Top1Accuracy", "MAP@4"]
    for r in results:
        assert np.isfinite(r.result()[0])


def test_duplicate_metric_names_accumulate_separately(setup):
    from bigdl_tpu.nn import CrossEntropyCriterion

    model, params, state, x, y = setup
    ev = Evaluator(model, params, state, batch_size=8)
    # both are named "Loss" but compute different values (the model emits
    # log-probs; CrossEntropyCriterion applies its own log-softmax on top)
    res = ev.test(DataSet.tensors(x, y),
                  [Loss(ClassNLLCriterion()), Loss(CrossEntropyCriterion())])
    v0, v1 = res[0].result()[0], res[1].result()[0]
    assert v0 != v1, "two different Loss metrics were merged by name"


def test_evaluator_requires_labels(setup):
    model, params, state, x, _ = setup
    ev = Evaluator(model, params, state)
    with pytest.raises(ValueError, match="labels"):
        ev.test(DataSet.tensors(x), [Top1Accuracy()])


def test_prediction_service_concurrent(setup):
    model, params, state, x, _ = setup
    with PredictionService(model, params, state, n_concurrent=3) as svc:
        outs = [None] * 12
        def call(i):
            outs[i] = svc.predict(x[i])
        threads = [threading.Thread(target=call, args=(i,)) for i in range(12)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert svc.served == 12
    full, _ = model.apply(params, x, state=state)
    for i in (0, 5, 11):
        np.testing.assert_allclose(outs[i], np.asarray(full)[i], rtol=1e-5)


def test_prediction_service_accepts_sample(setup):
    model, params, state, x, y = setup
    with PredictionService(model, params, state) as svc:
        out = svc.predict(Sample.of(x[0], y[0]))
    assert out.shape == (4,)
