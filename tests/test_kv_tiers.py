"""KV memory hierarchy (PR 18): host tier beneath the device PagePool.

The load-bearing properties, per the subsystem contract:

- the HEADLINE: engine output with the host tier ON is bit-identical to
  OFF — greedy and sampled, float and int8 KV, short and chunk-spanning
  tails — including revisits served by a host->device restore (an
  offloaded page holds the same bytes a fresh prefill writes);
- offload→restore actually moves pages through the host tier
  (``kv_offload_pages``/``kv_restore_pages`` > 0) and restore MOVES the
  entry (a page lives in exactly one tier at a time);
- stream swap-out under QoS pressure (``submit(priority=)``) parks the
  lowest-priority idle stream and resumes it byte-exact, while the
  higher-priority waiter admits immediately;
- compile-once: the host tier rides the PR-15 handoff gather/scatter —
  warmup plus offload plus restore traffic leaves exactly one trace of
  each;
- faults at ``kv.offload``/``kv.restore`` fail only the affected
  entry/stream (offload → page evicts plainly; restore → degrade to a
  miss) and never strand pages in either tier;
- leaf-first prefix eviction: under equal pressure a shorter shared
  prefix outlives a single branch's deep tail (the PR-18 bugfix);
- both tiers' gauges drain to zero at close.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu import faults
from bigdl_tpu.faults import InjectedFault
from bigdl_tpu.nn.layers.attention import Transformer
from bigdl_tpu.serving import (
    GenerationEngine,
    HostPageStore,
    PagePool,
    PagedDecodeKernels,
    PrefixCache,
)

SLOTS, MAXLEN = 4, 48


@pytest.fixture(scope="module")
def lm():
    model = Transformer(vocab_size=64, hidden_size=32, num_heads=4,
                        filter_size=64, num_hidden_layers=2)
    params, _ = model.init(jax.random.key(0))
    kernels = PagedDecodeKernels(model)
    return model, params, kernels


def make_engine(lm, **kw):
    model, params, kernels = lm
    kw.setdefault("max_slots", SLOTS)
    kw.setdefault("max_len", MAXLEN)
    kw.setdefault("kernels", kernels)
    kw.setdefault("page_size", 4)
    kw.setdefault("prefill_chunk", 4)
    return GenerationEngine(model, params, **kw)


def family_prompts(n_families=4):
    """n 3-page prefix families with two divergent tails each — more
    published pages (12) than a 12-page pool can keep alongside a live
    5-page reservation, so admissions force LRU evictions and the
    revisit pass below exercises the host->device restore path."""
    fams = [[int(t) for t in np.random.RandomState(100 + i).randint(1, 60, 12)]
            for i in range(n_families)]
    return fams, [[1, 2], [3, 4]]


# ------------------------------------------------------ store (unit) ----


class TestHostPageStore:
    def test_put_take_move_semantics(self):
        st = HostPageStore(8, page_bytes=64)
        rows = {"k": np.ones(3)}
        assert st.put_prefix(0, (1, 2, 3, 4), rows)
        assert st.has_prefix(0, (1, 2, 3, 4))
        assert not st.has_prefix(1, (1, 2, 3, 4))     # version keyed
        assert st.pages == 1 and st.bytes_used == 64
        got = st.take_prefix(0, (1, 2, 3, 4))
        assert got is rows
        # MOVE: the entry left with the restore
        assert not st.has_prefix(0, (1, 2, 3, 4))
        assert st.take_prefix(0, (1, 2, 3, 4)) is None
        assert st.pages == 0
        assert st.offloaded_pages == 1 and st.restored_pages == 1

    def test_lru_capacity_eviction(self):
        st = HostPageStore(2)
        st.put_prefix(0, (1,), "a")
        st.put_prefix(0, (2,), "b")
        st.put_prefix(0, (1,), "a2")     # refresh in place, no eviction
        assert st.evicted_pages == 0 and st.prefix_pages == 2
        st.put_prefix(0, (3,), "c")      # capacity: (2,) is now oldest
        assert st.evicted_pages == 1
        assert not st.has_prefix(0, (2,))
        assert st.has_prefix(0, (1,)) and st.has_prefix(0, (3,))
        assert st.take_prefix(0, (1,)) == "a2"

    def test_drop_and_park_bookkeeping(self):
        st = HostPageStore(4, page_bytes=10)
        st.put_prefix(0, (1,), "a")
        assert st.drop_prefix(0, (1,)) and not st.drop_prefix(0, (1,))
        st.record_drop(2)
        assert st.dropped_pages == 3
        st.park_stream(7, 5)
        assert st.stream_pages == 5 and st.pages == 5
        snap = st.snapshot()
        assert snap["tier"] == "host"
        assert snap["by_owner"] == {"stream": 5}
        assert snap["bytes_in_use"] == 50
        assert st.unpark_stream(7) == 5
        assert st.unpark_stream(7) == 0   # idempotent: every exit path
        assert st.stream_swaps_out == 1 and st.stream_swaps_in == 1
        st.put_prefix(0, (2,), "b")
        assert st.clear() == 1 and st.pages == 0

    def test_capacity_validation(self):
        with pytest.raises(ValueError):
            HostPageStore(0)


# ------------------------------------- leaf-first eviction (PR-18 fix) ----


class TestLeafFirstEviction:
    def _tree(self):
        """root -> n1 {n2 -> n3, n4}: chain A (3 pages, stamp 1) and a
        younger branch B sharing the first page (n1, n4 stamped 2)."""
        pool = PagePool(16, 4, 32)
        cache = PrefixCache(pool)
        a = list(range(1, 13))               # c1+c2+c3
        b = a[:4] + list(range(21, 25))      # c1+c4
        pa = pool.alloc(3)
        cache.publish(a, pa)
        pool.release(pa)
        pb = pool.alloc(2)
        cache.publish(b, pb)
        pool.release(pb)
        assert cache.pages == 4
        return pool, cache, a, b

    def test_shorter_shared_prefix_survives(self):
        """The regression: one cold deep leaf (n3, stamp 1) used to let
        eviction climb its ancestor chain — dropping n2 (stamp 1)
        before the YOUNGER branch leaf n4 (stamp 2). Leaf-first rounds
        evict both current leaves before any exposed parent."""
        pool, cache, a, b = self._tree()
        assert cache.evict(2) == 2
        # survivors are the shared prefix chain n1 -> n2, not n4
        matched, _, _ = cache.lookup(a + [63])
        assert matched == 8
        assert len(cache.match_pages(b, 2)) == 1   # c4 gone, c1 lives

    def test_round_order_shortest_prefix_last(self):
        """Full drain leaves individually, leaves before their parents
        and LRU within a round — the granularity the host tier offloads
        candidates in."""
        pool, cache, a, b = self._tree()
        order = []
        cache.evict(4, on_evict=lambda prefix, page: order.append(prefix))
        assert cache.pages == 0 and pool.in_use == 0
        c1, c2, c3 = tuple(a[:4]), tuple(a[4:8]), tuple(a[8:12])
        c4 = tuple(b[4:8])
        assert order == [c1 + c2 + c3,   # round 1, stamp 1
                         c1 + c4,        # round 1, stamp 2
                         c1 + c2,        # round 2: exposed parent
                         c1]             # round 3: root child last

    def test_protect_shields_matched_chain(self):
        pool, cache, a, _ = self._tree()
        _, _, nodes = cache.lookup(a + [63])   # matches the whole A chain
        assert cache.evict(4, protect=frozenset(nodes)) == 1  # n4 only
        assert cache.pages == 3


# -------------------------------------------------- engine headline ----


class TestOffloadRestoreIdentity:
    @pytest.mark.parametrize("spec_kw,cache_dtype", [
        ({}, jnp.float32),
        (dict(temperature=0.9, top_k=20, top_p=0.95), jnp.float32),
        ({}, "int8"),
        (dict(temperature=0.9, top_k=20, top_p=0.95), "int8"),
    ], ids=["greedy-f32", "sampled-f32", "greedy-int8", "sampled-int8"])
    def test_bit_identical_host_tier_on_vs_off(self, lm, spec_kw,
                                               cache_dtype):
        """THE acceptance assertion: a pool too small for the working
        set (3 prefix families, 9 published pages, 12-page pool) with
        the host tier on serves revisits by restoring offloaded pages —
        and every stream is bit-identical to the no-host-tier engine.
        Short and chunk-spanning divergent tails ride in the prompt
        set, so whole and chunked prefills both cross the tier."""
        fams, tails = family_prompts()
        long_tail = [int(t) for t in np.random.RandomState(9).randint(1, 60, 7)]
        prompts = [f + t for f in fams for t in tails]
        revisit = [f + [5, 6] for f in fams] + [fams[0] + long_tail]

        def run(host_pages):
            eng = make_engine(lm, max_slots=2, seed=3, num_pages=12,
                              cache_dtype=cache_dtype, prefix_cache=True,
                              host_pages=host_pages)
            outs = [eng.generate(p, max_new_tokens=3, timeout=60, **spec_kw)
                    for p in prompts + revisit]
            host = eng.host_store
            snap = eng.metrics.snapshot()
            eng.close()
            assert eng.pages_in_use == 0 and eng.shared_pages == 0
            return outs, snap, host

        want, _, none_host = run(None)
        assert none_host is None
        got, snap, host = run(32)
        assert got == want
        # pages really moved through the tier, both directions
        assert snap["kv_offload_pages"] > 0
        assert snap["kv_restore_pages"] > 0
        assert host.offloaded_pages == snap["kv_offload_pages"]
        assert host.restored_pages == snap["kv_restore_pages"]
        assert snap["host_pages_peak"] > 0
        # drain gate: close cleared the tier, gauges at zero
        assert host.pages == 0

    def test_restore_cheaper_than_reprefill(self, lm):
        """A restored prefix skips its covered chunks exactly like a
        device-index hit: the revisit pass runs fewer prefill chunks
        than the no-host engine's full re-prefills."""
        fams, tails = family_prompts()
        prompts = [f + t for f in fams for t in tails]
        revisit = [f + [5, 6] for f in fams]

        def run(host_pages):
            eng = make_engine(lm, max_slots=2, num_pages=12,
                              prefix_cache=True, host_pages=host_pages)
            for p in prompts:
                eng.generate(p, max_new_tokens=3, timeout=60)
            pre = eng.metrics.snapshot()["prefill_chunks"]
            for p in revisit:
                eng.generate(p, max_new_tokens=3, timeout=60)
            snap = eng.metrics.snapshot()
            eng.close()
            return snap["prefill_chunks"] - pre, snap

        chunks_off, _ = run(None)
        chunks_on, snap = run(32)
        assert snap["kv_restore_pages"] > 0
        assert chunks_on < chunks_off


class TestCompileOnce:
    def test_host_copies_add_no_traces(self, lm):
        """Warmup compiles the gather/scatter pair once; offload and
        restore traffic reuses both executables — the host tier adds
        zero traces on top of the PR-15 handoff shapes."""
        fams, tails = family_prompts()
        eng = make_engine(lm, max_slots=2, num_pages=12,
                          prefix_cache=True, host_pages=32)
        eng.warmup()
        assert eng.handoff_gather_compilations == 1
        assert eng.handoff_scatter_compilations == 1
        for p in [f + t for f in fams for t in tails] + \
                [f + [5, 6] for f in fams]:
            eng.generate(p, max_new_tokens=3, timeout=60)
        snap = eng.metrics.snapshot()
        eng.close()
        assert snap["kv_offload_pages"] > 0 and snap["kv_restore_pages"] > 0
        assert eng.handoff_gather_compilations == 1
        assert eng.handoff_scatter_compilations == 1


# ----------------------------------------------------- stream swap ----


class TestStreamSwap:
    def _swap_run(self, lm, **arm):
        """Two low-priority long streams fill both 12-page lanes; a
        priority-5 request then heads the FIFO. Returns the three
        streams' results (or errors) plus the engine's final metrics."""
        eng = make_engine(lm, max_slots=3, num_pages=24,
                          prefix_cache=True, host_pages=64)
        # 6 + 42 = 48 tokens = max_len: each low reserves a full
        # 12-page lane, so the 24-page pool has zero free pages and the
        # priority-5 head can only admit by swapping a low out
        lows = [eng.submit([i + 1] * 6, max_new_tokens=42)
                for i in range(2)]
        deadline = time.monotonic() + 30
        while eng.active_slots < 2 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert eng.active_slots == 2
        high = eng.submit([40, 41, 42], max_new_tokens=4, priority=5)
        outs = []
        for s in [high] + lows:
            try:
                outs.append(("ok", s.result(timeout=60)))
            except InjectedFault as e:
                outs.append(("fault", type(e).__name__))
        host = eng.host_store
        snap = eng.metrics.snapshot()
        eng.close()
        assert eng.pages_in_use == 0 and host.pages == 0
        return outs, snap

    def test_swap_out_and_resume_byte_exact(self, lm):
        refs = {}
        eng = make_engine(lm, max_slots=3, num_pages=24)
        for i in range(2):
            refs[i] = eng.generate([i + 1] * 6, max_new_tokens=42,
                                   timeout=60)
        refs["high"] = eng.generate([40, 41, 42], max_new_tokens=4,
                                    timeout=60)
        eng.close()

        outs, snap = self._swap_run(lm)
        assert snap["kv_swaps_out"] >= 1
        assert snap["kv_swaps_in"] == snap["kv_swaps_out"]
        assert outs[0] == ("ok", refs["high"])
        # the parked stream resumed BYTE-EXACT: same tokens as an
        # unpressured run (pages, PRNG key, position all round-tripped)
        assert outs[1] == ("ok", refs[0]) and outs[2] == ("ok", refs[1])

    def test_equal_priority_never_swaps(self, lm):
        eng = make_engine(lm, max_slots=3, num_pages=24,
                          prefix_cache=True, host_pages=64)
        # three 9-page reservations against a 24-page pool: the third
        # waits at the FIFO head under pressure, but with equal
        # priorities it must WAIT (a delay, never a swap)
        streams = [eng.submit([i + 1] * 6, max_new_tokens=30)
                   for i in range(3)]
        outs = [s.result(timeout=60) for s in streams]
        snap = eng.metrics.snapshot()
        eng.close()
        assert all(len(o) == 30 for o in outs)
        assert snap["kv_swaps_out"] == 0

    def test_swap_resume_fault_fails_only_that_stream(self, lm):
        """An injected ``kv.restore`` (kind='swap') at the parked
        stream's resume fails ONLY that stream; the high-priority
        request and the untouched low both complete, and both tiers
        still drain to zero."""
        with faults.armed("kv.restore", nth=1,
                          only=lambda kind=None, **_: kind == "swap"):
            outs, snap = self._swap_run(lm)
        assert snap["kv_swaps_out"] >= 1
        assert outs[0][0] == "ok" and len(outs[0][1]) == 4
        kinds = sorted(o[0] for o in outs[1:])
        assert kinds == ["fault", "ok"]


# ---------------------------------------------------------- faults ----


class TestHostTierFaults:
    def test_offload_fault_drops_entry_never_strands(self, lm):
        """Every offload copy faults: pages evict plainly (dropped
        counter, empty host tier), streams are untouched, gauges
        drain."""
        fams, tails = family_prompts()
        prompts = [f + t for f in fams for t in tails]
        eng = make_engine(lm, max_slots=2, num_pages=12,
                          prefix_cache=True, host_pages=32)
        with faults.armed("kv.offload",
                          only=lambda engine=None, **_: engine is eng):
            outs = [eng.generate(p, max_new_tokens=3, timeout=60)
                    for p in prompts]
        host = eng.host_store
        snap = eng.metrics.snapshot()
        eng.close()
        assert all(len(o) == 3 for o in outs)
        assert host.offloaded_pages == 0 and host.pages == 0
        assert snap["kv_offload_dropped"] > 0
        assert snap["kv_offload_pages"] == 0
        assert eng.pages_in_use == 0 and eng.shared_pages == 0

    def test_restore_fault_degrades_to_miss(self, lm):
        """A faulted prefix restore drops the affected host entries and
        re-prefills — the stream's tokens are still bit-identical to
        the no-host reference."""
        fams, tails = family_prompts()
        prompts = [f + t for f in fams for t in tails]
        revisit = [f + [5, 6] for f in fams]

        ref = make_engine(lm, max_slots=2, num_pages=12, prefix_cache=True)
        want = [ref.generate(p, max_new_tokens=3, timeout=60)
                for p in prompts + revisit]
        ref.close()

        eng = make_engine(lm, max_slots=2, num_pages=12,
                          prefix_cache=True, host_pages=32)
        outs = [eng.generate(p, max_new_tokens=3, timeout=60)
                for p in prompts]
        with faults.armed("kv.restore",
                          only=lambda engine=None, kind=None, **_:
                          engine is eng and kind == "prefix"):
            outs += [eng.generate(p, max_new_tokens=3, timeout=60)
                     for p in revisit]
        host = eng.host_store
        snap = eng.metrics.snapshot()
        eng.close()
        assert outs == want
        assert snap["kv_restore_pages"] == 0
        assert host.dropped_pages > 0        # degraded entries left the tier
        assert host.pages == 0 and eng.pages_in_use == 0


# -------------------------------------------------- gauges and API ----


class TestAccountingAndValidation:
    def test_tier_tagged_snapshots_and_drain(self, lm):
        fams, tails = family_prompts()
        eng = make_engine(lm, max_slots=2, num_pages=12,
                          prefix_cache=True, host_pages=32)
        for p in [f + t for f in fams for t in tails]:
            eng.generate(p, max_new_tokens=3, timeout=60)
        pool_snap = eng._pool.snapshot()
        host_snap = eng.host_store.snapshot()
        assert pool_snap["tier"] == "hbm"
        assert host_snap["tier"] == "host"
        assert host_snap["pages_in_use"] == eng.host_pages_in_use
        eng.close()
        closed = eng.metrics.snapshot()
        assert closed["host_pages"] == 0 and closed["host_bytes"] == 0
        assert eng.host_pages_in_use == 0
        assert eng.host_store.snapshot()["by_owner"] == {}

    def test_host_pages_requires_paged_prefix_engine(self, lm):
        with pytest.raises(ValueError, match="paged"):
            make_engine(lm, page_size=None, kernels=None, host_pages=8)
        with pytest.raises(ValueError, match="prefix_cache"):
            make_engine(lm, host_pages=8)
        with pytest.raises(ValueError, match="prefill"):
            make_engine(lm, prefix_cache=True, host_pages=8, role="decode")
