"""Examples tier: each reference ``DL/example/*`` counterpart runs
end-to-end on tiny synthetic data (reference test strategy: examples are
exercised by ``pyspark/test/local_integration`` shell runs; here they are
plain pytest cases since the mains are importable functions)."""

import numpy as np
import pytest


def test_text_classification_runs():
    from bigdl_tpu.examples import text_classification

    params, _ = text_classification.main(
        ["-z", "16", "--maxIteration", "3", "-s", "160", "-w", "500"])
    assert params is not None


def test_text_classification_glove(tmp_path):
    from bigdl_tpu.examples.text_classification import Dictionary, load_glove

    d = Dictionary([["alpha", "beta"]])
    p = tmp_path / "glove.txt"
    p.write_text("alpha 1.0 2.0 3.0\nmissing 4.0 5.0 6.0\n")
    table = load_glove(str(p), d, 3)
    assert table.shape == (3, 3)
    np.testing.assert_allclose(table[d.word2index["alpha"]], [1.0, 2.0, 3.0])


def test_udf_predictor_runs():
    from bigdl_tpu.examples import udf_predictor

    docs = udf_predictor.main(["-z", "16", "-e", "1", "-s", "160"])
    assert "predicted" in docs.columns and len(docs) == 16


def test_tree_lstm_sentiment_parse():
    from bigdl_tpu.examples.tree_lstm_sentiment import parse_sst

    tokens, nodes, root = parse_sst("(3 (2 good) (2 (2 very) (2 movie)))")
    assert tokens == ["good", "very", "movie"]
    assert root == 3
    # children-first: the root row is last and references earlier nodes
    left, right, leaf = nodes[-1]
    assert leaf == 0 and left > 0 and right > 0


def test_tree_lstm_sentiment_runs():
    from bigdl_tpu.examples import tree_lstm_sentiment

    params, _ = tree_lstm_sentiment.main(
        ["-b", "16", "--maxIteration", "3", "--hiddenSize", "8",
         "--embedDim", "8"])
    assert params is not None


def test_load_model_bigdl(tmp_path):
    import jax

    from bigdl_tpu.examples import load_model
    from bigdl_tpu.models import lenet
    from bigdl_tpu.utils.serializer import save_module

    model = lenet.build()
    params, state = model.init(jax.random.key(0))
    path = str(tmp_path / "lenet.bigdl")
    save_module(path, model, params, state)
    mod, p, s = load_model.load_any("bigdl", path)
    assert mod is not None and p is not None


def test_lenet_local_trio(tmp_path):
    from bigdl_tpu.examples import lenet_local

    common = ["--modelDir", str(tmp_path), "-b", "32"]
    lenet_local.main(["--mode", "train", "--maxIteration", "2"] + common)
    res = lenet_local.main(["--mode", "test"] + common)
    assert 0.0 <= res[0].result()[0] <= 1.0
    classes = lenet_local.main(["--mode", "predict", "--nPredict", "4"] + common)
    assert classes.shape == (4,)


def test_ml_pipeline_lr():
    from bigdl_tpu.examples import ml_pipeline

    acc = ml_pipeline.main(["--app", "lr", "-e", "10", "--nSamples", "128"])
    assert acc > 0.8


def test_ml_pipeline_multilabel():
    from bigdl_tpu.examples import ml_pipeline

    mse = ml_pipeline.main(["--app", "multilabel", "-e", "20",
                            "--nSamples", "128"])
    assert mse < 1.0


def test_int8_inference_runs(capsys):
    from bigdl_tpu.examples import int8_inference

    fp, q = int8_inference.main(["--arch", "resnet50", "-b", "8",
                                 "--classNum", "10"])
    out = capsys.readouterr().out
    assert "scales" in out and len(fp) == 2 and len(q) == 2


def test_tf_transfer_learning_runs():
    from bigdl_tpu.examples import tf_transfer_learning

    params, _ = tf_transfer_learning.main(
        ["-b", "16", "-e", "2", "--nSamples", "64"])
    assert params is not None


def test_image_classification_predict():
    from bigdl_tpu.examples import image_classification

    out = image_classification.main(["-b", "4", "--classNum", "10"])
    assert "prediction" in out.columns and len(out) == 8


def test_dlframes_image_inference():
    from bigdl_tpu.examples import dlframes_image

    out = dlframes_image.main(["--app", "inference", "-b", "4",
                               "--classNum", "10", "--nSamples", "4"])
    assert "prediction" in out.columns


def test_dlframes_image_transfer():
    from bigdl_tpu.examples import dlframes_image

    acc = dlframes_image.main(["--app", "transfer", "-b", "8", "-e", "5",
                               "--nSamples", "16"])
    assert acc >= 0.5


def test_keras_train_runs():
    from bigdl_tpu.examples import keras_train

    scores = keras_train.main(["-b", "64", "-e", "1", "--nSamples", "256"])
    assert scores


def test_language_model_runs(tmp_path):
    from bigdl_tpu.examples import language_model

    corpus = tmp_path / "corpus.txt"
    corpus.write_text("the quick brown fox jumps over the lazy dog .\n" * 200)
    params, _ = language_model.main(
        ["-f", str(corpus), "-b", "8", "--maxIteration", "2",
         "--seqLength", "8", "--hiddenSize", "8", "--vocabSize", "50"])
    assert params is not None


def test_recommendation_ncf():
    from bigdl_tpu.examples import recommendation

    hr = recommendation.main(["-b", "128", "--maxIteration", "20",
                              "--embedDim", "8", "--evalNeg", "20"])
    assert 0.0 <= hr <= 1.0


def test_maskrcnn_cli_predict_and_evaluate():
    from bigdl_tpu.models import maskrcnn

    out = maskrcnn.main(["--mode", "predict", "--numClasses", "5",
                         "--depth", "18", "--minSize", "96",
                         "--maxSize", "128"])
    assert "masks" in out
    ap = maskrcnn.main(["--mode", "evaluate", "--numClasses", "5",
                        "--depth", "18", "--minSize", "96",
                        "--maxSize", "128", "--nImages", "2"])
    assert 0.0 <= ap <= 1.0


def test_continuous_batching_demo_runs():
    """The generation-serving demo: staggered clients through the router,
    every request served, and the engine's token accounting adds up."""
    from bigdl_tpu.examples import continuous_batching_demo

    snap = continuous_batching_demo.main(
        ["-n", "12", "-c", "4", "-s", "2", "--long", "24"])
    assert snap["served"] == 12 and snap["rejected_clients"] == 0
    assert snap["prefills"] == 12 and snap["tokens_out"] > 12
    assert snap["ttft_ms"] is not None
    assert snap["continuous_vs_static"] > 0


def test_speculative_decoding_demo_runs():
    """The speculative demo: draft+target behind the router, zero greedy
    mismatches (asserted inside), self-draft acceptance near the upper
    bound, and the target amortized over more tokens than forwards."""
    from bigdl_tpu.examples import speculative_decoding_demo

    snap = speculative_decoding_demo.main(
        ["-n", "8", "-s", "2", "--new", "12", "--max-len", "48"])
    assert snap["mismatches"] == 0
    assert snap["verify_steps"] > 0
    assert snap["acceptance_rate"] >= 0.5  # self-draft: near the bound
    # amortization clearly above the zero-acceptance floor (~`slots`
    # tokens per verify from batching alone; self-draft at k=3 lands
    # near the k+1=4-per-slot ceiling)
    assert snap["tokens_per_verify"] > 4.0


def test_structured_generation_demo_runs():
    """The structured-generation demo: a JSON tool-call schema through
    the router — every constrained stream parses (rate 1.0), the
    grammar compiles once and is shared, and the masked-vocab gauge is
    live."""
    from bigdl_tpu.examples import structured_generation_demo

    snap = structured_generation_demo.main(["-n", "8", "-c", "4", "-s", "2"])
    assert snap["parse_rate"] == 1.0
    assert snap["served"] == 8 and snap["failed"] == 0
    assert snap["constrained_streams"] == 8
    # one submit published the grammar key; the other seven hit it
    assert snap["grammar_compile_cache_hits"] == 7
    assert 0.0 < snap["masked_vocab_frac"] <= 1.0


def test_elastic_fleet_demo_runs():
    """The autoscaler demo: an open-loop burst past one member's
    modeled capacity grows the fleet, the calm tail shrinks it, and
    the scale cycle strands nothing."""
    from bigdl_tpu.examples import elastic_fleet_demo

    out = elastic_fleet_demo.main(
        ["--rps", "60", "--burst-s", "2.0", "--calm-s", "2.0"])
    assert out["served"] > 0
    assert out["served"] + out["shed"] == out["offered"]
    assert out["scale_ups"] >= 1
    assert out["peak_prefill"] > 1 or out["peak_decode"] > 1
    assert out["pages_in_use"] == 0


def test_parallel_training_example_runs():
    from bigdl_tpu.examples import parallel_training

    assert parallel_training.main(["--steps", "2"]) == 0


def test_fault_tolerant_training_example_preempt_then_resume(tmp_path):
    """The ckpt demo: a simulated eviction commits a preempted manifest
    entry; rerunning the same command auto-resumes past it to --iters."""
    from bigdl_tpu.ckpt import load_manifest
    from bigdl_tpu.examples import fault_tolerant_training

    wd = str(tmp_path / "ft")
    opt = fault_tolerant_training.main(
        ["--workdir", wd, "--iters", "20", "--preempt-at", "6"])
    stopped_at = opt.state.iteration
    assert stopped_at < 20
    entries = load_manifest(wd)
    assert entries[-1].preempted and entries[-1].step == stopped_at

    opt2 = fault_tolerant_training.main(["--workdir", wd, "--iters", "20"])
    assert opt2.state.iteration >= 20
    assert load_manifest(wd)[-1].step >= 20
