"""Structured generation (PR 20): grammar-constrained decoding as a
first-class request type.

The load-bearing properties, per the subsystem contract:

- a regex / JSON-schema grammar lowers to a char DFA, lifts to a token
  automaton over the vocabulary, and compiles ONCE per distinct
  (grammar, vocab, eos) — the module cache shares automata across
  requests and engines;
- every constrained stream PARSES: the per-state mask enters the jitted
  step as a per-slot additive bias, greedy is argmax over the legal
  set, and a stream that cannot reach a legal continuation retires with
  a typed ``GrammarViolation`` instead of emitting garbage;
- the composition matrix holds: {greedy, sampled} x {f32, int8} x
  {whole, chunked prefill} x {plain, speculative} constrained streams
  all parse, are identical across admission orders and runs, and
  engine == static under the same grammar;
- compile-once survives: the mask is DATA riding the bias argument
  (always an array on a vocab-bearing model — zero rows for
  unconstrained slots), so constrained traffic adds no kernel traces;
- satellite 1: the paged decode attention branch COMPOSES an external
  bias with the position-validity mask (the PR-6 unreachable-arm
  ValueError is gone); a zero bias is bit-identical to the unbiased
  reference path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu.grammar import (
    DEAD,
    NEG_BIAS,
    RegexError,
    SchemaError,
    clear_compile_cache,
    compile_cache_stats,
    compile_grammar,
    compile_regex,
    json_schema_grammar,
    json_schema_regex,
    regex_grammar,
)
from bigdl_tpu.nn.layers.attention import Attention, Transformer
from bigdl_tpu.nn.module import Context
from bigdl_tpu.serving import (
    DecodeKernels,
    GenerationEngine,
    GrammarViolation,
    PagedDecodeKernels,
    ServingMetrics,
    SpeculativeKernels,
    static_generate,
)

SLOTS, MAXLEN = 4, 64
EOS = 1

# toy tokenizer over the 64-id test vocab: one printable char per id
# (ids 2..), id 0 = pad, id 1 = EOS, the rest placeholders no char DFA
# can step through
_CHARS = "abcdefghijklmnopqrstuvwxyz0123456789{}\":,.-[] "


def make_vocab(n=64):
    vocab = [f"<{i}>" for i in range(n)]
    for j, ch in enumerate(_CHARS):
        vocab[j + 2] = ch
    return vocab


VOCAB = make_vocab()

# finite grammars only (parse-guaranteed under greedy): a fixed-length
# regex and an enum+boolean-only schema terminate via EOS inside any
# reasonable budget; an unbounded [0-9]* integer field would not
REGEX_PATTERN = "id-[0-9][0-9]"
TOOL_SCHEMA = {
    "type": "object",
    "properties": {"tool": {"enum": ["search", "calc"]},
                   "ok": {"type": "boolean"}},
    "required": ["tool", "ok"],
}


@pytest.fixture(scope="module")
def lm():
    model = Transformer(vocab_size=64, hidden_size=32, num_heads=4,
                        filter_size=64, num_hidden_layers=2)
    params, _ = model.init(jax.random.key(0))
    # one kernel set for the whole module: the jit cache persists
    # across engines, so each test pays bookkeeping, not recompilation
    kernels = PagedDecodeKernels(model)
    skernels = SpeculativeKernels(model, model)
    return model, params, kernels, skernels


@pytest.fixture(scope="module")
def grammars(lm):
    model = lm[0]
    g_re = compile_grammar(regex_grammar(REGEX_PATTERN), VOCAB, eos_id=EOS)
    g_js = compile_grammar(json_schema_grammar(TOOL_SCHEMA), VOCAB,
                           eos_id=EOS)
    assert g_re.vocab_size == model.vocab_size
    return g_re, g_js


def make_engine(lm, *, speculate=0, **kw):
    model, params, kernels, skernels = lm
    kw.setdefault("max_slots", SLOTS)
    kw.setdefault("max_len", MAXLEN)
    kw.setdefault("page_size", 8)
    kw.setdefault("eos_id", EOS)
    kw.setdefault("metrics", ServingMetrics())
    if speculate:
        kw.setdefault("kernels", skernels)
        kw.setdefault("speculate", (model, params, speculate))
    else:
        kw.setdefault("kernels", kernels)
    return GenerationEngine(model, params, **kw)


PROMPTS = [[4, 9, 2], [7, 3, 5, 11], [2], [12, 8]]


# --------------------------------------------------- automaton level ----


class TestRegexAndSchema:
    def test_char_dfa_fullmatch(self):
        dfa = compile_regex("a(b|c)d*", _CHARS)
        assert dfa.fullmatch("abd")
        assert dfa.fullmatch("ac")
        assert dfa.fullmatch("abddd")
        assert not dfa.fullmatch("ad")
        assert not dfa.fullmatch("abdx")
        assert not dfa.fullmatch("")

    def test_bad_regex_raises(self):
        with pytest.raises(RegexError):
            compile_regex("a(b", _CHARS)

    def test_schema_regex_matches_canonical_json(self):
        import json as _json

        dfa = compile_regex(json_schema_regex(TOOL_SCHEMA), _CHARS)
        assert dfa.fullmatch('{"tool":"search","ok":true}')
        assert dfa.fullmatch('{"tool":"calc","ok":false}')
        assert not dfa.fullmatch('{"tool":"grep","ok":true}')
        # the accepted surface IS canonical compact JSON
        assert dfa.fullmatch(_json.dumps(
            {"tool": "calc", "ok": True}, separators=(",", ":")))

    def test_bad_schema_raises(self):
        with pytest.raises(SchemaError):
            json_schema_regex({"enum": []})
        with pytest.raises(SchemaError):
            json_schema_regex({"type": "object", "properties": {}})

    def test_automaton_advance_masks_and_terminal(self):
        g = compile_grammar(regex_grammar("ab"), VOCAB, eos_id=EOS)
        a_id, b_id = VOCAB.index("a"), VOCAB.index("b")
        s0 = g.start_state
        row = g.bias_row(s0)
        assert row[a_id] == 0.0
        assert row[b_id] == NEG_BIAS and row[EOS] == NEG_BIAS
        assert g.legal_count(s0) == 1
        assert g.masked_frac(s0) == pytest.approx(63 / 64)
        s1 = g.advance(s0, a_id)
        assert not g.is_accepting(s1) and g.has_continuation(s1)
        s2 = g.advance(s1, b_id)
        # accepting terminal: only EOS is legal
        assert g.is_accepting(s2) and not g.has_continuation(s2)
        assert g.bias_row(s2)[EOS] == 0.0
        # illegal token -> DEAD, DEAD propagates, DEAD row is all-zeros
        assert g.advance(s0, b_id) == DEAD
        assert g.advance(DEAD, a_id) == DEAD
        assert not np.any(g.bias_row(DEAD))
        assert g.masked_frac(DEAD) == 1.0
        assert g.matches([a_id, b_id, EOS])
        assert g.matches([a_id, b_id])
        assert not g.matches([a_id])
        assert g.text_of([a_id, b_id, EOS]) == "ab"

    def test_compile_cache_shares_automata(self):
        clear_compile_cache()
        h0, m0 = compile_cache_stats()
        g1 = compile_grammar(regex_grammar("xy"), VOCAB, eos_id=EOS)
        g2 = compile_grammar(regex_grammar("xy"), VOCAB, eos_id=EOS)
        assert g2 is g1
        h1, m1 = compile_cache_stats()
        assert (h1 - h0, m1 - m0) == (1, 1)
        # a different vocab (or eos) is a different automaton
        g3 = compile_grammar(regex_grammar("xy"), make_vocab(80), eos_id=EOS)
        assert g3 is not g1
        assert compile_cache_stats()[1] - m0 == 2


# ---------------------------------------------- satellite 1: attention ----


class TestPagedDecodeBiasComposition:
    """The PR-6 paged decode branch used to raise ``ValueError`` on any
    external bias; PR 20 replaced the arm with real mask/bias
    composition (the grammar mask reaches attention through it)."""

    def _setup(self, rng, heads=2, d=8, n_pages=6, ps=4, slots=3):
        attn = Attention(hidden_size=heads * d, num_heads=heads)
        params, _ = attn.init(jax.random.key(1))
        pools = {
            "k": jnp.asarray(rng.randn(n_pages, heads, ps, d)
                             .astype(np.float32)),
            "v": jnp.asarray(rng.randn(n_pages, heads, ps, d)
                             .astype(np.float32)),
            "map": jnp.asarray(np.stack(
                [rng.choice(n_pages, 2, replace=False)
                 for _ in range(slots)]).astype(np.int32)),
        }
        positions = jnp.asarray([2, 5, 7], jnp.int32)
        x = jnp.asarray(rng.randn(slots, 1, heads * d).astype(np.float32))
        ctx = Context(params, {}, False, None)
        return attn, ctx, pools, positions, x

    def test_zero_bias_bit_identical_to_unbiased(self):
        """An all-zero external bias must trace the same op sequence
        (and bits) as the reference path the unbiased arm takes."""
        rng = np.random.RandomState(0)
        attn, ctx, pools, positions, x = self._setup(rng)
        want, _ = attn.forward(ctx, x, cache_index=positions, paged=pools)
        lanes = pools["map"].shape[1] * pools["k"].shape[2]
        zero = jnp.zeros((x.shape[0], 1, 1, lanes), jnp.float32)
        got, _ = attn.forward(ctx, x, bias=zero, cache_index=positions,
                              paged=pools)
        assert np.array_equal(np.asarray(got), np.asarray(want))

    def test_bias_masks_to_single_column(self):
        """A bias that leaves ONE column legal pins the attention
        weight there: the output is exactly the projected V row that
        this step just wrote (the freshest token attends to itself)."""
        rng = np.random.RandomState(1)
        attn, ctx, pools, positions, x = self._setup(rng)
        lanes = pools["map"].shape[1] * pools["k"].shape[2]
        cols = np.arange(lanes)
        bias = np.where(cols[None, :] == np.asarray(positions)[:, None],
                        0.0, float(NEG_BIAS)).astype(np.float32)
        bias = jnp.asarray(bias)[:, None, None, :]
        out, _ = attn.forward(ctx, x, bias=bias, cache_index=positions,
                              paged=pools)
        v = attn._split_heads(attn.run_child(ctx, "v_layer", x))
        want = attn.run_child(ctx, "output_layer", attn._join_heads(v))
        np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                                   atol=1e-5)

    def test_verify_branch_still_rejects_bias(self):
        """The W>1 verify arm keeps its guard — only the decode arm
        grew composition (verify masks ride speculative scratch
        states, never an attention bias)."""
        rng = np.random.RandomState(2)
        attn, ctx, pools, positions, x = self._setup(rng)
        pools = dict(pools, trash=5)
        xw = jnp.asarray(rng.randn(3, 2, 16).astype(np.float32))
        lanes = pools["map"].shape[1] * pools["k"].shape[2]
        bias = jnp.zeros((3, 1, 1, lanes), jnp.float32)
        with pytest.raises(ValueError, match="no external bias"):
            attn.forward(ctx, xw, bias=bias, cache_index=positions,
                         paged=pools)


# ----------------------------------------------------- engine level ----


def submit_all(eng, specs, *, order=None):
    """Submit (prompt, max_new, grammar, sampling) specs in the given
    admission order; return streams re-sorted to spec order."""
    idx = list(order if order is not None else range(len(specs)))
    streams = [None] * len(specs)
    for i in idx:
        p, n, g, sample = specs[i]
        streams[i] = eng.submit(p, max_new_tokens=n, grammar=g, **sample)
    return streams


class TestConstrainedStreams:
    def test_constrained_greedy_parses_and_is_deterministic(self, lm,
                                                            grammars):
        g_re, g_js = grammars
        specs = [(PROMPTS[0], 40, g_re, {}),
                 (PROMPTS[1], 40, g_js, {}),
                 (PROMPTS[2], 40, g_re, {}),
                 (PROMPTS[3], 8, None, {})]   # unconstrained neighbour
        outs = []
        for order in (None, [3, 2, 1, 0]):
            eng = make_engine(lm)
            streams = submit_all(eng, specs, order=order)
            outs.append([s.result(timeout=60) for s in streams])
            eng.close()
        # identical across admission orders, and every constrained
        # stream is a word of its grammar
        assert outs[0] == outs[1]
        assert g_re.matches(outs[0][0])
        assert g_js.matches(outs[0][1])
        assert g_re.matches(outs[0][2])
        # same grammar + same greedy argmax -> same surface
        assert g_re.text_of(outs[0][0]) == g_re.text_of(outs[0][2])
        import json as _json

        _json.loads(g_js.text_of(outs[0][1]))

    def test_metrics_rows(self, lm, grammars):
        g_re, _ = grammars
        eng = make_engine(lm)
        for p in PROMPTS[:3]:
            eng.submit(p, max_new_tokens=40,
                       grammar=g_re).result(timeout=60)
        snap = eng.metrics.snapshot()
        table = eng.metrics.format_table()
        eng.close()
        assert snap["constrained_streams"] == 3
        # one submit published the key, the other two hit it
        assert snap["grammar_compile_cache_hits"] == 2
        assert 0.0 < snap["masked_vocab_frac"] <= 1.0
        assert list(snap)[-3:] == ["constrained_streams",
                                   "grammar_compile_cache_hits",
                                   "masked_vocab_frac"]
        assert "constrained_streams" in table
        assert "masked_vocab_frac" in table

    def test_submit_validation(self, lm, grammars):
        g_re, _ = grammars
        model, params = lm[0], lm[1]
        # dense engines have no per-slot bias plumbing
        dense = GenerationEngine(model, params, max_slots=SLOTS,
                                 max_len=MAXLEN, eos_id=EOS,
                                 kernels=DecodeKernels(model))
        with pytest.raises(ValueError, match="paged"):
            dense.submit(PROMPTS[0], max_new_tokens=4, grammar=g_re)
        dense.close()
        eng = make_engine(lm)
        with pytest.raises(TypeError, match="TokenAutomaton"):
            eng.submit(PROMPTS[0], max_new_tokens=4, grammar="a[0-9]")
        # eos mismatch: the EOS column is the accept bit of the mask
        g_bad = compile_grammar(regex_grammar(REGEX_PATTERN), VOCAB,
                                eos_id=2)
        with pytest.raises(ValueError, match="eos"):
            eng.submit(PROMPTS[0], max_new_tokens=4, grammar=g_bad)
        g_small = compile_grammar(regex_grammar(REGEX_PATTERN),
                                  make_vocab(80), eos_id=EOS)
        with pytest.raises(ValueError, match="vocab"):
            eng.submit(PROMPTS[0], max_new_tokens=4, grammar=g_small)
        eng.close()

    def test_budget_exhaustion_is_grammar_violation(self, lm, grammars):
        """A budget that ends mid-parse retires the stream with the
        typed violation — never a silently truncated non-word."""
        g_re, _ = grammars
        eng = make_engine(lm)
        s = eng.submit(PROMPTS[0], max_new_tokens=2, grammar=g_re)
        with pytest.raises(GrammarViolation) as ei:
            s.result(timeout=60)
        assert ei.value.grammar_key == g_re.key
        assert eng.metrics.snapshot()["failed"] == 1
        eng.close()

    def test_stuck_state_is_grammar_violation(self, lm):
        """A vocabulary that cannot spell any continuation: after 'a'
        the automaton has no legal token and no legal EOS -> stuck."""
        vocab = make_vocab()
        b_id = VOCAB.index("b")
        vocab[b_id] = "<gone>"
        g = compile_grammar(regex_grammar("ab"), vocab, eos_id=EOS)
        eng = make_engine(lm)
        s = eng.submit(PROMPTS[0], max_new_tokens=8, grammar=g)
        with pytest.raises(GrammarViolation, match="stuck"):
            s.result(timeout=60)
        eng.close()

    def test_compile_once_and_slot_reuse(self, lm, grammars):
        """Constrained traffic adds ZERO kernel traces over warmup, and
        a slot that carried a grammar is clean for its next tenant."""
        g_re, g_js = grammars
        kernels = lm[2]
        eng = make_engine(lm)
        eng.warmup()
        warm = (kernels.prefill_traces, kernels.chunk_traces,
                kernels.decode_traces)
        for g in (g_re, g_js, None, g_re):
            out = eng.submit(PROMPTS[0], max_new_tokens=40,
                             grammar=g).result(timeout=60)
            if g is not None:
                assert g.matches(out)
        post = (kernels.prefill_traces, kernels.chunk_traces,
                kernels.decode_traces)
        eng.close()
        assert post == warm

    def test_async_scheduling_matches_sync(self, lm, grammars):
        g_re, g_js = grammars
        specs = [(PROMPTS[0], 40, g_re, {}),
                 (PROMPTS[1], 40, g_js, {}),
                 (PROMPTS[2], 6, None, {})]
        outs = []
        for async_sched in (False, True):
            eng = make_engine(lm, async_scheduling=async_sched)
            streams = submit_all(eng, specs)
            outs.append([s.result(timeout=60) for s in streams])
            eng.close()
        assert outs[0] == outs[1]
        assert g_re.matches(outs[1][0]) and g_js.matches(outs[1][1])


# ---------------------------------------------- composition matrix ----


class TestCompositionMatrix:
    @pytest.mark.parametrize("speculate", [0, 3],
                             ids=["plain", "speculative"])
    @pytest.mark.parametrize("chunked", [False, True],
                             ids=["whole", "chunked"])
    @pytest.mark.parametrize("quantize", [None, "int8"],
                             ids=["f32", "int8"])
    @pytest.mark.parametrize("sampled", [False, True],
                             ids=["greedy", "sampled"])
    def test_matrix(self, lm, grammars, sampled, quantize, chunked,
                    speculate):
        model, params = lm[0], lm[1]
        g_re, g_js = grammars
        sample = (dict(temperature=0.9, top_k=8, seed=11)
                  if sampled else {})
        specs = [(PROMPTS[0], 40, g_re, sample),
                 (PROMPTS[1], 40, g_js, sample),
                 (PROMPTS[2], 6, None, sample)]
        kw = dict(quantize=quantize)
        if chunked:
            kw["prefill_chunk"] = 8
        runs = []
        for order in (None, [2, 1, 0]):
            eng = make_engine(lm, speculate=speculate, **kw)
            streams = submit_all(eng, specs, order=order)
            runs.append([s.result(timeout=60) for s in streams])
            eng.close()
        # identical across admission orders/runs; constrained parse
        assert runs[0] == runs[1]
        assert g_re.matches(runs[0][0])
        assert g_js.matches(runs[0][1])
        # engine == static under the same grammar
        sampling = [dict(s[3], grammar=s[2]) if s[2] is not None
                    else dict(s[3]) for s in specs]
        souts, _ = static_generate(
            model, params, [(s[0], s[1]) for s in specs],
            max_slots=SLOTS, max_len=MAXLEN, eos_id=EOS,
            kernels=lm[3] if speculate else lm[2], page_size=8,
            prefill_chunk=8 if chunked else None, sampling=sampling,
            quantize=quantize,
            speculate=(model, params, speculate) if speculate else None)
        assert souts == runs[0]

    def test_speculative_greedy_equals_plain_constrained(self, lm,
                                                         grammars):
        """Masked tokens have ZERO target probability, so masked
        speculative greedy is lossless vs plain constrained greedy."""
        g_re, g_js = grammars
        specs = [(PROMPTS[0], 40, g_re, {}), (PROMPTS[1], 40, g_js, {})]
        outs = []
        for speculate in (0, 3):
            eng = make_engine(lm, speculate=speculate)
            streams = submit_all(eng, specs)
            outs.append([s.result(timeout=60) for s in streams])
            eng.close()
        assert outs[0] == outs[1]

    def test_int8_cache_dtype_constrained(self, lm, grammars):
        g_re, _ = grammars
        eng = make_engine(lm, cache_dtype="int8")
        out = eng.submit(PROMPTS[0], max_new_tokens=40,
                         grammar=g_re).result(timeout=60)
        eng.close()
        assert g_re.matches(out)


# ----------------------------------------------------- oracle level ----


class TestSamplingOracle:
    def test_sample_tokens_bias_matches_numpy_oracle(self):
        """Fixed seed, 10 masked steps x 4 slots under mixed
        temperature / top-k / top-p: the jitted sampler under a grammar
        bias picks the SAME token as the per-step numpy oracle, and
        every draw is legal under the mask."""
        from bigdl_tpu.core.rng import threefry_key_data
        from bigdl_tpu.ops.sampling import (
            numpy_reference_sample,
            sample_tokens,
            split_key_data,
        )

        rng = np.random.RandomState(3)
        temps = np.asarray([0.0, 0.8, 1.0, 1.4], np.float32)
        top_ks = np.asarray([0, 8, 0, 5], np.int32)
        top_ps = np.asarray([1.0, 1.0, 0.9, 1.0], np.float32)
        keys = np.stack([threefry_key_data(200 + s) for s in range(4)])
        fn = jax.jit(sample_tokens)
        for _ in range(10):
            logits = rng.randn(4, 64).astype(np.float32) * 2.0
            legal = rng.rand(4, 64) < 0.2
            legal[:, 0] = True  # at least one legal token per row
            bias = np.where(legal, 0.0, float(NEG_BIAS)).astype(np.float32)
            toks, new_keys = fn(jnp.asarray(logits), jnp.asarray(temps),
                                jnp.asarray(top_ks), jnp.asarray(top_ps),
                                jnp.asarray(keys), jnp.asarray(bias))
            toks, new_keys = np.asarray(toks), np.asarray(new_keys)
            for s in range(4):
                _, u = split_key_data(keys[s])
                want = numpy_reference_sample(
                    logits[s], float(temps[s]), int(top_ks[s]),
                    float(top_ps[s]), u, bias[s])
                assert int(toks[s]) == want
                assert legal[s, int(toks[s])]
            keys = new_keys
