"""Keras 1.2 converter tests (reference: ``PY/keras/converter.py`` with
its run-keras parity suite — here the oracle is (a) hand-built fixtures in
the exact Keras-1.x JSON/HDF5 format with numpy-computed expectations and
(b) a real tf.keras model saved to h5)."""

import json

import numpy as np
import jax
import pytest

from bigdl_tpu.keras.converter import DefinitionLoader, WeightLoader, load_keras


def _write_keras1_h5(path, layers):
    """Emulate Keras 1.x save_weights: attrs['layer_names'],
    per-group attrs['weight_names'] + datasets."""
    import h5py

    with h5py.File(path, "w") as f:
        f.attrs["layer_names"] = np.asarray(
            [l[0].encode() for l in layers])
        for lname, weights in layers:
            g = f.create_group(lname)
            wnames = [f"{lname}_{i}".encode() for i in range(len(weights))]
            g.attrs["weight_names"] = np.asarray(wnames)
            for wn, w in zip(wnames, weights):
                g.create_dataset(wn.decode(), data=w)


def _mlp_json():
    return json.dumps({
        "class_name": "Sequential",
        "config": [
            {"class_name": "Dense", "config": {
                "name": "d1", "output_dim": 8, "activation": "relu",
                "batch_input_shape": [None, 5]}},
            {"class_name": "Dropout", "config": {"name": "drop", "p": 0.3}},
            {"class_name": "Dense", "config": {
                "name": "d2", "output_dim": 3, "activation": "softmax"}},
        ],
    })


def test_definition_loader_builds_model():
    model = DefinitionLoader.from_json_str(_mlp_json())
    x = np.random.RandomState(0).rand(4, 5).astype("float32")
    out = model.predict(x)
    assert out.shape == (4, 3)
    np.testing.assert_allclose(out.sum(-1), 1.0, rtol=1e-5)


def test_mlp_weights_convert_and_predict(tmp_path):
    rs = np.random.RandomState(1)
    w1 = rs.randn(5, 8).astype("float32")   # keras Dense: (in, out)
    b1 = rs.randn(8).astype("float32")
    w2 = rs.randn(8, 3).astype("float32")
    b2 = rs.randn(3).astype("float32")
    h5 = str(tmp_path / "w.h5")
    _write_keras1_h5(h5, [("d1", [w1, b1]), ("drop", []), ("d2", [w2, b2])])

    model = load_keras(json_str=_mlp_json(), hdf5_path=h5)
    x = rs.rand(6, 5).astype("float32")
    got = model.predict(x)

    h = np.maximum(x @ w1 + b1, 0)
    logits = h @ w2 + b2
    want = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


def test_convnet_with_bn_converts(tmp_path):
    rs = np.random.RandomState(2)
    spec = json.dumps({
        "class_name": "Sequential",
        "config": [
            {"class_name": "Convolution2D", "config": {
                "name": "c1", "nb_filter": 4, "nb_row": 3, "nb_col": 3,
                "border_mode": "same", "dim_ordering": "th",
                "batch_input_shape": [None, 2, 8, 8]}},
            {"class_name": "BatchNormalization", "config": {
                "name": "bn", "epsilon": 1e-3}},
            {"class_name": "Activation", "config": {
                "name": "act", "activation": "relu"}},
            {"class_name": "MaxPooling2D", "config": {
                "name": "mp", "pool_size": [2, 2]}},
            {"class_name": "Flatten", "config": {"name": "fl"}},
            {"class_name": "Dense", "config": {
                "name": "out", "output_dim": 2}},
        ],
    })
    wc = rs.randn(4, 2, 3, 3).astype("float32") * 0.3  # th: OIHW
    bc = rs.randn(4).astype("float32") * 0.1
    gamma = (rs.rand(4).astype("float32") + 0.5)
    beta = rs.randn(4).astype("float32") * 0.1
    mean = rs.randn(4).astype("float32") * 0.1
    var = rs.rand(4).astype("float32") * 0.5 + 0.5
    wd = rs.randn(4 * 4 * 4, 2).astype("float32") * 0.1
    bd = rs.randn(2).astype("float32")
    h5 = str(tmp_path / "c.h5")
    _write_keras1_h5(h5, [
        ("c1", [wc, bc]), ("bn", [gamma, beta, mean, var]),
        ("act", []), ("mp", []), ("fl", []), ("out", [wd, bd]),
    ])

    model = load_keras(json_str=spec, hdf5_path=h5)
    x = rs.rand(3, 2, 8, 8).astype("float32")
    got = model.predict(x)

    from jax import lax
    import jax.numpy as jnp

    y = lax.conv_general_dilated(
        jnp.asarray(x), jnp.asarray(wc), (1, 1), "SAME",
        dimension_numbers=("NCHW", "OIHW", "NCHW")) + bc[None, :, None, None]
    inv = gamma / np.sqrt(var + 1e-3)
    y = np.asarray(y) * inv[None, :, None, None] + (
        beta - mean * inv)[None, :, None, None]
    y = np.maximum(y, 0)
    y = np.asarray(lax.reduce_window(jnp.asarray(y), -jnp.inf, lax.max,
                                     (1, 1, 2, 2), (1, 1, 2, 2), "VALID"))
    want = y.reshape(3, -1) @ wd + bd
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_tf_keras_saved_weights_convert(tmp_path):
    """Gold standard: a real tf.keras model's save_weights h5 loads and
    predicts identically (tf.keras h5 keeps the Keras-1.x weight layout,
    channels_last kernels)."""
    tf = pytest.importorskip("tensorflow")

    tfm = tf.keras.Sequential([
        tf.keras.layers.Dense(8, activation="relu", input_shape=(5,), name="fc1"),
        tf.keras.layers.Dense(3, name="fc2"),
    ])
    x = np.random.RandomState(3).rand(4, 5).astype("float32")
    want = tfm.predict(x, verbose=0)
    h5 = str(tmp_path / "tfk.weights.h5")
    tfm.save_weights(h5)

    spec = json.dumps({
        "class_name": "Sequential",
        "config": [
            {"class_name": "Dense", "config": {
                "name": "fc1", "units": 8, "activation": "relu",
                "batch_input_shape": [None, 5]}},
            {"class_name": "Dense", "config": {"name": "fc2", "units": 3}},
        ],
    })
    model = load_keras(json_str=spec, hdf5_path=h5)
    got = model.predict(x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_unsupported_layer_raises():
    spec = json.dumps({
        "class_name": "Sequential",
        "config": [{"class_name": "Lambda", "config": {"name": "l"}}],
    })
    with pytest.raises(ValueError, match="unsupported Keras layer"):
        DefinitionLoader.from_json_str(spec)


def _functional_json():
    """Two-branch functional graph: input -> (d_a, d_b) -> Merge(sum) -> out."""
    return json.dumps({
        "class_name": "Model",
        "config": {
            "layers": [
                {"class_name": "InputLayer", "name": "inp",
                 "config": {"name": "inp", "batch_input_shape": [None, 6]},
                 "inbound_nodes": []},
                {"class_name": "Dense", "name": "d_a",
                 "config": {"name": "d_a", "output_dim": 4, "activation": "relu"},
                 "inbound_nodes": [[["inp", 0, 0]]]},
                {"class_name": "Dense", "name": "d_b",
                 "config": {"name": "d_b", "output_dim": 4},
                 "inbound_nodes": [[["inp", 0, 0]]]},
                {"class_name": "Merge", "name": "add",
                 "config": {"name": "add", "mode": "sum"},
                 "inbound_nodes": [[["d_a", 0, 0], ["d_b", 0, 0]]]},
                {"class_name": "Dense", "name": "out",
                 "config": {"name": "out", "output_dim": 2},
                 "inbound_nodes": [[["add", 0, 0]]]},
            ],
            "input_layers": [["inp", 0, 0]],
            "output_layers": [["out", 0, 0]],
        },
    })


def test_functional_model_converts_with_by_name_weights(tmp_path):
    """VERDICT round-2 item 7: graph Models convert (inbound_nodes
    topology) and HDF5 weights load by layer name."""
    rs = np.random.RandomState(3)
    wa, ba = rs.randn(6, 4).astype("f4"), rs.randn(4).astype("f4")
    wb, bb = rs.randn(6, 4).astype("f4"), rs.randn(4).astype("f4")
    wo, bo = rs.randn(4, 2).astype("f4"), rs.randn(2).astype("f4")
    h5 = str(tmp_path / "func.h5")
    # h5 order deliberately scrambled: loading is by NAME, not position
    _write_keras1_h5(h5, [("out", [wo, bo]), ("d_b", [wb, bb]),
                          ("d_a", [wa, ba])])

    model = load_keras(json_str=_functional_json(), hdf5_path=h5)
    x = rs.rand(5, 6).astype("f4")
    got = model.predict(x)
    want = (np.maximum(x @ wa + ba, 0) + (x @ wb + bb)) @ wo + bo
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_functional_keras2_merge_classes():
    """keras-2 style: Concatenate with explicit axis instead of Merge."""
    spec = json.dumps({
        "class_name": "Functional",
        "config": {
            "layers": [
                {"class_name": "InputLayer", "name": "inp",
                 "config": {"name": "inp", "batch_input_shape": [None, 3]},
                 "inbound_nodes": []},
                {"class_name": "Dense", "name": "d1",
                 "config": {"name": "d1", "units": 5},
                 "inbound_nodes": [[["inp", 0, 0, {}]]]},
                {"class_name": "Concatenate", "name": "cat",
                 "config": {"name": "cat", "axis": -1},
                 "inbound_nodes": [[["inp", 0, 0, {}], ["d1", 0, 0, {}]]]},
            ],
            "input_layers": [["inp", 0, 0]],
            "output_layers": [["cat", 0, 0]],
        },
    })
    model = DefinitionLoader.from_json_str(spec)
    out = model.predict(np.random.RandomState(4).rand(2, 3).astype("f4"))
    assert out.shape == (2, 8)


def _siamese_json():
    """Two-tower graph with a SHARED Dense: both inputs run through the
    same 'tower' layer (two inbound call sites), downstream references
    pick call outputs by keras node_index."""
    return json.dumps({
        "class_name": "Model",
        "config": {
            "layers": [
                {"class_name": "InputLayer", "name": "in_a",
                 "config": {"name": "in_a", "batch_input_shape": [None, 6]},
                 "inbound_nodes": []},
                {"class_name": "InputLayer", "name": "in_b",
                 "config": {"name": "in_b", "batch_input_shape": [None, 6]},
                 "inbound_nodes": []},
                {"class_name": "Dense", "name": "tower",
                 "config": {"name": "tower", "output_dim": 4},
                 "inbound_nodes": [[["in_a", 0, 0]], [["in_b", 0, 0]]]},
                {"class_name": "Merge", "name": "add",
                 "config": {"name": "add", "mode": "sum"},
                 "inbound_nodes": [[["tower", 0, 0], ["tower", 1, 0]]]},
            ],
            "input_layers": [["in_a", 0, 0], ["in_b", 0, 0]],
            "output_layers": [["add", 0, 0]],
        },
    })


def test_functional_shared_layer_siamese(tmp_path):
    """VERDICT round-3 item 4: shared layers convert — one params subtree,
    every call site reads the same weights (reference
    PY/keras/converter.py:289,462 multi-node handling)."""
    rs = np.random.RandomState(7)
    w, b = rs.randn(6, 4).astype("f4"), rs.randn(4).astype("f4")
    h5 = str(tmp_path / "siamese.h5")
    _write_keras1_h5(h5, [("tower", [w, b])])

    model = load_keras(json_str=_siamese_json(), hdf5_path=h5)
    params, state = model._require_params()
    # the shared layer owns exactly ONE params subtree
    graph_params = params["graph"]
    assert list(graph_params) == ["tower"], list(graph_params)

    xa = rs.rand(5, 6).astype("f4")
    xb = rs.rand(5, 6).astype("f4")
    got, _ = model.apply(params, (xa, xb), state=state, training=False)
    want = (xa @ w + b) + (xb @ w + b)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-4, atol=1e-5)


def test_functional_shared_layer_grads_accumulate(tmp_path):
    """Gradients from both call sites flow into the single shared
    subtree (the point of weight sharing)."""
    import jax
    import jax.numpy as jnp

    model = DefinitionLoader.from_json_str(_siamese_json())
    params, state = model._require_params()
    rs = np.random.RandomState(8)
    xa = jnp.asarray(rs.rand(3, 6).astype("f4"))
    xb = jnp.asarray(rs.rand(3, 6).astype("f4"))

    def loss(p):
        out, _ = model.apply(p, (xa, xb), state=state, training=False)
        return (out * out).sum()

    g = jax.grad(loss)(params)
    leaves = jax.tree_util.tree_leaves(g["graph"]["tower"])
    assert leaves, "shared tower has no param leaves"
    total = sum(float(np.abs(np.asarray(gl)).sum()) for gl in leaves)
    assert np.isfinite(total) and total > 0


def test_keras3_functional_json_with_shared_layer_oracle(tmp_path):
    """VERDICT r3 weak #6: Keras-3 functional JSON (inbound_nodes as
    {"args": [__keras_tensor__...]}) converts — including a shared layer —
    and matches the live Keras-3 oracle bit-for-bit with .weights.h5
    weights loaded by name."""
    keras3 = pytest.importorskip("keras")
    import jax  # noqa: F401  (backend forced to cpu by conftest)

    inp_a = keras3.Input((6,), name="in_a")
    inp_b = keras3.Input((6,), name="in_b")
    tower = keras3.layers.Dense(4, name="tower", activation="relu")
    merged = keras3.layers.Add(name="add")([tower(inp_a), tower(inp_b)])
    out = keras3.layers.Dense(2, name="out")(merged)
    model = keras3.Model([inp_a, inp_b], out)

    rs = np.random.RandomState(0)
    xa = rs.rand(5, 6).astype("f4")
    xb = rs.rand(5, 6).astype("f4")
    want = np.asarray(model([xa, xb]))
    h5 = str(tmp_path / "k3.weights.h5")
    model.save_weights(h5)

    m2 = load_keras(json_str=model.to_json(), hdf5_path=h5)
    params, state = m2._require_params()
    assert sorted(params["graph"]) == ["out", "tower"]  # one shared subtree
    got, _ = m2.apply(params, (xa, xb), state=state, training=False)
    np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-6)


def test_keras3_sequential_json_oracle(tmp_path):
    keras3 = pytest.importorskip("keras")

    model = keras3.Sequential([
        keras3.layers.Input((8,)),
        keras3.layers.Dense(5, activation="tanh", name="h"),
        keras3.layers.Dense(3, name="o"),
    ])
    rs = np.random.RandomState(1)
    x = rs.rand(4, 8).astype("f4")
    want = np.asarray(model(x))
    h5 = str(tmp_path / "k3seq.weights.h5")
    model.save_weights(h5)

    m2 = load_keras(json_str=model.to_json(), hdf5_path=h5)
    got = m2.predict(x)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_keras3_recurrent_weights_convert_oracle(tmp_path):
    """Recurrent weight conversion vs the live Keras-3 oracle: SimpleRNN /
    LSTM (packed (in+H, gates) kernel, keras gate order i,f,c,o == this
    repo's i,f,g,o) and GRU (reset_after=True mapping onto the split
    r/z + candidate params). Weights ride keras-3's nested cell/vars h5
    groups with the layer name on the dataset-less direct vars group."""
    keras3 = pytest.importorskip("keras")

    rs = np.random.RandomState(0)
    x = rs.rand(3, 6, 5).astype("f4")
    for layer_cls, name in [(keras3.layers.SimpleRNN, "rnn"),
                            (keras3.layers.LSTM, "lstm"),
                            (keras3.layers.GRU, "gru")]:
        model = keras3.Sequential([keras3.layers.Input((6, 5)),
                                   layer_cls(4, name=name)])
        want = np.asarray(model(x))
        h5 = str(tmp_path / f"{name}.weights.h5")
        model.save_weights(h5)
        m2 = load_keras(json_str=model.to_json(), hdf5_path=h5)
        got = m2.predict(x)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6,
                                   err_msg=name)


def test_keras1_lstm_12_array_weights_convert(tmp_path):
    """Keras-1.2 LSTM layout: 12 per-gate arrays in (i, c, f, o) order
    reorder into the packed (i, f, g, o) kernel."""
    rs = np.random.RandomState(1)
    I, H = 5, 4
    gates = {g: (rs.randn(I, H).astype("f4") * 0.4,
                 rs.randn(H, H).astype("f4") * 0.4,
                 rs.randn(H).astype("f4") * 0.1) for g in "icfo"}
    weights = [a for g in "icfo" for a in gates[g]]
    h5 = str(tmp_path / "k1_lstm.h5")
    _write_keras1_h5(h5, [("l", weights)])
    spec = json.dumps({
        "class_name": "Sequential",
        "config": [{"class_name": "LSTM", "config": {
            "name": "l", "output_dim": H, "return_sequences": False,
            "batch_input_shape": [None, 6, I]}}],
    })
    model = load_keras(json_str=spec, hdf5_path=h5)
    x = rs.rand(2, 6, I).astype("f4")
    got = model.predict(x)

    # numpy LSTM oracle, gates i,f,g,o with sigmoid/tanh
    W = np.concatenate([gates[g][0] for g in "ifco"], axis=1)
    U = np.concatenate([gates[g][1] for g in "ifco"], axis=1)
    b = np.concatenate([gates[g][2] for g in "ifco"])
    sig = lambda v: 1 / (1 + np.exp(-v))
    h = np.zeros((2, H), "f4")
    c = np.zeros((2, H), "f4")
    for t in range(6):
        z = x[:, t] @ W + h @ U + b
        i_, f_, g_, o_ = np.split(z, 4, axis=1)
        c = sig(f_) * c + sig(i_) * np.tanh(g_)
        h = sig(o_) * np.tanh(c)
    np.testing.assert_allclose(got, h, rtol=1e-4, atol=1e-5)


def test_keras3_no_bias_recurrent_converts_with_zero_bias(tmp_path):
    """Code-review r4: use_bias=False layers must overlay explicit ZERO
    biases (not leave the cell's random init in place), and a no-bias GRU
    with reset_after=True must convert, not be misdiagnosed."""
    keras3 = pytest.importorskip("keras")

    rs = np.random.RandomState(3)
    x = rs.rand(3, 6, 5).astype("f4")
    for layer_cls, name in [(keras3.layers.LSTM, "lstm_nb"),
                            (keras3.layers.GRU, "gru_nb")]:
        model = keras3.Sequential([
            keras3.layers.Input((6, 5)),
            layer_cls(4, name=name, use_bias=False)])
        want = np.asarray(model(x))
        h5 = str(tmp_path / f"{name}.weights.h5")
        model.save_weights(h5)
        m2 = load_keras(json_str=model.to_json(), hdf5_path=h5)
        got = m2.predict(x)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6,
                                   err_msg=name)


def test_gru_reset_after_false_rejected_even_without_bias(tmp_path):
    """Code-review r4: the GRU variant comes from layer CONFIG, not
    inferred from bias shape — a no-bias reset_after=False GRU must be
    rejected, not silently mapped onto the wrong recurrence."""
    keras3 = pytest.importorskip("keras")

    model = keras3.Sequential([
        keras3.layers.Input((6, 5)),
        keras3.layers.GRU(4, name="g", use_bias=False, reset_after=False)])
    h5 = str(tmp_path / "g.weights.h5")
    model.save_weights(h5)
    with pytest.raises(ValueError, match="reset_after"):
        load_keras(json_str=model.to_json(), hdf5_path=h5)


def test_predict_multi_input_functional():
    """predict() batch-slices a list of inputs together for multi-input
    functional Models (two-tower inference path)."""
    model = DefinitionLoader.from_json_str(_siamese_json())
    rs = np.random.RandomState(9)
    xa = rs.rand(70, 6).astype("f4")
    xb = rs.rand(70, 6).astype("f4")
    got = model.predict([xa, xb], batch_size=32)  # 3 uneven batches
    params, state = model._require_params()
    want, _ = model.apply(params, (xa, xb), state=state, training=False)
    np.testing.assert_allclose(got, np.asarray(want), rtol=1e-5, atol=1e-6)


def test_predict_single_input_accepts_plain_python_list():
    """Dispatch is on model arity: a plain list of samples for a
    single-input model is ONE array, and mismatched multi-input lengths
    raise clearly."""
    model = DefinitionLoader.from_json_str(_mlp_json())
    got = model.predict([[0.1] * 5, [0.2] * 5])
    assert got.shape == (2, 3)

    siam = DefinitionLoader.from_json_str(_siamese_json())
    rs = np.random.RandomState(4)
    with pytest.raises(ValueError, match="equal-length"):
        siam.predict([rs.rand(5, 6).astype("f4"),
                      rs.rand(4, 6).astype("f4")])
    with pytest.raises(ValueError, match="inputs"):
        siam.predict([rs.rand(5, 6).astype("f4")])
