"""Unified telemetry plane (bigdl_tpu/obs/) — PR 11.

The load-bearing properties, per the subsystem contract:

- per-request TRACES are structurally deterministic: the span tree of a
  chunked (and a speculative) request through ModelRouter -> ReplicaSet
  -> GenerationEngine is a pure function of the workload under a fake
  clock, annotated with routing context at every layer, exported as
  JSONL + a waterfall;
- tracing DISABLED is free: the submit-path hook costs < 2 us/call
  (the faults disarmed-site budget);
- one MetricsRegistry.collect() surfaces serving + paging + replica +
  ckpt + faults + pipeline + train gauges under flat STABLE keys, and
  the Prometheus endpoint round-trips them over real HTTP (every
  numeric key present exactly once, valid exposition charset);
- /healthz reflects replica eviction and rejoin; endpoint close() joins
  its thread (no leaks — the chaos drain-gate pattern);
- the flight recorder is bounded, fault firings/watchdog stalls leave
  structured events, RetryPolicy and CheckpointManager count their
  healing;
- the engine step-timeline rows append strictly after the PR-10
  speculative block (the append-only golden contract).
"""

import json
import os
import re
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from bigdl_tpu import faults
from bigdl_tpu.nn.layers.attention import Transformer
from bigdl_tpu.utils.errors import fresh_exception
from bigdl_tpu.obs import (
    FlightRecorder,
    MetricsEndpoint,
    MetricsRegistry,
    Tracer,
    engine_health,
    flight_recorder,
    format_trace,
    prometheus_name,
    replica_health,
    submit_trace,
    to_prometheus,
)
from bigdl_tpu.serving import (
    GenerationEngine,
    ModelRouter,
    PagedDecodeKernels,
    PagePool,
    ReplicaSet,
    ServingMetrics,
    SpeculativeKernels,
)

SLOTS, MAXLEN, MAXPROMPT, CHUNK = 4, 48, 16, 4


class FakeClock:
    """Deterministic monotonic clock: +1 ms per read (the faults-tier
    fake-clock pattern)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.t = 0.0

    def __call__(self) -> float:
        with self._lock:
            self.t += 0.001
            return self.t


@pytest.fixture(scope="module")
def paged_lm():
    model = Transformer(vocab_size=64, hidden_size=32, num_heads=4,
                        filter_size=64, num_hidden_layers=2)
    params, _ = model.init(jax.random.key(0))
    kernels = PagedDecodeKernels(model)  # shared: compile once
    return model, params, kernels


# ------------------------------------------------------------ tracing ----


def _traced_run(paged_lm):
    """One full routed workload under a fresh tracer + fake clock:
    ModelRouter -> ReplicaSet(2 engines) -> paged engines, with a
    chunked prompt in the mix. Returns traces sorted by submit order."""
    model, params, kernels = paged_lm
    tracer = Tracer(clock=FakeClock())
    engines = [GenerationEngine(model, params, max_slots=SLOTS,
                                max_len=MAXLEN, max_prompt_len=MAXPROMPT,
                                kernels=kernels, page_size=8,
                                prefill_chunk=CHUNK, tracer=tracer,
                                metrics=ServingMetrics())
               for _ in range(2)]
    router = ModelRouter()
    router.register("lm", engines)
    requests = [([1, 5, 9], 4),
                (list(range(1, 11)), 5),   # 10 tokens: chunked (4+4+2)
                ([2, 4], 3)]
    # submit all THEN wait: both replicas serve concurrently, placement
    # (least-loaded, index tiebreak) stays a pure function of the
    # single-threaded submission order
    streams = [router.submit("lm", p, max_new_tokens=m)
               for p, m in requests]
    outs = [s.result(timeout=60) for s in streams]
    router.close()  # drains + joins the loops BEFORE counters are read
    timeline_iters = sum(e.timeline.snapshot()["iterations"]
                         for e in engines)
    engine_steps = sum(e.metrics.snapshot()["engine_steps"]
                       for e in engines)
    traces = sorted(tracer.finished(), key=lambda t: t.trace_id)
    return tracer, traces, outs, timeline_iters, engine_steps


@pytest.mark.slow  # compile-heavy (2 engines + buckets): the 870 s
# tier-1 budget is already spent by the earlier tiers — plain
# `pytest tests/` runs this (the ROADMAP slow-marker pattern)
def test_trace_structure_deterministic_through_router_and_replicas(
        paged_lm):
    """The span tree of every request — chunked included, across 2
    engines behind a ReplicaSet behind a ModelRouter — is run-invariant,
    and each layer stamped its routing context onto the trace."""
    tracer1, traces1, outs1, tl_iters, steps = _traced_run(paged_lm)
    tracer2, traces2, outs2, _, _ = _traced_run(paged_lm)
    assert outs1 == outs2  # the workload itself is deterministic
    assert len(traces1) == len(traces2) == 3
    assert [t.structure() for t in traces1] \
        == [t.structure() for t in traces2]
    # the chunked request's waterfall: 3 prefill chunks, counted decode
    chunked = traces1[1]
    names = [sp.name for sp in chunked.spans]
    assert names == ["queue_wait", "page_reserve", "prefill_chunk",
                     "prefill_chunk", "prefill_chunk", "decode"]
    assert chunked.spans[-1].count == 5 - 1  # prefill emits token 1
    assert [sp.attrs.get("final") for sp in chunked.spans[2:5]] \
        == [False, False, True]
    # every layer annotated: the router's model name, the set's
    # placement, the engine's outcome + token count
    for t in traces1:
        assert t.attrs["model"] == "lm"
        assert t.attrs["replica_set"] == "lm"
        assert t.attrs["replica"] in ("r0", "r1")
        assert t.outcome == "done"
        assert t.attrs["tokens"] == t.attrs["max_new_tokens"]
        assert [e[0] for e in t.events] == ["submit", "first_token"]
    # the engine loop fed the step timeline and the metrics block
    assert tl_iters > 0 and steps == tl_iters
    # the waterfall renders every lifecycle stage (durations are NOT
    # compared here: the fake clock is shared by two engine loop
    # threads, so absolute read counts interleave — structure is the
    # run-invariant, and the single-engine tests pin the rest)
    waterfall = format_trace(chunked)
    for needle in ("outcome=done", "queue_wait", "page_reserve",
                   "prefill_chunk", "decode", "x4", "first_token"):
        assert needle in waterfall, needle


@pytest.mark.slow  # compiles a SpeculativeKernels set (see above)
def test_trace_structure_deterministic_speculative(paged_lm):
    """A speculative request's trace counts verify ROUNDS (never one
    span per round) and is run-invariant."""
    model, params, _ = paged_lm
    spec_kernels = SpeculativeKernels(model, model)

    def run():
        tracer = Tracer(clock=FakeClock())
        eng = GenerationEngine(model, params, max_slots=2, max_len=MAXLEN,
                               max_prompt_len=MAXPROMPT, page_size=8,
                               prefill_chunk=CHUNK, tracer=tracer,
                               kernels=spec_kernels,
                               speculate=(model, params, 2),
                               metrics=ServingMetrics())
        out = eng.submit([1, 2, 3], max_new_tokens=5).result(timeout=60)
        eng.close()
        return out, [t.structure() for t in tracer.finished()]

    out1, s1 = run()
    out2, s2 = run()
    assert out1 == out2 and s1 == s2 and len(s1) == 1
    kind, outcome, spans, _ = s1[0]
    assert outcome == "done"
    span_names = [n for n, _ in spans]
    assert span_names == ["queue_wait", "page_reserve", "prefill_chunk",
                          "verify_round"]
    assert dict(spans)["verify_round"] >= 1


def test_trace_jsonl_export(paged_lm, tmp_path):
    model, params, kernels = paged_lm
    tracer = Tracer()
    eng = GenerationEngine(model, params, max_slots=2, max_len=MAXLEN,
                           max_prompt_len=MAXPROMPT, kernels=kernels,
                           page_size=8, prefill_chunk=CHUNK,
                           tracer=tracer, metrics=ServingMetrics())
    eng.generate([3, 1, 4], max_new_tokens=3, timeout=60)
    eng.close()
    path = tmp_path / "traces.jsonl"
    n = tracer.dump_jsonl(str(path))
    lines = path.read_text().splitlines()
    assert n == len(lines) == 1
    rec = json.loads(lines[0])
    assert rec["outcome"] == "done"
    assert [s["name"] for s in rec["spans"]][:2] == ["queue_wait",
                                                     "page_reserve"]
    assert tracer.snapshot() == {"started": 1, "finished": 1,
                                 "active": 0, "retained": 1}


def test_disabled_tracer_submit_hook_within_budget():
    """Tracing off must be noise on the submit path: the hook is one
    ``is None`` test (<= 2 us/call with wide CI margin — the same
    budget the disarmed faults.fire pin uses)."""
    n = 20000
    t0 = time.perf_counter()
    for i in range(n):
        submit_trace(None, "generate", prompt_len=7, max_new_tokens=8,
                     sampled=False)
    per_call = (time.perf_counter() - t0) / n
    assert per_call < 2e-6, f"disabled hook costs {per_call * 1e6:.2f} us"


# ----------------------------------------------------------- registry ----


def _full_registry(tmp_path=None):
    """A registry wired across every tier (no engine — pure host)."""
    serving = ServingMetrics()
    serving.record_batch(3, 4)
    serving.record_served(0.010, 0.004)
    serving.record_engine_step(0.001, 0.009)
    pool = PagePool(8, 4, 16)
    pool.alloc(2, owner="target")
    stats_src = {"pipeline": __import__(
        "bigdl_tpu.dataset.parallel_pipeline",
        fromlist=["PipelineStats"]).PipelineStats()}
    stats = stats_src["pipeline"]
    stats.stage("produce").record(4, 400)
    inj = faults.FaultInjector()
    inj.arm("scratch.site", nth=1)
    try:
        inj.fire("scratch.site")
    except faults.InjectedFault:
        pass
    policy = faults.RetryPolicy(max_attempts=2, base_delay=0.0)
    reg = (MetricsRegistry()
           .register("serving", serving)
           .register("pages", pool)
           .register("pipeline", stats)
           .register("faults", inj)
           .register("retry", policy)
           .register("train", lambda: {"loss": 0.5, "iteration": 7,
                                       "learning_rate": 0.1}))
    return reg


def test_registry_collect_flat_stable_keys():
    reg = _full_registry()
    flat1 = reg.collect()
    flat2 = reg.collect()
    assert list(flat1) == list(flat2)  # stable key ORDER, not just set
    for key in ("serving.served", "serving.engine_steps",
                "serving.step_host_frac", "pages.pages_in_use",
                "pages.by_owner.target", "pipeline.produce.items",
                "faults.scratch.site.fired", "retry.retries",
                "train.loss", "train.learning_rate"):
        assert key in flat1, key
    assert flat1["pages.by_owner.target"] == 2
    assert flat1["faults.scratch.site.fired"] == 1
    assert flat1["train.iteration"] == 7


def test_registry_rejects_duplicates_and_junk():
    reg = MetricsRegistry()
    reg.register("a", lambda: {"x": 1})
    with pytest.raises(ValueError, match="already registered"):
        reg.register("a", lambda: {})
    with pytest.raises(ValueError, match="name"):
        reg.register("", lambda: {})
    with pytest.raises(TypeError, match="snapshot"):
        reg.register("b", object())
    # a raising source degrades to an error gauge, not a dead scrape
    reg.register("broken", lambda: 1 / 0)
    flat = reg.collect()
    assert flat["broken.collect_error"] == 1
    assert flat["a.x"] == 1


def test_registry_unregister_and_idempotent_reregister():
    """PR-16 regression: fleet membership churn must keep /metrics
    clean — a scaled-down or SIGKILLed replica's source unregisters
    (idempotently), and a replacement re-registers under the same name
    without tripping the duplicate guard."""
    reg = MetricsRegistry()
    reg.register("fleet.r0", lambda: {"x": 1})
    reg.register("fleet.r1", lambda: {"x": 2})
    assert reg.unregister("fleet.r1") is True
    assert reg.unregister("fleet.r1") is False      # idempotent
    flat = reg.collect()
    assert "fleet.r1.x" not in flat                 # no dead entry
    assert "fleet.r1.collect_error" not in flat     # and no degradation
    # the replacement member reuses the slot name
    reg.register("fleet.r1", lambda: {"x": 3})
    assert reg.collect()["fleet.r1.x"] == 3
    # replace=True swaps in place, KEEPING the key-order position (the
    # Prometheus round trip pins stable key order)
    reg.register("fleet.r0", lambda: {"x": 9}, replace=True)
    flat = reg.collect()
    assert flat["fleet.r0.x"] == 9
    assert list(flat) == ["fleet.r0.x", "fleet.r1.x"]
    # without replace, the duplicate guard still guards
    with pytest.raises(ValueError, match="already registered"):
        reg.register("fleet.r0", lambda: {})


# ----------------------------------------------------------- endpoint ----


def _parse_exposition(text):
    """Tiny in-test Prometheus text-format parser: name charset checked,
    duplicate sample names rejected."""
    samples = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, value = line.partition(" ")
        assert re.fullmatch(r"[a-zA-Z_:][a-zA-Z0-9_:]*", name), name
        assert name not in samples, f"duplicate sample {name}"
        samples[name] = float(value)
    return samples


def test_prometheus_http_round_trip():
    reg = _full_registry()
    with MetricsEndpoint(reg) as ep:
        body = urllib.request.urlopen(ep.url("/metrics"),
                                      timeout=10).read().decode()
        jbody = urllib.request.urlopen(ep.url("/metrics.json"),
                                       timeout=10).read().decode()
    samples = _parse_exposition(body)
    flat = reg.collect()
    numeric = {k: v for k, v in flat.items()
               if isinstance(v, (int, float)) and not isinstance(v, bool)}
    # every registered numeric key present EXACTLY once, value intact
    for key, v in numeric.items():
        name = prometheus_name(key)
        assert name in samples, key
        assert samples[name] == pytest.approx(float(v))
    assert len(samples) == len({prometheus_name(k) for k in numeric})
    # JSON side carries everything, strings included
    parsed = json.loads(jbody)
    assert parsed["serving.served"] == 1
    # counters scraped twice are monotonic
    with MetricsEndpoint(reg) as ep:
        one = _parse_exposition(urllib.request.urlopen(
            ep.url("/metrics"), timeout=10).read().decode())
        flat2 = reg.collect()  # no traffic between scrapes
        two = _parse_exposition(urllib.request.urlopen(
            ep.url("/metrics"), timeout=10).read().decode())
    assert two[prometheus_name("serving.served")] \
        >= one[prometheus_name("serving.served")]
    assert flat2["serving.served"] == 1


class _StubHandle:
    def __init__(self, error=None):
        self.error = error
        self.trace = None

    def add_done_callback(self, fn):
        fn(self)

    def result(self, timeout=None):
        if self.error is not None:
            raise fresh_exception(self.error)  # per-call copy (GL001)
        return [1]


class _StubBackend:
    def __init__(self):
        self.metrics = ServingMetrics()
        self.fail = False

    def submit(self, x, **kw):
        if self.fail:
            raise RuntimeError("stub backend down")
        return _StubHandle()

    def reload(self, params, state=None):
        pass

    def close(self, drain=True, timeout=None):
        pass


def test_healthz_reflects_eviction_and_rejoin():
    backends = [_StubBackend(), _StubBackend()]
    rset = ReplicaSet(backends, max_failures=1, probe=lambda b: None,
                      probe_interval=0, name="hz")
    reg = MetricsRegistry().register("serving", rset.metrics)
    ep = MetricsEndpoint(reg, health={"replicas": replica_health(rset)})

    def healthz():
        try:
            resp = urllib.request.urlopen(ep.url("/healthz"), timeout=10)
            return resp.status, json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read().decode())

    code, body = healthz()
    assert code == 200 and body["ok"] is True
    assert body["checks"]["replicas"]["degraded"] is False

    backends[0].fail = True
    rset.submit([1]).result()          # fails over; r0 evicted
    code, body = healthz()
    assert code == 200 and body["checks"]["replicas"]["degraded"] is True
    assert body["checks"]["replicas"]["healthy"] == ["r1"]

    backends[1].fail = True
    with pytest.raises(Exception):
        rset.submit([1])               # both down -> ReplicaUnavailable
    code, body = healthz()
    assert code == 503 and body["ok"] is False

    backends[0].fail = backends[1].fail = False
    assert rset.probe_once() == 2      # both rejoin
    code, body = healthz()
    assert code == 200 and body["checks"]["replicas"]["degraded"] is False
    ep.close()
    rset.close()


def test_healthz_tracks_live_membership_under_scaling():
    """PR-16 satellite: degraded means QUARANTINE, not head-count. A
    deliberately scaled-down fleet reports ok; a mid-scale-up fleet
    (warming member) neither flaps 503 nor reads degraded; the member
    only counts against health once it is IN rotation and fails out."""
    rset = ReplicaSet([_StubBackend(), _StubBackend()], max_failures=1,
                      probe=lambda b: None, probe_interval=0, name="el")
    ep = MetricsEndpoint(MetricsRegistry(),
                         health={"replicas": replica_health(rset)})

    def healthz():
        try:
            resp = urllib.request.urlopen(ep.url("/healthz"), timeout=10)
            return resp.status, json.loads(resp.read().decode())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read().decode())

    # a deliberate scale-down LEFT the rotation — it did not fail out
    rset.remove_replica("r1")
    code, body = healthz()
    assert code == 200 and body["ok"] is True
    assert body["checks"]["replicas"]["degraded"] is False
    assert body["checks"]["replicas"]["total"] == 1

    # mid-scale-up: the warming member is visible but not yet held to
    # the health bar — no 503 flap, no degraded while it compiles
    rset.add_replica(_StubBackend(), warming=True)
    code, body = healthz()
    assert code == 200 and body["ok"] is True
    assert body["checks"]["replicas"]["degraded"] is False
    assert body["checks"]["replicas"]["total"] == 2
    assert body["checks"]["replicas"]["warming"] == 1

    rset.activate_replica("r2")
    code, body = healthz()
    assert code == 200 and body["checks"]["replicas"]["warming"] == 0
    assert body["checks"]["replicas"]["healthy"] == ["r0", "r2"]

    # once IN rotation, failing out is quarantine again
    rset.replicas[0].fail = True
    rset.submit([1]).result()          # fails over; r0 evicted
    code, body = healthz()
    assert code == 200 and body["checks"]["replicas"]["degraded"] is True
    assert body["checks"]["replicas"]["healthy"] == ["r2"]
    ep.close()
    rset.close()


def test_endpoint_close_joins_thread_no_leaks():
    reg = MetricsRegistry().register("x", lambda: {"v": 1})
    ep = MetricsEndpoint(reg)
    assert urllib.request.urlopen(ep.url("/metrics"),
                                  timeout=10).status == 200
    ep.close()
    ep.close()  # idempotent
    assert not [t for t in threading.enumerate()
                if t.name == "bigdl-obs-endpoint" and t.is_alive()]
    with pytest.raises(Exception):
        urllib.request.urlopen(ep.url("/metrics"), timeout=2)


# ----------------------------------------------------- flight recorder ----


def test_flight_recorder_is_bounded_and_structured():
    rec = FlightRecorder(capacity=4)
    for i in range(10):
        rec.record("scratch.kind", i=i)
    events = rec.dump()
    assert len(events) == 4                      # ring bound
    assert [e["i"] for e in events] == [6, 7, 8, 9]
    assert rec.count() == 10                     # total keeps counting
    snap = rec.snapshot()
    assert snap["events_total"] == 10 and snap["events_retained"] == 4
    table = rec.format_events()
    assert "scratch.kind" in table and "i=9" in table
    rec.clear()
    assert rec.dump() == [] and rec.count() == 0


def test_fault_fire_and_watchdog_stall_leave_recorder_events():
    rec = flight_recorder()
    base_faults = rec.count("fault.fired")
    base_stalls = rec.count("watchdog.stall")
    faults.arm("scratch.obs_site", nth=1)
    try:
        with pytest.raises(faults.InjectedFault):
            faults.fire("scratch.obs_site", key=3)
        fired = [e for e in rec.dump(kind="fault.fired")
                 if e.get("site") == "scratch.obs_site"]
        assert fired and fired[-1]["effect"] == "InjectedFault"
        assert fired[-1]["key"] == 3
        assert rec.count("fault.fired") == base_faults + 1
    finally:
        faults.reset()

    stalls = []
    wd = faults.Watchdog("obs-test", 0.05, stalls.append)
    wd.arm("unit of work")
    deadline = time.monotonic() + 10
    while not stalls and time.monotonic() < deadline:
        time.sleep(0.01)
    wd.close()
    assert stalls
    assert rec.count("watchdog.stall") == base_stalls + 1
    ev = rec.dump(kind="watchdog.stall")[-1]
    assert ev["name"] == "obs-test" and ev["label"] == "unit of work"


def test_retry_policy_counts_healing_and_exhaustion():
    policy = faults.RetryPolicy(max_attempts=3, base_delay=0.0)
    attempts = []

    def flaky():
        attempts.append(1)
        if len(attempts) < 3:
            raise OSError("transient")
        return "ok"

    assert policy.call(flaky, sleep=lambda s: None) == "ok"
    assert policy.snapshot()["retries"] == 2
    assert policy.snapshot()["exhaustions"] == 0

    def always_bad():
        raise OSError("still broken")

    with pytest.raises(OSError):
        policy.call(always_bad, sleep=lambda s: None)
    snap = policy.snapshot()
    assert snap["retries"] == 4 and snap["exhaustions"] == 1
    # permanent errors are NOT exhaustion
    with pytest.raises(ValueError):
        policy.call(lambda: (_ for _ in ()).throw(ValueError("perm")),
                    sleep=lambda s: None)
    assert policy.snapshot()["exhaustions"] == 1


def test_ckpt_manager_counters_and_snapshot(tmp_path):
    from bigdl_tpu.ckpt.manager import CheckpointManager

    d = str(tmp_path / "ckpt")
    params = {"w": np.ones((2, 2), np.float32)}
    with CheckpointManager(d) as mgr:
        mgr.save("model.iter1", params, {}, {}, meta={"iteration": 1},
                 blocking=True)
        mgr.save("model.iter2", params, {}, {}, meta={"iteration": 2},
                 blocking=True)
        assert mgr.snapshot()["commits"] == 2
        # corrupt the newest blob: restore must fall back and count it
        with open(os.path.join(d, "model.iter2.ckpt"), "wb") as fh:
            fh.write(b"garbage")
        payload, entry = mgr.restore_latest()
        assert entry.tag == "model.iter1"
        snap = mgr.snapshot()
        assert snap["restore_fallbacks"] == 1 and snap["restores"] == 1
        assert snap["commit_failures"] == 0
        assert snap["retry"]["retries"] == 0
    rec_events = flight_recorder().dump(kind="ckpt")
    assert any(e["kind"] == "ckpt.commit" and e["tag"] == "model.iter2"
               for e in rec_events)
    assert any(e["kind"] == "ckpt.fallback" for e in rec_events)


def test_optimizer_registers_train_gauges(tmp_path):
    """set_metrics_registry publishes the per-step train gauges (and
    the configured pipeline/ckpt sources) into the same registry the
    serving tiers use — one collect() spans train AND serve."""
    import bigdl_tpu.nn as nn
    from bigdl_tpu import optim
    from bigdl_tpu.core.rng import RandomGenerator
    from bigdl_tpu.dataset import DataSet, FunctionTransformer, \
        SampleToMiniBatch
    from bigdl_tpu.dataset.sample import Sample

    rs = np.random.RandomState(3)
    xs = rs.randn(32, 8).astype(np.float32)
    ys = (xs.sum(axis=1) > 0).astype(np.int32)
    ds = DataSet.array([(xs[i], ys[i]) for i in range(len(xs))],
                       rng=RandomGenerator(5)) \
        >> (FunctionTransformer(lambda t: Sample(t[0], np.int32(t[1])))
            >> SampleToMiniBatch(16))
    model = nn.Sequential(nn.Linear(8, 4), nn.ReLU(), nn.Linear(4, 2),
                          nn.LogSoftMax())
    opt = optim.LocalOptimizer(model, ds, nn.ClassNLLCriterion(),
                               batch_size=16)
    opt.set_optim_method(optim.SGD(learning_rate=0.5))
    opt.set_end_when(optim.Trigger.max_iteration(3))
    opt.set_checkpoint(str(tmp_path / "ck"),
                       optim.Trigger.several_iteration(2))
    reg = MetricsRegistry().register("serving", ServingMetrics())
    opt.set_metrics_registry(reg)
    opt.optimize()
    flat = reg.collect()
    assert flat["train.iteration"] == 3
    assert flat["train.learning_rate"] == pytest.approx(0.5)
    assert flat["train.throughput"] > 0
    assert np.isfinite(flat["train.loss"])
    assert flat["train.ckpt.commits"] >= 1
    assert "serving.served" in flat  # train + serve in ONE snapshot
    opt.checkpoint_manager.close()


# -------------------------------------------------------- step timeline ----


def test_step_timeline_metrics_rows_append_after_speculative_block():
    """PR-11 golden contract: step-timeline rows render strictly AFTER
    the PR-10 speculative block — append-only, never reordered."""
    m = ServingMetrics()
    m.record_batch(3, 4)
    m.record_served(0.010, 0.004)
    m.record_prefill(5, 8, 0.002)
    m.record_decode_step(3, 4)
    m.record_chunk(8, 8)
    m.set_pages(5, 32)
    m.record_reload()
    m.set_replicas(2, 2, {"r0": 1})
    m.set_kv_cache(4096, "int8")
    m.set_quantized_gemms(13)
    m.record_verify_step(8, 5, 5)
    pre_lines = m.format_table().splitlines()

    m.record_engine_step(0.002, 0.006)
    m.record_engine_step(0.001, 0.007)
    full_lines = m.format_table().splitlines()
    assert full_lines[:len(pre_lines)] == pre_lines
    extra = [ln.split()[0] for ln in full_lines[len(pre_lines):]]
    assert extra == ["engine_steps", "step_host_ms", "step_device_ms",
                     "step_host_frac"]
    snap = m.snapshot()
    # immediately before the PR-12 prefix-cache keys (append-only;
    # re-anchored for the PR-18 KV-tier, PR-19 async, and PR-20
    # structured-generation blocks)
    assert list(snap)[-27:-23] == ["engine_steps", "step_host_ms",
                                 "step_device_ms", "step_host_frac"]
    assert snap["engine_steps"] == 2
    assert snap["step_host_ms"] == pytest.approx(3.0)
    assert snap["step_device_ms"] == pytest.approx(13.0)
    assert snap["step_host_frac"] == pytest.approx(3 / 16)


def test_async_overlap_rows_append_after_kv_tier_block():
    """PR-19 golden contract: the async-scheduling rows render strictly
    AFTER the PR-18 KV-tier block — append-only, never reordered — and
    the snapshot keys land at the tail."""
    m = ServingMetrics()
    m.record_served(0.010, 0.004)
    m.record_decode_step(3, 4)
    m.record_engine_step(0.002, 0.006)
    m.record_itl(0.005)
    m.record_offload(4)
    m.record_restore(2)
    m.record_swap_out()
    m.record_swap_in()
    m.set_host_pages(2, 4096)
    pre_tokens = [ln.split()[0] for ln in m.format_table().splitlines()]
    assert "overlapped_steps" not in pre_tokens   # sync engine: no rows

    m.record_engine_step(0.001, 0.008, overlapped=True)
    m.record_engine_step(0.001, 0.008, overlapped=True)
    tokens = [ln.split()[0] for ln in m.format_table().splitlines()]
    # the async rows are the table TAIL, strictly after the KV-tier
    # block; every earlier row keeps its position (values aside)
    assert tokens[:-2] == pre_tokens
    assert tokens[-2:] == ["overlapped_steps", "step_overlap_frac"]
    assert tokens.index("host_pages_peak") < tokens.index(
        "overlapped_steps")
    snap = m.snapshot()
    # re-anchored past the PR-20 structured-generation tail keys
    assert list(snap)[-5:-3] == ["overlapped_steps", "step_overlap_frac"]
    assert list(snap)[-3:] == ["constrained_streams",
                               "grammar_compile_cache_hits",
                               "masked_vocab_frac"]
    assert snap["overlapped_steps"] == 2
    assert snap["step_overlap_frac"] == pytest.approx(2 / 3)


def test_step_timeline_overlap_fields():
    """PR-19: the timeline ring carries the per-iteration overlap
    split and aggregates it in the snapshot (appended at the tail)."""
    from bigdl_tpu.obs import StepTimeline

    tl = StepTimeline(capacity=8)
    tl.record(host_s=0.001, decode_s=0.004)
    tl.record(host_s=0.001, decode_s=0.004, step_gap_s=0.0005,
              host_overlapped_s=0.003, active=2, occupancy=0.5)
    snap = tl.snapshot()
    assert snap["step_gap_ms"] == pytest.approx(0.5)
    assert snap["host_overlapped_ms"] == pytest.approx(3.0)
    assert list(snap)[-2:] == ["step_gap_ms", "host_overlapped_ms"]
    row = tl.recent(last=1)[0]
    assert row["step_gap_s"] == pytest.approx(0.0005)
    assert row["host_overlapped_s"] == pytest.approx(0.003)


def test_step_timeline_ring_and_summary():
    from bigdl_tpu.obs import StepTimeline

    tl = StepTimeline(capacity=4)
    for i in range(6):
        tl.record(host_s=0.001, decode_s=0.004, active=2, queue_depth=i,
                  occupancy=0.5)
    assert tl.snapshot()["iterations"] == 6
    assert tl.snapshot()["window_iterations"] == 4     # ring bound
    assert tl.snapshot()["host_frac"] == pytest.approx(0.2)
    rows = tl.recent(last=2)
    assert [r["queue_depth"] for r in rows] == [4, 5]
    table = tl.format_timeline()
    assert table.splitlines()[0].split()[0] == "iter"
    assert len(table.splitlines()) == 5                # header + ring
