"""Caffe bridge tests (reference: ``DL/utils/caffe/CaffeLoader.scala``,
``CaffePersister.scala``; reference tests load fixture prototxts from
``spark/dl/src/test/resources/caffe``).

The round-trip strategy replaces the reference's live-Caffe oracle: persist
a randomly-initialized model to prototxt+caffemodel, reload through the
loader, and require numerically identical predictions.
"""

import numpy as np
import jax
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.interop.caffe import CaffeLoader, load_caffe, save_caffe
from bigdl_tpu.models import vgg


def _predict(model, params, state, x):
    out, _ = model.apply(params, jax.numpy.asarray(x), state=state, training=False)
    return np.asarray(out)


@pytest.fixture(scope="module")
def small_net():
    model = nn.Sequential(
        nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1),
        nn.SpatialBatchNormalization(8),
        nn.ReLU(),
        nn.SpatialMaxPooling(2, 2, 2, 2),
        nn.SpatialConvolution(8, 12, 3, 3, 1, 1, 1, 1, n_group=2),
        nn.ReLU(),
        nn.SpatialCrossMapLRN(5, 1.0, 0.75, 1.0),
        nn.Dropout(0.4),
        nn.Linear(12 * 8 * 8, 10),
    )
    # Linear needs flattened input; mirror caffe's implicit flatten
    model = nn.Sequential(*list(model._modules.values())[:-1]) \
        .add(nn.Reshape([12 * 8 * 8])).add(nn.Linear(12 * 8 * 8, 10)) \
        .add(nn.SoftMax())
    params, state = model.init(jax.random.key(7))
    # non-trivial running stats so the BatchNorm path is actually exercised
    rs = np.random.RandomState(3)
    state = dict(state)
    bn_key = [k for k in state if "BatchNorm" in k or k == "1"][0]
    state[bn_key] = {
        "running_mean": rs.randn(8).astype("float32") * 0.1,
        "running_var": (rs.rand(8).astype("float32") * 0.5 + 0.5),
    }
    return model, params, state


def test_roundtrip_small_net(tmp_path, small_net):
    model, params, state, = small_net
    rs = np.random.RandomState(0)
    x = rs.rand(2, 3, 16, 16).astype("float32")
    want = _predict(model, params, state, x)

    proto = str(tmp_path / "net.prototxt")
    weights = str(tmp_path / "net.caffemodel")
    save_caffe(model, params, state, proto, weights, input_shape=(1, 3, 16, 16))

    graph, gparams, gstate = load_caffe(proto, weights)
    got = _predict(graph, gparams, gstate, x)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_prototxt_text_format_parses(tmp_path, small_net):
    model, params, state = small_net
    proto = str(tmp_path / "net.prototxt")
    weights = str(tmp_path / "net.caffemodel")
    save_caffe(model, params, state, proto, weights, input_shape=(1, 3, 16, 16))
    text = open(proto).read()
    assert "Convolution" in text and "blobs" not in text
    net = CaffeLoader.parse_prototxt(proto)
    assert net.layer[0].type == "Input"
    # definition-only load (random weights) must still build the graph
    graph, p, s = load_caffe(proto)
    out = _predict(graph, p, s, np.zeros((1, 3, 16, 16), "float32"))
    assert out.shape == (1, 10)


def test_eltwise_concat_graph_roundtrip(tmp_path):
    """Graph export/import with fan-out, Eltwise SUM and Concat."""
    from bigdl_tpu.nn.graph import Graph, Input, Node

    inp = Input()
    c1 = Node(nn.SpatialConvolution(4, 6, 1, 1).set_name("branch_a"), [inp])
    c2 = Node(nn.SpatialConvolution(4, 6, 1, 1).set_name("branch_b"), [inp])
    add = Node(nn.CAddTable().set_name("sum"), [c1, c2])
    cat = Node(nn.JoinTable(1).set_name("cat"), [add, c1])
    out = Node(nn.ReLU().set_name("out_relu"), [cat])
    g = Graph(inp, out)
    params, state = g.init(jax.random.key(1))

    rs = np.random.RandomState(5)
    x = rs.rand(2, 4, 5, 5).astype("float32")
    want = _predict(g, params, state, x)

    proto = str(tmp_path / "g.prototxt")
    weights = str(tmp_path / "g.caffemodel")
    save_caffe(g, params, state, proto, weights, input_shape=(1, 4, 5, 5))
    g2, p2, s2 = load_caffe(proto, weights)
    got = _predict(g2, p2, s2, x)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_v1_legacy_layers_load(tmp_path):
    """V1LayerParameter nets (enum-typed `layers`) must load too
    (reference ``V1LayerConverter``)."""
    from bigdl_tpu.interop.caffe import caffe_pb2 as pb

    net = pb.NetParameter(name="legacy")
    net.input.append("data")
    net.input_dim.extend([1, 2, 6, 6])
    conv = net.layers.add(name="c1", type=pb.V1LayerParameter.CONVOLUTION,
                          bottom=["data"], top=["c1"])
    conv.convolution_param.num_output = 3
    conv.convolution_param.kernel_size.append(3)
    w = np.arange(3 * 2 * 3 * 3, dtype=np.float32).reshape(3, 2, 3, 3) * 0.01
    blob = conv.blobs.add()
    blob.num, blob.channels, blob.height, blob.width = 3, 2, 3, 3  # legacy dims
    blob.data.extend(w.reshape(-1).tolist())
    blob2 = conv.blobs.add()
    blob2.num = blob2.channels = blob2.height = 1
    blob2.width = 3
    blob2.data.extend([0.1, 0.2, 0.3])
    net.layers.add(name="r1", type=pb.V1LayerParameter.RELU,
                   bottom=["c1"], top=["c1"])

    proto = str(tmp_path / "v1.prototxt")
    weights = str(tmp_path / "v1.caffemodel")
    from google.protobuf import text_format
    with open(proto, "w") as f:
        f.write(text_format.MessageToString(net))
    with open(weights, "wb") as f:
        f.write(net.SerializeToString())

    g, p, s = load_caffe(proto, weights)
    x = np.random.RandomState(0).rand(1, 2, 6, 6).astype("float32")
    out = _predict(g, p, s, x)
    assert out.shape == (1, 3, 4, 4)
    # weights really came from the caffemodel
    np.testing.assert_allclose(np.asarray(p["c1"]["weight"]), w, rtol=1e-6)
    assert (out >= 0).all()  # in-place ReLU applied


def test_nested_sequential_roundtrip(tmp_path):
    """Nested Sequentials must export with unique layer names
    (walker path-qualified naming) and round-trip numerically."""
    block = lambda cin, cout: nn.Sequential(  # noqa: E731
        nn.SpatialConvolution(cin, cout, 3, 3, 1, 1, 1, 1), nn.ReLU())
    model = nn.Sequential(block(3, 4), block(4, 5))
    params, state = model.init(jax.random.key(2))
    x = np.random.RandomState(0).rand(2, 3, 6, 6).astype("float32")
    want = _predict(model, params, state, x)

    proto = str(tmp_path / "n.prototxt")
    weights = str(tmp_path / "n.caffemodel")
    save_caffe(model, params, state, proto, weights, input_shape=(1, 3, 6, 6))
    net = CaffeLoader.parse_prototxt(proto)
    names = [l.name for l in net.layer]
    assert len(names) == len(set(names)), f"duplicate layer names: {names}"
    g, p, s = load_caffe(proto, weights)
    got = _predict(g, p, s, x)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_floor_mode_pooling_roundtrips(tmp_path):
    """Floor-mode pooling must survive persist->load (round_mode=FLOOR);
    caffe's default is ceil."""
    model = nn.Sequential(nn.SpatialMaxPooling(3, 3, 2, 2))  # floor by default
    params, state = model.init(jax.random.key(0))
    x = np.random.RandomState(0).rand(1, 2, 8, 8).astype("float32")
    want = _predict(model, params, state, x)
    assert want.shape == (1, 2, 3, 3)

    proto = str(tmp_path / "p.prototxt")
    weights = str(tmp_path / "p.caffemodel")
    save_caffe(model, params, state, proto, weights, input_shape=(1, 2, 8, 8))
    g, p, s = load_caffe(proto, weights)
    got = _predict(g, p, s, x)
    assert got.shape == want.shape
    np.testing.assert_allclose(got, want)


def test_anisotropic_kernel_and_dilation(tmp_path):
    from google.protobuf import text_format

    from bigdl_tpu.interop.caffe import caffe_pb2 as pb

    net = pb.NetParameter(name="aniso")
    inp = net.layer.add(name="data", type="Input", top=["data"])
    inp.input_param.shape.add().dim.extend([1, 2, 9, 9])
    c = net.layer.add(name="c", type="Convolution", bottom=["data"], top=["c"])
    c.convolution_param.num_output = 3
    c.convolution_param.kernel_size.extend([3, 5])  # kh=3, kw=5
    d = net.layer.add(name="d", type="Convolution", bottom=["c"], top=["d"])
    d.convolution_param.num_output = 3
    d.convolution_param.kernel_size.append(3)
    d.convolution_param.dilation.append(2)

    proto = str(tmp_path / "a.prototxt")
    with open(proto, "w") as f:
        f.write(text_format.MessageToString(net))
    g, p, s = load_caffe(proto)
    assert p["c"]["weight"].shape == (3, 2, 3, 5)
    x = np.zeros((1, 2, 9, 9), "float32")
    out = _predict(g, p, s, x)
    # c: (9-3+1, 9-5+1) = (7, 5); d dilated 3x3 (eff 5): (3, 1)
    assert out.shape == (1, 3, 3, 1)


def test_standalone_scale_layer(tmp_path):
    from google.protobuf import text_format

    from bigdl_tpu.interop.caffe import caffe_pb2 as pb

    net = pb.NetParameter(name="scalenet")
    inp = net.layer.add(name="data", type="Input", top=["data"])
    inp.input_param.shape.add().dim.extend([1, 3, 4, 4])
    sc = net.layer.add(name="sc", type="Scale", bottom=["data"], top=["sc"])
    sc.scale_param.bias_term = True
    gamma = np.asarray([2.0, 3.0, 4.0], np.float32)
    beta = np.asarray([0.5, -0.5, 0.0], np.float32)
    for arr in (gamma, beta):
        blob = sc.blobs.add()
        blob.shape.dim.append(3)
        blob.data.extend(arr.tolist())

    proto = str(tmp_path / "s.prototxt")
    weights = str(tmp_path / "s.caffemodel")
    with open(proto, "w") as f:
        f.write(text_format.MessageToString(net))
    with open(weights, "wb") as f:
        f.write(net.SerializeToString())
    g, p, s = load_caffe(proto, weights)
    x = np.ones((1, 3, 4, 4), "float32")
    out = _predict(g, p, s, x)
    np.testing.assert_allclose(out[0, :, 0, 0], gamma + beta, rtol=1e-6)


@pytest.mark.slow  # full VGG16 build + roundtrip dominates tier-1 (~50 s);
# the conv/BN/pool/IP conversion paths stay covered by the lighter
# per-layer and inception/resnet roundtrips above
def test_vgg16_caffe_roundtrip(tmp_path):
    """The BASELINE 'VGG-16 Caffe-loaded inference' config: persist our
    VGG-16 (width-reduced for CPU test speed via the same builder code
    path), reload from caffemodel, predictions must agree exactly."""
    model = vgg.build_vgg16(class_num=10)
    params, state = model.init(jax.random.key(0))

    proto = str(tmp_path / "vgg16.prototxt")
    weights = str(tmp_path / "vgg16.caffemodel")
    save_caffe(model, params, state, proto, weights, input_shape=(1, 3, 224, 224))

    net = CaffeLoader.parse_prototxt(proto)
    conv_layers = [l for l in net.layer if l.type == "Convolution"]
    fc_layers = [l for l in net.layer if l.type == "InnerProduct"]
    pools = [l for l in net.layer if l.type == "Pooling"]
    assert len(conv_layers) == 13 and len(fc_layers) == 3 and len(pools) == 5

    graph, gparams, gstate = load_caffe(proto, weights)
    rs = np.random.RandomState(1)
    x = rs.rand(1, 3, 224, 224).astype("float32")
    want = _predict(model, params, state, x)
    got = _predict(graph, gparams, gstate, x)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    assert int(np.argmax(got)) == int(np.argmax(want))
