"""Data pipeline: transformer chains, minibatch padding, loaders, prefetch.

Reference: ``DLT/dataset/*Spec.scala`` (DataSetSpec, TransformersSpec,
MiniBatchSpec with padding strategies).
"""

import numpy as np
import pytest

from bigdl_tpu.dataset import (
    DataSet,
    MiniBatch,
    PaddingParam,
    Sample,
    SampleToMiniBatch,
    FunctionTransformer,
    device_prefetch,
)
from bigdl_tpu.dataset.datasets import load_cifar10, load_mnist, load_ptb
from bigdl_tpu.dataset.image import (
    BGRImgNormalizer,
    CenterCropper,
    GreyImgNormalizer,
    GreyImgToSample,
    HFlip,
    RandomCropper,
)


def test_minibatch_stack_and_size():
    samples = [Sample.of(np.ones((3, 4)) * i, i) for i in range(5)]
    mb = MiniBatch.stack(samples)
    assert mb.input.shape == (5, 3, 4)
    assert mb.target.shape == (5,)
    assert mb.size() == 5


def test_minibatch_padding():
    samples = [Sample.of(np.ones((n, 2)), 0) for n in (3, 5, 2)]
    with pytest.raises(ValueError, match="PaddingParam"):
        MiniBatch.stack(samples)
    mb = MiniBatch.stack(samples, feature_padding=PaddingParam(padding_value=-1))
    assert mb.input.shape == (3, 5, 2)
    assert mb.input[0, 3, 0] == -1  # padded region
    mb2 = MiniBatch.stack(samples, feature_padding=PaddingParam(fixed_length=6))
    assert mb2.input.shape == (3, 6, 2)


def test_transformer_chain_and_batching():
    data = [(np.full((28 * 28,), i, np.float32).tobytes(), i % 10) for i in range(10)]
    # emulate BytesToGreyImg via FunctionTransformer on float bytes
    to_img = FunctionTransformer(
        lambda t: (np.frombuffer(t[0], np.float32).reshape(28, 28), t[1])
    )
    chain = to_img >> GreyImgNormalizer(0.0, 1.0) >> GreyImgToSample() >> SampleToMiniBatch(4)
    batches = list(chain(iter(data)))
    assert len(batches) == 2  # 10 // 4, partial dropped
    assert batches[0].input.shape == (4, 1, 28, 28)
    assert batches[0].target.shape == (4,)


def test_dataset_train_iterator_infinite_and_shuffled():
    ds = DataSet.tensors(np.arange(20).reshape(10, 2).astype(np.float32), np.arange(10))
    assert ds.size() == 10
    it = ds.data(train=True)
    seen = [next(it).label for _ in range(25)]  # crosses epoch boundaries
    assert len(seen) == 25
    # eval iterator is finite and ordered
    labels = [s.label for s in ds.data(train=False)]
    assert labels == list(range(10))


def test_image_transforms():
    imgs = [(np.random.RandomState(i).rand(3, 10, 10).astype(np.float32), i) for i in range(4)]
    out = list(BGRImgNormalizer((0.5, 0.5, 0.5), (0.25, 0.25, 0.25))(iter(imgs)))
    assert out[0][0].shape == (3, 10, 10)
    out = list(RandomCropper(8, 8)(iter(imgs)))
    assert out[0][0].shape == (3, 8, 8)
    out = list(CenterCropper(6, 6)(iter(imgs)))
    assert out[0][0].shape == (3, 6, 6)
    out = list(HFlip(threshold=1.1)(iter(imgs)))  # always flip
    np.testing.assert_allclose(out[0][0], imgs[0][0][..., ::-1])


def test_loaders_synthetic_fallback():
    x, y = load_mnist(None, synthetic_size=64)
    assert x.shape == (64, 28, 28) and y.shape == (64,)
    assert x.min() >= 0 and x.max() <= 255
    x2, y2 = load_cifar10(None, synthetic_size=32)
    assert x2.shape == (32, 3, 32, 32)
    stream = load_ptb(None, synthetic_tokens=1000)
    assert stream.shape == (1000,) and stream.dtype == np.int32
    # deterministic
    x3, _ = load_mnist(None, synthetic_size=64)
    np.testing.assert_allclose(x, x3)


def test_device_prefetch():
    ds = DataSet.tensors(
        np.random.RandomState(0).rand(32, 4).astype(np.float32), np.arange(32) % 3
    )
    batches = SampleToMiniBatch(8).apply(ds.data(train=False))
    out = list(device_prefetch(batches, buffer_size=2))
    assert len(out) == 4
    x, y = out[0]
    assert x.shape == (8, 4) and y.shape == (8,)


def test_tensor_dataset_sliced_batches_fast_path():
    """TensorDataSet.batches slices batches directly (no per-sample
    objects) and matches the sample-path content."""
    rs = np.random.RandomState(0)
    x = rs.rand(20, 3).astype(np.float32)
    y = (np.arange(20) % 4).astype(np.int32)
    ds = DataSet.tensors(x, y)

    evs = list(ds.batches(8, train=False, partial_batch=True))
    assert [b.size() for b in evs] == [8, 8, 4]
    np.testing.assert_allclose(np.concatenate([b.input for b in evs]), x)
    np.testing.assert_array_equal(np.concatenate([b.target for b in evs]), y)

    it = ds.batches(8, train=True)
    seen = [next(it) for _ in range(5)]  # crosses an epoch boundary (2/epoch)
    for b in seen:
        assert b.input.shape == (8, 3)
        # each batch row must be an original row with its own label
        for row, lab in zip(b.input, b.target):
            j = np.where((x == row).all(axis=1))[0][0]
            assert y[j] == lab


def test_device_prefetch_nonpositive_buffer_falls_back_to_unbuffered():
    """Regression (ISSUE 4 satellite): buffer_size<=0 used to seed an
    empty deque whose `while queue` loop never started — every batch was
    silently dropped. It must fall back to unbuffered iteration."""
    ds = DataSet.tensors(
        np.random.RandomState(0).rand(32, 4).astype(np.float32), np.arange(32) % 3
    )
    for buffer_size in (0, -1):
        batches = SampleToMiniBatch(8).apply(ds.data(train=False))
        out = list(device_prefetch(batches, buffer_size=buffer_size))
        assert len(out) == 4, f"buffer_size={buffer_size} dropped batches"
        x, y = out[0]
        assert x.shape == (8, 4) and y.shape == (8,)


def test_host_prefetch_blocked_producer_wakes_on_abandon():
    """The producer blocked on a FULL queue must be woken by the
    consumer walking away (condition notify, not a poll tick)."""
    import threading
    import time as _time

    from bigdl_tpu.dataset.prefetch import host_prefetch

    before = threading.active_count()
    # depth 1 and a fast producer: it will sit blocked in put()
    gen = host_prefetch(iter(np.zeros((100, 2))), depth=1)
    next(gen)
    _time.sleep(0.1)  # producer now blocked on the full queue
    t0 = _time.monotonic()
    gen.close()
    deadline = _time.monotonic() + 5
    while _time.monotonic() < deadline and threading.active_count() > before:
        _time.sleep(0.02)
    assert threading.active_count() <= before
    assert _time.monotonic() - t0 < 2.0


def test_host_prefetch_records_stats():
    from bigdl_tpu.dataset import PipelineStats
    from bigdl_tpu.dataset.prefetch import host_prefetch

    stats = PipelineStats()
    items = [np.full((4,), i, np.float32) for i in range(10)]
    out = list(host_prefetch(iter(items), depth=3, stats=stats))
    assert len(out) == 10
    snap = stats.snapshot()["stage"]
    assert snap["items"] == 10
    assert snap["mb"] == pytest.approx(10 * 16 / 1e6)
    assert snap["queue_cap"] == 3


def test_host_prefetch_thread_and_errors():
    from bigdl_tpu.dataset.prefetch import host_prefetch

    # arrays pass through in order
    items = [np.full((2,), i) for i in range(10)]
    out = list(host_prefetch(iter(items), depth=3))
    assert len(out) == 10
    np.testing.assert_array_equal(out[7], items[7])

    # abandoning the consumer retires the producer thread promptly
    import threading
    import time as _time
    before = threading.active_count()
    gen = host_prefetch(iter(np.zeros((100, 2))), depth=2)
    next(gen)
    gen.close()  # consumer walks away (optimizer break path)
    _time.sleep(0.3)
    assert threading.active_count() <= before + 1

    # producer exceptions surface in the consumer
    def boom():
        yield np.zeros(1)
        raise RuntimeError("pipeline exploded")

    import pytest as _pytest
    with _pytest.raises(RuntimeError, match="pipeline exploded"):
        list(host_prefetch(boom(), depth=2))


def test_optimizer_uses_fast_path_for_tensor_dataset():
    import bigdl_tpu.nn as nn
    from bigdl_tpu import optim

    rs = np.random.RandomState(1)
    x = rs.rand(64, 4).astype(np.float32)
    y = (x.sum(axis=1) > 2).astype(np.int32)
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2), nn.LogSoftMax())
    # pass the RAW TensorDataSet (not pre-batched): optimizer takes the
    # sliced fast path and still trains. Explicit rng: the global default
    # generator's state depends on test order.
    from bigdl_tpu.core.rng import RandomGenerator

    opt = optim.LocalOptimizer(model, DataSet.tensors(x, y, rng=RandomGenerator(5)),
                               nn.ClassNLLCriterion(), batch_size=16)
    opt.set_optim_method(optim.SGD(learning_rate=0.5))
    opt.set_end_when(optim.Trigger.max_iteration(60))
    params, _ = opt.optimize()
    assert opt.state.loss < 0.5


# ------------------------------------------------------ RowTransformer
def test_row_transformer_numeric_all():
    from bigdl_tpu.dataset.datamining import RowTransformer

    rows = [{"a": 1.0, "b": [2.0, 3.0], "c": 4.0}]
    out = list(RowTransformer.numeric()(rows))
    np.testing.assert_allclose(out[0]["all"], [1.0, 2.0, 3.0, 4.0])


def test_row_transformer_numeric_groups():
    from bigdl_tpu.dataset.datamining import RowTransformer

    rows = [{"a": 1.0, "b": 2.0, "c": 3.0}] * 2
    t = RowTransformer.numeric({"x": ["a", "c"], "y": ["b"]})
    out = list(t(rows))
    assert len(out) == 2
    np.testing.assert_allclose(out[0]["x"], [1.0, 3.0])
    np.testing.assert_allclose(out[0]["y"], [2.0])


def test_row_transformer_atomic_and_mixed():
    from bigdl_tpu.dataset.datamining import RowTransformer

    rows = [{"name": "alpha", "f1": 1.5, "f2": 2.5}]
    t = RowTransformer.atomic_with_numeric(["name"], {"feats": ["f1", "f2"]})
    out = list(t(rows))[0]
    assert out["name"].item() == "alpha"
    np.testing.assert_allclose(out["feats"], [1.5, 2.5])
    # positional selection over plain sequences
    t2 = RowTransformer.atomic([0, 2], row_size=3)
    out2 = list(t2([(10, 20, 30)]))[0]
    assert out2["0"].item() == 10 and out2["2"].item() == 30


def test_row_transformer_duplicate_key_and_bounds():
    from bigdl_tpu.dataset.datamining import ColsToNumeric, RowTransformer

    with pytest.raises(ValueError):
        RowTransformer([ColsToNumeric("k"), ColsToNumeric("k")])
    with pytest.raises(ValueError):
        RowTransformer.atomic([5], row_size=3)


# ------------------------------------------------------ SequenceFile
def test_seqfile_roundtrip(tmp_path):
    from bigdl_tpu.dataset.seqfile import SeqFileReader, SeqFileWriter

    p = str(tmp_path / "a.seq")
    with SeqFileWriter(p) as w:
        for i in range(300):  # enough bytes to cross sync intervals
            w.append(f"key{i}".encode(), bytes([i % 251]) * (50 + i))
    got = list(SeqFileReader(p))
    assert len(got) == 300
    assert got[0][0] == b"key0" and got[299][0] == b"key299"
    assert got[7][1] == bytes([7]) * 57


def test_seqfile_vint_edge_cases():
    from bigdl_tpu.dataset.seqfile import read_vint, write_vint

    for n in (0, 1, 127, -112, 128, 255, 256, 70000, 2**31 - 1, -113, -70000):
        buf = write_vint(n)
        val, pos = read_vint(buf, 0)
        assert val == n and pos == len(buf), n


def test_imagenet_seqfile_pipeline(tmp_path):
    from bigdl_tpu.dataset.seqfile import (
        BGRImgToLocalSeqFile, load_imagenet_seqfiles, read_label, read_name,
    )

    rng = np.random.RandomState(0)
    records = [(i % 5 + 1, f"img_{i}.jpg", rng.randint(0, 255, (8, 6, 3), np.uint8))
               for i in range(23)]
    writer = BGRImgToLocalSeqFile(10, str(tmp_path / "imagenet"), has_name=True)
    paths = list(writer(records))
    assert len(paths) == 3  # 10 + 10 + 3

    decoded = list(load_imagenet_seqfiles(str(tmp_path)))
    assert len(decoded) == 23
    img, label = decoded[0]
    np.testing.assert_array_equal(img, records[0][2])
    assert label == float(records[0][0])
    assert read_label("name\n7".encode()) == "7"
    assert read_name("name\n7".encode()) == "name"


def test_mt_image_to_batch_with_seqfiles(tmp_path):
    """seq files -> decode -> native batch assembly, the reference's
    ImageNet hot path end-to-end."""
    from bigdl_tpu.dataset.image import MTImageToBatch
    from bigdl_tpu.dataset.seqfile import (
        BGRImgToLocalSeqFile, load_imagenet_seqfiles,
    )

    rng = np.random.RandomState(3)
    records = [(i % 3 + 1, f"i{i}", rng.randint(0, 255, (6, 6, 3), np.uint8))
               for i in range(10)]
    list(BGRImgToLocalSeqFile(10, str(tmp_path / "part"), has_name=True)(records))

    batcher = MTImageToBatch(4, means=(110.0,) * 3, stds=(60.0,) * 3)
    batches = list(batcher(load_imagenet_seqfiles(str(tmp_path))))
    assert len(batches) == 2  # 10 images, batch 4, partial dropped
    x = batches[0].get_input()
    assert x.shape == (4, 3, 6, 6) and x.dtype == np.float32
    expect = (records[0][2].astype(np.float32) - 110.0) / 60.0
    np.testing.assert_allclose(x[0], expect.transpose(2, 0, 1), atol=1e-5)


def test_load_movielens_synthetic_and_file(tmp_path):
    from bigdl_tpu.dataset.datasets import load_movielens

    rows = load_movielens()
    assert rows.shape[1] == 3 and rows[:, 2].min() >= 1 and rows[:, 2].max() <= 5
    (tmp_path / "ratings.dat").write_text("1::10::4::978300760\n2::20::5::978300761\n")
    rows = load_movielens(str(tmp_path))
    np.testing.assert_array_equal(rows, [[1, 10, 4], [2, 20, 5]])
