// Native runtime support library.
//
// The reference keeps its performance-critical non-XLA machinery in native
// code behind JNI (BigDL-core submodule: MKL BLAS/VML kernels, MKL-DNN
// primitives, aligned Memory allocator, CPU affinity — SURVEY.md §2.1) plus
// Java-side CRC framing for TFRecord/TensorBoard files (Crc32c.java).
//
// On TPU the compute kernels belong to XLA/Pallas, so the native tier here
// is the *runtime around the compute*: checksum/record framing for event &
// record files, an aligned buffer pool (host staging buffers for infeed),
// a multi-threaded prefetch ring (the analogue of the reference's
// ThreadPool-driven data pipeline, DL/utils/ThreadPool.scala), and hot
// uint8 image preprocessing loops (normalize/flip/crop — the analogue of
// dataset/image/* transformers' inner loops).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <functional>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <queue>
#include <thread>
#include <vector>

extern "C" {

// ---------------------------------------------------------------- crc32c

static uint32_t crc_table[256];
static std::once_flag crc_once;

static void crc_init() {
  const uint32_t poly = 0x82f63b78u;  // Castagnoli, reflected
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? (poly ^ (c >> 1)) : (c >> 1);
    crc_table[i] = c;
  }
}

uint32_t bigdl_crc32c(const uint8_t* data, uint64_t n, uint32_t seed) {
  std::call_once(crc_once, crc_init);
  uint32_t c = seed ^ 0xffffffffu;
  for (uint64_t i = 0; i < n; i++) c = crc_table[(c ^ data[i]) & 0xff] ^ (c >> 8);
  return c ^ 0xffffffffu;
}

// TFRecord / TensorBoard masked crc (Crc32c.java mask convention)
uint32_t bigdl_masked_crc32c(const uint8_t* data, uint64_t n) {
  uint32_t crc = bigdl_crc32c(data, n, 0);
  return ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
}

// ------------------------------------------------------ aligned buffers

void* bigdl_aligned_alloc(uint64_t alignment, uint64_t size) {
  void* p = nullptr;
  if (posix_memalign(&p, alignment, size) != 0) return nullptr;
  return p;
}

void bigdl_aligned_free(void* p) { free(p); }

// ------------------------------------------------------- prefetch ring
//
// A bounded MPMC byte-buffer queue: producer threads (C++ or Python) push
// filled buffers; the consumer pops in order. This is the host-side
// staging stage between storage and device infeed.

struct Ring {
  std::mutex mu;
  std::condition_variable not_empty, not_full;
  std::queue<std::vector<uint8_t>> q;
  size_t capacity;
  std::atomic<bool> closed{false};
};

void* bigdl_ring_new(uint64_t capacity) {
  Ring* r = new Ring();
  r->capacity = capacity ? capacity : 1;
  return r;
}

void bigdl_ring_free(void* h) { delete static_cast<Ring*>(h); }

void bigdl_ring_close(void* h) {
  Ring* r = static_cast<Ring*>(h);
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->closed = true;
  }
  r->not_empty.notify_all();
  r->not_full.notify_all();
}

// returns 0 on success, -1 if closed
int bigdl_ring_push(void* h, const uint8_t* data, uint64_t n) {
  Ring* r = static_cast<Ring*>(h);
  std::unique_lock<std::mutex> lk(r->mu);
  r->not_full.wait(lk, [&] { return r->q.size() < r->capacity || r->closed; });
  if (r->closed) return -1;
  r->q.emplace(data, data + n);
  lk.unlock();
  r->not_empty.notify_one();
  return 0;
}

// returns payload size (>= 0; zero-length records are legal), or -1 if
// closed-and-drained. Caller passes a buffer of bigdl_ring_peek_size()
// bytes (call under the same single consumer).
int64_t bigdl_ring_pop(void* h, uint8_t* out, uint64_t out_cap) {
  Ring* r = static_cast<Ring*>(h);
  std::unique_lock<std::mutex> lk(r->mu);
  r->not_empty.wait(lk, [&] { return !r->q.empty() || r->closed; });
  if (r->q.empty()) return -1;
  std::vector<uint8_t> buf = std::move(r->q.front());
  r->q.pop();
  lk.unlock();
  r->not_full.notify_one();
  uint64_t n = buf.size() < out_cap ? buf.size() : out_cap;
  memcpy(out, buf.data(), n);
  return static_cast<int64_t>(buf.size());
}

// returns the front payload size (>= 0), or -1 if closed-and-drained —
// distinct values so a legal zero-length record is not read as end-of-stream
int64_t bigdl_ring_peek_size(void* h) {
  Ring* r = static_cast<Ring*>(h);
  std::unique_lock<std::mutex> lk(r->mu);
  r->not_empty.wait(lk, [&] { return !r->q.empty() || r->closed; });
  if (r->q.empty()) return -1;
  return static_cast<int64_t>(r->q.front().size());
}

int64_t bigdl_ring_size(void* h) {
  Ring* r = static_cast<Ring*>(h);
  std::lock_guard<std::mutex> lk(r->mu);
  return static_cast<int64_t>(r->q.size());
}

// -------------------------------------------------- image preprocessing
//
// Hot inner loops of the reference's image transformers
// (BGRImgNormalizer / HFlip / crop, DL/dataset/image/*), multi-threaded
// over the batch dimension like Engine.default.invokeAndWait.

static void parallel_for(int64_t n, int n_threads,
                         const std::function<void(int64_t, int64_t)>& fn) {
  if (n_threads <= 1 || n < 2) {
    fn(0, n);
    return;
  }
  std::vector<std::thread> ts;
  int64_t chunk = (n + n_threads - 1) / n_threads;
  for (int t = 0; t < n_threads; t++) {
    int64_t lo = t * chunk, hi = std::min(n, lo + chunk);
    if (lo >= hi) break;
    ts.emplace_back(fn, lo, hi);
  }
  for (auto& t : ts) t.join();
}

// u8 (N, C, H, W) -> f32 normalized (x/scale - mean[c]) / std[c]
void bigdl_normalize_u8(const uint8_t* src, float* dst, int64_t n, int64_t c,
                        int64_t hw, const float* mean, const float* stdv,
                        float scale, int n_threads) {
  parallel_for(n, n_threads, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; i++) {
      for (int64_t ch = 0; ch < c; ch++) {
        const uint8_t* s = src + (i * c + ch) * hw;
        float* d = dst + (i * c + ch) * hw;
        float m = mean[ch], sd = stdv[ch];
        for (int64_t k = 0; k < hw; k++) d[k] = (s[k] / scale - m) / sd;
      }
    }
  });
}

// horizontal flip in place, u8 (N, C, H, W)
void bigdl_hflip_u8(uint8_t* data, int64_t n, int64_t c, int64_t h, int64_t w,
                    int n_threads) {
  parallel_for(n * c, n_threads, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; i++) {
      uint8_t* plane = data + i * h * w;
      for (int64_t y = 0; y < h; y++) {
        uint8_t* row = plane + y * w;
        for (int64_t x = 0; x < w / 2; x++) std::swap(row[x], row[w - 1 - x]);
      }
    }
  });
}

// crop u8 (C, H, W) -> (C, ch, cw) at offset (y0, x0)
void bigdl_crop_u8(const uint8_t* src, uint8_t* dst, int64_t c, int64_t h,
                   int64_t w, int64_t y0, int64_t x0, int64_t ch, int64_t cw) {
  for (int64_t pc = 0; pc < c; pc++)
    for (int64_t y = 0; y < ch; y++)
      memcpy(dst + (pc * ch + y) * cw, src + (pc * h + (y0 + y)) * w + x0, cw);
}

// One-pass batch assembly: decoded (N, H, W, C) u8 images ->
// (N, C, H, W) f32 normalized batch, threaded over images. This is the
// reference's MTLabeledBGRImgToBatch hot loop (transpose + normalize
// fused so each byte is touched once).
void bigdl_batch_hwc_to_nchw_f32(const uint8_t* src, float* dst, int64_t n,
                                 int64_t h, int64_t w, int64_t c,
                                 const float* mean, const float* stdv,
                                 float scale, int n_threads) {
  int64_t hw = h * w;
  parallel_for(n, n_threads, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; i++) {
      const uint8_t* s = src + i * hw * c;
      float* d = dst + i * hw * c;
      for (int64_t ch = 0; ch < c; ch++) {
        float m = mean[ch], inv = 1.0f / stdv[ch];
        float* dc = d + ch * hw;
        const uint8_t* sc = s + ch;
        for (int64_t k = 0; k < hw; k++) dc[k] = (sc[k * c] / scale - m) * inv;
      }
    }
  });
}

// ------------------------------------------------------- tfrecord scan
// One native pass over an in-memory TFRecord file: validate the
// length+payload CRCs and emit (payload offset, length) pairs so Python
// slices records zero-copy instead of doing per-record read()+struct+crc
// (the reference's record parsing is JVM-side for the same reason).
// Returns #records parsed (stops at `cap`, clean EOF, or a truncated
// trailing record). *err_off = -1 on clean EOF / cap; the truncation
// start offset when the tail is partial (records before it ARE
// returned); on a corrupt CRC returns -1 with the bad offset in
// *err_off. All bounds math is unsigned: a crafted/corrupt 2^63-scale
// length field must report truncation, never read out of bounds.
int64_t bigdl_tfrecord_scan(const uint8_t* buf, int64_t len, int64_t start,
                            int64_t* offsets, int64_t* lengths, int64_t cap,
                            int verify, int64_t* err_off) {
  int64_t pos = start, n = 0;
  *err_off = -1;
  while (n < cap) {
    uint64_t avail = (uint64_t)(len - pos);
    if (avail == 0) return n;  // clean EOF
    if (avail < 12) { *err_off = pos; return n; }
    uint64_t rec_len;
    memcpy(&rec_len, buf + pos, 8);  // little-endian host assumed (x86/ARM)
    uint32_t len_crc;
    memcpy(&len_crc, buf + pos + 8, 4);
    if (verify && bigdl_masked_crc32c(buf + pos, 8) != len_crc) {
      *err_off = pos;
      return -1;
    }
    if (avail < 16 || rec_len > avail - 16) { *err_off = pos; return n; }
    uint32_t data_crc;
    memcpy(&data_crc, buf + pos + 12 + rec_len, 4);
    if (verify && bigdl_masked_crc32c(buf + pos + 12, rec_len) != data_crc) {
      *err_off = pos;
      return -1;
    }
    offsets[n] = pos + 12;
    lengths[n] = (int64_t)rec_len;
    n++;
    pos += 16 + (int64_t)rec_len;
  }
  return n;
}

}  // extern "C"
