"""Deterministic, seeded fault injection.

A :class:`FaultInjector` holds a registry of named **sites** — fixed
points in the stack (pipeline worker element processing, checkpoint
blob/manifest writes, replica submit, engine decode steps, hot-reload
manifest reads, socket feed producers) that call :meth:`fire` on their
hot path. A site that is not armed costs one dict lookup and a ``None``
check, so the hooks stay on in production; an armed site evaluates its
:class:`FaultSpec` and raises a chosen exception and/or injects latency
on a deterministic schedule:

- ``nth=k`` — fault exactly the k-th matching call (1-based);
- ``after=k`` — fault every matching call past the first k (the
  "replica dies after N steps" shape);
- ``rate=p`` — fault with probability ``p`` drawn from a splitmix64
  stream keyed on ``(seed, site, key-or-call-index)`` — the same
  determinism recipe as ``core.rng.element_seed``. Sites that process
  identifiable elements pass ``key=`` (the pipeline passes the element
  index), making the fault schedule a pure function of the element,
  independent of worker count, chunking, or thread interleaving;
- no selector — fault every matching call.

``times=n`` caps the total faults a spec injects (then it goes quiet);
``only=`` filters by the context kwargs the site passes to ``fire``
(``key=`` included — e.g. ``only=lambda engine=None, **_: engine is
replica0`` scopes an ``engine.decode`` arm to one of several engines in
the process, ``only=lambda key=None, **_: key == 7`` poisons exactly
element 7 of a pipeline);
``latency=s`` sleeps instead of (``exc=None``) or before (``exc=...``)
raising. Arming is test/chaos-harness machinery — nothing in the
library arms a site on its own.
"""

from __future__ import annotations

import contextlib
import threading
import time
import zlib
from typing import Any, Callable, Dict, Optional

from bigdl_tpu.core.rng import uniform01
from bigdl_tpu.obs.recorder import record_event
from bigdl_tpu.utils.errors import fresh_exception

# Catalogue of the sites wired into the stack (name -> where it fires).
# Purely documentary — fire() accepts any name, and tests may invent
# scratch sites — but arming a misspelled production site is a silent
# no-op, so FaultInjector.arm warns when the name is not listed here
# and not previously fired.
SITES: Dict[str, str] = {
    "pipeline.worker": "parallel pipeline worker, once per element "
                       "(key = element index)",
    "ckpt.blob_write": "CheckpointManager blob+sidecar write attempt",
    "ckpt.manifest_write": "CheckpointManager MANIFEST.json write attempt",
    "ckpt.watch_manifest": "CheckpointWatcher manifest poll",
    "replica.submit": "ReplicaSet backend submit (ctx: replica=backend)",
    "engine.decode": "GenerationEngine decode step (ctx: engine=)",
    "engine.prefill": "GenerationEngine prefill / prefill chunk "
                      "(ctx: engine=)",
    "engine.prefix_attach": "GenerationEngine paged admission with "
                            "prefix caching on, after cached pages "
                            "attach + fresh pages reserve, before the "
                            "first prefill/decode step (ctx: engine=)",
    "engine.page_handoff": "disaggregated page handoff, once per "
                           "request per side — stage='export' on the "
                           "prefill-role engine before the KV block "
                           "gathers, stage='adopt' on the decode-role "
                           "engine before its pool adopts the pages "
                           "(ctx: engine=, stage=)",
    "engine.draft": "GenerationEngine speculative draft leg, once per "
                    "round before the k+1 draft steps (ctx: engine=)",
    "engine.verify": "GenerationEngine speculative target verify step, "
                     "once per round (ctx: engine=)",
    "kv.offload": "host-tier page offload, once per page-block copy — "
                  "kind='prefix' before an evicted prefix page's device "
                  "gather dispatches, kind='swap' before a stream "
                  "swap-out's block gathers; a fault drops ONLY the "
                  "affected entry/swap (the page evicts plainly, the "
                  "stream stays resident) — nothing strands in either "
                  "tier (ctx: engine=, kind=)",
    "kv.restore": "host-tier page restore, once per host->device "
                  "page-block copy — kind='prefix' before a restored "
                  "chain allocates device pages (a fault degrades the "
                  "affected entries to a miss and drops them from the "
                  "host store; the request re-prefills), kind='swap' "
                  "before a parked stream's resume adoption (a fault "
                  "fails ONLY that stream; its pages release) "
                  "(ctx: engine=, kind=)",
    "feed.producer": "SocketFeedDataSet producer reader, once per frame "
                     "(key = frame index)",
    "rpc.connect": "RemoteReplica client connect attempt "
                   "(ctx: endpoint=)",
    "rpc.send": "RemoteReplica client, once per request frame sent "
                "(key = request index, ctx: endpoint=, method=)",
    "rpc.recv_delay": "RemoteReplica client, once per response frame "
                      "received — latency-oriented (arm with latency=) "
                      "(ctx: endpoint=)",
    "rpc.peer_kill": "ReplicaServer, once per handled request BEFORE "
                     "dispatch; an injected fault here hard-exits the "
                     "server process (the SIGKILL shape, in-band and "
                     "seeded) (key = request index)",
}


class InjectedFault(RuntimeError):
    """Default exception an armed site raises. Carries the site name and
    the (1-based) matching-call index so failure paths that chain or
    stringify the error name their origin."""

    def __init__(self, site: str, call_index: int):
        super().__init__(
            f"injected fault at site '{site}' (call {call_index})")
        self.site = site
        self.call_index = call_index

    def __reduce__(self):
        # Exception's default reduction replays args (the formatted
        # message) into our two-arg __init__ — this keeps the fault
        # picklable, so it survives the process-pool failure path
        return (InjectedFault, (self.site, self.call_index))


class FaultSpec:
    """One armed plan for one site. Built via :meth:`FaultInjector.arm`;
    mutable counters (``calls`` seen, ``fired`` faults) are guarded by
    the owning injector's lock."""

    __slots__ = ("site", "nth", "after", "rate", "seed", "times", "exc",
                 "latency", "only", "calls", "fired")

    def __init__(self, site: str, *, nth: Optional[int] = None,
                 after: Optional[int] = None, rate: Optional[float] = None,
                 seed: int = 0, times: Optional[int] = None,
                 exc: Any = None, latency: float = 0.0,
                 only: Optional[Callable[..., bool]] = None):
        if sum(x is not None for x in (nth, after, rate)) > 1:
            raise ValueError("arm with at most one of nth/after/rate")
        if rate is not None and not 0.0 <= rate <= 1.0:
            raise ValueError(f"rate must be in [0, 1], got {rate}")
        self.site = site
        self.nth = nth
        self.after = after
        self.rate = rate
        self.seed = int(seed)
        self.times = times
        self.exc = exc
        self.latency = float(latency)
        self.only = only
        self.calls = 0   # matching calls seen
        self.fired = 0   # faults injected

    def _should_fire(self, key: Optional[int]) -> bool:
        """Decide for the CURRENT call (``self.calls`` already counts
        it). Caller holds the injector lock."""
        if self.times is not None and self.fired >= self.times:
            return False
        if self.nth is not None:
            return self.calls == self.nth
        if self.after is not None:
            return self.calls > self.after
        if self.rate is not None:
            # keyed draw when the site identifies its element; falls back
            # to the per-spec call counter (deterministic per-run order)
            idx = self.calls if key is None else int(key)
            u = uniform01(self.seed, idx,
                          stream=zlib.crc32(self.site.encode()))
            return u < self.rate
        return True

    def _build_exc(self) -> BaseException:
        exc = self.exc
        if exc is None:
            return InjectedFault(self.site, self.calls)
        if isinstance(exc, type):
            return exc(f"injected fault at site '{self.site}' "
                       f"(call {self.calls})")
        # an armed INSTANCE on a multi-fire plan: raise a fresh copy per
        # injection — raising one shared object would let a later fire
        # mutate the __traceback__/__context__ a stream already captured
        return fresh_exception(exc, keep_traceback=False)


class FaultInjector:
    """Process-global registry of armed fault sites (one spec per site;
    re-arming replaces). The module-level default instance is what the
    library's hot points fire into — construct private injectors only
    for isolated harnesses."""

    def __init__(self):
        self._lock = threading.Lock()
        self._sites: Dict[str, FaultSpec] = {}
        self._history: Dict[str, Dict[str, int]] = {}

    # ----------------------------------------------------------- arming --
    def arm(self, site: str, **kw) -> FaultSpec:
        """Arm ``site`` with a :class:`FaultSpec` (see module docs for
        the selector/effect kwargs). Returns the spec (its ``calls`` /
        ``fired`` counters are live)."""
        spec = FaultSpec(site, **kw)
        with self._lock:
            replaced = self._sites.get(site)
            if replaced is not None:
                # re-arming without a disarm must not lose the old
                # spec's counts: snapshot() is how a chaos harness
                # proves its schedule actually fired
                self._remember(replaced)
            self._sites[site] = spec
        if site not in SITES and site not in self._history:
            import logging

            logging.getLogger("bigdl_tpu.faults").warning(
                "arming fault site '%s', which is not in the catalogue "
                "and has never fired — a misspelled production site is a "
                "silent no-op", site)
        return spec

    def disarm(self, site: str) -> None:
        with self._lock:
            spec = self._sites.pop(site, None)
            if spec is not None:
                self._remember(spec)

    def reset(self) -> None:
        """Disarm everything and clear history (test isolation)."""
        with self._lock:
            self._sites.clear()
            self._history.clear()

    @contextlib.contextmanager
    def armed(self, site: str, **kw):
        """``with faults.armed("ckpt.blob_write", nth=1, exc=OSError):``
        — arm for the block, disarm on exit (even on error)."""
        spec = self.arm(site, **kw)
        try:
            yield spec
        finally:
            self.disarm(site)

    def spec(self, site: str) -> Optional[FaultSpec]:
        with self._lock:
            return self._sites.get(site)

    # ------------------------------------------------------- hot path ----
    def fire(self, site: str, key: Optional[int] = None, **ctx) -> None:
        """The hot-point check. Disarmed: one dict lookup and a ``None``
        test. Armed: count the call, evaluate the plan, and inject
        (sleep and/or raise). ``key`` identifies the element for keyed
        ``rate`` draws; other kwargs are context for ``only=``."""
        spec = self._sites.get(site)
        if spec is None:
            return
        with self._lock:
            # re-check under the lock: disarm may have raced the lookup
            if self._sites.get(site) is not spec:
                return
            if spec.only is not None and not spec.only(key=key, **ctx):
                return
            spec.calls += 1
            if not spec._should_fire(key):
                return
            spec.fired += 1
            exc = None if (spec.latency > 0 and spec.exc is None) \
                else spec._build_exc()
            latency = spec.latency
            call_index = spec.calls
        # flight-recorder breadcrumb (outside the lock, before the
        # effect lands): chaos runs reconcile these against snapshot()
        # to prove every scheduled fault is reconstructable
        record_event("fault.fired", site=site, key=key, call=call_index,
                     effect=("latency" if exc is None
                             else type(exc).__name__),
                     latency=latency)
        if latency > 0:
            time.sleep(latency)  # outside the lock: never stall siblings
        if exc is not None:
            raise exc

    # ------------------------------------------------------ observers ----
    def _remember(self, spec: FaultSpec) -> None:
        h = self._history.setdefault(spec.site, {"calls": 0, "fired": 0})
        h["calls"] += spec.calls
        h["fired"] += spec.fired

    def snapshot(self) -> Dict[str, Dict[str, int]]:
        """Per-site ``{"calls", "fired"}`` counts, armed specs merged
        with disarmed history — the chaos harness reads this to prove
        the schedule actually exercised its sites."""
        with self._lock:
            out = {k: dict(v) for k, v in self._history.items()}
            for site, spec in self._sites.items():
                h = out.setdefault(site, {"calls": 0, "fired": 0})
                h["calls"] += spec.calls
                h["fired"] += spec.fired
            return out
