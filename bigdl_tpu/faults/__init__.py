"""Deterministic fault injection + the self-healing it exercises.

The reference framework inherited its reliability story from Spark —
task retry, straggler re-execution, driver recovery all came from the
runtime. This package is the TPU-native replacement: failure becomes a
first-class, *testable* input.

- :class:`FaultInjector` / the module-level ``arm``/``fire``/``armed``
  — seeded, schedulable faults at named sites across the stack (see
  :data:`SITES` for the catalogue). Disarmed sites cost one dict
  lookup; armed plans are deterministic (splitmix64 keyed on
  ``(seed, site, element)``), so a chaos run replays exactly.
- :class:`RetryPolicy` — the shared transient-vs-permanent
  classification + bounded exponential backoff with deterministic
  jitter, adopted by the checkpoint writer, the checkpoint watcher,
  and the ``ReplicaSet`` prober.
- :class:`Watchdog` — stall detection for step loops: an armed unit of
  work that makes no progress past its deadline fails pending work
  with a :class:`StallError` diagnostic instead of hanging forever.

The usual test/chaos shape::

    from bigdl_tpu import faults

    with faults.armed("ckpt.blob_write", nth=1, exc=OSError):
        manager.save(...)          # healed by the writer's RetryPolicy

    faults.arm("pipeline.worker", rate=0.02, seed=7)   # keyed per element
    ...                                                # supervision replays
    faults.reset()                                     # test isolation
"""

from bigdl_tpu.faults.injector import (
    SITES,
    FaultInjector,
    FaultSpec,
    InjectedFault,
)
from bigdl_tpu.faults.retry import RetryPolicy
from bigdl_tpu.faults.watchdog import StallError, Watchdog

#: The process-global injector every hot point in the library fires into.
_default = FaultInjector()


def default() -> FaultInjector:
    """The process-global injector (what ``arm``/``fire`` act on)."""
    return _default


# module-level conveniences over the default injector — the API the
# ISSUE's `faults.site("pipeline.worker", ...)` arming recipe names
arm = _default.arm
disarm = _default.disarm
reset = _default.reset
armed = _default.armed
fire = _default.fire
spec = _default.spec
snapshot = _default.snapshot


def site(name: str, **kw):
    """Arm ``name`` when plan kwargs are given, else return its current
    :class:`FaultSpec` (or None). ``faults.site("pipeline.worker",
    nth=3)`` reads as "declare a fault at this site"."""
    if kw:
        return _default.arm(name, **kw)
    return _default.spec(name)


__all__ = [
    "SITES",
    "FaultInjector",
    "FaultSpec",
    "InjectedFault",
    "RetryPolicy",
    "StallError",
    "Watchdog",
    "arm",
    "armed",
    "default",
    "disarm",
    "fire",
    "reset",
    "site",
    "snapshot",
    "spec",
]
