"""Stall detection for step loops that must never hang silently.

A :class:`Watchdog` owns one background thread and one armed deadline.
The watched loop brackets each unit of work with :meth:`arm` /
:meth:`disarm` (or the :meth:`watching` context manager) and calls
:meth:`beat` whenever it makes observable progress; if an armed period
outlives ``timeout`` seconds without a beat, the watchdog fires
``on_stall(StallError(diagnostic))`` from its own thread — ONCE per
armed period — and stays alive for the next arm. The stuck thread
itself is never interrupted (a wedged XLA dispatch cannot be unwound
from Python); the point is to turn "hangs forever" into "fails pending
work with a diagnostic": the generation engine fails its streams and
refuses new submits, the optimizer poisons its input stream so the
blocked loop surfaces the stall instead of waiting on a dead producer.

While idle (disarmed) the thread sleeps on a condition with no deadline
— an idle engine costs nothing and never false-fires.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional

log = logging.getLogger("bigdl_tpu.faults")


class StallError(RuntimeError):
    """No progress past the watchdog deadline. ``diagnostic`` names the
    watchdog, the stalled unit of work, and how long it has been stuck."""


class Watchdog:
    """One deadline, one checker thread, one ``on_stall`` callback.

    ``on_stall`` runs on the watchdog thread — it must not block
    indefinitely (fail futures, set flags, poison queues; don't join
    the stuck thread). ``clock`` is injectable for tests.
    """

    def __init__(self, name: str, timeout: float,
                 on_stall: Callable[[StallError], None], *,
                 clock: Callable[[], float] = time.monotonic):
        if timeout <= 0:
            raise ValueError("watchdog timeout must be > 0")
        self.name = name
        self.timeout = float(timeout)
        self.on_stall = on_stall
        self.stalls = 0
        self._clock = clock
        self._cond = threading.Condition()
        self._armed = False
        self._fired = False   # once per armed period
        self._label = ""
        self._last_beat = 0.0
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name=f"bigdl-watchdog-{name}", daemon=True)
        self._thread.start()

    # ------------------------------------------------------- loop side --
    def arm(self, label: str = "") -> None:
        """Start (or restart) the deadline for one unit of work."""
        with self._cond:
            self._armed = True
            self._fired = False
            self._label = label
            self._last_beat = self._clock()
            self._cond.notify_all()

    def beat(self) -> None:
        """Progress heartbeat: pushes the armed deadline out. Progress
        AFTER a stall fired also re-enables the watchdog — a handler
        that heals the stall (rather than aborting) must get a fresh
        detection for the NEXT stall of the same armed period."""
        with self._cond:
            self._last_beat = self._clock()
            if self._fired:
                self._fired = False
                self._cond.notify_all()

    def disarm(self) -> None:
        """The unit of work completed; stop watching until the next arm."""
        with self._cond:
            self._armed = False
            self._cond.notify_all()

    def watching(self, label: str = ""):
        """``with wd.watching("decode step"):`` — arm/disarm bracket."""
        return _Watching(self, label)

    def close(self, timeout: Optional[float] = 5.0) -> None:
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout)

    def __enter__(self) -> "Watchdog":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --------------------------------------------------- watchdog side --
    def _run(self) -> None:
        while True:
            with self._cond:
                while not self._closed and (not self._armed or self._fired):
                    self._cond.wait()  # idle: no deadline, no wakeups
                if self._closed:
                    return
                age = self._clock() - self._last_beat
                if age < self.timeout:
                    self._cond.wait(self.timeout - age)
                    continue
                # stalled: fire once for this armed period
                self._fired = True
                self.stalls += 1
                label = self._label or "step"
                err = StallError(
                    f"watchdog '{self.name}': no progress in {label} for "
                    f"{age:.1f}s (deadline {self.timeout:.1f}s) — failing "
                    "pending work instead of hanging")
            from bigdl_tpu.obs.recorder import flight_recorder

            recorder = flight_recorder()
            recorder.record("watchdog.stall", name=self.name, label=label,
                            age=round(age, 3), timeout=self.timeout)
            log.error("%s", err)
            # the stall is exactly the moment "what just happened?"
            # matters: dump the recorder's recent events next to the
            # diagnostic instead of leaving a bare error line
            log.error("flight recorder (last 16 events):\n%s",
                      recorder.format_events(last=16))
            try:
                self.on_stall(err)
            except Exception:
                log.exception("watchdog '%s' on_stall callback failed",
                              self.name)


class _Watching:
    __slots__ = ("_wd", "_label")

    def __init__(self, wd: Watchdog, label: str):
        self._wd = wd
        self._label = label

    def __enter__(self):
        self._wd.arm(self._label)
        return self._wd

    def __exit__(self, *exc):
        self._wd.disarm()
