"""Shared retry/backoff policy for the self-healing tier.

One :class:`RetryPolicy` answers three questions every recovery loop in
the stack otherwise re-invents: *is this failure worth retrying*
(transient-vs-permanent classification), *how long to wait before the
next attempt* (exponential backoff, capped, with DETERMINISTIC jitter —
a splitmix64 draw keyed on ``(seed, attempt)``, so tests can assert the
exact schedule against a fake clock and two processes never sync their
retries when given distinct seeds), and *when to give up* (bounded
attempts, last error re-raised loudly).

Adopters: the checkpoint writer (transient ``OSError`` on blob/manifest
writes), the checkpoint watcher (failed polls back off instead of
hammering), and the ``ReplicaSet`` prober (a long-dead backend is probed
on a growing interval capped at ~30 s, reset on rejoin). ``backoff()``
is a pure function of the attempt number, so it also serves as a bare
schedule for loops that wait rather than call (the prober).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Optional, Tuple, Type

from bigdl_tpu.core.rng import uniform01
from bigdl_tpu.obs.recorder import record_event

log = logging.getLogger("bigdl_tpu.faults")


class RetryPolicy:
    """Bounded retries with capped exponential backoff and deterministic
    jitter.

    ``max_attempts`` counts TOTAL tries (1 = no retry). ``transient``
    is the tuple of exception types worth retrying; ``classify`` (when
    given) overrides it entirely — an ``exc -> bool`` predicate for
    cases like "OSError yes, but ENOSPC no". Everything else (and every
    ``BaseException`` that is not an ``Exception``) is permanent and
    re-raised immediately.

    ``backoff(attempt)`` (0-based) = ``base_delay * multiplier**attempt``
    capped at ``max_delay``, scaled by ``1 + jitter * (u - 0.5)`` with
    ``u`` drawn from splitmix64 on ``(seed, attempt)`` — deterministic,
    so a fake-clock test can assert the exact schedule.
    """

    def __init__(self, max_attempts: int = 3, *, base_delay: float = 0.05,
                 max_delay: float = 30.0, multiplier: float = 2.0,
                 jitter: float = 0.1, seed: int = 0,
                 transient: Tuple[Type[BaseException], ...] = (OSError,),
                 classify: Optional[Callable[[BaseException], bool]] = None):
        if max_attempts < 1:
            raise ValueError("max_attempts must be >= 1")
        if not 0.0 <= jitter < 1.0:
            raise ValueError("jitter must be in [0, 1)")
        self.max_attempts = int(max_attempts)
        self.base_delay = float(base_delay)
        self.max_delay = float(max_delay)
        self.multiplier = float(multiplier)
        self.jitter = float(jitter)
        self.seed = int(seed)
        self.transient = tuple(transient)
        self.classify = classify
        # healing gauges (obs tier): how often this policy absorbed a
        # transient, and how often the budget ran out anyway — the
        # registry surfaces them next to the counters of whatever the
        # policy protects (ckpt writer, watcher, prober)
        self._lock = threading.Lock()
        self.retries = 0      # transient failures retried (healed-so-far)
        self.exhaustions = 0  # budgets exhausted (last error re-raised)

    def snapshot(self) -> dict:
        """Registry-friendly counters."""
        with self._lock:
            return {"retries": self.retries,
                    "exhaustions": self.exhaustions,
                    "max_attempts": self.max_attempts}

    @classmethod
    def poll_schedule(cls, base_interval: float, *,
                      cap: float = 30.0, seed: int = 0) -> "RetryPolicy":
        """The shared pacing recipe for recovery POLLERS (the ReplicaSet
        prober, the checkpoint watcher's error polls): base interval,
        doubling per fruitless pass, capped — but never pacing a BROKEN
        target faster than the healthy path, so a base interval above
        the cap lifts the cap."""
        base = max(float(base_interval), 1e-3)
        return cls(max_attempts=1, base_delay=base,
                   max_delay=max(cap, base), multiplier=2.0, jitter=0.1,
                   seed=seed)

    # ---------------------------------------------------------- pieces --
    def is_transient(self, exc: BaseException) -> bool:
        if not isinstance(exc, Exception):
            return False  # KeyboardInterrupt/SystemExit are never retried
        if self.classify is not None:
            return bool(self.classify(exc))
        return isinstance(exc, self.transient)

    def backoff(self, attempt: int) -> float:
        """Delay before try ``attempt + 1`` (attempt is 0-based). Safe
        for unbounded counters: a prober or watcher stuck on a backend
        dead for hours feeds attempt numbers large enough to overflow
        float exponentiation, so the exponent is clamped at the point
        the schedule saturates at ``max_delay`` anyway."""
        attempt = max(0, int(attempt))
        if self.base_delay <= 0:
            delay = 0.0
        else:
            exp = attempt
            if self.multiplier > 1.0:
                import math

                saturate = math.log(
                    max(self.max_delay / self.base_delay, 1.0),
                    self.multiplier)
                exp = min(attempt, int(saturate) + 1)
            delay = min(self.base_delay * self.multiplier ** exp,
                        self.max_delay)
        if self.jitter:
            u = uniform01(self.seed, attempt)
            delay *= 1.0 + self.jitter * (u - 0.5)
        return delay

    def delays(self):
        """The full retry schedule: ``max_attempts - 1`` delays."""
        return [self.backoff(i) for i in range(self.max_attempts - 1)]

    # ------------------------------------------------------------ call --
    def call(self, fn: Callable, *args, describe: str = "",
             sleep: Callable[[float], None] = time.sleep,
             on_retry: Optional[Callable[[BaseException, int], None]] = None,
             **kwargs):
        """Run ``fn`` under the policy: transient failures are retried
        (after ``backoff``), permanent ones re-raise immediately, and
        exhausting the budget re-raises the LAST transient error. Every
        retried failure is logged — a healed fault still leaves a trace.
        ``sleep`` is injectable for fake-clock tests; ``on_retry(exc,
        attempt)`` fires before each backoff."""
        what = describe or getattr(fn, "__name__", "call")
        for attempt in range(self.max_attempts):
            try:
                return fn(*args, **kwargs)
            except BaseException as e:
                if not self.is_transient(e) \
                        or attempt + 1 >= self.max_attempts:
                    if self.is_transient(e):
                        # transient but out of budget: exhaustion, not
                        # a permanent error — count it so the registry
                        # can tell "healed" from "gave up"
                        with self._lock:
                            self.exhaustions += 1
                        record_event("retry.exhausted", what=what,
                                     error=type(e).__name__,
                                     attempts=self.max_attempts)
                    raise
                with self._lock:
                    self.retries += 1
                record_event("retry", what=what, error=type(e).__name__,
                             attempt=attempt + 1)
                delay = self.backoff(attempt)
                log.warning(
                    "%s failed with transient %s: %s — retrying in %.3fs "
                    "(attempt %d/%d)", what, type(e).__name__, e, delay,
                    attempt + 1, self.max_attempts)
                if on_retry is not None:
                    on_retry(e, attempt)
                sleep(delay)
