"""Shared module-tree walker for the format exporters.

The Caffe/TF/ONNX exporters all fold a Sequential/Graph tree into a chain
of per-leaf emissions; this is the one implementation they share. Each
exporter supplies ``emit_leaf(module, params, state, inputs, name)`` which
returns an opaque token (the emitted node's output name) for downstream
wiring.
"""

from __future__ import annotations

from typing import Callable, List, Optional

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.graph import Graph


def walk_model(model, params, state, x, emit_leaf: Callable,
               name: Optional[str] = None, _prefix: str = ""):
    """Emit ``model`` (token-in ``x`` -> token-out). Containers recurse;
    leaves go to ``emit_leaf``.

    Leaf names are path-qualified ("block1_0_conv") so nested containers
    never produce duplicate names; a top-level Graph's node names pass
    through exactly (loaders key params by them).
    """
    params = params or {}
    state = state or {}
    if isinstance(model, Graph):
        if len(model.inputs) != 1:
            raise ValueError("export supports single-input graphs only")
        tops = {id(model.inputs[0]): x}
        for node in model._topo:
            if node.element is None:
                continue
            nname = model._names[id(node)]
            qual = f"{_prefix}{nname}"
            ins = [tops[id(p)] for p in node.prev]
            tops[id(node)] = _walk_node(
                node.element, params.get(nname, {}), state.get(nname, {}),
                ins, emit_leaf, qual)
        return tops[id(model.outputs[0])]
    if isinstance(model, nn.Sequential):
        for cname, child in model._modules.items():
            x = walk_model(child, params.get(cname, {}), state.get(cname, {}),
                           x, emit_leaf, f"{_prefix}{cname}",
                           _prefix=f"{_prefix}{cname}_")
        return x
    return emit_leaf(model, params, state, [x], name)


def _walk_node(module, params, state, ins: List, emit_leaf, name):
    """A graph node: containers with a single input recurse; real leaves
    (possibly multi-input) emit directly."""
    if isinstance(module, (nn.Sequential, Graph)) and len(ins) == 1:
        return walk_model(module, params, state, ins[0], emit_leaf, name,
                          _prefix=f"{name}_" if name else "")
    return emit_leaf(module, params, state, ins, name)
