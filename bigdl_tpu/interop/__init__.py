"""Interop tier: loaders/savers for foreign model formats.

Reference: ``DL/utils/caffe/`` (Caffe bridge), ``DL/utils/tf/`` (TensorFlow
GraphDef bridge), ``DL/nn/onnx`` + ``PY/contrib/onnx`` (ONNX ops/loader).
"""
