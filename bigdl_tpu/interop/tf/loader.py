"""TensorFlow GraphDef importer.

Reference: ``DL/utils/tf/TensorflowLoader.scala:43`` — parse a (frozen)
GraphDef, map nodes to BigDL modules via 161 per-op loader classes, build a
Graph. ``DL/utils/tf/Session.scala:43`` drives a loaded graph.

TPU-native redesign: instead of pattern-matching TF subgraphs onto a layer
zoo (the reference needs this because its layers own their backward), the
importer evaluates the GraphDef **node by node as a pure jax function** —
each op maps to a jnp/lax expression, the whole graph jits into one XLA
program, and autodiff works through it for free. Large ``Const`` tensors
(the frozen weights) are lifted into the params pytree so they behave like
ordinary module parameters (donation, sharding, checkpointing).

``TFGraphModule`` is a regular :class:`Module`: ``load_tf_graph(pb_path,
inputs=[...], outputs=[...])`` then ``model.apply(params, x)``.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.interop.tf import tensorflow_pb2 as pb
from bigdl_tpu.nn.module import Context, Module

_NP_DTYPES = {
    pb.DT_FLOAT: np.float32,
    pb.DT_DOUBLE: np.float64,
    pb.DT_INT32: np.int32,
    pb.DT_UINT8: np.uint8,
    pb.DT_INT16: np.int16,
    pb.DT_INT8: np.int8,
    pb.DT_INT64: np.int64,
    pb.DT_BOOL: np.bool_,
    pb.DT_HALF: np.float16,
    pb.DT_BFLOAT16: None,  # handled explicitly (ml_dtypes)
    pb.DT_UINT16: np.uint16,
    pb.DT_UINT32: np.uint32,
    pb.DT_UINT64: np.uint64,
}


def tensor_to_numpy(t: "pb.TensorProto") -> np.ndarray:
    shape = [int(d.size) for d in t.tensor_shape.dim]
    if t.dtype == pb.DT_STRING:
        # string consts appear in training graphs (Assert messages, reader
        # patterns); keep them as object arrays so import doesn't choke
        vals = list(t.string_val)
        n = int(np.prod(shape)) if shape else 1
        if len(vals) < n:  # trailing-repeat compression (TF MakeNdarray)
            vals = vals + [vals[-1] if vals else b""] * (n - len(vals))
        arr = np.empty(len(vals), dtype=object)
        arr[:] = vals
        return arr.reshape(shape) if shape else arr.reshape(())
    if t.dtype == pb.DT_BFLOAT16:
        import ml_dtypes

        dt = ml_dtypes.bfloat16
    else:
        dt = _NP_DTYPES.get(t.dtype)
        if dt is None:
            raise ValueError(f"unsupported TensorProto dtype {t.dtype}")
    if t.tensor_content:
        arr = np.frombuffer(t.tensor_content, dtype=dt)
        return arr.reshape(shape) if shape else arr.reshape(())
    for field in ("float_val", "double_val", "int_val", "int64_val", "bool_val"):
        vals = getattr(t, field)
        if len(vals):
            arr = np.asarray(list(vals), dtype=dt)
            n = int(np.prod(shape)) if shape else 1
            if arr.size == 1 and n > 1:  # splat encoding
                arr = np.full(n, arr[0], dtype=dt)
            return arr.reshape(shape)
    return np.zeros(shape, dtype=dt)


def numpy_to_tensor(arr: np.ndarray) -> "pb.TensorProto":
    arr = np.asarray(arr)
    rev = {v: k for k, v in _NP_DTYPES.items() if v is not None}
    t = pb.TensorProto()
    if arr.dtype.name == "bfloat16":
        t.dtype = pb.DT_BFLOAT16
    else:
        t.dtype = rev.get(arr.dtype.type, pb.DT_FLOAT)
    for d in arr.shape:
        t.tensor_shape.dim.add().size = d
    t.tensor_content = np.ascontiguousarray(arr).tobytes()
    return t


def _ref(name: str) -> Tuple[str, int]:
    """'node:2' -> ('node', 2); control inputs '^node' -> ('node', -1)."""
    if name.startswith("^"):
        return name[1:], -1
    if ":" in name:
        base, idx = name.rsplit(":", 1)
        return base, int(idx)
    return name, 0


def _nhwc_pool_args(node):
    ksize = list(node.attr["ksize"].list.i)
    strides = list(node.attr["strides"].list.i)
    padding = node.attr["padding"].s.decode()
    fmt = node.attr["data_format"].s.decode() or "NHWC"
    return ksize, strides, padding, fmt


# ---------------------------------------------------------------- op set
# Each op: fn(inputs: list, node: NodeDef, ctx) -> output (or tuple).

def _conv2d(inp, node, ctx):
    x, w = inp  # x NHWC (or NCHW), w HWIO
    strides = list(node.attr["strides"].list.i)
    padding = node.attr["padding"].s.decode()
    fmt = node.attr["data_format"].s.decode() or "NHWC"
    dil = list(node.attr["dilations"].list.i) or [1, 1, 1, 1]
    if fmt == "NHWC":
        dn = ("NHWC", "HWIO", "NHWC")
        window_strides = strides[1:3]
        rhs_dil = dil[1:3]
    else:
        dn = ("NCHW", "HWIO", "NCHW")
        window_strides = strides[2:4]
        rhs_dil = dil[2:4]
    return lax.conv_general_dilated(
        x, w, window_strides, padding, rhs_dilation=rhs_dil, dimension_numbers=dn)


def _depthwise_conv2d(inp, node, ctx):
    x, w = inp  # w (kh, kw, in, multiplier)
    strides = list(node.attr["strides"].list.i)
    padding = node.attr["padding"].s.decode()
    kh, kw, cin, mult = w.shape
    w2 = w.reshape(kh, kw, 1, cin * mult)
    return lax.conv_general_dilated(
        x, w2, strides[1:3], padding, dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=cin)


def _bias_add(inp, node, ctx):
    x, b = inp
    fmt = node.attr["data_format"].s.decode() or "NHWC"
    if fmt == "NCHW" and x.ndim == 4:
        return x + b[None, :, None, None]
    return x + b


def _max_pool(inp, node, ctx):
    (x,) = inp
    ksize, strides, padding, fmt = _nhwc_pool_args(node)
    init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    return lax.reduce_window(x, init, lax.max, tuple(ksize), tuple(strides), padding)


def _avg_pool(inp, node, ctx):
    (x,) = inp
    ksize, strides, padding, fmt = _nhwc_pool_args(node)
    s = lax.reduce_window(x, 0.0, lax.add, tuple(ksize), tuple(strides), padding)
    ones = jnp.ones(x.shape, x.dtype)
    n = lax.reduce_window(ones, 0.0, lax.add, tuple(ksize), tuple(strides), padding)
    return s / n


def _attr_f(node, name, default):
    """Float attr with explicit-presence check (0.0 is a legal value)."""
    return float(node.attr[name].f) if name in node.attr else default


def _fused_batch_norm(inp, node, ctx):
    x, scale, offset, mean, var = inp
    eps = _attr_f(node, "epsilon", 1e-3)
    fmt = node.attr["data_format"].s.decode() or "NHWC"
    if len(mean) == 0:  # training-mode graphs carry empty mean/var
        axes = (0, 1, 2) if fmt == "NHWC" else (0, 2, 3)
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
    inv = lax.rsqrt(var + eps) * scale
    shift = offset - mean * inv
    if fmt == "NCHW":
        y = x * inv[None, :, None, None] + shift[None, :, None, None]
    else:
        y = x * inv + shift
    return y, mean, var, mean, var  # (y, batch_mean, batch_var, r1, r2)


def _matmul(inp, node, ctx):
    a, b = inp
    if node.attr["transpose_a"].b:
        a = a.T
    if node.attr["transpose_b"].b:
        b = b.T
    return a @ b


def _batch_matmul(inp, node, ctx):
    a, b = inp
    if node.attr["adj_x"].b:
        a = jnp.swapaxes(a, -1, -2)
    if node.attr["adj_y"].b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


def _concat_v2(inp, node, ctx):
    *xs, axis = inp
    return jnp.concatenate(xs, axis=int(axis))


def _concat(inp, node, ctx):
    axis, *xs = inp
    return jnp.concatenate(xs, axis=int(axis))


def _split(inp, node, ctx):
    axis, x = inp
    n = int(node.attr["num_split"].i)
    return tuple(jnp.split(x, n, axis=int(axis)))


def _pad(inp, node, ctx):
    x, paddings = inp
    pads = [(int(a), int(b)) for a, b in np.asarray(paddings)]
    return jnp.pad(x, pads)

def _strided_slice(inp, node, ctx):
    x, begin, end, strides = inp
    if any(isinstance(v, jax.core.Tracer) for v in (begin, end, strides)):
        return _strided_slice_dynamic(inp, node)
    begin, end, strides = (np.asarray(v).tolist() for v in (begin, end, strides))
    bm = int(node.attr["begin_mask"].i)
    em = int(node.attr["end_mask"].i)
    sm = int(node.attr["shrink_axis_mask"].i)
    nm = int(node.attr["new_axis_mask"].i)
    elm = int(node.attr["ellipsis_mask"].i)
    if nm:
        raise NotImplementedError("StridedSlice new_axis_mask")
    if elm:
        raise NotImplementedError("StridedSlice ellipsis_mask")
    idx = []
    for ax in range(len(begin)):
        if sm & (1 << ax):
            idx.append(int(begin[ax]))
            continue
        b = None if bm & (1 << ax) else int(begin[ax])
        e = None if em & (1 << ax) else int(end[ax])
        idx.append(slice(b, e, int(strides[ax])))
    return x[tuple(idx)]


def _strided_slice_dynamic(inp, node):
    """StridedSlice with loop-variable indices (the pattern while_v2
    bodies emit for ``x[:, t]``): lax.dynamic_slice with unit strides.
    Each sliced axis keeps its static extent unless masked out; a
    shrink axis takes one element at the dynamic index and squeezes."""
    x, begin, end, strides = inp
    bm = int(node.attr["begin_mask"].i)
    em = int(node.attr["end_mask"].i)
    sm = int(node.attr["shrink_axis_mask"].i)
    if int(node.attr["new_axis_mask"].i) or int(node.attr["ellipsis_mask"].i):
        raise NotImplementedError("dynamic StridedSlice with axis masks")
    if not isinstance(strides, jax.core.Tracer) and \
            not all(int(s) == 1 for s in np.asarray(strides).reshape(-1)):
        raise NotImplementedError("dynamic StridedSlice with strides != 1")
    n = begin.shape[0] if hasattr(begin, "shape") else len(begin)
    starts, sizes, squeeze = [], [], []
    for ax in range(x.ndim):
        if ax >= n:
            starts.append(0)
            sizes.append(x.shape[ax])
            continue
        b = begin[ax]
        if sm & (1 << ax):
            starts.append(b)
            sizes.append(1)
            squeeze.append(ax)
        elif (bm & (1 << ax)) and (em & (1 << ax)):
            starts.append(0)
            sizes.append(x.shape[ax])
        else:
            raise NotImplementedError(
                "dynamic StridedSlice with partial static bounds")
    starts = [s.astype(jnp.int32) if hasattr(s, "astype") else jnp.int32(s)
              for s in starts]
    y = lax.dynamic_slice(x, starts, sizes)
    return jnp.squeeze(y, axis=tuple(squeeze)) if squeeze else y


def _cast(inp, node, ctx):
    (x,) = inp
    dst = node.attr["DstT"].type
    if dst == pb.DT_BFLOAT16:
        return x.astype(jnp.bfloat16)
    return x.astype(_NP_DTYPES[dst])


def _one_hot(inp, node, ctx):
    indices, depth, on, off = inp
    return jax.nn.one_hot(indices, int(depth)) * (on - off) + off


def _reduction(fn):
    def op(inp, node, ctx):
        x, axes = inp
        axes = tuple(np.asarray(axes).reshape(-1).tolist())
        return fn(x, axis=axes or None, keepdims=bool(node.attr["keep_dims"].b))
    return op


_OPS: Dict[str, Callable] = {
    "Const": None,        # handled in build
    "Placeholder": None,  # handled in build
    "PlaceholderWithDefault": lambda i, n, c: i[0],
    "Identity": lambda i, n, c: i[0],
    "StopGradient": lambda i, n, c: lax.stop_gradient(i[0]),
    "NoOp": lambda i, n, c: None,
    "Add": lambda i, n, c: i[0] + i[1],
    "AddV2": lambda i, n, c: i[0] + i[1],
    "AddN": lambda i, n, c: sum(i[1:], i[0]),
    "Sub": lambda i, n, c: i[0] - i[1],
    "Mul": lambda i, n, c: i[0] * i[1],
    "Div": lambda i, n, c: i[0] / i[1],
    "RealDiv": lambda i, n, c: i[0] / i[1],
    "FloorDiv": lambda i, n, c: i[0] // i[1],
    "FloorMod": lambda i, n, c: i[0] % i[1],
    "Pow": lambda i, n, c: i[0] ** i[1],
    "SquaredDifference": lambda i, n, c: (i[0] - i[1]) ** 2,
    "Maximum": lambda i, n, c: jnp.maximum(i[0], i[1]),
    "Minimum": lambda i, n, c: jnp.minimum(i[0], i[1]),
    "Neg": lambda i, n, c: -i[0],
    "Abs": lambda i, n, c: jnp.abs(i[0]),
    "Square": lambda i, n, c: jnp.square(i[0]),
    "Sqrt": lambda i, n, c: jnp.sqrt(i[0]),
    "Rsqrt": lambda i, n, c: lax.rsqrt(i[0]),
    "Exp": lambda i, n, c: jnp.exp(i[0]),
    "Log": lambda i, n, c: jnp.log(i[0]),
    "Log1p": lambda i, n, c: jnp.log1p(i[0]),
    "Tanh": lambda i, n, c: jnp.tanh(i[0]),
    "Sigmoid": lambda i, n, c: jax.nn.sigmoid(i[0]),
    "Relu": lambda i, n, c: jax.nn.relu(i[0]),
    "Relu6": lambda i, n, c: jnp.clip(i[0], 0, 6),
    "Elu": lambda i, n, c: jax.nn.elu(i[0]),
    "Selu": lambda i, n, c: jax.nn.selu(i[0]),
    "Softplus": lambda i, n, c: jax.nn.softplus(i[0]),
    "Softsign": lambda i, n, c: jax.nn.soft_sign(i[0]),
    "LeakyRelu": lambda i, n, c: jax.nn.leaky_relu(
        i[0], negative_slope=_attr_f(n, "alpha", 0.2)),
    "Softmax": lambda i, n, c: jax.nn.softmax(i[0], axis=-1),
    "LogSoftmax": lambda i, n, c: jax.nn.log_softmax(i[0], axis=-1),
    "Sin": lambda i, n, c: jnp.sin(i[0]),
    "Cos": lambda i, n, c: jnp.cos(i[0]),
    "Floor": lambda i, n, c: jnp.floor(i[0]),
    "Ceil": lambda i, n, c: jnp.ceil(i[0]),
    "Round": lambda i, n, c: jnp.round(i[0]),
    "Sign": lambda i, n, c: jnp.sign(i[0]),
    "Reciprocal": lambda i, n, c: 1.0 / i[0],
    "Greater": lambda i, n, c: i[0] > i[1],
    "GreaterEqual": lambda i, n, c: i[0] >= i[1],
    "Less": lambda i, n, c: i[0] < i[1],
    "LessEqual": lambda i, n, c: i[0] <= i[1],
    "Equal": lambda i, n, c: i[0] == i[1],
    "NotEqual": lambda i, n, c: i[0] != i[1],
    "LogicalAnd": lambda i, n, c: jnp.logical_and(i[0], i[1]),
    "LogicalOr": lambda i, n, c: jnp.logical_or(i[0], i[1]),
    "LogicalNot": lambda i, n, c: jnp.logical_not(i[0]),
    "Select": lambda i, n, c: jnp.where(i[0], i[1], i[2]),
    "SelectV2": lambda i, n, c: jnp.where(i[0], i[1], i[2]),
    "MatMul": _matmul,
    "BatchMatMul": _batch_matmul,
    "BatchMatMulV2": _batch_matmul,
    "Conv2D": _conv2d,
    "DepthwiseConv2dNative": _depthwise_conv2d,
    "BiasAdd": _bias_add,
    "MaxPool": _max_pool,
    "AvgPool": _avg_pool,
    "FusedBatchNorm": _fused_batch_norm,
    "FusedBatchNormV2": _fused_batch_norm,
    "FusedBatchNormV3": _fused_batch_norm,
    "Reshape": lambda i, n, c: jnp.reshape(i[0], [int(d) for d in np.asarray(i[1])]),
    "Squeeze": lambda i, n, c: jnp.squeeze(
        i[0], axis=tuple(int(d) for d in n.attr["squeeze_dims"].list.i) or None),
    "ExpandDims": lambda i, n, c: jnp.expand_dims(i[0], int(i[1])),
    "Transpose": lambda i, n, c: jnp.transpose(i[0], np.asarray(i[1]).tolist()),
    "Shape": lambda i, n, c: jnp.asarray(i[0].shape, jnp.int32),
    "Size": lambda i, n, c: jnp.asarray(i[0].size, jnp.int32),
    "Rank": lambda i, n, c: jnp.asarray(i[0].ndim, jnp.int32),
    "Fill": lambda i, n, c: jnp.full([int(d) for d in np.asarray(i[0])], i[1]),
    "Range": lambda i, n, c: jnp.arange(int(i[0]), int(i[1]), int(i[2])),
    "Tile": lambda i, n, c: jnp.tile(i[0], np.asarray(i[1]).tolist()),
    "Pack": lambda i, n, c: jnp.stack(i, axis=int(n.attr["axis"].i)),
    "Unpack": lambda i, n, c: tuple(
        jnp.moveaxis(i[0], int(n.attr["axis"].i), 0)),
    "Gather": lambda i, n, c: jnp.take(i[0], i[1].astype(jnp.int32), axis=0),
    "GatherV2": lambda i, n, c: jnp.take(i[0], i[1].astype(jnp.int32), axis=int(i[2])),
    "ConcatV2": _concat_v2,
    "Concat": _concat,
    "Split": _split,
    "Pad": _pad,
    "StridedSlice": _strided_slice,
    "Slice": lambda i, n, c: lax.dynamic_slice(
        i[0], [int(b) for b in np.asarray(i[1])],
        [int(s) if s >= 0 else int(d) - int(b) for b, s, d in
         zip(np.asarray(i[1]), np.asarray(i[2]), i[0].shape)]),
    "Cast": _cast,
    "OneHot": _one_hot,
    "ArgMax": lambda i, n, c: jnp.argmax(i[0], axis=int(i[1])),
    "ArgMin": lambda i, n, c: jnp.argmin(i[0], axis=int(i[1])),
    "TopKV2": lambda i, n, c: lax.top_k(i[0], int(i[1])),
    "Sum": _reduction(jnp.sum),
    "Mean": _reduction(jnp.mean),
    "Max": _reduction(jnp.max),
    "Min": _reduction(jnp.min),
    "Prod": _reduction(jnp.prod),
    "All": _reduction(jnp.all),
    "Any": _reduction(jnp.any),
    "ZerosLike": lambda i, n, c: jnp.zeros_like(i[0]),
    "OnesLike": lambda i, n, c: jnp.ones_like(i[0]),
    # --- long tail (reference DL/utils/tf/loaders coverage, MIGRATION.md) ---
    "ApproximateEqual": lambda i, n, c: jnp.abs(i[0] - i[1]) < _attr_f(n, "tolerance", 1e-5),
    "Digamma": lambda i, n, c: jax.scipy.special.digamma(i[0]),
    "Lgamma": lambda i, n, c: jax.scipy.special.gammaln(i[0]),
    "Erf": lambda i, n, c: jax.scipy.special.erf(i[0]),
    "Erfc": lambda i, n, c: jax.scipy.special.erfc(i[0]),
    "Expm1": lambda i, n, c: jnp.expm1(i[0]),
    "Inv": lambda i, n, c: 1.0 / i[0],
    "IsFinite": lambda i, n, c: jnp.isfinite(i[0]),
    "IsInf": lambda i, n, c: jnp.isinf(i[0]),
    "IsNan": lambda i, n, c: jnp.isnan(i[0]),
    "Mod": lambda i, n, c: jnp.mod(i[0], i[1]),
    "TruncateMod": lambda i, n, c: jnp.fmod(i[0], i[1]),
    "TruncateDiv": lambda i, n, c: jnp.trunc(i[0] / i[1]).astype(i[0].dtype)
    if jnp.issubdtype(i[0].dtype, jnp.integer) else jnp.trunc(i[0] / i[1]),
    "Rint": lambda i, n, c: jnp.round(i[0]),
    "L2Loss": lambda i, n, c: 0.5 * jnp.sum(jnp.square(i[0])),
    "TopK": lambda i, n, c: lax.top_k(i[0], int(n.attr["k"].i)),
    "InTopK": lambda i, n, c: jnp.any(
        lax.top_k(i[0], int(n.attr["k"].i))[1]
        == i[1].astype(jnp.int32)[:, None], axis=1),
    "SegmentSum": lambda i, n, c: jax.ops.segment_sum(
        i[0], i[1].astype(jnp.int32)),
    "SoftmaxCrossEntropyWithLogits": lambda i, n, c: (
        -jnp.sum(i[1] * jax.nn.log_softmax(i[0], axis=-1), axis=-1),
        i[1] - jax.nn.softmax(i[0], axis=-1),  # (loss, backprop) outputs
    ),
    "LRN": lambda i, n, c: _lrn(i, n),
    "ResizeBilinear": lambda i, n, c: _resize_bilinear(i, n),
    "Conv3D": lambda i, n, c: _conv3d(i, n),
    "Assert": lambda i, n, c: None,  # graph-mode assert: no-op at import
}


def _lrn(i, n):
    # TF LRN is NHWC cross-channel: alpha is per-element (not /size);
    # default radius 5 applies only when the attr is ABSENT (0 is valid)
    depth_radius = (int(n.attr["depth_radius"].i)
                    if "depth_radius" in n.attr else 5)
    bias = _attr_f(n, "bias", 1.0)
    alpha = _attr_f(n, "alpha", 1.0)
    beta = _attr_f(n, "beta", 0.5)
    size = 2 * depth_radius + 1
    sq = jnp.square(i[0])
    window = lax.reduce_window(
        sq, 0.0, lax.add, (1, 1, 1, size), (1, 1, 1, 1),
        [(0, 0), (0, 0), (0, 0), (depth_radius, depth_radius)])
    return i[0] / (bias + alpha * window) ** beta


def _resize_bilinear(i, n):
    """TF1 ResizeBilinear semantics: default (align_corners=False) uses
    the legacy asymmetric mapping src = dst * (src_len/dst_len);
    align_corners=True uses src = dst * (src_len-1)/(dst_len-1). Neither
    is jax.image.resize's half-pixel-center convention, so sample
    explicitly with a separable gather + lerp."""
    x = i[0]  # NHWC
    out_h, out_w = (int(v) for v in np.asarray(i[1]).reshape(-1)[:2])
    if "half_pixel_centers" in n.attr and n.attr["half_pixel_centers"].b:
        # TF2-style resize: jax.image.resize's bilinear IS half-pixel
        return jax.image.resize(x, (x.shape[0], out_h, out_w, x.shape[3]),
                                method="bilinear")
    align = bool(n.attr["align_corners"].b) if "align_corners" in n.attr \
        else False

    def src_coords(dst_len, src_len):
        d = jnp.arange(dst_len, dtype=jnp.float32)
        if align and dst_len > 1:
            return d * ((src_len - 1) / (dst_len - 1))
        return d * (src_len / dst_len)

    def lerp_axis(arr, dst_len, axis):
        src_len = arr.shape[axis]
        s = jnp.clip(src_coords(dst_len, src_len), 0, src_len - 1)
        lo = jnp.floor(s).astype(jnp.int32)
        hi = jnp.minimum(lo + 1, src_len - 1)
        frac = (s - lo).astype(arr.dtype)
        shape = [1] * arr.ndim
        shape[axis] = dst_len
        frac = frac.reshape(shape)
        return (jnp.take(arr, lo, axis=axis) * (1 - frac)
                + jnp.take(arr, hi, axis=axis) * frac)

    return lerp_axis(lerp_axis(x, out_h, 1), out_w, 2)


def _conv3d(i, n):
    strides = tuple(int(s) for s in n.attr["strides"].list.i)[1:4]
    pad = n.attr["padding"].s.decode()
    return lax.conv_general_dilated(
        i[0], i[1], strides, pad,
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))

class _TensorList:
    """A TF TensorList (while_v2's TensorArray): a fixed-size stack of
    same-shaped elements. ``buf`` is lazy — materialized as zeros on the
    first SetItem once the element shape is known (TensorListReserve's
    element_shape is usually the unknown sentinel -1)."""

    def __init__(self, buf, size: int):
        self.buf = buf
        self.size = size


def _tl_set_item(i, n, c):
    tl, idx, item = i[0], i[1], i[2]
    buf = tl.buf
    if buf is None:
        buf = jnp.zeros((tl.size,) + tuple(item.shape), item.dtype)
    idx = jnp.asarray(idx, jnp.int32)
    buf = lax.dynamic_update_slice(
        buf, item[None].astype(buf.dtype),
        (idx,) + (jnp.int32(0),) * item.ndim)
    return _TensorList(buf, tl.size)


_TL_OPS = {
    "TensorListReserve": lambda i, n, c: _TensorList(
        None, int(np.asarray(i[1]))),
    "TensorListSetItem": _tl_set_item,
    "TensorListGetItem": lambda i, n, c: lax.dynamic_index_in_dim(
        i[0].buf, jnp.asarray(i[1], jnp.int32), 0, keepdims=False),
    "TensorListStack": lambda i, n, c: i[0].buf,
    "TensorListFromTensor": lambda i, n, c: _TensorList(
        i[0], i[0].shape[0]),
    "TensorListLength": lambda i, n, c: jnp.int32(i[0].size),
}
_OPS.update(_TL_OPS)


def _eval_function(module, fdef, args, ctx):
    """Evaluate a FunctionDef (while_v2 cond/body) with positional arg
    values. Function-internal references use the ``node:port:index``
    form; bare names are signature args."""
    values: Dict[str, object] = {}
    for a, v in zip(fdef.signature.input_arg, args):
        values[a.name] = v

    def resolve(ref):
        parts = ref.split(":")
        if len(parts) == 1:
            return values[parts[0]]
        v = values[parts[0]]
        idx = int(parts[-1]) if len(parts) == 3 else 0
        return v[idx] if isinstance(v, (tuple, list)) else v

    # node_def order is NOT guaranteed topological (same reason the main
    # graph path runs _topo): order by dependencies first
    by_name = {nd.name: nd for nd in fdef.node_def}
    order, state = [], {}

    def visit(name):
        if state.get(name) == 1 or name not in by_name:
            return
        if state.get(name) == 0:
            raise ValueError(f"cycle in FunctionDef at {name!r}")
        state[name] = 0
        for r in by_name[name].input:
            if not r.startswith("^"):
                visit(r.split(":")[0])
        state[name] = 1
        order.append(name)

    for nd in fdef.node_def:
        visit(nd.name)

    for name in order:
        nd = by_name[name]
        if nd.op == "Const":
            values[nd.name] = tensor_to_numpy(nd.attr["value"].tensor)
            continue
        nd_args = [resolve(r) for r in nd.input if not r.startswith("^")]
        values[nd.name] = module._eval_op(nd, nd_args, ctx)
    return [resolve(fdef.ret[a.name]) for a in fdef.signature.output_arg]


# weights smaller than this stay inline constants; larger ones are lifted
# into the params tree
_PARAM_THRESHOLD = 32


class TFGraphModule(Module):
    """A frozen TF graph as a pure Module (reference ``Session.scala`` /
    ``TensorflowLoader``). Inputs are fed positionally in ``inputs`` order;
    ``forward`` returns the ``outputs`` values (tuple if several)."""

    def __init__(self, graph_def: "pb.GraphDef", inputs: Sequence[str],
                 outputs: Sequence[str]):
        super().__init__()
        self.graph_def = graph_def
        self.input_names = [_ref(i)[0] for i in inputs]
        self.output_refs = [_ref(o) for o in outputs]
        self.nodes: Dict[str, "pb.NodeDef"] = {n.name: n for n in graph_def.node}
        # while_v2 cond/body FunctionDefs (graph.library)
        self._functions = {f.signature.name: f
                           for f in graph_def.library.function}
        self._consts: Dict[str, np.ndarray] = {}
        self._param_names: List[str] = []
        self._var_init: Dict[str, np.ndarray] = {}
        for n in graph_def.node:
            if n.op == "Const":
                arr = tensor_to_numpy(n.attr["value"].tensor)
                if arr.size >= _PARAM_THRESHOLD and np.issubdtype(arr.dtype, np.floating):
                    self._param_names.append(n.name)
                self._consts[n.name] = arr
        # Variable nodes become trainable params (reference Session.scala
        # trains the loaded graph; frozen graphs simply have none). The
        # initial value comes from the variable's Assign(var, Const)
        # initializer when present, else zeros of the shape attr.
        by_name = {n.name: n for n in graph_def.node}

        def resolve_const(name: str, depth: int = 0):
            """Follow Identity/read chains to a Const (the standard
            tf.Variable export shape is Assign(var, Identity(Const)))."""
            if depth > 8:
                return None
            if name in self._consts:
                return self._consts[name]
            node = by_name.get(name)
            if node is not None and node.op in ("Identity", "Snapshot") and node.input:
                return resolve_const(_ref(node.input[0])[0], depth + 1)
            return None

        for n in graph_def.node:
            if n.op in ("Variable", "VariableV2"):
                init = None
                for m in graph_def.node:
                    if m.op == "Assign" and m.input and _ref(m.input[0])[0] == n.name:
                        init = resolve_const(_ref(m.input[1])[0])
                        break
                if init is None:
                    shape = [d.size for d in n.attr["shape"].shape.dim]
                    init = np.zeros(shape, np.float32)
                    import logging

                    logging.getLogger("bigdl_tpu.interop.tf").warning(
                        "variable %r has no Const-resolvable initializer; "
                        "starting from zeros (random initializer ops are "
                        "not evaluated at import)", n.name)
                self._var_init[n.name] = np.asarray(init)
        # needed set: nodes reachable from outputs
        self._order = self._topo()

    def _topo(self) -> List[str]:
        # iterative DFS: real frozen graphs (ResNets, unrolled RNNs) have
        # input chains far deeper than Python's recursion limit. Fed nodes
        # (inputs) are leaves — their ancestors are pruned, so feeding an
        # interior node (e.g. a queue-dequeue in a training graph) cuts the
        # unsupported producer subgraph away entirely.
        order: List[str] = []
        state: Dict[str, int] = {}  # 0 visiting, 1 done
        fed = set(self.input_names)
        for root, _ in self.output_refs:
            stack: List[Tuple[str, bool]] = [(root, False)]
            while stack:
                name, processed = stack.pop()
                if processed:
                    state[name] = 1
                    order.append(name)
                    continue
                st = state.get(name)
                if st == 1:
                    continue
                if st == 0:
                    raise ValueError(
                        f"cycle at node {name!r} (control flow is not "
                        "supported in frozen-graph import)")
                state[name] = 0
                stack.append((name, True))
                if name in fed:
                    continue
                for ref in self.nodes[name].input:
                    base, idx = _ref(ref)
                    if idx >= 0 and state.get(base) != 1:  # skip control deps
                        stack.append((base, False))
        return order

    def build_params(self, rng):
        p = {name.replace("/", "__"): jnp.asarray(self._consts[name])
             for name in self._param_names}
        for name, init in self._var_init.items():
            p[name.replace("/", "__")] = jnp.asarray(init)
        return p

    def _eval_op(self, node, args, ctx):
        if node.op in ("While", "StatelessWhile"):
            return self._eval_while(node, args, ctx)
        if node.op in ("PartitionedCall", "StatefulPartitionedCall"):
            fdef = self._functions[node.attr["f"].func.name]
            outs = _eval_function(self, fdef, args, ctx)
            return outs[0] if len(outs) == 1 else tuple(outs)
        fn = _OPS.get(node.op)
        if fn is None:
            raise NotImplementedError(
                f"TF op {node.op!r} (node {node.name!r}) is not supported")
        return fn(args, node, ctx)

    def _eval_while(self, node, args, ctx):
        """while_v2 (`StatelessWhile`/`While`): loop vars carry through
        ``lax.while_loop``; cond/body are FunctionDefs. Lazy TensorLists
        in the carry are materialized by running the body once OUTSIDE
        the loop purely for shape discovery — its outputs are discarded,
        so XLA dead-code-eliminates that probe entirely."""
        body = self._functions[node.attr["body"].func.name]
        cond = self._functions[node.attr["cond"].func.name]
        carry = list(args)
        if any(isinstance(v, _TensorList) and v.buf is None for v in carry):
            probe = _eval_function(self, body, carry, ctx)
            for k, v in enumerate(carry):
                if isinstance(v, _TensorList) and v.buf is None:
                    pv = probe[k]
                    if not isinstance(pv, _TensorList) or pv.buf is None:
                        raise ValueError(
                            f"cannot infer element shape of TensorList loop "
                            f"var {k} of {node.name!r}: the loop body never "
                            "writes it")
                    carry[k] = _TensorList(
                        jnp.zeros(pv.buf.shape, pv.buf.dtype), v.size)
        kinds = [v.size if isinstance(v, _TensorList) else None
                 for v in carry]

        def pack(c):
            return tuple(v.buf if isinstance(v, _TensorList)
                         else jnp.asarray(v) for v in c)

        def unpack(t):
            return [_TensorList(b, k) if k is not None else b
                    for b, k in zip(t, kinds)]

        out = lax.while_loop(
            lambda c: jnp.asarray(
                _eval_function(self, cond, unpack(list(c)), ctx)[0]
            ).reshape(()),
            lambda c: pack(_eval_function(self, body, unpack(list(c)), ctx)),
            pack(carry))
        return tuple(unpack(out))

    def forward(self, ctx: Context, x):
        xs = (x,) if len(self.input_names) == 1 else tuple(x)
        if len(xs) != len(self.input_names):
            raise ValueError(
                f"expected {len(self.input_names)} inputs, got {len(xs)}")
        values: Dict[str, object] = {}
        for name, xi in zip(self.input_names, xs):
            values[name] = xi
        param_set = set(self._param_names)
        for name in self._order:
            if name in values:
                continue
            node = self.nodes[name]
            if node.op == "Const":
                if name in param_set:
                    values[name] = ctx.param(name.replace("/", "__"))
                else:
                    values[name] = self._consts[name]
                continue
            if node.op in ("Variable", "VariableV2"):
                values[name] = ctx.param(name.replace("/", "__"))
                continue
            if node.op in ("Placeholder", "PlaceholderWithDefault") and not node.input:
                raise ValueError(
                    f"placeholder {name!r} was not listed in inputs")
            args = []
            for ref in node.input:
                base, idx = _ref(ref)
                if idx < 0:
                    continue
                v = values[base]
                args.append(v[idx] if isinstance(v, (tuple, list)) else v)
            values[name] = self._eval_op(node, args, ctx)
        outs = []
        for base, idx in self.output_refs:
            v = values[base]
            outs.append(v[idx] if isinstance(v, (tuple, list)) else v)
        return outs[0] if len(outs) == 1 else tuple(outs)


class TensorflowLoader:
    """Reference ``TensorflowLoader.scala:43``."""

    @staticmethod
    def parse(path: str) -> "pb.GraphDef":
        g = pb.GraphDef()
        with open(path, "rb") as f:
            g.ParseFromString(f.read())
        return g

    @staticmethod
    def load(path: str, inputs: Sequence[str], outputs: Sequence[str]):
        """Returns ``(module, params, state)`` for a frozen GraphDef file."""
        module = TFGraphModule(TensorflowLoader.parse(path), inputs, outputs)
        params, state = module.init(jax.random.key(0))
        return module, params, state


def load_tf_graph(path: str, inputs: Sequence[str], outputs: Sequence[str]):
    return TensorflowLoader.load(path, inputs, outputs)


class TFSession:
    """Minimal Session.run over a frozen graph (reference
    ``DL/utils/tf/Session.scala:43`` BigDLSessionImpl; queue-runner input
    emulation is out of scope — feed host arrays directly)."""

    def __init__(self, graph_def_or_path, jit: bool = True):
        if isinstance(graph_def_or_path, str):
            self.graph_def = TensorflowLoader.parse(graph_def_or_path)
        else:
            self.graph_def = graph_def_or_path
        self._jit = jit
        self._cache: Dict[Tuple, Tuple] = {}

    def run(self, fetches: Sequence[str], feed_dict: Dict[str, np.ndarray]):
        feeds = list(feed_dict.keys())
        key = (tuple(fetches), tuple(feeds))
        if key not in self._cache:
            module = TFGraphModule(self.graph_def, feeds, fetches)
            params, _ = module.init(jax.random.key(0))
            fn = (lambda p, *xs: module.apply(p, xs if len(xs) > 1 else xs[0])[0])
            self._cache[key] = (jax.jit(fn) if self._jit else fn, params)
        fn, params = self._cache[key]
        out = fn(params, *[jnp.asarray(v) for v in feed_dict.values()])
        return [np.asarray(o) for o in (out if isinstance(out, tuple) else (out,))]

    def train(self, inputs: Sequence[str], loss_node: str, data,
              optim_method=None, n_steps: int = 100, batch_size: int = 32,
              steps_per_epoch: Optional[int] = None):
        """Train the graph's Variable nodes (reference
        ``BigDLSessionImpl.train``, ``Session.scala:111-132`` — which
        emulates the graph's queue runners to feed it; here the host
        arrays/iterator feed the jitted step directly, the TPU-native
        input path).

        ``inputs``: placeholder names, ``loss_node``: scalar loss output,
        ``data``: tuple of arrays (batched round-robin) or an iterator of
        per-step feed tuples. Returns (module, trained_params).
        """
        from bigdl_tpu.optim.optim_method import SGD

        method = optim_method or SGD(learning_rate=0.01)
        module = TFGraphModule(self.graph_def, list(inputs), [loss_node])
        if not module._var_init:
            raise ValueError("graph has no Variable nodes to train "
                             "(frozen graph? use run() for inference)")
        params, _ = module.init(jax.random.key(0))
        ostate = method.init_state(params)

        @jax.jit
        def step(params, ostate, epoch, *feeds):
            def loss_fn(p):
                out, _ = module.apply(p, feeds if len(feeds) > 1 else feeds[0])
                return jnp.asarray(out, jnp.float32).sum()

            loss, grads = jax.value_and_grad(loss_fn)(params)
            new_p, new_os = method.update(grads, params, ostate, epoch)
            return new_p, new_os, loss

        if isinstance(data, (tuple, list)):
            arrays = [np.asarray(a) for a in data]
            n = arrays[0].shape[0]

            def batches():
                i = 0
                while True:
                    idx = [(i + k) % n for k in range(batch_size)]
                    yield tuple(a[idx] for a in arrays)
                    i = (i + batch_size) % n
            it = batches()
        else:
            it = iter(data)
        loss = None
        for i in range(n_steps):
            feeds = next(it)
            epoch = jnp.int32(i // steps_per_epoch + 1 if steps_per_epoch else 1)
            params, ostate, loss = step(params, ostate, epoch,
                                        *map(jnp.asarray, feeds))
        return module, params, (None if loss is None else float(loss))
