"""TensorFlow GraphDef importer.

Reference: ``DL/utils/tf/TensorflowLoader.scala:43`` — parse a (frozen)
GraphDef, map nodes to BigDL modules via 161 per-op loader classes, build a
Graph. ``DL/utils/tf/Session.scala:43`` drives a loaded graph.

TPU-native redesign: instead of pattern-matching TF subgraphs onto a layer
zoo (the reference needs this because its layers own their backward), the
importer evaluates the GraphDef **node by node as a pure jax function** —
each op maps to a jnp/lax expression, the whole graph jits into one XLA
program, and autodiff works through it for free. Large ``Const`` tensors
(the frozen weights) are lifted into the params pytree so they behave like
ordinary module parameters (donation, sharding, checkpointing).

``TFGraphModule`` is a regular :class:`Module`: ``load_tf_graph(pb_path,
inputs=[...], outputs=[...])`` then ``model.apply(params, x)``.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.interop.tf import tensorflow_pb2 as pb
from bigdl_tpu.nn.module import Context, Module

_NP_DTYPES = {
    pb.DT_FLOAT: np.float32,
    pb.DT_DOUBLE: np.float64,
    pb.DT_INT32: np.int32,
    pb.DT_UINT8: np.uint8,
    pb.DT_INT16: np.int16,
    pb.DT_INT8: np.int8,
    pb.DT_INT64: np.int64,
    pb.DT_BOOL: np.bool_,
    pb.DT_HALF: np.float16,
    pb.DT_BFLOAT16: None,  # handled explicitly (ml_dtypes)
    pb.DT_UINT16: np.uint16,
    pb.DT_UINT32: np.uint32,
    pb.DT_UINT64: np.uint64,
}


def tensor_to_numpy(t: "pb.TensorProto") -> np.ndarray:
    shape = [int(d.size) for d in t.tensor_shape.dim]
    if t.dtype == pb.DT_STRING:
        # string consts appear in training graphs (Assert messages, reader
        # patterns); keep them as object arrays so import doesn't choke
        vals = list(t.string_val)
        n = int(np.prod(shape)) if shape else 1
        if len(vals) < n:  # trailing-repeat compression (TF MakeNdarray)
            vals = vals + [vals[-1] if vals else b""] * (n - len(vals))
        arr = np.empty(len(vals), dtype=object)
        arr[:] = vals
        return arr.reshape(shape) if shape else arr.reshape(())
    if t.dtype == pb.DT_BFLOAT16:
        import ml_dtypes

        dt = ml_dtypes.bfloat16
    else:
        dt = _NP_DTYPES.get(t.dtype)
        if dt is None:
            raise ValueError(f"unsupported TensorProto dtype {t.dtype}")
    if t.tensor_content:
        arr = np.frombuffer(t.tensor_content, dtype=dt)
        return arr.reshape(shape) if shape else arr.reshape(())
    for field in ("float_val", "double_val", "int_val", "int64_val", "bool_val"):
        vals = getattr(t, field)
        if len(vals):
            arr = np.asarray(list(vals), dtype=dt)
            n = int(np.prod(shape)) if shape else 1
            if arr.size == 1 and n > 1:  # splat encoding
                arr = np.full(n, arr[0], dtype=dt)
            return arr.reshape(shape)
    return np.zeros(shape, dtype=dt)


def numpy_to_tensor(arr: np.ndarray) -> "pb.TensorProto":
    arr = np.asarray(arr)
    rev = {v: k for k, v in _NP_DTYPES.items() if v is not None}
    t = pb.TensorProto()
    if arr.dtype.name == "bfloat16":
        t.dtype = pb.DT_BFLOAT16
    else:
        t.dtype = rev.get(arr.dtype.type, pb.DT_FLOAT)
    for d in arr.shape:
        t.tensor_shape.dim.add().size = d
    t.tensor_content = np.ascontiguousarray(arr).tobytes()
    return t


def _ref(name: str) -> Tuple[str, int]:
    """'node:2' -> ('node', 2); control inputs '^node' -> ('node', -1)."""
    if name.startswith("^"):
        return name[1:], -1
    if ":" in name:
        base, idx = name.rsplit(":", 1)
        return base, int(idx)
    return name, 0


def _nhwc_pool_args(node):
    ksize = list(node.attr["ksize"].list.i)
    strides = list(node.attr["strides"].list.i)
    padding = node.attr["padding"].s.decode()
    fmt = node.attr["data_format"].s.decode() or "NHWC"
    return ksize, strides, padding, fmt


# ---------------------------------------------------------------- op set
# Each op: fn(inputs: list, node: NodeDef, ctx) -> output (or tuple).

def _conv2d(inp, node, ctx):
    x, w = inp  # x NHWC (or NCHW), w HWIO
    strides = list(node.attr["strides"].list.i)
    padding = node.attr["padding"].s.decode()
    fmt = node.attr["data_format"].s.decode() or "NHWC"
    dil = list(node.attr["dilations"].list.i) or [1, 1, 1, 1]
    if fmt == "NHWC":
        dn = ("NHWC", "HWIO", "NHWC")
        window_strides = strides[1:3]
        rhs_dil = dil[1:3]
    else:
        dn = ("NCHW", "HWIO", "NCHW")
        window_strides = strides[2:4]
        rhs_dil = dil[2:4]
    return lax.conv_general_dilated(
        x, w, window_strides, padding, rhs_dilation=rhs_dil, dimension_numbers=dn)


def _depthwise_conv2d(inp, node, ctx):
    x, w = inp  # w (kh, kw, in, multiplier)
    strides = list(node.attr["strides"].list.i)
    padding = node.attr["padding"].s.decode()
    kh, kw, cin, mult = w.shape
    w2 = w.reshape(kh, kw, 1, cin * mult)
    return lax.conv_general_dilated(
        x, w2, strides[1:3], padding, dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=cin)


def _conv2d_backprop_input(inp, node, ctx):
    """Forward deconvolution: ``tf.nn.conv2d_transpose`` emits this op as
    its FORWARD computation (it is only a "gradient op" when autodiff
    authored it — those subgraphs are never imported here). Semantics =
    transposed conv with the true conv's padding geometry."""
    out_sizes, w, dy = inp  # (input_sizes, filter HWIO (h,w,out,in), dy)
    strides = list(node.attr["strides"].list.i)
    padding = node.attr["padding"].s.decode()
    fmt = node.attr["data_format"].s.decode() or "NHWC"
    if fmt != "NHWC":
        raise NotImplementedError("Conv2DBackpropInput NCHW")
    dil = list(node.attr["dilations"].list.i) or [1, 1, 1, 1]
    if any(d != 1 for d in dil):
        raise NotImplementedError(
            f"dilated conv2d_transpose at {node.name!r} (dilations {dil})")
    y = lax.conv_transpose(
        dy, w, tuple(strides[1:3]), padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"), transpose_kernel=True)
    want = tuple(int(d) for d in np.asarray(out_sizes).reshape(-1))
    if tuple(y.shape) != want:
        raise ValueError(
            f"conv2d_transpose shape mismatch at {node.name!r}: produced "
            f"{tuple(y.shape)}, graph expects {want} (odd output_shape "
            "geometry not representable by lax.conv_transpose)")
    return y


def _bias_add(inp, node, ctx):
    x, b = inp
    fmt = node.attr["data_format"].s.decode() or "NHWC"
    if fmt == "NCHW" and x.ndim == 4:
        return x + b[None, :, None, None]
    return x + b


def _max_pool(inp, node, ctx):
    (x,) = inp
    ksize, strides, padding, fmt = _nhwc_pool_args(node)
    init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
    return lax.reduce_window(x, init, lax.max, tuple(ksize), tuple(strides), padding)


def _avg_pool(inp, node, ctx):
    (x,) = inp
    ksize, strides, padding, fmt = _nhwc_pool_args(node)
    s = lax.reduce_window(x, 0.0, lax.add, tuple(ksize), tuple(strides), padding)
    ones = jnp.ones(x.shape, x.dtype)
    n = lax.reduce_window(ones, 0.0, lax.add, tuple(ksize), tuple(strides), padding)
    return s / n


def _attr_f(node, name, default):
    """Float attr with explicit-presence check (0.0 is a legal value)."""
    return float(node.attr[name].f) if name in node.attr else default


def _fused_batch_norm(inp, node, ctx):
    x, scale, offset, mean, var = inp
    eps = _attr_f(node, "epsilon", 1e-3)
    fmt = node.attr["data_format"].s.decode() or "NHWC"
    if len(mean) == 0:  # training-mode graphs carry empty mean/var
        axes = (0, 1, 2) if fmt == "NHWC" else (0, 2, 3)
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
    inv = lax.rsqrt(var + eps) * scale
    shift = offset - mean * inv
    if fmt == "NCHW":
        y = x * inv[None, :, None, None] + shift[None, :, None, None]
    else:
        y = x * inv + shift
    return y, mean, var, mean, var  # (y, batch_mean, batch_var, r1, r2)


def _matmul(inp, node, ctx):
    a, b = inp
    if node.attr["transpose_a"].b:
        a = a.T
    if node.attr["transpose_b"].b:
        b = b.T
    return a @ b


def _batch_matmul(inp, node, ctx):
    a, b = inp
    if node.attr["adj_x"].b:
        a = jnp.swapaxes(a, -1, -2)
    if node.attr["adj_y"].b:
        b = jnp.swapaxes(b, -1, -2)
    return jnp.matmul(a, b)


def _concat_v2(inp, node, ctx):
    *xs, axis = inp
    return jnp.concatenate(xs, axis=int(axis))


def _concat(inp, node, ctx):
    axis, *xs = inp
    return jnp.concatenate(xs, axis=int(axis))


def _split(inp, node, ctx):
    axis, x = inp
    n = int(node.attr["num_split"].i)
    return tuple(jnp.split(x, n, axis=int(axis)))


def _pad(inp, node, ctx):
    x, paddings = inp
    pads = [(int(a), int(b)) for a, b in np.asarray(paddings)]
    return jnp.pad(x, pads)

def _strided_slice(inp, node, ctx):
    x, begin, end, strides = inp
    if any(isinstance(v, jax.core.Tracer) for v in (begin, end, strides)):
        return _strided_slice_dynamic(inp, node)
    begin, end, strides = (np.asarray(v).tolist() for v in (begin, end, strides))
    bm = int(node.attr["begin_mask"].i)
    em = int(node.attr["end_mask"].i)
    sm = int(node.attr["shrink_axis_mask"].i)
    nm = int(node.attr["new_axis_mask"].i)
    elm = int(node.attr["ellipsis_mask"].i)
    if nm:
        raise NotImplementedError("StridedSlice new_axis_mask")
    if elm:
        raise NotImplementedError("StridedSlice ellipsis_mask")
    idx = []
    for ax in range(len(begin)):
        if sm & (1 << ax):
            idx.append(int(begin[ax]))
            continue
        b = None if bm & (1 << ax) else int(begin[ax])
        e = None if em & (1 << ax) else int(end[ax])
        idx.append(slice(b, e, int(strides[ax])))
    return x[tuple(idx)]


def _strided_slice_dynamic(inp, node):
    """StridedSlice with loop-variable indices (the pattern while_v2
    bodies emit for ``x[:, t]``): lax.dynamic_slice with unit strides.
    Each sliced axis keeps its static extent unless masked out; a
    shrink axis takes one element at the dynamic index and squeezes."""
    x, begin, end, strides = inp
    bm = int(node.attr["begin_mask"].i)
    em = int(node.attr["end_mask"].i)
    sm = int(node.attr["shrink_axis_mask"].i)
    if int(node.attr["new_axis_mask"].i) or int(node.attr["ellipsis_mask"].i):
        raise NotImplementedError("dynamic StridedSlice with axis masks")
    if not isinstance(strides, jax.core.Tracer) and \
            not all(int(s) == 1 for s in np.asarray(strides).reshape(-1)):
        raise NotImplementedError("dynamic StridedSlice with strides != 1")
    n = begin.shape[0] if hasattr(begin, "shape") else len(begin)
    starts, sizes, squeeze = [], [], []
    for ax in range(x.ndim):
        if ax >= n:
            starts.append(0)
            sizes.append(x.shape[ax])
            continue
        b = begin[ax]
        if sm & (1 << ax):
            starts.append(b)
            sizes.append(1)
            squeeze.append(ax)
        elif (bm & (1 << ax)) and (em & (1 << ax)):
            starts.append(0)
            sizes.append(x.shape[ax])
        else:
            raise NotImplementedError(
                "dynamic StridedSlice with partial static bounds")
    starts = [s.astype(jnp.int32) if hasattr(s, "astype") else jnp.int32(s)
              for s in starts]
    y = lax.dynamic_slice(x, starts, sizes)
    return jnp.squeeze(y, axis=tuple(squeeze)) if squeeze else y


def _cast(inp, node, ctx):
    (x,) = inp
    dst = node.attr["DstT"].type
    if dst == pb.DT_BFLOAT16:
        return x.astype(jnp.bfloat16)
    return x.astype(_NP_DTYPES[dst])


def _one_hot(inp, node, ctx):
    indices, depth, on, off = inp
    return jax.nn.one_hot(indices, int(depth)) * (on - off) + off


def _reduction(fn):
    def op(inp, node, ctx):
        x, axes = inp
        axes = tuple(np.asarray(axes).reshape(-1).tolist())
        return fn(x, axis=axes or None, keepdims=bool(node.attr["keep_dims"].b))
    return op


_OPS: Dict[str, Callable] = {
    "Const": None,        # handled in build
    "Placeholder": None,  # handled in build
    "PlaceholderWithDefault": lambda i, n, c: i[0],
    "Identity": lambda i, n, c: i[0],
    "StopGradient": lambda i, n, c: lax.stop_gradient(i[0]),
    "NoOp": lambda i, n, c: None,
    "Add": lambda i, n, c: i[0] + i[1],
    "AddV2": lambda i, n, c: i[0] + i[1],
    "AddN": lambda i, n, c: sum(i[1:], i[0]),
    "Sub": lambda i, n, c: i[0] - i[1],
    "Mul": lambda i, n, c: i[0] * i[1],
    "Div": lambda i, n, c: i[0] / i[1],
    "RealDiv": lambda i, n, c: i[0] / i[1],
    "FloorDiv": lambda i, n, c: i[0] // i[1],
    "FloorMod": lambda i, n, c: i[0] % i[1],
    "Pow": lambda i, n, c: i[0] ** i[1],
    "SquaredDifference": lambda i, n, c: (i[0] - i[1]) ** 2,
    "Maximum": lambda i, n, c: jnp.maximum(i[0], i[1]),
    "Minimum": lambda i, n, c: jnp.minimum(i[0], i[1]),
    "Neg": lambda i, n, c: -i[0],
    "Abs": lambda i, n, c: jnp.abs(i[0]),
    "Square": lambda i, n, c: jnp.square(i[0]),
    "Sqrt": lambda i, n, c: jnp.sqrt(i[0]),
    "Rsqrt": lambda i, n, c: lax.rsqrt(i[0]),
    "Exp": lambda i, n, c: jnp.exp(i[0]),
    "Log": lambda i, n, c: jnp.log(i[0]),
    "Log1p": lambda i, n, c: jnp.log1p(i[0]),
    "Tanh": lambda i, n, c: jnp.tanh(i[0]),
    "Sigmoid": lambda i, n, c: jax.nn.sigmoid(i[0]),
    "Relu": lambda i, n, c: jax.nn.relu(i[0]),
    "Relu6": lambda i, n, c: jnp.clip(i[0], 0, 6),
    "Elu": lambda i, n, c: jax.nn.elu(i[0]),
    "Selu": lambda i, n, c: jax.nn.selu(i[0]),
    "Softplus": lambda i, n, c: jax.nn.softplus(i[0]),
    "Softsign": lambda i, n, c: jax.nn.soft_sign(i[0]),
    "LeakyRelu": lambda i, n, c: jax.nn.leaky_relu(
        i[0], negative_slope=_attr_f(n, "alpha", 0.2)),
    "Softmax": lambda i, n, c: jax.nn.softmax(i[0], axis=-1),
    "LogSoftmax": lambda i, n, c: jax.nn.log_softmax(i[0], axis=-1),
    "Sin": lambda i, n, c: jnp.sin(i[0]),
    "Cos": lambda i, n, c: jnp.cos(i[0]),
    "Floor": lambda i, n, c: jnp.floor(i[0]),
    "Ceil": lambda i, n, c: jnp.ceil(i[0]),
    "Round": lambda i, n, c: jnp.round(i[0]),
    "Sign": lambda i, n, c: jnp.sign(i[0]),
    "Reciprocal": lambda i, n, c: 1.0 / i[0],
    "Greater": lambda i, n, c: i[0] > i[1],
    "GreaterEqual": lambda i, n, c: i[0] >= i[1],
    "Less": lambda i, n, c: i[0] < i[1],
    "LessEqual": lambda i, n, c: i[0] <= i[1],
    "Equal": lambda i, n, c: i[0] == i[1],
    "NotEqual": lambda i, n, c: i[0] != i[1],
    "LogicalAnd": lambda i, n, c: jnp.logical_and(i[0], i[1]),
    "LogicalOr": lambda i, n, c: jnp.logical_or(i[0], i[1]),
    "LogicalNot": lambda i, n, c: jnp.logical_not(i[0]),
    "Select": lambda i, n, c: jnp.where(i[0], i[1], i[2]),
    "SelectV2": lambda i, n, c: jnp.where(i[0], i[1], i[2]),
    # v1 cond-style Switch (outside while frames): both ports carry the
    # value — both branches are computed and the paired Merge selects
    # (reference executes these dynamically, ControlOps.scala:65; here the
    # lowering is compute-both + select, and XLA DCEs the unused side of
    # ops the select doesn't need)
    "Switch": lambda i, n, c: (i[0], i[0]),
    "RefSwitch": lambda i, n, c: (i[0], i[0]),
    "MatMul": _matmul,
    "BatchMatMul": _batch_matmul,
    "BatchMatMulV2": _batch_matmul,
    "Conv2D": _conv2d,
    "Conv2DBackpropInput": _conv2d_backprop_input,
    "DepthwiseConv2dNative": _depthwise_conv2d,
    "BiasAdd": _bias_add,
    "MaxPool": _max_pool,
    "AvgPool": _avg_pool,
    "FusedBatchNorm": _fused_batch_norm,
    "FusedBatchNormV2": _fused_batch_norm,
    "FusedBatchNormV3": _fused_batch_norm,
    "Reshape": lambda i, n, c: jnp.reshape(i[0], [int(d) for d in np.asarray(i[1])]),
    "Squeeze": lambda i, n, c: jnp.squeeze(
        i[0], axis=tuple(int(d) for d in n.attr["squeeze_dims"].list.i) or None),
    "ExpandDims": lambda i, n, c: jnp.expand_dims(i[0], int(i[1])),
    "Transpose": lambda i, n, c: jnp.transpose(i[0], np.asarray(i[1]).tolist()),
    "Shape": lambda i, n, c: jnp.asarray(i[0].shape, jnp.int32),
    "Size": lambda i, n, c: jnp.asarray(i[0].size, jnp.int32),
    "Rank": lambda i, n, c: jnp.asarray(i[0].ndim, jnp.int32),
    "Fill": lambda i, n, c: jnp.full([int(d) for d in np.asarray(i[0])], i[1]),
    "Range": lambda i, n, c: jnp.arange(int(i[0]), int(i[1]), int(i[2])),
    "Tile": lambda i, n, c: jnp.tile(i[0], np.asarray(i[1]).tolist()),
    "Pack": lambda i, n, c: jnp.stack(i, axis=int(n.attr["axis"].i)),
    "Unpack": lambda i, n, c: tuple(
        jnp.moveaxis(i[0], int(n.attr["axis"].i), 0)),
    "Gather": lambda i, n, c: jnp.take(i[0], i[1].astype(jnp.int32), axis=0),
    "GatherV2": lambda i, n, c: jnp.take(i[0], i[1].astype(jnp.int32), axis=int(i[2])),
    "ConcatV2": _concat_v2,
    "Concat": _concat,
    "Split": _split,
    "Pad": _pad,
    "StridedSlice": _strided_slice,
    "Slice": lambda i, n, c: lax.dynamic_slice(
        i[0], [int(b) for b in np.asarray(i[1])],
        [int(s) if s >= 0 else int(d) - int(b) for b, s, d in
         zip(np.asarray(i[1]), np.asarray(i[2]), i[0].shape)]),
    "Cast": _cast,
    "OneHot": _one_hot,
    "ArgMax": lambda i, n, c: jnp.argmax(i[0], axis=int(i[1])),
    "ArgMin": lambda i, n, c: jnp.argmin(i[0], axis=int(i[1])),
    "TopKV2": lambda i, n, c: lax.top_k(i[0], int(i[1])),
    "Sum": _reduction(jnp.sum),
    "Mean": _reduction(jnp.mean),
    "Max": _reduction(jnp.max),
    "Min": _reduction(jnp.min),
    "Prod": _reduction(jnp.prod),
    "All": _reduction(jnp.all),
    "Any": _reduction(jnp.any),
    "ZerosLike": lambda i, n, c: jnp.zeros_like(i[0]),
    "OnesLike": lambda i, n, c: jnp.ones_like(i[0]),
    # --- long tail (reference DL/utils/tf/loaders coverage, MIGRATION.md) ---
    "ApproximateEqual": lambda i, n, c: jnp.abs(i[0] - i[1]) < _attr_f(n, "tolerance", 1e-5),
    "Digamma": lambda i, n, c: jax.scipy.special.digamma(i[0]),
    "Lgamma": lambda i, n, c: jax.scipy.special.gammaln(i[0]),
    "Erf": lambda i, n, c: jax.scipy.special.erf(i[0]),
    "Erfc": lambda i, n, c: jax.scipy.special.erfc(i[0]),
    "Expm1": lambda i, n, c: jnp.expm1(i[0]),
    "Inv": lambda i, n, c: 1.0 / i[0],
    "IsFinite": lambda i, n, c: jnp.isfinite(i[0]),
    "IsInf": lambda i, n, c: jnp.isinf(i[0]),
    "IsNan": lambda i, n, c: jnp.isnan(i[0]),
    "Mod": lambda i, n, c: jnp.mod(i[0], i[1]),
    "TruncateMod": lambda i, n, c: jnp.fmod(i[0], i[1]),
    "TruncateDiv": lambda i, n, c: jnp.trunc(i[0] / i[1]).astype(i[0].dtype)
    if jnp.issubdtype(i[0].dtype, jnp.integer) else jnp.trunc(i[0] / i[1]),
    "Rint": lambda i, n, c: jnp.round(i[0]),
    "L2Loss": lambda i, n, c: 0.5 * jnp.sum(jnp.square(i[0])),
    "TopK": lambda i, n, c: lax.top_k(i[0], int(n.attr["k"].i)),
    "InTopK": lambda i, n, c: jnp.any(
        lax.top_k(i[0], int(n.attr["k"].i))[1]
        == i[1].astype(jnp.int32)[:, None], axis=1),
    "SegmentSum": lambda i, n, c: jax.ops.segment_sum(
        i[0], i[1].astype(jnp.int32)),
    "SoftmaxCrossEntropyWithLogits": lambda i, n, c: (
        -jnp.sum(i[1] * jax.nn.log_softmax(i[0], axis=-1), axis=-1),
        i[1] - jax.nn.softmax(i[0], axis=-1),  # (loss, backprop) outputs
    ),
    "LRN": lambda i, n, c: _lrn(i, n),
    "ResizeBilinear": lambda i, n, c: _resize_bilinear(i, n),
    "Conv3D": lambda i, n, c: _conv3d(i, n),
    "Assert": lambda i, n, c: None,  # graph-mode assert: no-op at import
}


def _lrn(i, n):
    # TF LRN is NHWC cross-channel: alpha is per-element (not /size);
    # default radius 5 applies only when the attr is ABSENT (0 is valid)
    depth_radius = (int(n.attr["depth_radius"].i)
                    if "depth_radius" in n.attr else 5)
    bias = _attr_f(n, "bias", 1.0)
    alpha = _attr_f(n, "alpha", 1.0)
    beta = _attr_f(n, "beta", 0.5)
    size = 2 * depth_radius + 1
    sq = jnp.square(i[0])
    window = lax.reduce_window(
        sq, 0.0, lax.add, (1, 1, 1, size), (1, 1, 1, 1),
        [(0, 0), (0, 0), (0, 0), (depth_radius, depth_radius)])
    return i[0] / (bias + alpha * window) ** beta


def _resize_bilinear(i, n):
    """TF1 ResizeBilinear semantics: default (align_corners=False) uses
    the legacy asymmetric mapping src = dst * (src_len/dst_len);
    align_corners=True uses src = dst * (src_len-1)/(dst_len-1). Neither
    is jax.image.resize's half-pixel-center convention, so sample
    explicitly with a separable gather + lerp."""
    x = i[0]  # NHWC
    out_h, out_w = (int(v) for v in np.asarray(i[1]).reshape(-1)[:2])
    if "half_pixel_centers" in n.attr and n.attr["half_pixel_centers"].b:
        # TF2-style resize: jax.image.resize's bilinear IS half-pixel
        return jax.image.resize(x, (x.shape[0], out_h, out_w, x.shape[3]),
                                method="bilinear")
    align = bool(n.attr["align_corners"].b) if "align_corners" in n.attr \
        else False

    def src_coords(dst_len, src_len):
        d = jnp.arange(dst_len, dtype=jnp.float32)
        if align and dst_len > 1:
            return d * ((src_len - 1) / (dst_len - 1))
        return d * (src_len / dst_len)

    def lerp_axis(arr, dst_len, axis):
        src_len = arr.shape[axis]
        s = jnp.clip(src_coords(dst_len, src_len), 0, src_len - 1)
        lo = jnp.floor(s).astype(jnp.int32)
        hi = jnp.minimum(lo + 1, src_len - 1)
        frac = (s - lo).astype(arr.dtype)
        shape = [1] * arr.ndim
        shape[axis] = dst_len
        frac = frac.reshape(shape)
        return (jnp.take(arr, lo, axis=axis) * (1 - frac)
                + jnp.take(arr, hi, axis=axis) * frac)

    return lerp_axis(lerp_axis(x, out_h, 1), out_w, 2)


def _conv3d(i, n):
    strides = tuple(int(s) for s in n.attr["strides"].list.i)[1:4]
    pad = n.attr["padding"].s.decode()
    return lax.conv_general_dilated(
        i[0], i[1], strides, pad,
        dimension_numbers=("NDHWC", "DHWIO", "NDHWC"))

class _TensorList:
    """A TF TensorList (while_v2's TensorArray): a fixed-size stack of
    same-shaped elements. ``buf`` is lazy — materialized as zeros on the
    first SetItem once the element shape is known (TensorListReserve's
    element_shape is usually the unknown sentinel -1).

    Also used as the *flow* value of a v1 ``TensorArrayV3``; ``ragged``
    holds variable-length elements (TensorArraySplitV3 only — those never
    ride a loop carry)."""

    def __init__(self, buf, size: int, ragged=None):
        self.buf = buf
        self.size = size
        self.ragged = ragged


def _buf_write(tl, idx, item, dtype=None):
    """One-element write into a (possibly lazy) TensorList/TensorArray
    buffer; materializes zeros of the element shape on first write."""
    buf = tl.buf
    if buf is None:
        buf = jnp.zeros((tl.size,) + tuple(item.shape), dtype or item.dtype)
    idx = jnp.asarray(idx, jnp.int32)
    buf = lax.dynamic_update_slice(
        buf, item[None].astype(buf.dtype),
        (idx,) + (jnp.int32(0),) * item.ndim)
    return _TensorList(buf, tl.size)


def _tl_set_item(i, n, c):
    return _buf_write(i[0], i[1], i[2])


def _tl_buf(tl, node):
    """The materialized buffer of a TensorList/TensorArray flow; reads
    before any write have no element shape to materialize from."""
    if tl.ragged is not None:
        raise NotImplementedError(
            f"ragged TensorArray (from SplitV3) read as dense at node "
            f"{node.name!r}; only ConcatV3 accepts ragged arrays")
    if tl.buf is None:
        raise ValueError(
            f"TensorList/TensorArray at node {node.name!r} is read before "
            "any element was written: the element shape is unknown "
            "(a reserve-then-read-only list cannot be materialized)")
    return tl.buf


_TL_OPS = {
    "TensorListReserve": lambda i, n, c: _TensorList(
        None, int(np.asarray(i[1]))),
    "TensorListSetItem": _tl_set_item,
    "TensorListGetItem": lambda i, n, c: lax.dynamic_index_in_dim(
        _tl_buf(i[0], n), jnp.asarray(i[1], jnp.int32), 0, keepdims=False),
    "TensorListStack": lambda i, n, c: _tl_buf(i[0], n),
    "TensorListFromTensor": lambda i, n, c: _TensorList(
        i[0], i[0].shape[0]),
    "TensorListLength": lambda i, n, c: jnp.int32(i[0].size),
}
_OPS.update(_TL_OPS)


# ------------------------------------------------- v1 TensorArray (V3 ops)
# Reference: ``DL/nn/tf/DataFlowOps.scala:45-293`` (TensorArrayCreator /
# Write / Read / Gather / Scatter / Split / Concat / Size). The reference
# keeps a mutable per-frame array store; here the TensorArray's *flow*
# output carries the buffer as a :class:`_TensorList` — TF already threads
# the flow through Enter/Merge/Switch/NextIteration as a loop variable
# precisely to order reads after writes, so a buffer riding the flow turns
# in-loop writes into ordinary functional carry updates.

class _TAHandle:
    """Static metadata of a TensorArrayV3 (the DT_RESOURCE handle output);
    the data lives on the flow value."""

    def __init__(self, size: int, dtype):
        self.size = size
        self.dtype = dtype


def _ta_create(i, n, c):
    size = int(np.asarray(i[0]))
    dt = n.attr["dtype"].type
    dtype = jnp.bfloat16 if dt == pb.DT_BFLOAT16 else _NP_DTYPES.get(dt, np.float32)
    return _TAHandle(size, dtype), _TensorList(None, size)


def _ta_write(i, n, c):
    handle, idx, val, flow = i
    return _buf_write(flow, idx, val, dtype=handle.dtype)


def _ta_scatter(i, n, c):
    handle, indices, val, flow = i
    buf = flow.buf
    if buf is None:
        buf = jnp.zeros((flow.size,) + tuple(val.shape[1:]), handle.dtype)
    idx = jnp.asarray(indices, jnp.int32)
    return _TensorList(buf.at[idx].set(val.astype(buf.dtype)), flow.size)


def _ta_split(i, n, c):
    _handle, val, lengths, flow = i
    lens = [int(v) for v in np.asarray(lengths).reshape(-1)]
    elems, off = [], 0
    for ln in lens:
        elems.append(val[off:off + ln])
        off += ln
    return _TensorList(None, len(elems), ragged=elems)


def _ta_concat(i, n, c):
    _handle, flow = i
    if flow.ragged is not None:
        out = jnp.concatenate(flow.ragged, axis=0)
        lens = np.asarray([e.shape[0] for e in flow.ragged], np.int64)
    else:
        buf = _tl_buf(flow, n)
        out = buf.reshape((-1,) + buf.shape[2:])
        lens = np.full(buf.shape[0], buf.shape[1], np.int64)
    return out, jnp.asarray(lens)


_TA_OPS = {
    "TensorArrayV3": _ta_create,
    "TensorArrayWriteV3": _ta_write,
    "TensorArrayReadV3": lambda i, n, c: lax.dynamic_index_in_dim(
        _tl_buf(i[2], n), jnp.asarray(i[1], jnp.int32), 0, keepdims=False),
    "TensorArrayGatherV3": lambda i, n, c: jnp.take(
        _tl_buf(i[2], n), jnp.asarray(i[1], jnp.int32), axis=0),
    "TensorArrayScatterV3": _ta_scatter,
    "TensorArraySplitV3": _ta_split,
    "TensorArrayConcatV3": _ta_concat,
    "TensorArraySizeV3": lambda i, n, c: jnp.int32(i[1].size),
    "TensorArrayCloseV3": lambda i, n, c: None,
}
_OPS.update(_TA_OPS)


def _eval_function(module, fdef, args, ctx):
    """Evaluate a FunctionDef (while_v2 cond/body) with positional arg
    values. Function-internal references use the ``node:port:index``
    form; bare names are signature args."""
    values: Dict[str, object] = {}
    for a, v in zip(fdef.signature.input_arg, args):
        values[a.name] = v

    def resolve(ref):
        parts = ref.split(":")
        if len(parts) == 1:
            return values[parts[0]]
        v = values[parts[0]]
        # 'node:out:idx' (function-internal) or short-form 'node:1'
        if len(parts) == 3:
            idx = int(parts[-1])
        else:
            idx = int(parts[1]) if parts[1].isdigit() else 0
        return v[idx] if isinstance(v, (tuple, list)) else v

    # node_def order is NOT guaranteed topological (same reason the main
    # graph path runs _topo): order by dependencies first
    by_name = {nd.name: nd for nd in fdef.node_def}
    order, state = [], {}

    def visit(name):
        if state.get(name) == 1 or name not in by_name:
            return
        if state.get(name) == 0:
            raise ValueError(f"cycle in FunctionDef at {name!r}")
        state[name] = 0
        for r in by_name[name].input:
            if not r.startswith("^"):
                visit(r.split(":")[0])
        state[name] = 1
        order.append(name)

    for nd in fdef.node_def:
        visit(nd.name)

    for name in order:
        nd = by_name[name]
        if nd.op == "Const":
            values[nd.name] = tensor_to_numpy(nd.attr["value"].tensor)
            continue
        nd_args = [resolve(r) for r in nd.input if not r.startswith("^")]
        values[nd.name] = module._eval_op(nd, nd_args, ctx)
    return [resolve(fdef.ret[a.name]) for a in fdef.signature.output_arg]


class _V1Frame:
    """One TF-1 while frame: the ``Enter → Merge → Switch → (body) →
    NextIteration`` cycle closed by ``Exit`` (reference executes these
    dynamically with ``DL/nn/Scheduler.scala`` + ``FrameManager.scala``
    interpreting ``DL/nn/tf/ControlOps.scala:65-229``).

    TPU-native redesign: the frame is lowered *structurally*, once, into a
    single functional loop — ``lax.scan`` when the trip count is statically
    derivable (the canonical ``i < N; i += 1`` counter pattern), else
    ``lax.while_loop``. Merges become the loop carry, Switch's true port is
    the carry inside the body, loop-invariant Enters close over outer
    values, Exits read the final carry."""

    def __init__(self, name):
        self.name = name
        self.members = set()    # node names inside the frame
        self.merges = []        # loop-var Merge names, graph order
        self.init_refs = []     # per merge: outer ref feeding its Enter
        self.body_refs = []     # per merge: in-frame ref of the next value
        self.switches = {}      # merge name -> Switch name
        self.invariants = {}    # loop-invariant Enter name -> outer ref
        self.cond_ref = ""      # LoopCond's input ref
        self.exits = {}         # Exit node name -> merge index
        self.external = []      # outer node names the frame depends on


# weights smaller than this stay inline constants; larger ones are lifted
# into the params tree
_PARAM_THRESHOLD = 32


class TFGraphModule(Module):
    """A frozen TF graph as a pure Module (reference ``Session.scala`` /
    ``TensorflowLoader``). Inputs are fed positionally in ``inputs`` order;
    ``forward`` returns the ``outputs`` values (tuple if several)."""

    def __init__(self, graph_def: "pb.GraphDef", inputs: Sequence[str],
                 outputs: Sequence[str]):
        super().__init__()
        self.graph_def = graph_def
        self.input_names = [_ref(i)[0] for i in inputs]
        self.output_refs = [_ref(o) for o in outputs]
        self.nodes: Dict[str, "pb.NodeDef"] = {n.name: n for n in graph_def.node}
        # while_v2 cond/body FunctionDefs (graph.library)
        self._functions = {f.signature.name: f
                           for f in graph_def.library.function}
        self._consts: Dict[str, np.ndarray] = {}
        self._param_names: List[str] = []
        self._var_init: Dict[str, np.ndarray] = {}
        for n in graph_def.node:
            if n.op == "Const":
                arr = tensor_to_numpy(n.attr["value"].tensor)
                if arr.size >= _PARAM_THRESHOLD and np.issubdtype(arr.dtype, np.floating):
                    self._param_names.append(n.name)
                self._consts[n.name] = arr
        # Variable nodes become trainable params (reference Session.scala
        # trains the loaded graph; frozen graphs simply have none). The
        # initial value comes from the variable's Assign(var, Const)
        # initializer when present, else zeros of the shape attr.
        by_name = {n.name: n for n in graph_def.node}

        def resolve_const(name: str, depth: int = 0):
            """Follow Identity/read chains to a Const (the standard
            tf.Variable export shape is Assign(var, Identity(Const)))."""
            if depth > 8:
                return None
            if name in self._consts:
                return self._consts[name]
            node = by_name.get(name)
            if node is not None and node.op in ("Identity", "Snapshot") and node.input:
                return resolve_const(_ref(node.input[0])[0], depth + 1)
            return None

        for n in graph_def.node:
            if n.op in ("Variable", "VariableV2"):
                init = None
                for m in graph_def.node:
                    if m.op == "Assign" and m.input and _ref(m.input[0])[0] == n.name:
                        init = resolve_const(_ref(m.input[1])[0])
                        break
                if init is None:
                    shape = [d.size for d in n.attr["shape"].shape.dim]
                    init = np.zeros(shape, np.float32)
                    import logging

                    logging.getLogger("bigdl_tpu.interop.tf").warning(
                        "variable %r has no Const-resolvable initializer; "
                        "starting from zeros (random initializer ops are "
                        "not evaluated at import)", n.name)
                self._var_init[n.name] = np.asarray(init)
        # TF-1 while frames: collapse each Enter→…→Exit cycle into one
        # functional loop before the (acyclic) topological walk
        self._exit_to_frame: Dict[str, _V1Frame] = {}
        if any(n.op in ("Enter", "RefEnter") for n in graph_def.node):
            self._build_frames()
        # v1 cond-style Merges (tf.cond without frames): pred + true-input
        self._cond_merges = self._analyze_cond_merges()
        # needed set: nodes reachable from outputs
        self._order = self._topo()

    def _follow_identity(self, base: str) -> str:
        """Skip Identity/Snapshot chains (pred_id pivots, Switch:1
        wrappers) for pattern matching."""
        for _ in range(8):
            nd = self.nodes.get(base)
            if nd is None or nd.op not in ("Identity", "Snapshot") \
                    or not nd.input:
                break
            base = _ref(nd.input[0])[0]
        return base

    def _analyze_cond_merges(self) -> Dict[str, Tuple[str, int]]:
        """For every Merge OUTSIDE a while frame, find the cond PREDICATE
        whose Switch ports dominate its two inputs; the Merge lowers to
        ``where(pred, true_branch, false_branch)``. Reference: SwitchOps /
        MergeOps run data-driven (``DL/nn/tf/ControlOps.scala:65-107`` +
        ``Scheduler.scala``); functionally both branches compute and the
        select picks (dead side must be pure, which tf.cond guarantees).

        tf.cond creates a SEPARATE Switch per captured tensor (named after
        the consuming op), all sharing one predicate — so domination is
        keyed on the Identity-normalized predicate, and nested conds are
        handled by descending through inner Switches' data inputs."""
        frame_members: set = set()
        for fr in set(self._exit_to_frame.values()):
            frame_members |= fr.members
        out: Dict[str, Tuple[str, int]] = {}
        self._cond_unsupported: Dict[str, str] = {}
        for nd in self.graph_def.node:
            if nd.op not in ("Merge", "RefMerge") or nd.name in frame_members:
                continue
            if len(nd.input) != 2:
                # deferred: only an error if this Merge is actually
                # reachable from the fetched outputs (fed interior inputs
                # prune whole subgraphs — _topo's documented contract)
                self._cond_unsupported[nd.name] = (
                    f"v1 cond Merge {nd.name!r} with {len(nd.input)} inputs")
                continue
            sets = []
            pred_ref_of: Dict[str, str] = {}
            for ref in nd.input:
                ports, stack, seen = set(), [_ref(ref)], set()
                while stack:
                    b, p = stack.pop()
                    if (b, p) in seen:
                        continue
                    seen.add((b, p))
                    n2 = self.nodes.get(b)
                    if n2 is None:
                        continue
                    if n2.op in ("Switch", "RefSwitch"):
                        key = self._follow_identity(_ref(n2.input[1])[0])
                        ports.add((key, p))
                        pred_ref_of.setdefault(key, n2.input[1])
                        # descend through the data input too: a NESTED
                        # cond's branches sit behind inner Switches but
                        # are still dominated by the outer predicate
                        stack.append(_ref(n2.input[0]))
                        continue
                    # control deps included: a branch returning a Const is
                    # anchored to the cond pivot only via ^switch_t/f
                    stack.extend((bb, max(pp, 0))
                                 for bb, pp in map(_ref, n2.input))
                sets.append(ports)
            hit = next(((k, p) for (k, p) in sets[0]
                        if (k, 1 - p) in sets[1]), None)
            if hit is None and sets[0] and not sets[1]:
                hit = next(iter(sets[0]))
            elif hit is None and sets[1] and not sets[0]:
                k, p = next(iter(sets[1]))
                hit = (k, 1 - p)
            if hit is None:
                self._cond_unsupported[nd.name] = (
                    f"cannot pair v1 Merge {nd.name!r} with a dominating "
                    "Switch (non-cond dataflow Merge is unsupported)")
                continue
            k, p = hit
            out[nd.name] = (pred_ref_of[k], 0 if p == 1 else 1)
        return out

    def _build_frames(self):
        from collections import defaultdict

        consumers = defaultdict(list)
        for nd in self.graph_def.node:
            for ref in nd.input:
                consumers[_ref(ref)[0]].append(nd.name)
        enters_by_frame = defaultdict(list)
        for nd in self.graph_def.node:
            if nd.op in ("Enter", "RefEnter"):
                enters_by_frame[nd.attr["frame_name"].s.decode()].append(nd.name)

        for fname, enters in enters_by_frame.items():
            fr = _V1Frame(fname)
            enter_set = set(enters)
            work = list(enters)
            while work:
                nm = work.pop()
                if nm in fr.members:
                    continue
                fr.members.add(nm)
                nd = self.nodes[nm]
                if nd.op in ("Exit", "RefExit"):
                    continue  # frame boundary: consumers are outer
                if nd.op in ("Enter", "RefEnter") and nm not in enter_set:
                    raise NotImplementedError(
                        f"nested v1 while frames: {fname!r} contains Enter "
                        f"node {nm!r} of another frame")
                work.extend(consumers.get(nm, []))

            members = [nd for nd in self.graph_def.node
                       if nd.name in fr.members]
            loopconds = [nd for nd in members if nd.op == "LoopCond"]
            if len(loopconds) != 1:
                raise NotImplementedError(
                    f"frame {fname!r} has {len(loopconds)} LoopCond nodes "
                    "(expected exactly 1)")
            fr.cond_ref = loopconds[0].input[0]

            loop_var_enters = set()
            for nd in members:
                if nd.op not in ("Merge", "RefMerge"):
                    continue
                ins = [_ref(r)[0] for r in nd.input]
                ei = [k for k, b in enumerate(ins) if b in enter_set]
                if len(ei) != 1:
                    raise NotImplementedError(
                        f"Merge {nd.name!r} in frame {fname!r} does not pair "
                        "one Enter with one NextIteration (v1 cond-style "
                        "Switch/Merge outside a loop is not supported)")
                e, other = ins[ei[0]], ins[1 - ei[0]]
                if self.nodes[other].op not in ("NextIteration",
                                                "RefNextIteration"):
                    raise NotImplementedError(
                        f"Merge {nd.name!r}: second input {other!r} is "
                        f"{self.nodes[other].op}, expected NextIteration")
                loop_var_enters.add(e)
                fr.merges.append(nd.name)
                fr.init_refs.append(self.nodes[e].input[0])
                fr.body_refs.append(self.nodes[other].input[0])

            for nd in members:
                if nd.op in ("Switch", "RefSwitch"):
                    data = _ref(nd.input[0])[0]
                    if data in fr.merges:
                        fr.switches[data] = nd.name

            for e in enters:
                if e not in loop_var_enters:
                    fr.invariants[e] = self.nodes[e].input[0]

            for nd in members:
                if nd.op in ("Exit", "RefExit"):
                    sw = _ref(nd.input[0])[0]
                    midx = next((k for k, m in enumerate(fr.merges)
                                 if fr.switches.get(m) == sw), None)
                    if midx is None:
                        raise NotImplementedError(
                            f"Exit {nd.name!r} does not read a loop-var "
                            "Switch")
                    fr.exits[nd.name] = midx
                    self._exit_to_frame[nd.name] = fr

            ext = set()
            for nd in members:
                for ref in nd.input:
                    base, idx = _ref(ref)
                    if idx >= 0 and base not in fr.members:
                        ext.add(base)
            fr.external = sorted(ext)

    def _topo(self) -> List[str]:
        # iterative DFS: real frozen graphs (ResNets, unrolled RNNs) have
        # input chains far deeper than Python's recursion limit. Fed nodes
        # (inputs) are leaves — their ancestors are pruned, so feeding an
        # interior node (e.g. a queue-dequeue in a training graph) cuts the
        # unsupported producer subgraph away entirely.
        order: List[str] = []
        state: Dict[str, int] = {}  # 0 visiting, 1 done
        fed = set(self.input_names)
        for root, _ in self.output_refs:
            stack: List[Tuple[str, bool]] = [(root, False)]
            while stack:
                name, processed = stack.pop()
                if processed:
                    state[name] = 1
                    order.append(name)
                    continue
                st = state.get(name)
                if st == 1:
                    continue
                if st == 0:
                    raise ValueError(
                        f"cycle at node {name!r} (fetching a node from "
                        "INSIDE a v1 while frame is not supported — fetch "
                        "the loop's Exit outputs instead)")
                state[name] = 0
                stack.append((name, True))
                if name in fed:
                    continue
                if name in self._exit_to_frame:
                    # the whole frame evaluates as one unit when its first
                    # Exit is reached; depend on the frame's outer inputs
                    for base in self._exit_to_frame[name].external:
                        if state.get(base) != 1:
                            stack.append((base, False))
                    continue
                for ref in self.nodes[name].input:
                    base, idx = _ref(ref)
                    if idx >= 0 and state.get(base) != 1:  # skip control deps
                        stack.append((base, False))
                if name in self._cond_merges:
                    # the select predicate: may be reachable only via
                    # control deps (both branches Const), so depend on it
                    # explicitly
                    pb = _ref(self._cond_merges[name][0])[0]
                    if state.get(pb) != 1:
                        stack.append((pb, False))
        return order

    def build_params(self, rng):
        p = {name.replace("/", "__"): jnp.asarray(self._consts[name])
             for name in self._param_names}
        for name, init in self._var_init.items():
            p[name.replace("/", "__")] = jnp.asarray(init)
        return p

    def _eval_op(self, node, args, ctx):
        if node.op in ("While", "StatelessWhile"):
            return self._eval_while(node, args, ctx)
        if node.op in ("If", "StatelessIf"):
            # cond_v2: then/else FunctionDefs -> lax.cond (both traced,
            # one executed — the v2 analogue of the v1 Switch/Merge select)
            then_f = self._functions[node.attr["then_branch"].func.name]
            else_f = self._functions[node.attr["else_branch"].func.name]
            pred, rest = args[0], list(args[1:])
            out = lax.cond(
                jnp.asarray(pred).reshape(()),
                lambda ops: tuple(_eval_function(self, then_f, ops, ctx)),
                lambda ops: tuple(_eval_function(self, else_f, ops, ctx)),
                tuple(rest))
            return out[0] if len(out) == 1 else out
        if node.op in ("PartitionedCall", "StatefulPartitionedCall"):
            fdef = self._functions[node.attr["f"].func.name]
            outs = _eval_function(self, fdef, args, ctx)
            return outs[0] if len(outs) == 1 else tuple(outs)
        fn = _OPS.get(node.op)
        if fn is None:
            raise NotImplementedError(
                f"TF op {node.op!r} (node {node.name!r}) is not supported")
        return fn(args, node, ctx)

    def _run_loop(self, cond_fn, body_fn, carry, loop_name, trip=None):
        """Run a TF loop functionally. ``cond_fn``/``body_fn`` take and
        return *unpacked* lists (arrays and :class:`_TensorList` flows).

        Lazy TensorLists in the carry are materialized by running the body
        once OUTSIDE the loop purely for shape discovery — its outputs are
        discarded, so XLA dead-code-eliminates that probe entirely.

        With a static ``trip`` count the loop lowers to ``lax.scan`` —
        which, unlike ``lax.while_loop``, is reverse-differentiable, so
        imported v1 RNN graphs can be trained with jax.grad."""
        carry = list(carry)
        if any(isinstance(v, _TensorList) and v.buf is None for v in carry):
            probe = body_fn(list(carry))
            for k, v in enumerate(carry):
                if isinstance(v, _TensorList) and v.buf is None:
                    pv = probe[k]
                    if not isinstance(pv, _TensorList) or pv.buf is None:
                        raise ValueError(
                            f"cannot infer element shape of TensorList loop "
                            f"var {k} of {loop_name!r}: the loop body never "
                            "writes it")
                    carry[k] = _TensorList(
                        jnp.zeros(pv.buf.shape, pv.buf.dtype), v.size)
        for k, v in enumerate(carry):
            if isinstance(v, _TensorList) and v.ragged is not None:
                raise NotImplementedError(
                    f"ragged TensorArray as loop var {k} of {loop_name!r}")
            if isinstance(v, _TAHandle):
                raise NotImplementedError(
                    f"TensorArray handle as loop var {k} of {loop_name!r} "
                    "(handles normally enter frames as loop invariants)")
        kinds = [v.size if isinstance(v, _TensorList) else None
                 for v in carry]

        def pack(c):
            return tuple(v.buf if isinstance(v, _TensorList)
                         else jnp.asarray(v) for v in c)

        def unpack(t):
            return [_TensorList(b, k) if k is not None else b
                    for b, k in zip(t, kinds)]

        if trip is not None:
            out, _ = lax.scan(
                lambda c, _: (pack(body_fn(unpack(list(c)))), None),
                pack(carry), None, length=trip)
        else:
            out = lax.while_loop(
                lambda c: jnp.asarray(
                    cond_fn(unpack(list(c)))).reshape(()),
                lambda c: pack(body_fn(unpack(list(c)))),
                pack(carry))
        return unpack(out)

    def _eval_while(self, node, args, ctx):
        """while_v2 (`StatelessWhile`/`While`): loop vars carry through
        the functional loop; cond/body are FunctionDefs."""
        body = self._functions[node.attr["body"].func.name]
        cond = self._functions[node.attr["cond"].func.name]
        out = self._run_loop(
            lambda c: _eval_function(self, cond, c, ctx)[0],
            lambda c: _eval_function(self, body, c, ctx),
            list(args), node.name)
        return tuple(out)

    def _eval_v1_frame(self, fr: _V1Frame, values, ctx):
        """Evaluate one v1 while frame; writes every Exit's value into
        ``values``. See :class:`_V1Frame` for the lowering."""

        def outer(ref):
            base, idx = _ref(ref)
            v = values[base]
            return v[idx] if isinstance(v, (tuple, list)) else v

        inv = {nm: outer(ref) for nm, ref in fr.invariants.items()}
        init = [outer(r) for r in fr.init_refs]

        def subgraph(carry, refs):
            """Evaluate in-frame refs with Merges/Switches seeded from the
            carry (Switch is seeded on both ports: during body execution
            the predicate is true, and the false port is only read by
            Exit, which lives outside this evaluation)."""
            local: Dict[str, object] = dict(inv)
            for k, m in enumerate(fr.merges):
                local[m] = carry[k]
                sw = fr.switches.get(m)
                if sw is not None:
                    local[sw] = (carry[k], carry[k])

            def eval_node(root):
                # iterative DFS: loop bodies can chain arbitrarily many
                # sequential ops (same rationale as _topo's iterative walk)
                stack = [(root, False)]
                while stack:
                    base, ready = stack.pop()
                    if base in local:
                        continue
                    if base not in fr.members:
                        local[base] = values[base]
                        continue
                    nd = self.nodes[base]
                    if nd.op == "Const":
                        local[base] = tensor_to_numpy(nd.attr["value"].tensor)
                        continue
                    if nd.op in ("Enter", "RefEnter", "Merge", "RefMerge",
                                 "Switch", "RefSwitch", "NextIteration",
                                 "RefNextIteration", "LoopCond"):
                        raise NotImplementedError(
                            f"control node {base!r} ({nd.op}) in frame "
                            f"{fr.name!r} is not part of the canonical while "
                            "pattern (tf.cond inside a loop body?)")
                    deps = [_ref(r) for r in nd.input]
                    if not ready:
                        stack.append((base, True))
                        stack.extend((b, False) for b, idx in deps
                                     if idx >= 0 and b not in local)
                        continue
                    args = []
                    for b, idx in deps:
                        if idx < 0:
                            continue
                        v = local[b]
                        args.append(v[idx] if isinstance(v, (tuple, list))
                                    else v)
                    local[base] = self._eval_op(nd, args, ctx)

            out = []
            for ref in refs:
                b, idx = _ref(ref)
                eval_node(b)
                v = local[b]
                out.append(v[idx] if isinstance(v, (tuple, list)) else v)
            return out

        final = self._run_loop(
            lambda c: subgraph(c, [fr.cond_ref])[0],
            lambda c: subgraph(c, fr.body_refs),
            init, fr.name, trip=self._static_trip_count(fr, values, init))
        for exit_name, k in fr.exits.items():
            values[exit_name] = final[k]

    def _static_trip_count(self, fr: _V1Frame, values, init):
        """Detect the canonical counted loop — cond ``Less(i, limit)`` with
        loop-invariant concrete ``limit`` and body ``i + 1`` — so the loop
        can lower to differentiable ``lax.scan``. Returns None when the
        pattern doesn't hold (falls back to ``lax.while_loop``)."""

        follow = self._follow_identity

        def static_value(ref):
            base = follow(_ref(ref)[0])
            if base in fr.invariants:
                v = values.get(_ref(fr.invariants[base])[0])
            elif base in fr.members:
                nd = self.nodes[base]
                if nd.op != "Const":
                    return None
                v = tensor_to_numpy(nd.attr["value"].tensor)
            else:
                v = values.get(base)
            if v is None or isinstance(v, (jax.core.Tracer, tuple, list,
                                           _TensorList, _TAHandle)):
                return None
            try:
                return int(np.asarray(v))
            except (TypeError, ValueError):
                return None

        cnd = self.nodes.get(follow(_ref(fr.cond_ref)[0]))
        if cnd is None or cnd.op != "Less":
            return None
        i_merge = follow(_ref(cnd.input[0])[0])
        if i_merge not in fr.merges:
            return None
        k = fr.merges.index(i_merge)
        if k >= len(init):
            return None
        limit = static_value(cnd.input[1])
        i0 = init[k]
        if limit is None or isinstance(i0, jax.core.Tracer):
            return None
        # body must be i + 1 off the loop var's Switch true port
        inc = self.nodes.get(follow(_ref(fr.body_refs[k])[0]))
        sw = fr.switches.get(i_merge)
        if inc is None or inc.op not in ("Add", "AddV2") or sw is None:
            return None
        if not any(follow(_ref(r)[0]) == sw for r in inc.input):
            return None
        step = next((static_value(r) for r in inc.input
                     if follow(_ref(r)[0]) != sw), None)
        if step != 1:
            return None
        try:
            return max(0, limit - int(np.asarray(i0)))
        except (TypeError, ValueError):
            return None

    def forward(self, ctx: Context, x):
        xs = (x,) if len(self.input_names) == 1 else tuple(x)
        if len(xs) != len(self.input_names):
            raise ValueError(
                f"expected {len(self.input_names)} inputs, got {len(xs)}")
        values: Dict[str, object] = {}
        for name, xi in zip(self.input_names, xs):
            values[name] = xi
        param_set = set(self._param_names)
        for name in self._order:
            if name in values:
                continue
            if name in self._exit_to_frame:
                # fills values[] for every Exit of the frame at once
                self._eval_v1_frame(self._exit_to_frame[name], values, ctx)
                continue
            node = self.nodes[name]
            if node.op in ("Merge", "RefMerge"):
                if name in self._cond_unsupported:
                    raise NotImplementedError(self._cond_unsupported[name])
                pred_ref, true_idx = self._cond_merges[name]
                pb, pi = _ref(pred_ref)
                pv = values[pb]
                pred = pv[pi] if isinstance(pv, (tuple, list)) else pv
                branches = []
                for ref in node.input:
                    b, idx = _ref(ref)
                    v = values[b]
                    branches.append(v[idx] if isinstance(v, (tuple, list))
                                    else v)
                sel = jnp.where(pred, branches[true_idx],
                                branches[1 - true_idx])
                # port 1 = value_index (which input produced the value)
                vidx = jnp.where(pred, jnp.int32(true_idx),
                                 jnp.int32(1 - true_idx))
                values[name] = (sel, vidx)
                continue
            if node.op == "Const":
                if name in param_set:
                    values[name] = ctx.param(name.replace("/", "__"))
                else:
                    values[name] = self._consts[name]
                continue
            if node.op in ("Variable", "VariableV2"):
                values[name] = ctx.param(name.replace("/", "__"))
                continue
            if node.op in ("Placeholder", "PlaceholderWithDefault") and not node.input:
                raise ValueError(
                    f"placeholder {name!r} was not listed in inputs")
            args = []
            for ref in node.input:
                base, idx = _ref(ref)
                if idx < 0:
                    continue
                v = values[base]
                args.append(v[idx] if isinstance(v, (tuple, list)) else v)
            values[name] = self._eval_op(node, args, ctx)
        outs = []
        for base, idx in self.output_refs:
            v = values[base]
            outs.append(v[idx] if isinstance(v, (tuple, list)) else v)
        return outs[0] if len(outs) == 1 else tuple(outs)


class TensorflowLoader:
    """Reference ``TensorflowLoader.scala:43``."""

    @staticmethod
    def parse(path: str) -> "pb.GraphDef":
        g = pb.GraphDef()
        with open(path, "rb") as f:
            g.ParseFromString(f.read())
        return g

    @staticmethod
    def load(path: str, inputs: Sequence[str], outputs: Sequence[str]):
        """Returns ``(module, params, state)`` for a frozen GraphDef file."""
        module = TFGraphModule(TensorflowLoader.parse(path), inputs, outputs)
        params, state = module.init(jax.random.key(0))
        return module, params, state


def load_tf_graph(path: str, inputs: Sequence[str], outputs: Sequence[str]):
    return TensorflowLoader.load(path, inputs, outputs)


class TFSession:
    """Minimal Session.run over a frozen graph (reference
    ``DL/utils/tf/Session.scala:43`` BigDLSessionImpl; queue-runner input
    emulation is out of scope — feed host arrays directly)."""

    def __init__(self, graph_def_or_path, jit: bool = True):
        if isinstance(graph_def_or_path, str):
            self.graph_def = TensorflowLoader.parse(graph_def_or_path)
        else:
            self.graph_def = graph_def_or_path
        self._jit = jit
        self._cache: Dict[Tuple, Tuple] = {}

    def run(self, fetches: Sequence[str], feed_dict: Dict[str, np.ndarray]):
        feeds = list(feed_dict.keys())
        key = (tuple(fetches), tuple(feeds))
        if key not in self._cache:
            module = TFGraphModule(self.graph_def, feeds, fetches)
            params, _ = module.init(jax.random.key(0))
            fn = (lambda p, *xs: module.apply(p, xs if len(xs) > 1 else xs[0])[0])
            self._cache[key] = (jax.jit(fn) if self._jit else fn, params)
        fn, params = self._cache[key]
        out = fn(params, *[jnp.asarray(v) for v in feed_dict.values()])
        return [np.asarray(o) for o in (out if isinstance(out, tuple) else (out,))]

    def train(self, inputs: Sequence[str], loss_node: str, data,
              optim_method=None, n_steps: int = 100, batch_size: int = 32,
              steps_per_epoch: Optional[int] = None):
        """Train the graph's Variable nodes (reference
        ``BigDLSessionImpl.train``, ``Session.scala:111-132`` — which
        emulates the graph's queue runners to feed it; here the host
        arrays/iterator feed the jitted step directly, the TPU-native
        input path).

        ``inputs``: placeholder names, ``loss_node``: scalar loss output,
        ``data``: tuple of arrays (batched round-robin) or an iterator of
        per-step feed tuples. Returns (module, trained_params).
        """
        from bigdl_tpu.optim.optim_method import SGD

        method = optim_method or SGD(learning_rate=0.01)
        module = TFGraphModule(self.graph_def, list(inputs), [loss_node])
        if not module._var_init:
            raise ValueError("graph has no Variable nodes to train "
                             "(frozen graph? use run() for inference)")
        params, _ = module.init(jax.random.key(0))
        ostate = method.init_state(params)

        @jax.jit
        def step(params, ostate, epoch, *feeds):
            def loss_fn(p):
                out, _ = module.apply(p, feeds if len(feeds) > 1 else feeds[0])
                return jnp.asarray(out, jnp.float32).sum()

            loss, grads = jax.value_and_grad(loss_fn)(params)
            new_p, new_os = method.update(grads, params, ostate, epoch)
            return new_p, new_os, loss

        if isinstance(data, (tuple, list)):
            arrays = [np.asarray(a) for a in data]
            n = arrays[0].shape[0]

            def batches():
                i = 0
                while True:
                    idx = [(i + k) % n for k in range(batch_size)]
                    yield tuple(a[idx] for a in arrays)
                    i = (i + batch_size) % n
            it = batches()
        else:
            it = iter(data)
        loss = None
        for i in range(n_steps):
            feeds = next(it)
            epoch = jnp.int32(i // steps_per_epoch + 1 if steps_per_epoch else 1)
            params, ostate, loss = step(params, ostate, epoch,
                                        *map(jnp.asarray, feeds))
        return module, params, (None if loss is None else float(loss))
