"""TensorFlow GraphDef exporter.

Reference: ``DL/utils/tf/TensorflowSaver.scala`` / ``BigDLToTensorflow.scala``
— map each module to TF nodes, weights as ``Const``, write a frozen
GraphDef. Same module coverage philosophy as the Caffe persister; exported
graphs reload through :mod:`bigdl_tpu.interop.tf.loader` for a round-trip
guarantee and load in stock TensorFlow.

All tensors are emitted in the model's native NCHW layout (TF supports
``data_format: "NCHW"``); explicit paddings become ``Pad`` nodes since TF
convs/pools only know SAME/VALID.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.interop.tf import tensorflow_pb2 as pb
from bigdl_tpu.interop.tf.loader import numpy_to_tensor
from bigdl_tpu.nn.graph import Graph


class TensorflowSaver:
    def __init__(self, model, params, state=None):
        self.model = model
        self.params = params
        self.state = state or {}
        self.graph = pb.GraphDef()
        self.graph.versions.producer = 27
        self._seq = 0

    # -- node helpers ------------------------------------------------------
    def _name(self, base: str) -> str:
        self._seq += 1
        return f"{base}_{self._seq}"

    _TYPE_ATTRS = frozenset(
        {"dtype", "T", "DstT", "SrcT", "Tidx", "Tshape", "Tpaddings", "out_type"})

    def _node(self, op: str, name: str, inputs: List[str], **attrs) -> str:
        node = self.graph.node.add(name=name, op=op, input=inputs)
        for k, v in attrs.items():
            a = node.attr[k]
            if k in self._TYPE_ATTRS:
                a.type = v  # DataType enum values are ints; dispatch by key
            elif isinstance(v, bool):
                a.b = v
            elif isinstance(v, int):
                a.i = v
            elif isinstance(v, float):
                a.f = v
            elif isinstance(v, bytes):
                a.s = v
            elif isinstance(v, str):
                a.s = v.encode()
            elif isinstance(v, list) and all(isinstance(x, int) for x in v):
                a.list.i.extend(v)
            elif isinstance(v, pb.TensorProto):
                a.tensor.CopyFrom(v)
            else:
                raise TypeError(f"attr {k}={v!r}")
        return name

    def _const(self, arr, base: str = "const") -> str:
        name = self._name(base)
        t = numpy_to_tensor(np.asarray(arr))
        return self._node("Const", name, [], value=t, dtype=t.dtype)

    def _pad(self, x: str, pads: List[Tuple[int, int]]) -> str:
        if all(p == (0, 0) for p in pads):
            return x
        p = self._const(np.asarray(pads, np.int32), "paddings")
        return self._node("Pad", self._name("pad"), [x, p],
                          T=pb.DT_FLOAT, Tpaddings=pb.DT_INT32)

    # -- model walk --------------------------------------------------------
    def save(self, path: str, input_name: str = "input",
             input_shape: Optional[Tuple[int, ...]] = None) -> "pb.GraphDef":
        from bigdl_tpu.interop.walker import walk_model

        node = self.graph.node.add(name=input_name, op="Placeholder")
        node.attr["dtype"].type = pb.DT_FLOAT
        if input_shape is not None:
            for d in input_shape:
                node.attr["shape"].shape.dim.add().size = d
        out = walk_model(self.model, self.params, self.state, input_name,
                         self._emit_leaf)
        self._node("Identity", "output", [out], T=pb.DT_FLOAT)
        with open(path, "wb") as f:
            f.write(self.graph.SerializeToString())
        return self.graph

    def _emit_leaf(self, m, p, s, ins: List[str], name=None) -> str:
        x = ins[0] if ins else None

        if type(m) is nn.Linear:
            w = self._const(np.asarray(p["weight"]).T, "weight")  # (in, out)
            y = self._node("MatMul", self._name("matmul"), [x, w], T=pb.DT_FLOAT)
            if m.with_bias:
                b = self._const(np.asarray(p["bias"]), "bias")
                y = self._node("BiasAdd", self._name("bias_add"), [y, b],
                               T=pb.DT_FLOAT)
            return y

        if type(m) in (nn.SpatialConvolution, nn.SpatialShareConvolution):
            if m.n_group != 1:
                raise ValueError("tf export: grouped conv unsupported")
            ph, pw = m.pad
            tf_padding = b"VALID"
            if ph == -1 or pw == -1:  # TF-style SAME padding mode
                tf_padding = b"SAME"
            else:
                x = self._pad(x, [(0, 0), (0, 0), (ph, ph), (pw, pw)])
            # wire OIHW (via the module's storage-layout export) -> TF HWIO
            w_oihw = np.asarray(m.weight_as_oihw(p["weight"]))
            w = self._const(w_oihw.transpose(2, 3, 1, 0), "weight")
            sh, sw = m.stride
            y = self._node("Conv2D", self._name("conv"), [x, w],
                           strides=[1, 1, sh, sw], padding=tf_padding,
                           data_format=b"NCHW", T=pb.DT_FLOAT)
            if m.with_bias:
                b_ = self._const(np.asarray(p["bias"]), "bias")
                y = self._node("BiasAdd", self._name("bias_add"), [y, b_],
                               data_format=b"NCHW", T=pb.DT_FLOAT)
            return y

        if isinstance(m, (nn.SpatialMaxPooling, nn.SpatialAveragePooling)):
            if m.ceil_mode:
                raise ValueError("tf export: ceil-mode pooling unsupported")
            ph, pw = m.pad
            if isinstance(m, nn.SpatialMaxPooling) and (ph or pw):
                # -inf padding must not win the max: pad AFTER clamping via
                # explicit Pad with zeros is wrong for negative activations,
                # so reject instead of silently corrupting
                raise ValueError("tf export: padded max-pooling unsupported")
            if isinstance(m, nn.SpatialAveragePooling) and (ph or pw) \
                    and not m.count_include_pad:
                # explicit zero Pad + VALID makes padded cells count in the
                # divisor, i.e. count_include_pad=True semantics only
                raise ValueError(
                    "tf export: padded avg-pooling with count_include_pad="
                    "False unsupported")
            x = self._pad(x, [(0, 0), (0, 0), (ph, ph), (pw, pw)])
            kh, kw = m.kernel
            sh, sw = m.stride
            op = "MaxPool" if isinstance(m, nn.SpatialMaxPooling) else "AvgPool"
            return self._node(op, self._name(op.lower()), [x],
                              ksize=[1, 1, kh, kw], strides=[1, 1, sh, sw],
                              padding=b"VALID", data_format=b"NCHW",
                              T=pb.DT_FLOAT)

        if isinstance(m, nn.SpatialBatchNormalization):
            mean = np.asarray(s["running_mean"])
            var = np.asarray(s["running_var"])
            gamma = np.asarray(p["weight"]) if m.affine else np.ones_like(mean)
            beta = np.asarray(p["bias"]) if m.affine else np.zeros_like(mean)
            inv = gamma / np.sqrt(var + m.eps)
            shift = beta - mean * inv
            scale = self._const(inv.reshape(1, -1, 1, 1).astype(np.float32), "bn_scale")
            off = self._const(shift.reshape(1, -1, 1, 1).astype(np.float32), "bn_shift")
            y = self._node("Mul", self._name("bn_mul"), [x, scale], T=pb.DT_FLOAT)
            return self._node("Add", self._name("bn_add"), [y, off], T=pb.DT_FLOAT)

        if isinstance(m, nn.GlobalAveragePooling2D):
            axes = self._const(np.asarray([2, 3], np.int32), "axes")
            return self._node("Mean", self._name("mean"), [x, axes],
                              keep_dims=False, T=pb.DT_FLOAT, Tidx=pb.DT_INT32)

        if isinstance(m, nn.Reshape):
            shape = self._const(np.asarray([-1] + list(m.size), np.int32), "shape")
            return self._node("Reshape", self._name("reshape"), [x, shape],
                              T=pb.DT_FLOAT, Tshape=pb.DT_INT32)

        if isinstance(m, nn.Dropout):
            return self._node("Identity", self._name("dropout"), [x], T=pb.DT_FLOAT)
        if isinstance(m, nn.Identity):
            return self._node("Identity", self._name("identity"), [x], T=pb.DT_FLOAT)

        simple = {nn.ReLU: "Relu", nn.Tanh: "Tanh", nn.Sigmoid: "Sigmoid",
                  nn.SoftMax: "Softmax", nn.LogSoftMax: "LogSoftmax"}
        for cls, op in simple.items():
            if type(m) is cls:
                return self._node(op, self._name(op.lower()), [x], T=pb.DT_FLOAT)

        if isinstance(m, nn.CAddTable):
            return self._node("AddN", self._name("addn"), ins, N=len(ins),
                              T=pb.DT_FLOAT)
        if isinstance(m, nn.JoinTable):
            ax = self._const(np.asarray(m.dimension, np.int32), "axis")
            return self._node("ConcatV2", self._name("concat"), ins + [ax],
                              N=len(ins), T=pb.DT_FLOAT, Tidx=pb.DT_INT32)

        raise ValueError(f"tf export does not support {type(m).__name__}")


def save_tf_graph(model, params, state, path: str,
                  input_shape: Optional[Tuple[int, ...]] = None) -> None:
    TensorflowSaver(model, params, state).save(path, input_shape=input_shape)
