"""TensorFlow bridge (reference: ``DL/utils/tf/`` — TensorflowLoader 4,206
LoC + 161 per-op loaders, TensorflowSaver, Session).

``load_tf_graph(path, inputs, outputs)`` -> (TFGraphModule, params, state);
``save_tf_graph(model, params, state, path)``; ``TFSession(path).run(...)``.
"""

from bigdl_tpu.interop.tf.loader import (  # noqa: F401
    TFGraphModule, TFSession, TensorflowLoader, load_tf_graph,
)
from bigdl_tpu.interop.tf.saver import TensorflowSaver, save_tf_graph  # noqa: F401
