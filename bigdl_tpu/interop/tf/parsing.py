"""TFRecord Example parsing (the reference's ParsingOps).

Reference: ``DL/nn/tf/ParsingOps.scala`` (ParseExample over
``tf.train.Example`` records) fed by the TFRecord reader
(``DL/utils/tf/TFRecordIterator``).

Host-side decode into numpy batches — on TPU, record parsing belongs in
the input pipeline (it feeds ``SampleToMiniBatch``/device prefetch), not
in the compiled graph like TF's in-graph parsing ops.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from bigdl_tpu.interop.tf import example_pb2 as pb


class FixedLenFeature:
    """Dense feature spec (reference/TF ``FixedLenFeature``): fixed
    ``shape``, ``dtype`` in {float32, int64, bytes}, optional default."""

    def __init__(self, shape: Sequence[int], dtype, default=None):
        self.shape = tuple(int(d) for d in shape)
        self.dtype = np.dtype(dtype) if dtype is not bytes else bytes
        self.default = default


class VarLenFeature:
    """Ragged feature spec: values come back as a plain list per record."""

    def __init__(self, dtype):
        self.dtype = np.dtype(dtype) if dtype is not bytes else bytes


def _feature_values(feature: "pb.Feature"):
    kind = feature.WhichOneof("kind")
    if kind == "bytes_list":
        return list(feature.bytes_list.value)
    if kind == "float_list":
        return list(feature.float_list.value)
    if kind == "int64_list":
        return list(feature.int64_list.value)
    return []


def parse_single_example(serialized: bytes, features: Dict[str, object]) -> Dict[str, object]:
    """One serialized Example -> {name: array | list} per the spec
    (reference ``ParseExample`` single-record path)."""
    ex = pb.Example.FromString(serialized)
    fmap = ex.features.feature
    out: Dict[str, object] = {}
    for name, spec in features.items():
        vals = _feature_values(fmap[name]) if name in fmap else None
        if isinstance(spec, VarLenFeature):
            if vals is None:
                out[name] = []
            elif spec.dtype is bytes:
                out[name] = vals
            else:
                out[name] = np.asarray(vals, spec.dtype)
            continue
        want = int(np.prod(spec.shape)) if spec.shape else 1
        if vals is None or len(vals) == 0:
            if spec.default is None:
                raise ValueError(f"example is missing feature {name!r} "
                                 "and the spec has no default")
            if spec.dtype is bytes:
                vals = [spec.default] * want
            else:
                vals = np.broadcast_to(
                    np.asarray(spec.default), spec.shape).reshape(-1).tolist()
        if spec.dtype is bytes:
            if len(vals) != want:
                raise ValueError(
                    f"feature {name!r}: got {len(vals)} bytes values, spec "
                    f"shape {spec.shape} wants {want}")
            out[name] = vals[0] if spec.shape == () else list(vals)
            continue
        arr = np.asarray(vals, spec.dtype)
        if arr.size != want:
            raise ValueError(
                f"feature {name!r}: got {arr.size} values, spec shape "
                f"{spec.shape} wants {want}")
        out[name] = arr.reshape(spec.shape)
    return out


def parse_example(serialized_batch: Iterable[bytes],
                  features: Dict[str, object]) -> Dict[str, object]:
    """Batch parse (reference ``ParseExample``): dense specs stack into
    (N, *shape) arrays; VarLen and bytes specs return per-record lists."""
    rows = [parse_single_example(s, features) for s in serialized_batch]
    out: Dict[str, object] = {}
    for name, spec in features.items():
        col = [r[name] for r in rows]
        if isinstance(spec, FixedLenFeature) and spec.dtype is not bytes:
            out[name] = (np.stack(col) if col
                         else np.zeros((0,) + spec.shape, spec.dtype))
        else:
            out[name] = col
    return out


def build_example(feature_dict: Dict[str, object]) -> bytes:
    """Serialize {name: value} into a tf.train.Example (the writer side,
    pairing with ``dataset/tfrecord.py``'s TFRecordWriter)."""
    ex = pb.Example()
    for name, value in feature_dict.items():
        feat = ex.features.feature[name]
        if isinstance(value, (bytes, bytearray)):
            feat.bytes_list.value.append(bytes(value))
        elif isinstance(value, str):
            feat.bytes_list.value.append(value.encode())
        elif isinstance(value, (list, tuple, np.ndarray)):
            arr = np.asarray(value)
            if arr.dtype.kind in "SU" or (
                    arr.dtype == object and len(arr) and
                    isinstance(arr.reshape(-1)[0], (bytes, str))):
                for v in arr.reshape(-1):
                    feat.bytes_list.value.append(
                        v if isinstance(v, bytes) else str(v).encode())
            elif arr.dtype.kind in "iu":
                feat.int64_list.value.extend(int(v) for v in arr.reshape(-1))
            else:
                feat.float_list.value.extend(float(v) for v in arr.reshape(-1))
        elif isinstance(value, (int, np.integer)):
            feat.int64_list.value.append(int(value))
        else:
            feat.float_list.value.append(float(value))
    return ex.SerializeToString()
