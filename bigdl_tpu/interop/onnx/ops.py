"""ONNX op modules (reference: ``DL/nn/onnx/Gemm.scala``, ``Reshape.scala``,
``Shape.scala`` — the reference's tiny ONNX module tier)."""

from __future__ import annotations

import jax.numpy as jnp

from bigdl_tpu.nn.module import Context, Module


class Gemm(Module):
    """y = alpha * A' B' + beta * C (reference ``DL/nn/onnx/Gemm.scala``).
    Takes a table (A, B, C) like the reference's three-input graph node."""

    def __init__(self, alpha: float = 1.0, beta: float = 1.0,
                 trans_a: bool = False, trans_b: bool = False):
        super().__init__()
        self.alpha = alpha
        self.beta = beta
        self.trans_a = trans_a
        self.trans_b = trans_b

    def forward(self, ctx: Context, x):
        a, b, c = x
        if self.trans_a:
            a = a.T
        if self.trans_b:
            b = b.T
        return self.alpha * (a @ b) + self.beta * c


class Reshape(Module):
    """ONNX Reshape semantics: 0 copies the input dim, -1 infers
    (reference ``DL/nn/onnx/Reshape.scala``)."""

    def __init__(self, shape):
        super().__init__()
        self.shape = list(shape)

    def forward(self, ctx: Context, x):
        dims = [x.shape[i] if d == 0 else d for i, d in enumerate(self.shape)]
        return jnp.reshape(x, dims)


class Shape(Module):
    """Returns the input's shape as an int64 vector (reference
    ``DL/nn/onnx/Shape.scala``)."""

    def forward(self, ctx: Context, x):
        return jnp.asarray(x.shape, jnp.int64)
