"""ONNX bridge (reference: ``DL/nn/onnx/`` + ``PY/contrib/onnx``).

``load_onnx(path)`` -> (ONNXModule, params, state);
``save_onnx(model, params, state, path)``; module ops in ``ops``.
"""

from bigdl_tpu.interop.onnx.loader import ONNXModule, load_onnx  # noqa: F401
from bigdl_tpu.interop.onnx.exporter import ONNXExporter, save_onnx  # noqa: F401
from bigdl_tpu.interop.onnx.ops import Gemm, Reshape, Shape  # noqa: F401
