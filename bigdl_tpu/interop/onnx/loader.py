"""ONNX model importer.

Reference: ``PY/contrib/onnx/onnx_loader.py`` (node-by-node mapping) and
``DL/nn/onnx/`` (Gemm / Reshape / Shape modules). Same functional design
as the TF importer: each ONNX node lowers to a jnp/lax expression inside
one pure Module; initializers become params.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.interop.onnx import onnx_pb2 as pb
from bigdl_tpu.nn.module import Context, Module

_NP_DTYPES = {
    pb.TensorProto.FLOAT: np.float32,
    pb.TensorProto.DOUBLE: np.float64,
    pb.TensorProto.INT32: np.int32,
    pb.TensorProto.INT64: np.int64,
    pb.TensorProto.INT8: np.int8,
    pb.TensorProto.UINT8: np.uint8,
    pb.TensorProto.BOOL: np.bool_,
    pb.TensorProto.FLOAT16: np.float16,
}


def tensor_to_numpy(t: "pb.TensorProto") -> np.ndarray:
    dt = _NP_DTYPES.get(t.data_type)
    if dt is None:
        raise ValueError(f"unsupported ONNX tensor dtype {t.data_type}")
    dims = [int(d) for d in t.dims]
    if t.raw_data:
        return np.frombuffer(t.raw_data, dtype=dt).reshape(dims)
    if t.data_type == pb.TensorProto.FLOAT16 and len(t.int32_data):
        # spec: fp16 typed data is stored as uint16 BIT PATTERNS in
        # int32_data — reinterpret, don't value-cast
        bits = np.asarray(list(t.int32_data), dtype=np.uint16)
        return bits.view(np.float16).reshape(dims)
    for field in ("float_data", "int32_data", "int64_data", "double_data"):
        vals = getattr(t, field)
        if len(vals):
            return np.asarray(list(vals), dtype=dt).reshape(dims)
    return np.zeros(dims, dtype=dt)


def numpy_to_tensor(arr: np.ndarray, name: str = "") -> "pb.TensorProto":
    arr = np.asarray(arr)
    rev = {v: k for k, v in _NP_DTYPES.items()}
    t = pb.TensorProto(name=name, data_type=rev[arr.dtype.type])
    t.dims.extend(int(d) for d in arr.shape)
    t.raw_data = np.ascontiguousarray(arr).tobytes()
    return t


def _attrs(node) -> Dict[str, object]:
    out = {}
    for a in node.attribute:
        if a.type == pb.AttributeProto.FLOAT:
            out[a.name] = float(a.f)
        elif a.type == pb.AttributeProto.INT:
            out[a.name] = int(a.i)
        elif a.type == pb.AttributeProto.STRING:
            out[a.name] = a.s.decode()
        elif a.type == pb.AttributeProto.TENSOR:
            out[a.name] = tensor_to_numpy(a.t)
        elif a.type == pb.AttributeProto.FLOATS:
            out[a.name] = [float(v) for v in a.floats]
        elif a.type == pb.AttributeProto.INTS:
            out[a.name] = [int(v) for v in a.ints]
    return out


def _conv(inp, attrs):
    x, w = inp[0], inp[1]
    strides = attrs.get("strides", [1, 1])
    pads = attrs.get("pads", [0, 0, 0, 0])  # [top, left, bottom, right]
    dil = attrs.get("dilations", [1, 1])
    group = attrs.get("group", 1)
    auto_pad = attrs.get("auto_pad", "NOTSET")
    if auto_pad not in ("NOTSET", ""):
        if auto_pad == "SAME_UPPER":
            padding = "SAME"
        elif auto_pad == "SAME_LOWER":
            padding = "SAME_LOWER"  # lax supports it natively
        else:  # VALID
            padding = "VALID"
    else:
        padding = [(pads[0], pads[2]), (pads[1], pads[3])]
    y = lax.conv_general_dilated(
        x, w, tuple(strides), padding, rhs_dilation=tuple(dil),
        feature_group_count=group,
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    if len(inp) > 2 and inp[2] is not None:
        y = y + inp[2][None, :, None, None]
    return y


def _gemm(inp, attrs):
    """Reference module: ``DL/nn/onnx/Gemm.scala`` — alpha*A'B' + beta*C."""
    a, b = inp[0], inp[1]
    if attrs.get("transA", 0):
        a = a.T
    if attrs.get("transB", 0):
        b = b.T
    y = attrs.get("alpha", 1.0) * (a @ b)
    if len(inp) > 2 and inp[2] is not None:
        y = y + attrs.get("beta", 1.0) * inp[2]
    return y


def _pool(inp, attrs, reducer, init, is_avg=False):
    (x,) = inp
    k = attrs["kernel_shape"]
    strides = attrs.get("strides", [1] * len(k))
    pads = attrs.get("pads", [0] * 2 * len(k))
    n = len(k)
    window = (1, 1) + tuple(k)
    stride = (1, 1) + tuple(strides)
    pad = ((0, 0), (0, 0)) + tuple((pads[i], pads[i + n]) for i in range(n))
    s = lax.reduce_window(x, init, reducer, window, stride, pad)
    if is_avg:
        if attrs.get("count_include_pad", 0):
            return s / float(np.prod(k))
        ones = jnp.ones(x.shape, x.dtype)
        cnt = lax.reduce_window(ones, 0.0, lax.add, window, stride, pad)
        return s / cnt
    return s


def _batch_norm(inp, attrs):
    x, scale, b, mean, var = inp
    eps = attrs.get("epsilon", 1e-5)
    inv = lax.rsqrt(var + eps) * scale
    sh = [1, -1] + [1] * (x.ndim - 2)
    return x * inv.reshape(sh) + (b - mean * inv).reshape(sh)


def _slice(inp, attrs):
    x = inp[0]
    if len(inp) > 1:  # opset 10+: starts/ends/axes/steps as inputs
        starts = np.asarray(inp[1]).tolist()
        ends = np.asarray(inp[2]).tolist()
        axes = (np.asarray(inp[3]).tolist()
                if len(inp) > 3 and inp[3] is not None else list(range(len(starts))))
        steps = (np.asarray(inp[4]).tolist()
                 if len(inp) > 4 and inp[4] is not None else [1] * len(starts))
    else:
        starts = attrs["starts"]
        ends = attrs["ends"]
        axes = attrs.get("axes", list(range(len(starts))))
        steps = [1] * len(starts)
    idx = [slice(None)] * x.ndim
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        idx[int(ax)] = slice(int(st), None if en >= 2**31 - 1 else int(en), int(sp))
    return x[tuple(idx)]


_OPS: Dict[str, Callable] = {
    "Conv": _conv,
    "Gemm": _gemm,
    "MatMul": lambda i, a: jnp.matmul(i[0], i[1]),
    "Add": lambda i, a: i[0] + i[1],
    "Sub": lambda i, a: i[0] - i[1],
    "Mul": lambda i, a: i[0] * i[1],
    "Div": lambda i, a: i[0] / i[1],
    "Pow": lambda i, a: i[0] ** i[1],
    "Neg": lambda i, a: -i[0],
    "Sqrt": lambda i, a: jnp.sqrt(i[0]),
    "Exp": lambda i, a: jnp.exp(i[0]),
    "Log": lambda i, a: jnp.log(i[0]),
    "Abs": lambda i, a: jnp.abs(i[0]),
    "Relu": lambda i, a: jax.nn.relu(i[0]),
    "LeakyRelu": lambda i, a: jax.nn.leaky_relu(i[0], a.get("alpha", 0.01)),
    "Sigmoid": lambda i, a: jax.nn.sigmoid(i[0]),
    "Tanh": lambda i, a: jnp.tanh(i[0]),
    "Elu": lambda i, a: jax.nn.elu(i[0], a.get("alpha", 1.0)),
    "Softmax": lambda i, a: jax.nn.softmax(i[0], axis=a.get("axis", -1)),
    "LogSoftmax": lambda i, a: jax.nn.log_softmax(i[0], axis=a.get("axis", -1)),
    "Clip": lambda i, a: jnp.clip(
        i[0],
        i[1] if len(i) > 1 and i[1] is not None else a.get("min"),
        i[2] if len(i) > 2 and i[2] is not None else a.get("max")),
    "MaxPool": lambda i, a: _pool(i, a, lax.max, -jnp.inf),
    "AveragePool": lambda i, a: _pool(i, a, lax.add, 0.0, is_avg=True),
    "GlobalAveragePool": lambda i, a: jnp.mean(i[0], axis=(2, 3), keepdims=True),
    "GlobalMaxPool": lambda i, a: jnp.max(i[0], axis=(2, 3), keepdims=True),
    "BatchNormalization": _batch_norm,
    "Flatten": lambda i, a: i[0].reshape(
        int(np.prod(i[0].shape[:a.get("axis", 1)])), -1),
    "Reshape": lambda i, a: jnp.reshape(
        i[0], _resolve_reshape(i[0], np.asarray(i[1]).tolist())),
    "Shape": lambda i, a: jnp.asarray(i[0].shape, jnp.int64),
    "Squeeze": lambda i, a: jnp.squeeze(
        i[0], axis=tuple(_axes_arg(i, a)) or None),
    "Unsqueeze": lambda i, a: _unsqueeze(i[0], _axes_arg(i, a)),
    "Transpose": lambda i, a: jnp.transpose(i[0], a.get("perm")),
    "Concat": lambda i, a: jnp.concatenate(i, axis=a["axis"]),
    "Identity": lambda i, a: i[0],
    "Dropout": lambda i, a: i[0],
    "Constant": lambda i, a: jnp.asarray(a["value"]),
    "Gather": lambda i, a: jnp.take(i[0], i[1].astype(jnp.int32),
                                    axis=a.get("axis", 0)),
    "Slice": _slice,
    "ReduceMean": lambda i, a: jnp.mean(
        i[0], axis=tuple(_axes_arg(i, a)) or None,
        keepdims=bool(a.get("keepdims", 1))),
    "ReduceSum": lambda i, a: jnp.sum(
        i[0], axis=tuple(_axes_arg(i, a)) or None,
        keepdims=bool(a.get("keepdims", 1))),
    "Cast": lambda i, a: i[0].astype(_NP_DTYPES[a["to"]]),
}


def _axes_arg(inp, attrs):
    """Axes from attrs (opset <13) or from the second input (opset 13+) —
    Squeeze/ReduceSum/ReduceMean moved axes into an input tensor."""
    if len(inp) > 1 and inp[1] is not None:
        return [int(v) for v in np.asarray(inp[1]).reshape(-1)]
    return list(attrs.get("axes", []))


def _resolve_reshape(x, dims):
    # ONNX: 0 means copy input dim, -1 infers
    return [x.shape[i] if d == 0 else d for i, d in enumerate(dims)]


def _unsqueeze(x, axes):
    for ax in sorted(int(a) for a in axes):
        x = jnp.expand_dims(x, ax)
    return x


_PARAM_THRESHOLD = 32


class ONNXModule(Module):
    """An ONNX graph as a pure Module; initializers live in the params
    pytree (reference: ``PY/contrib/onnx`` loader builds a BigDL Graph)."""

    def __init__(self, model: "pb.ModelProto"):
        super().__init__()
        g = model.graph
        self.graph_proto = g
        self._init: Dict[str, np.ndarray] = {
            t.name: tensor_to_numpy(t) for t in g.initializer
        }
        self._param_names = [
            n for n, a in self._init.items()
            if a.size >= _PARAM_THRESHOLD and np.issubdtype(a.dtype, np.floating)
        ]
        self.input_names = [v.name for v in g.input if v.name not in self._init]
        self.output_names = [v.name for v in g.output]

    def build_params(self, rng):
        return {n.replace("/", "__").replace(".", "__"): jnp.asarray(self._init[n])
                for n in self._param_names}

    def forward(self, ctx: Context, x):
        xs = (x,) if len(self.input_names) == 1 else tuple(x)
        values: Dict[str, object] = {}
        param_set = set(self._param_names)
        for name, arr in self._init.items():
            if name in param_set:
                values[name] = ctx.param(name.replace("/", "__").replace(".", "__"))
            else:
                values[name] = arr
        for name, xi in zip(self.input_names, xs):
            values[name] = xi
        for node in self.graph_proto.node:
            fn = _OPS.get(node.op_type)
            if fn is None:
                raise NotImplementedError(
                    f"ONNX op {node.op_type!r} (node {node.name!r}) unsupported")
            # "" marks an omitted OPTIONAL input positionally — keep the slot
            # as None (dropping it would shift later inputs left); trailing
            # Nones are trimmed so len(args) checks keep working
            args = [values[i] if i else None for i in node.input]
            while args and args[-1] is None:
                args.pop()
            out = fn(args, _attrs(node))
            outs = out if isinstance(out, tuple) else (out,)
            for oname, val in zip(node.output, outs):
                values[oname] = val
        res = [values[n] for n in self.output_names]
        return res[0] if len(res) == 1 else tuple(res)


def load_onnx(path: str):
    """Returns ``(module, params, state)`` from an .onnx file."""
    model = pb.ModelProto()
    with open(path, "rb") as f:
        model.ParseFromString(f.read())
    module = ONNXModule(model)
    params, state = module.init(jax.random.key(0))
    return module, params, state
