"""ONNX exporter: (model, params, state) -> .onnx.

Reference: the ONNX direction the reference lacks an exporter for; coverage
mirrors the TF/Caffe persisters so the three interop tiers stay in sync.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.interop.onnx import onnx_pb2 as pb
from bigdl_tpu.interop.onnx.loader import numpy_to_tensor
from bigdl_tpu.nn.graph import Graph


class ONNXExporter:
    def __init__(self, model, params, state=None):
        self.model = model
        self.params = params
        self.state = state or {}
        self.g = pb.GraphProto(name=type(model).__name__)
        self._seq = 0

    def _name(self, base):
        self._seq += 1
        return f"{base}_{self._seq}"

    def _init(self, arr, base) -> str:
        name = self._name(base)
        self.g.initializer.append(numpy_to_tensor(np.asarray(arr, np.float32), name))
        return name

    def _init_i64(self, vals, base) -> str:
        name = self._name(base)
        self.g.initializer.append(
            numpy_to_tensor(np.asarray(vals, np.int64), name))
        return name

    def _node(self, op, inputs, base, **attrs) -> str:
        out = self._name(base)
        node = self.g.node.add(op_type=op, name=out)
        node.input.extend(inputs)
        node.output.append(out)
        for k, v in attrs.items():
            a = node.attribute.add(name=k)
            if isinstance(v, float):
                a.type = pb.AttributeProto.FLOAT
                a.f = v
            elif isinstance(v, int):
                a.type = pb.AttributeProto.INT
                a.i = v
            elif isinstance(v, (list, tuple)):
                a.type = pb.AttributeProto.INTS
                a.ints.extend(int(x) for x in v)
            elif isinstance(v, str):
                a.type = pb.AttributeProto.STRING
                a.s = v.encode()
            else:
                raise TypeError(f"attr {k}={v!r}")
        return out

    def save(self, path: str, input_shape: Optional[Tuple[int, ...]] = None):
        from bigdl_tpu.interop.walker import walk_model

        vi = self.g.input.add(name="input")
        vi.type.tensor_type.elem_type = pb.TensorProto.FLOAT
        if input_shape:
            for d in input_shape:
                vi.type.tensor_type.shape.dim.add().dim_value = d
        out = walk_model(self.model, self.params, self.state, "input",
                         self._emit_leaf)
        self.g.output.add(name=out).type.tensor_type.elem_type = pb.TensorProto.FLOAT
        model = pb.ModelProto(ir_version=8, producer_name="bigdl_tpu", graph=self.g)
        model.opset_import.add(domain="", version=13)
        with open(path, "wb") as f:
            f.write(model.SerializeToString())

    def _emit_leaf(self, m, p, s, ins: List[str], name=None) -> str:
        x = ins[0] if ins else None

        if type(m) is nn.Linear:
            w = self._init(p["weight"], "weight")  # (out, in), transB=1
            inputs = [x, w]
            if m.with_bias:
                inputs.append(self._init(p["bias"], "bias"))
            return self._node("Gemm", inputs, "gemm", transB=1)

        if type(m) in (nn.SpatialConvolution, nn.SpatialShareConvolution):
            # OIHW is onnx-native; HWIO storage transposes on export
            w = self._init(m.weight_as_oihw(p["weight"]), "weight")
            inputs = [x, w]
            if m.with_bias:
                inputs.append(self._init(p["bias"], "bias"))
            kh, kw = m.kernel
            sh, sw = m.stride
            ph, pw = m.pad
            if ph == -1 or pw == -1:  # TF-style SAME padding mode
                return self._node("Conv", inputs, "conv",
                                  kernel_shape=[kh, kw], strides=[sh, sw],
                                  group=m.n_group, auto_pad="SAME_UPPER")
            return self._node("Conv", inputs, "conv",
                              kernel_shape=[kh, kw], strides=[sh, sw],
                              pads=[ph, pw, ph, pw], group=m.n_group)

        if isinstance(m, (nn.SpatialMaxPooling, nn.SpatialAveragePooling)):
            if m.ceil_mode:
                raise ValueError("onnx export: ceil-mode pooling unsupported")
            kh, kw = m.kernel
            sh, sw = m.stride
            ph, pw = m.pad
            if isinstance(m, nn.SpatialMaxPooling):
                return self._node("MaxPool", [x], "maxpool",
                                  kernel_shape=[kh, kw], strides=[sh, sw],
                                  pads=[ph, pw, ph, pw])
            return self._node("AveragePool", [x], "averagepool",
                              kernel_shape=[kh, kw], strides=[sh, sw],
                              pads=[ph, pw, ph, pw],
                              count_include_pad=int(m.count_include_pad))

        if isinstance(m, nn.SpatialBatchNormalization):
            mean = np.asarray(s["running_mean"])
            var = np.asarray(s["running_var"])
            gamma = np.asarray(p["weight"]) if m.affine else np.ones_like(mean)
            beta = np.asarray(p["bias"]) if m.affine else np.zeros_like(mean)
            return self._node(
                "BatchNormalization",
                [x, self._init(gamma, "gamma"), self._init(beta, "beta"),
                 self._init(mean, "mean"), self._init(var, "var")],
                "bn", epsilon=float(m.eps))

        if isinstance(m, nn.GlobalAveragePooling2D):
            y = self._node("GlobalAveragePool", [x], "gap")
            return self._node("Flatten", [y], "flatten", axis=1)

        if isinstance(m, nn.Reshape):
            shape = self._init_i64([0] + list(m.size), "shape")
            return self._node("Reshape", [x, shape], "reshape")

        if isinstance(m, (nn.Dropout, nn.Identity)):
            return self._node("Identity", [x], "identity")

        simple = {nn.ReLU: "Relu", nn.Tanh: "Tanh", nn.Sigmoid: "Sigmoid",
                  nn.SoftMax: "Softmax", nn.LogSoftMax: "LogSoftmax"}
        for cls, op in simple.items():
            if type(m) is cls:
                return self._node(op, [x], op.lower())

        if isinstance(m, nn.CAddTable):
            out = ins[0]
            for other in ins[1:]:
                out = self._node("Add", [out, other], "add")
            return out
        if isinstance(m, nn.JoinTable):
            return self._node("Concat", ins, "concat", axis=int(m.dimension))

        raise ValueError(f"onnx export does not support {type(m).__name__}")


def save_onnx(model, params, state, path: str,
              input_shape: Optional[Tuple[int, ...]] = None) -> None:
    ONNXExporter(model, params, state).save(path, input_shape)
