"""Reference-format (protobuf) BigDL model serialization.

Reference: ``DL/utils/serializer/`` — models persist as one protobuf
``BigDLModule`` tree (``ModuleLoader.loadFromFile`` parses the raw
bytes, ``ModuleSerializable.doSerializeModule`` stores constructor args
in the ``attr`` map keyed by the Scala parameter names, and
``copyFromBigDL`` appends ``parameters`` = [weight, bias] tensors with
id-shared ``TensorStorage``). Schema: ``bigdl_model.proto`` here, wire-
compatible with ``spark/dl/src/main/resources/serialization/bigdl.proto``.

This module maps that format onto the TPU-native module zoo both ways:

- ``load_bigdl(path)`` -> ``(module, params, state)`` — reads a model
  saved by the reference (``Module.saveModule``) covering the
  Sequential/Graph container tier and the common layer set
  (conv/linear/BN/pool/activations/LRN/dropout/reshape/table ops/
  embedding/temporal conv).
- ``save_bigdl(path, module, params, state)`` — writes a file the
  reference can read back (ctor attrs under Scala names + module_tags/
  module_numerics markers + version).

BN running statistics travel as TENSOR attrs exactly like the reference
(``BatchNormalization.doSerializeModule`` persists ``runningMean`` /
``runningVar`` / ``saveMean`` / ``saveStd``, ``BatchNormalization.scala:396-433``);
they load into module *state* here and are emitted from state on save.

Weight layout conversions (Scala <-> here):
- SpatialConvolution: (nGroup, out/g, in/g, kH, kW) <-> (out, in/g, kH, kW)
- TemporalConvolution: (out, kW*in) <-> (out, in, kW)
- Linear/LookupTable/BN: identical shapes.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.interop.bigdl import bigdl_pb2 as pb

SCALA_NN = "com.intel.analytics.bigdl.nn."
_VERSION = "0.10.0"


# -- attr helpers -------------------------------------------------------------

def _attr_int(v: int) -> pb.AttrValue:
    return pb.AttrValue(dataType=pb.INT32, int32Value=int(v))


def _attr_double(v: float) -> pb.AttrValue:
    return pb.AttrValue(dataType=pb.DOUBLE, doubleValue=float(v))


def _attr_bool(v: bool) -> pb.AttrValue:
    return pb.AttrValue(dataType=pb.BOOL, boolValue=bool(v))


def _attr_str(v: str) -> pb.AttrValue:
    return pb.AttrValue(dataType=pb.STRING, stringValue=v)


def _attr_null(dtype) -> pb.AttrValue:
    """A null-valued attr (regularizer/tensor ctor args the zoo leaves
    unset — the reference writes the dataType with no value)."""
    return pb.AttrValue(dataType=dtype)


def _attr_int_array(vals: Sequence[int]) -> pb.AttrValue:
    a = pb.AttrValue(dataType=pb.ARRAY_VALUE)
    a.arrayValue.size = len(vals)
    a.arrayValue.datatype = pb.INT32
    a.arrayValue.i32.extend(int(v) for v in vals)
    return a


def _attr_str_array(vals: Sequence[str]) -> pb.AttrValue:
    a = pb.AttrValue(dataType=pb.ARRAY_VALUE)
    a.arrayValue.size = len(vals)
    a.arrayValue.datatype = pb.STRING
    a.arrayValue.str.extend(vals)
    return a


def _attr_data_format(fmt: str) -> pb.AttrValue:
    return pb.AttrValue(dataType=pb.DATA_FORMAT,
                        dataFormatValue=pb.NCHW if fmt == "NCHW" else pb.NHWC)


def _get(attrs, key: str, default=None):
    """Read one attr by its wire dataType."""
    if key not in attrs:
        return default
    a = attrs[key]
    field = a.WhichOneof("value")
    if field is None:
        return default
    v = getattr(a, field)
    if field == "arrayValue":
        dt = v.datatype
        if dt == pb.INT32:
            return list(v.i32)
        if dt == pb.STRING:
            return list(v.str)
        if dt == pb.FLOAT:
            return list(v.flt)
        if dt == pb.DOUBLE:
            return list(v.dbl)
        if dt == pb.BOOL:
            return list(v.boolean)
        return v
    if field == "dataFormatValue":
        return "NCHW" if v == pb.NCHW else "NHWC"
    return v


# -- tensor <-> proto ---------------------------------------------------------

class _StorageBook:
    """Shared-storage bookkeeping (reference ``TensorStorageManager``):
    tensors referencing the same storage id resolve to one array."""

    def __init__(self):
        self.by_id: Dict[int, np.ndarray] = {}
        self._next = 1

    def collect(self, module: pb.BigDLModule) -> None:
        attr_tensors = [a.tensorValue for a in module.attr.values()
                        if a.WhichOneof("value") == "tensorValue"]
        for t in list(module.parameters) + [module.weight, module.bias] + attr_tensors:
            if t.HasField("storage") and len(t.storage.float_data):
                self.by_id[t.storage.id] = np.asarray(
                    t.storage.float_data, np.float32)
            elif t.HasField("storage") and len(t.storage.double_data):
                self.by_id[t.storage.id] = np.asarray(
                    t.storage.double_data, np.float64).astype(np.float32)
        for sub in module.subModules:
            self.collect(sub)

    def tensor_to_np(self, t: pb.BigDLTensor) -> Optional[np.ndarray]:
        if t.dimension == 0 and not t.isScalar:
            return None
        data = self.by_id.get(t.storage.id if t.HasField("storage") else t.id)
        if data is None and t.HasField("storage"):
            data = (np.asarray(t.storage.float_data, np.float32)
                    if len(t.storage.float_data) else None)
        if data is None:
            return None
        off = max(0, t.offset - 1)  # Torch storageOffset is 1-based
        flat = data[off:off + t.nElements]
        return flat.reshape(tuple(t.size))

    def np_to_tensor(self, arr: np.ndarray) -> pb.BigDLTensor:
        arr = np.ascontiguousarray(np.asarray(arr, np.float32))
        sid = self._next
        self._next += 1
        strides = []
        acc = 1
        for d in reversed(arr.shape):
            strides.insert(0, acc)
            acc *= d
        t = pb.BigDLTensor(
            datatype=pb.FLOAT, size=list(arr.shape), stride=strides,
            offset=1, dimension=arr.ndim, nElements=arr.size,
            isScalar=(arr.ndim == 0), id=sid,
        )
        t.storage.datatype = pb.FLOAT
        t.storage.id = sid
        t.storage.float_data.extend(arr.reshape(-1).tolist())
        return t


# -- layer converters ---------------------------------------------------------
# each entry: scala short name -> (to_module(attrs), from_module(module))
# where to_module returns our Module and from_module returns (attr_dict).

def _conv_to(attrs):
    return nn.SpatialConvolution(
        _get(attrs, "nInputPlane"), _get(attrs, "nOutputPlane"),
        _get(attrs, "kernelW"), _get(attrs, "kernelH"),
        _get(attrs, "strideW", 1), _get(attrs, "strideH", 1),
        _get(attrs, "padW", 0), _get(attrs, "padH", 0),
        n_group=_get(attrs, "nGroup", 1),
        with_bias=_get(attrs, "withBias", True),
        data_format=_get(attrs, "format", "NCHW"),
    )


def _conv_from(m):
    return {
        "nInputPlane": _attr_int(m.n_input_plane),
        "nOutputPlane": _attr_int(m.n_output_plane),
        "kernelW": _attr_int(m.kernel[1]), "kernelH": _attr_int(m.kernel[0]),
        "strideW": _attr_int(m.stride[1]), "strideH": _attr_int(m.stride[0]),
        "padW": _attr_int(m.pad[1]), "padH": _attr_int(m.pad[0]),
        "nGroup": _attr_int(m.n_group), "propagateBack": _attr_bool(True),
        "wRegularizer": _attr_null(pb.REGULARIZER),
        "bRegularizer": _attr_null(pb.REGULARIZER),
        "initWeight": _attr_null(pb.TENSOR), "initBias": _attr_null(pb.TENSOR),
        "initGradWeight": _attr_null(pb.TENSOR),
        "initGradBias": _attr_null(pb.TENSOR),
        "withBias": _attr_bool(m.with_bias),
        "format": _attr_data_format(m.data_format),
    }


def _linear_to(attrs):
    return nn.Linear(_get(attrs, "inputSize"), _get(attrs, "outputSize"),
                     with_bias=_get(attrs, "withBias", True))


def _linear_from(m):
    return {
        "inputSize": _attr_int(m.input_size),
        "outputSize": _attr_int(m.output_size),
        "withBias": _attr_bool(m.with_bias),
        "wRegularizer": _attr_null(pb.REGULARIZER),
        "bRegularizer": _attr_null(pb.REGULARIZER),
        "initWeight": _attr_null(pb.TENSOR), "initBias": _attr_null(pb.TENSOR),
        "initGradWeight": _attr_null(pb.TENSOR),
        "initGradBias": _attr_null(pb.TENSOR),
    }


def _bn_to(attrs, spatial):
    cls = nn.SpatialBatchNormalization if spatial else nn.BatchNormalization
    kw = {}
    if spatial:
        kw["data_format"] = _get(attrs, "dataFormat", "NCHW")
    return cls(_get(attrs, "nOutput"), eps=_get(attrs, "eps", 1e-5),
               momentum=_get(attrs, "momentum", 0.1),
               affine=_get(attrs, "affine", True), **kw)


def _bn_from(m, spatial):
    d = {
        "nOutput": _attr_int(m.n_output), "eps": _attr_double(m.eps),
        "momentum": _attr_double(m.momentum), "affine": _attr_bool(m.affine),
        "initWeight": _attr_null(pb.TENSOR), "initBias": _attr_null(pb.TENSOR),
        "initGradWeight": _attr_null(pb.TENSOR),
        "initGradBias": _attr_null(pb.TENSOR),
    }
    if spatial:
        d["dataFormat"] = _attr_data_format(
            "NCHW" if m.ch_axis == 1 else "NHWC")
    return d


def _maxpool_to(attrs):
    m = nn.SpatialMaxPooling(
        _get(attrs, "kW"), _get(attrs, "kH"),
        _get(attrs, "dW", None) or _get(attrs, "kW"),
        _get(attrs, "dH", None) or _get(attrs, "kH"),
        _get(attrs, "padW", 0), _get(attrs, "padH", 0),
        data_format=_get(attrs, "format", "NCHW"),
    )
    if _get(attrs, "ceilMode", False):
        m.ceil_mode = True
    return m


def _pool_from(m):
    (kh, kw), (dh, dw), (ph, pw) = m.kernel, m.stride, m.pad
    return {
        "kW": _attr_int(kw), "kH": _attr_int(kh),
        "dW": _attr_int(dw), "dH": _attr_int(dh),
        "padW": _attr_int(pw), "padH": _attr_int(ph),
        "format": _attr_data_format(m.data_format),
        "ceilMode": _attr_bool(getattr(m, "ceil_mode", False)),
    }


def _avgpool_to(attrs):
    if _get(attrs, "globalPooling", False):
        return nn.GlobalAveragePooling2D(
            data_format=_get(attrs, "format", "NCHW"))
    m = nn.SpatialAveragePooling(
        _get(attrs, "kW"), _get(attrs, "kH"),
        _get(attrs, "dW", None) or _get(attrs, "kW"),
        _get(attrs, "dH", None) or _get(attrs, "kH"),
        _get(attrs, "padW", 0), _get(attrs, "padH", 0),
        count_include_pad=_get(attrs, "countIncludePad", True),
        data_format=_get(attrs, "format", "NCHW"),
    )
    if _get(attrs, "ceilMode", False):
        m.ceil_mode = True
    return m


def _avgpool_from(m):
    d = _pool_from(m)
    d["countIncludePad"] = _attr_bool(m.count_include_pad)
    d["globalPooling"] = _attr_bool(False)
    d["divide"] = _attr_bool(True)
    return d


_SIMPLE = {
    "ReLU": (lambda attrs: nn.ReLU(), lambda m: {"ip": _attr_bool(False)}),
    "Tanh": (lambda attrs: nn.Tanh(), lambda m: {}),
    "Sigmoid": (lambda attrs: nn.Sigmoid(), lambda m: {}),
    "LogSoftMax": (lambda attrs: nn.LogSoftMax(), lambda m: {}),
    "SoftMax": (lambda attrs: nn.SoftMax(), lambda m: {}),
    "Identity": (lambda attrs: nn.Identity(), lambda m: {}),
    "CAddTable": (lambda attrs: nn.CAddTable(),
                  lambda m: {"inplace": _attr_bool(False)}),
    "Input": (lambda attrs: nn.Identity(), lambda m: {}),
}


def _registry():
    reg: Dict[str, Tuple[Callable, type, Callable]] = {}

    def add(name, to_fn, cls, from_fn):
        reg[name] = (to_fn, cls, from_fn)

    add("SpatialConvolution", _conv_to, nn.SpatialConvolution, _conv_from)
    add("Linear", _linear_to, nn.Linear, _linear_from)
    add("SpatialBatchNormalization", lambda a: _bn_to(a, True),
        nn.SpatialBatchNormalization, lambda m: _bn_from(m, True))
    add("BatchNormalization", lambda a: _bn_to(a, False),
        nn.BatchNormalization, lambda m: _bn_from(m, False))
    add("SpatialMaxPooling", _maxpool_to, nn.SpatialMaxPooling, _pool_from)
    add("SpatialAveragePooling", _avgpool_to, nn.SpatialAveragePooling,
        _avgpool_from)
    add("Dropout", lambda a: nn.Dropout(_get(a, "initP", 0.5)),
        nn.Dropout, lambda m: {"initP": _attr_double(m.p),
                               "inplace": _attr_bool(False),
                               "scale": _attr_bool(True)})
    add("Reshape", lambda a: nn.Reshape(list(_get(a, "size"))),
        nn.Reshape, lambda m: {"size": _attr_int_array(m.size),
                               "batchMode": _attr_null(pb.BOOL)})
    add("View", lambda a: nn.View(*_get(a, "sizes")),
        nn.View, lambda m: {"sizes": _attr_int_array(m.sizes),
                            "num_input_dims": _attr_int(0)})
    add("SpatialCrossMapLRN",
        lambda a: nn.SpatialCrossMapLRN(_get(a, "size", 5),
                                        _get(a, "alpha", 1.0),
                                        _get(a, "beta", 0.75),
                                        _get(a, "k", 1.0)),
        nn.SpatialCrossMapLRN,
        lambda m: {"size": _attr_int(m.size), "alpha": _attr_double(m.alpha),
                   "beta": _attr_double(m.beta), "k": _attr_double(m.k)})
    add("JoinTable",
        lambda a: nn.JoinTable(_get(a, "dimension") - 1,
                               _get(a, "nInputDims", -1)),
        nn.JoinTable,
        lambda m: {"dimension": _attr_int(m.dimension + 1),
                   "nInputDims": _attr_int(m.n_input_dims)})
    add("LookupTable",
        lambda a: nn.LookupTable(_get(a, "nIndex"), _get(a, "nOutput"),
                                 padding_value=int(_get(a, "paddingValue", 0)) or None),
        nn.LookupTable,
        lambda m: {"nIndex": _attr_int(m.n_index),
                   "nOutput": _attr_int(m.n_output),
                   "paddingValue": _attr_double(m.padding_value or 0),
                   "maxNorm": _attr_double(1e20),
                   "normType": _attr_double(2.0),
                   "shouldScaleGradByFreq": _attr_bool(False),
                   "wRegularizer": _attr_null(pb.REGULARIZER)})
    add("TemporalConvolution",
        lambda a: nn.TemporalConvolution(_get(a, "inputFrameSize"),
                                         _get(a, "outputFrameSize"),
                                         _get(a, "kernelW"),
                                         _get(a, "strideW", 1)),
        nn.TemporalConvolution,
        lambda m: {"inputFrameSize": _attr_int(m.input_frame_size),
                   "outputFrameSize": _attr_int(m.output_frame_size),
                   "kernelW": _attr_int(m.kernel_w),
                   "strideW": _attr_int(m.stride_w),
                   "propagateBack": _attr_bool(True),
                   "wRegularizer": _attr_null(pb.REGULARIZER),
                   "bRegularizer": _attr_null(pb.REGULARIZER),
                   "initWeight": _attr_null(pb.TENSOR),
                   "initBias": _attr_null(pb.TENSOR),
                   "initGradWeight": _attr_null(pb.TENSOR),
                   "initGradBias": _attr_null(pb.TENSOR)})
    add("Padding",
        lambda a: nn.Padding(_get(a, "dim") - 1, _get(a, "pad"),
                             _get(a, "value", 0.0)),
        nn.Padding,
        lambda m: {"dim": _attr_int(m.dim + 1), "pad": _attr_int(m.pad),
                   "nInputDim": _attr_int(0),
                   "value": _attr_double(m.value), "nIndex": _attr_int(1)})
    for name, (to_fn, from_fn) in _SIMPLE.items():
        cls = type(to_fn({}))
        add(name, to_fn, cls, from_fn)
    return reg


_REG = _registry()


# -- weight layout conversions -----------------------------------------------

def _weights_to_ours(module, tensors: List[np.ndarray]) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {}
    if not tensors:
        return out
    if isinstance(module, nn.SpatialConvolution):
        w = tensors[0]
        if w.ndim == 5:  # (g, o/g, i/g, kh, kw) -> (o, i/g, kh, kw)
            w = w.reshape((-1,) + w.shape[2:])
        out["weight"] = module.weight_from_oihw(w)
    elif isinstance(module, nn.TemporalConvolution):
        w = tensors[0]
        if w.ndim == 2:  # (out, kw*in) frame-major -> (out, in, kw)
            w = w.reshape(w.shape[0], module.kernel_w,
                          module.input_frame_size).transpose(0, 2, 1)
        out["weight"] = w
    else:
        out["weight"] = tensors[0]
    if len(tensors) > 1:
        out["bias"] = tensors[1]
    return out


def _weights_from_ours(module, params: Dict[str, Any]) -> List[np.ndarray]:
    if not isinstance(params, dict) or "weight" not in params:
        return []
    w = np.asarray(params["weight"], np.float32)
    if isinstance(module, nn.SpatialConvolution):
        w = np.asarray(module.weight_as_oihw(w))
        o, ig, kh, kw = w.shape
        g = module.n_group
        w = w.reshape(g, o // g, ig, kh, kw)
    elif isinstance(module, nn.TemporalConvolution):
        w = w.transpose(0, 2, 1).reshape(w.shape[0], -1)
    tensors = [w]
    if "bias" in params:
        tensors.append(np.asarray(params["bias"], np.float32))
    return tensors


# -- load ---------------------------------------------------------------------

def _attr_tensor(attrs, key: str, book: _StorageBook) -> Optional[np.ndarray]:
    if key not in attrs:
        return None
    a = attrs[key]
    if a.WhichOneof("value") != "tensorValue":
        return None
    return book.tensor_to_np(a.tensorValue)


def _module_from_proto(mod: pb.BigDLModule, book: _StorageBook,
                       params_out: Dict[str, Any],
                       state_out: Dict[str, Any]) -> nn.Module:
    short = mod.moduleType.rsplit(".", 1)[-1]
    if short == "Sequential":
        seq = nn.Sequential()
        for i, sub in enumerate(mod.subModules):
            child_params: Dict[str, Any] = {}
            child_state: Dict[str, Any] = {}
            child = _module_from_proto(sub, book, child_params, child_state)
            name = sub.name or str(i)
            seq.add(child, name)
            if child_params:
                params_out[name] = child_params
            if child_state:
                state_out[name] = child_state
        if mod.name:
            seq.set_name(mod.name)
        return seq
    if short in ("ConcatTable", "Concat"):
        children = []
        for i, sub in enumerate(mod.subModules):
            child_params: Dict[str, Any] = {}
            child_state: Dict[str, Any] = {}
            child = _module_from_proto(sub, book, child_params, child_state)
            children.append((sub.name or str(i), child, child_params, child_state))
        if short == "Concat":
            cont = nn.Concat(int(_get(mod.attr, "dimension", 2)) - 1)
        else:
            cont = nn.ConcatTable()
        for name, child, child_params, child_state in children:
            cont.add(child, name)
            if child_params:
                params_out[name] = child_params
            if child_state:
                state_out[name] = child_state
        if mod.name:
            cont.set_name(mod.name)
        return cont
    if short in ("StaticGraph", "Graph", "DynamicGraph"):
        return _graph_from_proto(mod, book, params_out, state_out)

    if short not in _REG:
        raise ValueError(
            f"no converter for reference module type {mod.moduleType!r}")
    to_fn = _REG[short][0]
    module = to_fn(mod.attr)
    if mod.name:
        module.set_name(mod.name)
    tensors = [book.tensor_to_np(t) for t in mod.parameters]
    tensors = [t for t in tensors if t is not None]
    params_out.update(_weights_to_ours(module, tensors))
    if isinstance(module, nn.BatchNormalization):
        rm = _attr_tensor(mod.attr, "runningMean", book)
        rv = _attr_tensor(mod.attr, "runningVar", book)
        if rm is not None and rm.size:
            state_out["running_mean"] = rm.reshape(-1)
        if rv is not None and rv.size:
            state_out["running_var"] = rv.reshape(-1)
    return module


def _graph_from_proto(mod: pb.BigDLModule, book: _StorageBook,
                      params_out: Dict[str, Any],
                      state_out: Dict[str, Any]) -> nn.Module:
    """Rebuild a StaticGraph: subModules are forward-execution nodes with
    preModules linkage; inputNames/outputNames attrs name the endpoints
    (reference ``Graph.doSerializeModule``)."""
    input_names = list(_get(mod.attr, "inputNames", []))
    output_names = list(_get(mod.attr, "outputNames", []))
    nodes: Dict[str, Any] = {}
    order: List[Tuple[str, pb.BigDLModule]] = []
    for sub in mod.subModules:
        order.append((sub.name, sub))

    graph_inputs = []
    for name, sub in order:
        short = sub.moduleType.rsplit(".", 1)[-1]
        pre = [p for p in sub.preModules]
        if short == "Input" or (not pre and name in input_names):
            node = nn.Input()
            nodes[name] = node
            graph_inputs.append(node)
            continue
        child_params: Dict[str, Any] = {}
        child_state: Dict[str, Any] = {}
        child = _module_from_proto(sub, book, child_params, child_state)
        parents = [nodes[p] for p in pre]
        node = child(*parents)
        nodes[name] = node
        if child_params:
            params_out[name] = child_params
        if child_state:
            state_out[name] = child_state
    outs = [nodes[n] for n in output_names]
    graph = nn.Graph(graph_inputs, outs)
    if mod.name:
        graph.set_name(mod.name)
    return graph


def load_bigdl(path: str):
    """Load a reference-format protobuf model file. Returns
    (module, params, state)."""
    mod = pb.BigDLModule()
    with open(path, "rb") as f:
        mod.ParseFromString(f.read())
    book = _StorageBook()
    book.collect(mod)
    loaded_params: Dict[str, Any] = {}
    loaded_state: Dict[str, Any] = {}
    module = _module_from_proto(mod, book, loaded_params, loaded_state)

    import jax

    params, state = module.init(jax.random.key(0))
    merged = _merge(params, loaded_params)
    merged_state = _merge(state, loaded_state)
    return module, merged, merged_state


def _merge(inited, loaded):
    """Overlay loaded leaf arrays onto the init()-shaped tree (missing
    entries keep their init — e.g. BN running stats live in state)."""
    if not isinstance(inited, dict):
        return loaded if loaded is not None else inited
    out = {}
    for k, v in inited.items():
        if isinstance(loaded, dict) and k in loaded:
            lv = loaded[k]
            if isinstance(v, dict):
                out[k] = _merge(v, lv)
            else:
                arr = np.asarray(lv, np.float32)
                if tuple(arr.shape) != tuple(np.shape(v)):
                    raise ValueError(
                        f"shape mismatch for {k}: file {arr.shape} vs "
                        f"module {np.shape(v)}")
                out[k] = arr
        else:
            out[k] = v
    return out


# -- save ---------------------------------------------------------------------

def _module_to_proto(module: nn.Module, params, book: _StorageBook,
                     name: str, state=None) -> pb.BigDLModule:
    mod = pb.BigDLModule(version=_VERSION, train=False)
    mod.name = module.get_name() or name
    mod.attr["module_tags"].CopyFrom(_attr_str_array(["Float"]))
    mod.attr["module_numerics"].CopyFrom(_attr_str_array(["Float"]))

    if isinstance(module, nn.Graph):
        return _graph_to_proto(module, params, book, mod, state)

    if isinstance(module, (nn.Sequential, nn.ConcatTable, nn.Concat)):
        short = type(module).__name__
        mod.moduleType = SCALA_NN + short
        if isinstance(module, nn.Concat):
            mod.attr["dimension"].CopyFrom(_attr_int(module.dimension + 1))
        for child_name, child in module._modules.items():
            child_params = params.get(child_name, {}) if isinstance(params, dict) else {}
            child_state = state.get(child_name, {}) if isinstance(state, dict) else {}
            mod.subModules.append(
                _module_to_proto(child, child_params, book, child_name,
                                 child_state))
        return mod

    if isinstance(module, nn.GlobalAveragePooling2D):
        # reference encoding: SpatialAveragePooling with globalPooling=true
        mod.moduleType = SCALA_NN + "SpatialAveragePooling"
        fmt = "NCHW" if module.axes == (2, 3) else "NHWC"
        for k, v in {"kW": _attr_int(1), "kH": _attr_int(1),
                     "dW": _attr_int(1), "dH": _attr_int(1),
                     "padW": _attr_int(0), "padH": _attr_int(0),
                     "globalPooling": _attr_bool(True),
                     "ceilMode": _attr_bool(False),
                     "countIncludePad": _attr_bool(True),
                     "divide": _attr_bool(True),
                     "format": _attr_data_format(fmt)}.items():
            mod.attr[k].CopyFrom(v)
        return mod

    cls = type(module)
    short = next((k for k, (_, c, _) in _REG.items() if c is cls), None)
    if short is None:
        raise ValueError(f"no reference-format serializer for {cls.__name__} "
                         "(extend bigdl_tpu.interop.bigdl._registry)")
    mod.moduleType = SCALA_NN + short
    for k, v in _REG[short][2](module).items():
        mod.attr[k].CopyFrom(v)
    if isinstance(module, nn.BatchNormalization):
        # the reference loader reads all four stat attrs unconditionally
        # (BatchNormalization.scala doLoadModule); saveMean/saveStd are the
        # last-forward transients, re-derived here from the running stats
        st = state if isinstance(state, dict) else {}
        rm = np.asarray(st.get("running_mean",
                               np.zeros(module.n_output)), np.float32)
        rv = np.asarray(st.get("running_var",
                               np.ones(module.n_output)), np.float32)
        for key, arr in (("runningMean", rm), ("runningVar", rv),
                         ("saveMean", rm),
                         ("saveStd", 1.0 / np.sqrt(rv + module.eps))):
            a = pb.AttrValue(dataType=pb.TENSOR)
            a.tensorValue.CopyFrom(book.np_to_tensor(arr))
            mod.attr[key].CopyFrom(a)
    tensors = _weights_from_ours(module, params)
    if tensors:
        mod.hasParameters = True
        for t in tensors:
            mod.parameters.append(book.np_to_tensor(t))
    return mod


def _graph_to_proto(graph: nn.Graph, params, book: _StorageBook,
                    mod: pb.BigDLModule, state=None) -> pb.BigDLModule:
    mod.moduleType = SCALA_NN + "StaticGraph"
    input_names, output_names = [], []
    names = dict(graph._names)
    for node in graph._topo:
        name = names.get(id(node))
        if node.element is None:  # Input node
            name = name or f"input{len(input_names) + 1}"
            names[id(node)] = name
            sub = pb.BigDLModule(version=_VERSION, name=name,
                                 moduleType=SCALA_NN + "Input")
            sub.attr["module_tags"].CopyFrom(_attr_str_array(["Float"]))
            sub.attr["module_numerics"].CopyFrom(_attr_str_array(["Float"]))
            mod.subModules.append(sub)
            input_names.append(name)
            continue
        child_params = params.get(name, {}) if isinstance(params, dict) else {}
        child_state = state.get(name, {}) if isinstance(state, dict) else {}
        sub = _module_to_proto(node.element, child_params, book, name,
                               child_state)
        sub.name = name
        for p in node.prev:
            sub.preModules.append(names[id(p)])
        mod.subModules.append(sub)
    for out in graph.outputs:
        output_names.append(names[id(out)])
    mod.attr["inputNames"].CopyFrom(_attr_str_array(input_names))
    mod.attr["outputNames"].CopyFrom(_attr_str_array(output_names))
    return mod


def save_bigdl(path: str, module: nn.Module, params, state=None) -> str:
    """Write a reference-format protobuf model file."""
    book = _StorageBook()
    proto = _module_to_proto(module, params or {}, book, "model", state or {})
    with open(path, "wb") as f:
        f.write(proto.SerializeToString())
    return path
