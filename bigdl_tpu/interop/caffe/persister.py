"""Caffe model persister: (model, params, state) -> prototxt + caffemodel.

Reference: ``DL/utils/caffe/CaffePersister.scala`` — walk the module graph,
emit one caffe ``LayerParameter`` per module with its weight blobs, write
the definition as text prototxt and the weights as a binary caffemodel.

Supports the same module set the loader consumes, so
``persist -> load`` round-trips: SpatialConvolution, Linear (with its
implicit flatten), poolings, ReLU/Sigmoid/Tanh/Abs/Power, SoftMax,
Dropout, SpatialCrossMapLRN, SpatialBatchNormalization (emitted as the
caffe BatchNorm + Scale pair), CAdd/CMul/CMaxTable, JoinTable, Reshape,
Identity. Containers (Sequential / Graph) are walked recursively.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.interop.caffe import caffe_pb2 as pb
from bigdl_tpu.nn.graph import Graph


def _np(x):
    return np.asarray(x, dtype=np.float32)


def _add_blob(layer_msg, arr: np.ndarray):
    blob = layer_msg.blobs.add()
    blob.shape.dim.extend(int(d) for d in arr.shape)
    blob.data.extend(_np(arr).reshape(-1).tolist())


class CaffePersister:
    """Reference ``CaffePersister.persist``."""

    def __init__(self, model, params, state=None,
                 input_shape: Optional[Tuple[int, ...]] = None):
        self.model = model
        self.params = params
        self.state = state or {}
        self.input_shape = input_shape

    def persist(self, prototxt_path: str, caffemodel_path: str) -> None:
        net = self.to_netparameter()
        from google.protobuf import text_format

        # prototxt carries the definition only (no blobs)
        defn = pb.NetParameter()
        defn.CopyFrom(net)
        for layer in defn.layer:
            del layer.blobs[:]
        with open(prototxt_path, "w") as f:
            f.write(text_format.MessageToString(defn))
        with open(caffemodel_path, "wb") as f:
            f.write(net.SerializeToString())

    # ------------------------------------------------------------------
    def to_netparameter(self) -> "pb.NetParameter":
        from bigdl_tpu.interop.walker import walk_model

        net = pb.NetParameter(name=type(self.model).__name__)
        inp = net.layer.add(name="data", type="Input", top=["data"])
        if self.input_shape is not None:
            inp.input_param.shape.add().dim.extend(int(d) for d in self.input_shape)
        self._seq = 0
        self._net = net
        walk_model(self.model, self.params, self.state, "data", self._emit_leaf)
        return net

    def _next_name(self, base: str) -> str:
        self._seq += 1
        return f"{base}{self._seq}"

    def _emit_leaf(self, m, p, s, bottoms: List[str],
                   preferred_name: Optional[str] = None) -> str:
        net = self._net
        p = p or {}
        s = s or {}

        def add(type_: str, base: str, n_bottom=1):
            name = preferred_name or self._next_name(base)
            layer = net.layer.add(name=name, type=type_,
                                  bottom=bottoms[:n_bottom] if n_bottom else bottoms,
                                  top=[name])
            return name, layer

        if type(m) in (nn.SpatialConvolution, nn.SpatialShareConvolution):
            name, layer = add("Convolution", "conv")
            cp = layer.convolution_param
            cp.num_output = m.n_output_plane
            kh, kw = m.kernel
            sh, sw = m.stride
            ph, pw = m.pad
            if ph == -1 or pw == -1:
                raise ValueError(
                    "caffe export: TF-style SAME padding (pad = -1) has no "
                    "caffe equivalent; use explicit padding")
            cp.kernel_h, cp.kernel_w = kh, kw
            cp.stride_h, cp.stride_w = sh, sw
            cp.pad_h, cp.pad_w = ph, pw
            cp.group = m.n_group
            cp.bias_term = m.with_bias
            _add_blob(layer, _np(m.weight_as_oihw(p["weight"])))
            if m.with_bias:
                _add_blob(layer, _np(p["bias"]))
            return name

        if type(m) is nn.Linear:
            name, layer = add("InnerProduct", "fc")
            ip = layer.inner_product_param
            ip.num_output = m.output_size
            ip.bias_term = m.with_bias
            _add_blob(layer, _np(p["weight"]))
            if m.with_bias:
                _add_blob(layer, _np(p["bias"]))
            return name

        if isinstance(m, nn.SpatialMaxPooling) or isinstance(m, nn.SpatialAveragePooling):
            name, layer = add("Pooling", "pool")
            pp = layer.pooling_param
            pp.pool = (pb.PoolingParameter.AVE
                       if isinstance(m, nn.SpatialAveragePooling)
                       else pb.PoolingParameter.MAX)
            kh, kw = m.kernel
            sh, sw = m.stride
            ph, pw = m.pad
            pp.kernel_h, pp.kernel_w = kh, kw
            pp.stride_h, pp.stride_w = sh, sw
            pp.pad_h, pp.pad_w = ph, pw
            if not m.ceil_mode:  # caffe defaults to ceil; record floor mode
                pp.round_mode = pb.PoolingParameter.FLOOR
            return name

        if isinstance(m, nn.GlobalAveragePooling2D):
            name, layer = add("Pooling", "pool")
            layer.pooling_param.pool = pb.PoolingParameter.AVE
            layer.pooling_param.global_pooling = True
            return name
        if isinstance(m, nn.GlobalMaxPooling2D):
            name, layer = add("Pooling", "pool")
            layer.pooling_param.global_pooling = True
            return name

        if isinstance(m, nn.SpatialBatchNormalization):
            # caffe convention: BatchNorm (stats) + Scale (affine)
            bn_name, bn = add("BatchNorm", "bn")
            bn.batch_norm_param.use_global_stats = True
            bn.batch_norm_param.eps = float(m.eps)
            _add_blob(bn, _np(s.get("running_mean", np.zeros(m.n_output))))
            _add_blob(bn, _np(s.get("running_var", np.ones(m.n_output))))
            _add_blob(bn, np.asarray([1.0], np.float32))  # scale factor
            if m.affine:
                sc = net.layer.add(name=bn_name + "_scale", type="Scale",
                                   bottom=[bn_name], top=[bn_name + "_scale"])
                sc.scale_param.bias_term = True
                _add_blob(sc, _np(p["weight"]))
                _add_blob(sc, _np(p["bias"]))
                return bn_name + "_scale"
            return bn_name

        if isinstance(m, nn.SpatialCrossMapLRN):
            name, layer = add("LRN", "lrn")
            lp = layer.lrn_param
            lp.local_size = int(m.size)
            lp.alpha = float(m.alpha)
            lp.beta = float(m.beta)
            lp.k = float(m.k)
            return name

        if isinstance(m, nn.Dropout):
            name, layer = add("Dropout", "drop")
            layer.dropout_param.dropout_ratio = float(m.p)
            return name

        simple = {
            nn.ReLU: "ReLU", nn.Sigmoid: "Sigmoid", nn.Tanh: "TanH",
            nn.Abs: "AbsVal", nn.SoftMax: "Softmax", nn.Identity: "Split",
        }
        for cls, caffe_type in simple.items():
            if type(m) is cls:
                name, _ = add(caffe_type, caffe_type.lower())
                return name

        if isinstance(m, nn.Power):
            name, layer = add("Power", "power")
            layer.power_param.power = float(m.power)
            layer.power_param.scale = float(m.scale)
            layer.power_param.shift = float(m.shift)
            return name

        if isinstance(m, nn.CAddTable):
            name, _ = add("Eltwise", "add", n_bottom=None)
            return name
        if isinstance(m, nn.CMulTable):
            name, layer = add("Eltwise", "mul", n_bottom=None)
            layer.eltwise_param.operation = pb.EltwiseParameter.PROD
            return name
        if isinstance(m, nn.CMaxTable):
            name, layer = add("Eltwise", "max", n_bottom=None)
            layer.eltwise_param.operation = pb.EltwiseParameter.MAX
            return name
        if isinstance(m, nn.JoinTable):
            name, layer = add("Concat", "concat", n_bottom=None)
            layer.concat_param.axis = int(m.dimension)
            return name

        if isinstance(m, nn.Reshape):
            name, layer = add("Reshape", "reshape")
            layer.reshape_param.shape.dim.append(0)  # keep batch
            layer.reshape_param.shape.dim.extend(int(d) for d in m.size)
            return name

        raise ValueError(
            f"caffe export does not support module type {type(m).__name__}"
        )


def save_caffe(model, params, state, prototxt_path: str, caffemodel_path: str,
               input_shape: Optional[Tuple[int, ...]] = None) -> None:
    CaffePersister(model, params, state, input_shape).persist(
        prototxt_path, caffemodel_path)
