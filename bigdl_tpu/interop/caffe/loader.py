"""Caffe model loader: prototxt + caffemodel -> (Graph, params, state).

Reference: ``DL/utils/caffe/CaffeLoader.scala:57`` — parse the network
definition (text prototxt) and the trained weights (binary caffemodel),
convert each layer through a per-type converter registry
(``LayerConverter``/``V1LayerConverter``), and assemble a ``Graph``.

TPU-native design notes:

- Parsing uses the ``google.protobuf`` runtime against the scoped schema
  in ``caffe.proto`` (text_format for prototxt, wire decode for the
  caffemodel) instead of the reference's generated Java classes.
- Weights land directly in the Graph's params/state pytrees keyed by
  layer name — there is no mutable module to copy into (reference
  ``CaffeLoader.copyParameters``).
- Caffe's BatchNorm + Scale layer pair folds into one
  ``SpatialBatchNormalization`` (mean/var into module *state*, gamma/beta
  into *params*), matching how the reference fuses them.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.interop.caffe import caffe_pb2 as pb  # generated from caffe.proto
from bigdl_tpu.nn.graph import Graph, Input, Node

# V1 enum number -> V2 string type
_V1_TYPES = {
    1: "Accuracy", 3: "Concat", 4: "Convolution", 5: "Data", 6: "Dropout",
    8: "Flatten", 14: "InnerProduct", 15: "LRN", 17: "Pooling", 18: "ReLU",
    19: "Sigmoid", 20: "Softmax", 21: "SoftmaxWithLoss", 22: "Split",
    23: "TanH", 25: "Eltwise", 26: "Power", 35: "AbsVal", 39: "Deconvolution",
}

_SKIP_TYPES = {
    "Data", "DummyData", "ImageData", "HDF5Data", "MemoryData", "WindowData",
    "Accuracy", "Silence", "SilenceLayer",
}


def _hw(values, default):
    """(h, w) from a caffe repeated spatial field: entry i applies to
    spatial axis i; a single entry applies to both."""
    if len(values) >= 2:
        return int(values[0]), int(values[1])
    if len(values) == 1:
        return int(values[0]), int(values[0])
    return default, default


def _blob_array(blob) -> np.ndarray:
    data = np.asarray(blob.double_data if len(blob.double_data) else blob.data,
                      dtype=np.float32)
    if blob.HasField("shape") and len(blob.shape.dim):
        return data.reshape([int(d) for d in blob.shape.dim])
    dims = [d for d in (blob.num, blob.channels, blob.height, blob.width) if d]
    if dims and int(np.prod(dims)) == data.size:
        return data.reshape(dims)
    # legacy writers (e.g. the reference CaffePersister) set only some of
    # num/channels/height/width — leave flat; layer geometry reshapes it
    return data


def _conv_geometry(p):
    kh, kw = _hw(p.kernel_size, 1)
    if p.HasField("kernel_h"):
        kh, kw = int(p.kernel_h), int(p.kernel_w)
    sh, sw = _hw(p.stride, 1)
    if p.HasField("stride_h"):
        sh, sw = int(p.stride_h), int(p.stride_w)
    ph, pw = _hw(p.pad, 0)
    if p.HasField("pad_h"):
        ph, pw = int(p.pad_h), int(p.pad_w)
    return kh, kw, sh, sw, ph, pw


def _pool_geometry(p):
    kh = int(p.kernel_h) if p.HasField("kernel_h") else int(p.kernel_size)
    kw = int(p.kernel_w) if p.HasField("kernel_w") else kh
    sh = int(p.stride_h) if p.HasField("stride_h") else int(p.stride)
    sw = int(p.stride_w) if p.HasField("stride_w") else sh
    ph = int(p.pad_h) if p.HasField("pad_h") else int(p.pad)
    pw = int(p.pad_w) if p.HasField("pad_w") else ph
    return kh, kw, sh, sw, ph, pw


class _Layer:
    """Normalized view over V1/V2 layer messages."""

    def __init__(self, msg, v1: bool):
        self.msg = msg
        self.name = msg.name
        self.type = _V1_TYPES.get(int(msg.type), f"V1#{int(msg.type)}") if v1 else msg.type
        self.bottoms = list(msg.bottom)
        self.tops = list(msg.top)
        self.blobs = [_blob_array(b) for b in msg.blobs]
        self.include_phases = [r.phase for r in msg.include if r.HasField("phase")]

    def train_only(self) -> bool:
        return bool(self.include_phases) and all(
            p == pb.TRAIN for p in self.include_phases
        )


class CaffeLoader:
    """Builds a :class:`Graph` + params/state from Caffe files
    (reference ``CaffeLoader.scala:57``; ``loadCaffe`` entry :252)."""

    def __init__(self, def_path: str, model_path: Optional[str] = None):
        self.def_path = def_path
        self.model_path = model_path

    # -- parsing -----------------------------------------------------------
    @staticmethod
    def parse_prototxt(path: str) -> "pb.NetParameter":
        from google.protobuf import text_format

        net = pb.NetParameter()
        with open(path) as f:
            text_format.Merge(f.read(), net)
        return net

    @staticmethod
    def parse_caffemodel(path: str) -> "pb.NetParameter":
        net = pb.NetParameter()
        with open(path, "rb") as f:
            net.ParseFromString(f.read())
        return net

    # -- conversion --------------------------------------------------------
    def load(self):
        """Returns ``(graph, params, state)`` ready for ``Predictor``."""
        net = self.parse_prototxt(self.def_path)
        weight_layers: Dict[str, _Layer] = {}
        if self.model_path:
            wnet = self.parse_caffemodel(self.model_path)
            for msg in wnet.layer:
                weight_layers[msg.name] = _Layer(msg, v1=False)
            for msg in wnet.layers:
                weight_layers.setdefault(msg.name, _Layer(msg, v1=True))
        return self._build(net, weight_layers)

    def _build(self, net, weight_layers: Dict[str, _Layer]):
        layers = [_Layer(m, v1=False) for m in net.layer] or \
                 [_Layer(m, v1=True) for m in net.layers]
        layers = [l for l in layers if not l.train_only() and l.type not in _SKIP_TYPES]

        tops: Dict[str, Node] = {}
        inputs: List[Node] = []
        params: Dict[str, dict] = {}
        state: Dict[str, dict] = {}
        input_shapes: Dict[str, Tuple[int, ...]] = {}

        # net-level inputs (legacy `input:`/`input_dim:` or `input_shape`)
        for i, name in enumerate(net.input):
            node = Input()
            tops[name] = node
            inputs.append(node)
            if len(net.input_shape) > i:
                input_shapes[name] = tuple(int(d) for d in net.input_shape[i].dim)
            elif len(net.input_dim) >= 4 * (i + 1):
                input_shapes[name] = tuple(net.input_dim[4 * i:4 * i + 4])

        # caffe-semantics shape propagation (C, H, W) per top so modules can
        # be sized on definition-only loads (no weight blobs)
        shapes: Dict[str, Tuple[int, ...]] = {
            name: tuple(shape[1:]) for name, shape in input_shapes.items()
        }
        pending_bn: Dict[str, Tuple[str, _Layer]] = {}  # top -> (bn name, bn layer)

        for layer in layers:
            wl = weight_layers.get(layer.name, layer)
            blobs = wl.blobs if wl.blobs else layer.blobs

            if layer.type == "Input":
                node = Input()
                tops[layer.tops[0]] = node
                inputs.append(node)
                if layer.msg.HasField("input_param") and len(layer.msg.input_param.shape):
                    input_shapes[layer.tops[0]] = tuple(
                        int(d) for d in layer.msg.input_param.shape[0].dim
                    )
                    shapes[layer.tops[0]] = input_shapes[layer.tops[0]][1:]
                continue

            if layer.type == "Split":
                # pure fan-out: alias every top to the bottom's node
                src = tops[layer.bottoms[0]]
                for t in layer.tops:
                    tops[t] = src
                continue

            if layer.type == "Scale" and layer.bottoms and layer.bottoms[0] in pending_bn:
                # fold Scale into the preceding BatchNorm's affine params
                bn_name, _bn_layer = pending_bn.pop(layer.bottoms[0])
                if blobs:  # definition-only loads keep the BN's default affine
                    gamma = blobs[0].reshape(-1)
                    beta = (blobs[1].reshape(-1) if len(blobs) > 1
                            else np.zeros_like(gamma))
                    params[bn_name] = {"weight": gamma, "bias": beta}
                tops[layer.tops[0]] = tops[layer.bottoms[0]]
                if layer.bottoms[0] in shapes:
                    shapes[layer.tops[0]] = shapes[layer.bottoms[0]]
                continue

            in_shape = shapes.get(layer.bottoms[0]) if layer.bottoms else None
            module, p, s = self._convert(layer, blobs, in_shape)
            if module is None:
                if blobs:
                    raise ValueError(
                        f"unsupported caffe layer type {layer.type!r} "
                        f"({layer.name!r}) carries trained weights; refusing "
                        "to drop them"
                    )
                # weightless unhandled glue: identity passthrough
                module = nn.Identity()
            module.set_name(layer.name)
            out_shape = self._out_shape(layer, blobs, [
                shapes.get(b) for b in layer.bottoms
            ])
            if out_shape is not None:
                for t in layer.tops:
                    shapes[t] = out_shape
            parents = [tops[b] for b in layer.bottoms if b in tops]
            if not parents and not layer.bottoms:
                # a compute layer with no bottom consumes the net input
                # (reference CaffePersister emits such prototxts — the data
                # input declaration is dropped on persist)
                implicit = Input()
                inputs.append(implicit)
                parents = [implicit]
            node = Node(module, parents)
            for t in layer.tops:
                tops[t] = node
            if p:
                params[layer.name] = p
            if s:
                state[layer.name] = s
            if layer.type == "BatchNorm":
                pending_bn[layer.tops[0]] = (layer.name, layer)

        out_nodes, seen = [], set()
        consumed = set()
        for layer in layers:
            consumed.update(layer.bottoms)
        for name, node in tops.items():
            if name not in consumed and id(node) not in seen and node.element is not None:
                seen.add(id(node))
                out_nodes.append(node)
        if not out_nodes:  # fall back to the last layer
            out_nodes = [tops[layers[-1].tops[0]]]

        graph = Graph(inputs, out_nodes)
        full_params, full_state = self._merge_with_init(graph, params, state)
        graph.caffe_input_shapes = input_shapes
        return graph, full_params, full_state

    def _merge_with_init(self, graph: Graph, params, state):
        """Start from a fresh init (covers layers the caffemodel lacks) and
        overlay every loaded weight (reference ``copyParameters`` semantics:
        missing layers keep their initialization)."""
        import jax
        import jax.numpy as jnp

        init_params, init_state = graph.init(jax.random.key(0))

        def overlay(dst, src):
            out = dict(dst)
            for k, v in src.items():
                if isinstance(v, dict):
                    out[k] = overlay(dst.get(k, {}), v)
                else:
                    want = dst.get(k)
                    arr = jnp.asarray(v)
                    if want is not None and tuple(want.shape) != tuple(arr.shape):
                        raise ValueError(
                            f"caffe weight {k}: shape {arr.shape} does not match "
                            f"module param {tuple(want.shape)}"
                        )
                    out[k] = arr
            return out

        return overlay(init_params, params), overlay(init_state, state)

    @staticmethod
    def _out_shape(layer: _Layer, blobs, in_shapes) -> Optional[Tuple[int, ...]]:
        """Caffe output-shape semantics for one layer (channels, H, W)."""
        t = layer.type
        msg = layer.msg
        s0 = in_shapes[0] if in_shapes else None
        if t in ("Convolution", "Deconvolution"):
            if s0 is None or len(s0) != 3:
                return None
            kh, kw, sh, sw, ph, pw = _conv_geometry(msg.convolution_param)
            _, h, w = s0
            n_out = int(msg.convolution_param.num_output)
            if t == "Convolution":
                return (n_out, (h + 2 * ph - kh) // sh + 1, (w + 2 * pw - kw) // sw + 1)
            return (n_out, (h - 1) * sh + kh - 2 * ph, (w - 1) * sw + kw - 2 * pw)
        if t == "Pooling":
            p = msg.pooling_param
            if s0 is None or len(s0) != 3:
                return None
            if p.global_pooling:
                return (s0[0],)
            kh, kw, sh, sw, ph, pw = _pool_geometry(p)
            import math
            c, h, w = s0
            return (c, int(math.ceil((h + 2 * ph - kh) / sh)) + 1,
                    int(math.ceil((w + 2 * pw - kw) / sw)) + 1)
        if t == "InnerProduct":
            return (int(msg.inner_product_param.num_output),)
        if t == "Concat":
            if any(s is None for s in in_shapes) or not in_shapes:
                return None
            axis = int(msg.concat_param.axis) if msg.HasField("concat_param") else 1
            if axis != 1:
                return None
            c = sum(s[0] for s in in_shapes)
            return (c,) + tuple(in_shapes[0][1:])
        if t == "Flatten":
            return (int(np.prod(s0)),) if s0 else None
        if t == "Reshape":
            dims = [int(d) for d in msg.reshape_param.shape.dim]
            return tuple(d for d in dims[1:]) if dims else None
        # passthrough layers keep their input shape
        return s0

    def _convert(self, layer: _Layer, blobs: List[np.ndarray],
                 in_shape: Optional[Tuple[int, ...]] = None):
        """One caffe layer -> (module, params, state). Mirrors the
        per-type ``LayerConverter`` registry."""
        t = layer.type
        msg = layer.msg

        if t in ("Convolution", "Deconvolution"):
            p = msg.convolution_param
            kh, kw, sh, sw, ph, pw = _conv_geometry(p)
            dh, dw = _hw(p.dilation, 1)
            n_out = int(p.num_output)
            group = int(p.group)
            bias = bool(p.bias_term)
            w = blobs[0] if blobs else None
            if w is not None:
                if w.ndim != 4:  # legacy blob with partial dims: use geometry
                    w = w.reshape(n_out if t == "Convolution" else -1,
                                  -1 if t == "Convolution" else n_out // group,
                                  kh, kw)
                n_in = w.shape[1] * group
            elif in_shape:
                n_in = in_shape[0]
            else:
                raise ValueError(
                    f"cannot size conv layer {layer.name!r}: no weight blobs "
                    "and no input shape (add input_shape to the prototxt)"
                )
            if t == "Convolution":
                if (dh, dw) != (1, 1):
                    mod = nn.SpatialDilatedConvolution(
                        n_in, n_out, kw, kh, sw, sh, pw, ph, dw, dh,
                        n_group=group, with_bias=bias)
                else:
                    mod = nn.SpatialConvolution(
                        n_in, n_out, kw, kh, sw, sh, pw, ph, n_group=group,
                        with_bias=bias)
            else:
                # caffe Deconvolution blob: (in, out/group, kh, kw)
                if w is not None:
                    n_in, n_out = w.shape[0], w.shape[1] * group
                mod = nn.SpatialFullConvolution(
                    n_in, n_out, kw, kh, sw, sh, pw, ph, with_bias=bias)
                if w is not None:
                    w = w.transpose(1, 0, 2, 3)
            params = {}
            if w is not None:
                params["weight"] = w
                if bias and len(blobs) > 1:
                    params["bias"] = blobs[1].reshape(-1)
            return mod, params, None

        if t == "InnerProduct":
            p = msg.inner_product_param
            n_out = int(p.num_output)
            bias = bool(p.bias_term)
            w = blobs[0].reshape(n_out, -1) if blobs else None
            if w is not None:
                n_in = w.shape[1]
            elif in_shape:
                n_in = int(np.prod(in_shape))
            else:
                raise ValueError(
                    f"cannot size InnerProduct layer {layer.name!r}: no weight "
                    "blobs and no input shape"
                )
            # caffe flattens from axis 1 implicitly; make that explicit
            mod = nn.Sequential(nn.Reshape([n_in]), nn.Linear(n_in, n_out, with_bias=bias))
            params = {}
            if w is not None:
                sub = {"weight": w}
                if bias and len(blobs) > 1:
                    sub["bias"] = blobs[1].reshape(-1)
                params = {"1": sub}  # Sequential children are index-named
            return mod, params, None

        if t == "Pooling":
            p = msg.pooling_param
            if p.global_pooling:
                return (nn.GlobalAveragePooling2D() if p.pool == pb.PoolingParameter.AVE
                        else nn.GlobalMaxPooling2D()), None, None
            kh, kw, sh, sw, ph, pw = _pool_geometry(p)
            if p.pool == pb.PoolingParameter.AVE:
                mod = nn.SpatialAveragePooling(kw, kh, sw, sh, pw, ph)
            else:
                mod = nn.SpatialMaxPooling(kw, kh, sw, sh, pw, ph)
            # caffe's historical default is ceil; round_mode=FLOOR (upstream
            # field 13, also written by our persister) selects floor
            if p.round_mode == pb.PoolingParameter.FLOOR:
                return mod.floor(), None, None
            return mod.ceil(), None, None

        if t == "ReLU":
            return nn.ReLU(), None, None
        if t == "Sigmoid":
            return nn.Sigmoid(), None, None
        if t == "TanH":
            return nn.Tanh(), None, None
        if t == "AbsVal":
            return nn.Abs(), None, None
        if t == "Power":
            p = msg.power_param
            return nn.Power(float(p.power), float(p.scale), float(p.shift)), None, None
        if t in ("Softmax", "SoftmaxWithLoss"):
            return nn.SoftMax(), None, None
        if t == "Dropout":
            return nn.Dropout(float(msg.dropout_param.dropout_ratio)), None, None
        if t == "Flatten":
            return nn.Reshape([-1]), None, None

        if t == "LRN":
            p = msg.lrn_param
            return nn.SpatialCrossMapLRN(
                int(p.local_size), float(p.alpha), float(p.beta), float(p.k)
            ), None, None

        if t == "BatchNorm":
            p = msg.batch_norm_param
            n = blobs[0].size if blobs else (in_shape[0] if in_shape else 0)
            mod = nn.SpatialBatchNormalization(n, eps=float(p.eps))
            state = None
            if blobs:
                sf = float(blobs[2].reshape(-1)[0]) if len(blobs) > 2 else 1.0
                sf = 1.0 / sf if sf != 0 else 1.0
                state = {
                    "running_mean": blobs[0].reshape(-1) * sf,
                    "running_var": blobs[1].reshape(-1) * sf,
                }
            # gamma/beta arrive later from the paired Scale layer; default
            # identity affine if the net has no Scale
            return mod, None, state

        if t == "Scale":
            # standalone Scale (not folded into a BatchNorm pair): learned
            # per-channel gamma (+ beta) -> CMul (+ CAdd), i.e. nn.Scale
            bias_term = bool(msg.scale_param.bias_term)
            if blobs:
                gamma = blobs[0].reshape(-1)
                size = (gamma.size, 1, 1)
                if bias_term and len(blobs) > 1:
                    mod = nn.Scale(size)
                    p = {"cmul": {"weight": gamma.reshape(size)},
                         "cadd": {"bias": blobs[1].reshape(size)}}
                else:
                    mod = nn.CMul(size)
                    p = {"weight": gamma.reshape(size)}
                return mod, p, None
            if in_shape:
                size = (in_shape[0],) + (1,) * (len(in_shape) - 1)
                return (nn.Scale(size) if bias_term else nn.CMul(size)), None, None
            raise ValueError(
                f"cannot size standalone Scale layer {layer.name!r}: no blobs "
                "and no input shape"
            )

        if t == "Eltwise":
            op = msg.eltwise_param.operation
            coeff = list(msg.eltwise_param.coeff)
            if op == pb.EltwiseParameter.PROD:
                return nn.CMulTable(), None, None
            if op == pb.EltwiseParameter.MAX:
                return nn.CMaxTable(), None, None
            if coeff and any(c != 1.0 for c in coeff):
                raise ValueError(
                    f"Eltwise layer {layer.name!r} uses non-unit coefficients "
                    f"{coeff}; weighted sums are not supported"
                )
            return nn.CAddTable(), None, None

        if t == "Concat":
            axis = int(msg.concat_param.axis) if msg.HasField("concat_param") else 1
            return nn.JoinTable(axis), None, None

        if t == "Reshape":
            dims = [int(d) for d in msg.reshape_param.shape.dim]
            # caffe dim 0 = copy from bottom; our Reshape excludes batch
            return nn.Reshape([d for d in dims[1:]]), None, None

        return None, None, None


def load_caffe(def_path: str, model_path: Optional[str] = None):
    """Convenience entry (reference ``Module.loadCaffeModel``):
    returns ``(graph, params, state)``."""
    return CaffeLoader(def_path, model_path).load()
