"""Caffe bridge (reference: ``DL/utils/caffe/`` — CaffeLoader 2,995 LoC).

``load_caffe(prototxt, caffemodel)`` -> (Graph, params, state);
``save_caffe(model, params, state, prototxt, caffemodel)``.
"""

from bigdl_tpu.interop.caffe.loader import CaffeLoader, load_caffe  # noqa: F401
from bigdl_tpu.interop.caffe.persister import CaffePersister, save_caffe  # noqa: F401
