"""JSON schema -> regex lowering (the second grammar kind).

The engine constrains a stream token by token, so the schema is lowered
to a regular language over CHARACTERS and compiled by the same
:mod:`bigdl_tpu.grammar.regex` pipeline the regex kind uses. The
supported subset is the tool-call shape production traffic actually has
— compact (no inter-token whitespace) canonical JSON:

- ``{"type": "object", "properties": {...}}`` — properties are emitted
  in DECLARATION order and all of them are present (the canonical
  serialization a tool-call emitter produces; ``required`` may restate
  any subset, it cannot reorder or drop keys);
- ``{"type": "string"}`` — double-quoted, any characters except ``"``
  and ``\\`` (escape sequences are out of the subset);
- ``{"type": "integer"}`` / ``{"type": "number"}`` — canonical forms
  (no leading zeros, optional ``-``; numbers allow one fraction part);
- ``{"type": "boolean"}`` / ``{"type": "null"}``;
- ``{"enum": [...]}`` — alternation of the literal JSON encodings;
- ``{"type": "array", "items": ...}`` with optional ``minItems`` 0/1 —
  ``[]`` or ``[item(,item)*]``.

Anything outside the subset raises :class:`SchemaError` at compile time
— the contract is "every emitted stream parses", so an approximated
schema is a bug, not a fallback.
"""

from __future__ import annotations

import json


class SchemaError(ValueError):
    """JSON schema outside the supported lowering subset."""


_STRING_RE = '"[^"\\\\]*"'
_INTEGER_RE = "-?(0|[1-9][0-9]*)"
_NUMBER_RE = "-?(0|[1-9][0-9]*)(\\.[0-9]+)?"


def _escape_literal(text: str) -> str:
    """Regex-quote a literal string for the grammar regex subset."""
    out = []
    for ch in text:
        if ch in "\\.[]()|*+?{}^$":
            out.append("\\" + ch)
        else:
            out.append(ch)
    return "".join(out)


def json_schema_regex(schema) -> str:
    """Lower a schema dict (or JSON string) to an anchored regex."""
    if isinstance(schema, str):
        try:
            schema = json.loads(schema)
        except json.JSONDecodeError as e:
            raise SchemaError(f"schema is not valid JSON: {e}") from e
    if not isinstance(schema, dict):
        raise SchemaError(f"schema must be an object, got "
                          f"{type(schema).__name__}")

    if "enum" in schema:
        options = schema["enum"]
        if not options:
            raise SchemaError("empty enum matches nothing")
        return "(" + "|".join(
            _escape_literal(json.dumps(v, separators=(",", ":")))
            for v in options) + ")"

    kind = schema.get("type")
    if kind == "string":
        return _STRING_RE
    if kind == "integer":
        return "(" + _INTEGER_RE + ")"
    if kind == "number":
        return "(" + _NUMBER_RE + ")"
    if kind == "boolean":
        return "(true|false)"
    if kind == "null":
        return "null"
    if kind == "array":
        item = json_schema_regex(schema.get("items", {"type": "string"}))
        min_items = int(schema.get("minItems", 0))
        if min_items not in (0, 1):
            raise SchemaError("minItems > 1 outside the lowering subset")
        body = f"{item}(,{item})*"
        return ("\\[" + body + "\\]" if min_items
                else "\\[(" + body + ")?\\]")
    if kind == "object":
        props = schema.get("properties")
        if not props:
            raise SchemaError("object schema needs non-empty properties")
        required = schema.get("required")
        if required is not None and set(required) - set(props):
            raise SchemaError(
                f"required names unknown properties: "
                f"{sorted(set(required) - set(props))}")
        parts = []
        for name, sub in props.items():
            parts.append(
                _escape_literal(json.dumps(name)) + ":"
                + json_schema_regex(sub))
        return "\\{" + ",".join(parts) + "\\}"
    raise SchemaError(f"unsupported schema: {schema!r}")
