"""Token-level grammar automata over a vocabulary.

:func:`compile_grammar` lowers a :class:`Grammar` spec (regex or JSON
schema) to a character DFA, then lifts it to the TOKEN level against a
concrete vocabulary: for every DFA state, walk every token's characters
— the token is legal iff the walk stays defined and ends in a LIVE
state (one from which acceptance is still reachable). The result is a
dense ``(n_states, V)`` int32 destination table, the per-state legal
sets packed as bit masks (``np.packbits`` — the canonical compact
representation), and a precomputed ``(n_states, V)`` float32 additive
bias matrix (0 legal / -1e9 illegal) whose rows the engine copies into
the per-slot ``(S, V)`` bias array consumed inside the jitted sampler.

Compilation happens ONCE per distinct ``(grammar, vocabulary, eos)``
triple: a module-level cache shares the compiled automaton across
requests and engines (the per-state tables are immutable; per-request
state is just an int, advanced host-side as tokens stream back).

EOS is part of the automaton's contract, not of the text: the EOS
column of a state's mask is legal iff the state is ACCEPTING, so a
constrained stream can only terminate on a parse — and a state with no
legal continuation and no legal EOS is the stuck terminal the engine
fails with ``GrammarViolation``.
"""

from __future__ import annotations

import hashlib
import json
import threading
from typing import Optional, Sequence, Tuple

import numpy as np

from bigdl_tpu.grammar.regex import CharDFA, compile_regex
from bigdl_tpu.grammar.schema import json_schema_regex

NEG_BIAS = np.float32(-1e9)
DEAD = -1


class Grammar:
    """A grammar SPEC — kind + source, no vocabulary yet.

    Build via :func:`regex_grammar` / :func:`json_schema_grammar`;
    compile against a vocabulary with :func:`compile_grammar`. The
    ``key`` is a stable identity used by the compile cache and the
    engine's shared-grammar registry."""

    __slots__ = ("kind", "source", "pattern")

    def __init__(self, kind: str, source: str, pattern: str):
        self.kind = kind        # "regex" | "json"
        self.source = source    # the user-facing spec text
        self.pattern = pattern  # the lowered regex actually compiled

    @property
    def key(self) -> str:
        return f"{self.kind}:{self.source}"

    def __repr__(self):
        return f"Grammar(kind={self.kind!r}, source={self.source!r})"


def regex_grammar(pattern: str) -> Grammar:
    """Grammar spec from an anchored (fullmatch) regex pattern."""
    return Grammar("regex", pattern, pattern)


def json_schema_grammar(schema) -> Grammar:
    """Grammar spec from a JSON schema (dict or JSON text) — lowered to
    a regex over canonical compact JSON (see :mod:`grammar.schema`)."""
    pattern = json_schema_regex(schema)
    if not isinstance(schema, str):
        schema = json.dumps(schema, sort_keys=False,
                            separators=(",", ":"))
    return Grammar("json", schema, pattern)


class TokenAutomaton:
    """A grammar compiled against one vocabulary (immutable, shared).

    Per-request state is an int (``start_state`` to begin); the engine
    advances it host-side with :meth:`advance` on every emitted token
    and arms the next step's mask with :meth:`bias_row`."""

    def __init__(self, spec: Grammar, dfa: CharDFA,
                 vocab: Sequence[str], eos_id: Optional[int], key: str):
        self.spec = spec
        self.key = key
        self.vocab = tuple(vocab)
        self.vocab_size = len(vocab)
        self.eos_id = eos_id
        self.start_state = dfa.start
        self._dfa = dfa
        n, v = dfa.n_states, self.vocab_size

        dest = np.full((n, v), DEAD, np.int32)
        legal = np.zeros((n, v), bool)
        for s in range(n):
            if not dfa.live[s]:
                continue
            trans = dfa.trans
            for t, text in enumerate(self.vocab):
                if not text or t == eos_id:
                    continue  # empty tokens never advance; EOS below
                cur = s
                for ch in text:
                    cur = trans[cur].get(ch)
                    if cur is None:
                        break
                if cur is not None and dfa.live[cur]:
                    dest[s, t] = cur
                    legal[s, t] = True
        if eos_id is not None:
            legal[:, eos_id] = np.asarray(dfa.accepting, bool)
        self._dest = dest
        self._legal = legal
        self.packed_masks = np.packbits(legal, axis=1)
        self._bias = np.where(legal, np.float32(0.0), NEG_BIAS)
        self._accepting = np.asarray(dfa.accepting, bool)
        eos_col = (np.zeros(n, bool) if eos_id is None
                   else legal[:, eos_id])
        self._has_continuation = (legal.sum(axis=1)
                                  - eos_col.astype(int)) > 0
        self._masked_frac = 1.0 - legal.sum(axis=1) / float(v)

    @property
    def n_states(self) -> int:
        return self._dest.shape[0]

    def advance(self, state: int, token: int) -> int:
        """Next automaton state after emitting ``token`` (``DEAD`` for
        an illegal token or from a dead state)."""
        if state < 0:
            return DEAD
        return int(self._dest[state, token])

    def bias_row(self, state: int) -> np.ndarray:
        """(V,) float32 additive mask for ``state`` — 0 legal, -1e9
        illegal. A dead state returns all-zeros (unconstrained): the
        engine retires the stream before another step samples, and a
        uniform row keeps the array a no-op for the speculative rows
        past a stream's terminal."""
        if state < 0:
            return np.zeros(self.vocab_size, np.float32)
        return self._bias[state]

    def is_accepting(self, state: int) -> bool:
        return state >= 0 and bool(self._accepting[state])

    def has_continuation(self, state: int) -> bool:
        """True iff some non-EOS token is legal from ``state``."""
        return state >= 0 and bool(self._has_continuation[state])

    def legal_count(self, state: int) -> int:
        return 0 if state < 0 else int(self._legal[state].sum())

    def masked_frac(self, state: int) -> float:
        """Fraction of the vocabulary the state's mask excludes."""
        return 1.0 if state < 0 else float(self._masked_frac[state])

    def text_of(self, tokens: Sequence[int]) -> str:
        """Decode a token stream (EOS dropped) to its surface text."""
        return "".join(self.vocab[t] for t in tokens if t != self.eos_id)

    def matches(self, tokens: Sequence[int]) -> bool:
        """Does the emitted stream parse? (fullmatch of the decoded
        text — the contract every constrained stream must satisfy)."""
        return self._dfa.fullmatch(self.text_of(tokens))

    def __repr__(self):
        return (f"TokenAutomaton({self.spec.kind!r}, states="
                f"{self.n_states}, vocab={self.vocab_size})")


_CACHE: dict = {}
_CACHE_LOCK = threading.Lock()
_HITS = 0
_MISSES = 0


def _vocab_fingerprint(vocab: Sequence[str]) -> str:
    h = hashlib.sha256()
    for text in vocab:
        h.update(text.encode("utf-8", "surrogatepass"))
        h.update(b"\x00")
    return h.hexdigest()[:16]


def compile_grammar(spec: Grammar, vocab: Sequence[str],
                    eos_id: Optional[int] = None) -> TokenAutomaton:
    """Compile (or fetch) the token automaton for ``spec`` over
    ``vocab``. Cached per ``(grammar, vocabulary, eos)`` — every
    request sharing a grammar shares ONE compiled automaton."""
    global _HITS, _MISSES
    if not isinstance(spec, Grammar):
        raise TypeError(
            f"expected a Grammar spec (regex_grammar / "
            f"json_schema_grammar), got {type(spec).__name__}")
    key = (f"{spec.key}|vocab:{_vocab_fingerprint(vocab)}"
           f"|eos:{eos_id}")
    with _CACHE_LOCK:
        cached = _CACHE.get(key)
        if cached is not None:
            _HITS += 1
            return cached
    alphabet = set()
    for text in vocab:
        alphabet.update(text)
    dfa = compile_regex(spec.pattern, alphabet)
    automaton = TokenAutomaton(spec, dfa, vocab, eos_id, key)
    with _CACHE_LOCK:
        # a racing compile of the same key keeps the first one in
        if key in _CACHE:
            _HITS += 1
            return _CACHE[key]
        _CACHE[key] = automaton
        _MISSES += 1
    return automaton


def compile_cache_stats() -> Tuple[int, int]:
    """(hits, misses) of the module compile cache — misses count
    actual compilations."""
    with _CACHE_LOCK:
        return _HITS, _MISSES


def clear_compile_cache() -> None:
    """Testing hook: drop every cached automaton and zero the stats."""
    global _HITS, _MISSES
    with _CACHE_LOCK:
        _CACHE.clear()
        _HITS = 0
        _MISSES = 0
