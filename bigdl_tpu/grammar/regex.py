"""Regex -> character DFA, the host-side half of grammar compilation.

A deliberately small, dependency-free regex engine: recursive-descent
parse to an AST, Thompson construction to an epsilon-NFA, subset
construction to a DFA, then a liveness trim (states from which no
accepting state is reachable are DEAD — a token whose character walk
lands in one can never complete a parse, so the automaton marks it
illegal up front instead of discovering the dead end mid-stream).

The alphabet is FINITE and known at compile time: the union of every
character that appears in the vocabulary with every literal character in
the pattern. ``.`` and negated classes quantify over that alphabet, not
over unicode — legality is only ever tested on vocabulary strings, so
characters no token can emit are irrelevant by construction.

Supported syntax: literals, escapes (``\\d \\w \\s \\D \\W \\S`` and
escaped metacharacters), ``.``, character classes ``[a-z0-9_]`` /
``[^...]`` with ranges, groups ``(...)``, alternation ``|`` and the
quantifiers ``* + ? {m} {m,} {m,n}``. Matching is anchored (fullmatch
semantics): the grammar describes the ENTIRE emitted stream.
"""

from __future__ import annotations

from typing import Dict, FrozenSet, List, Set, Tuple

_DIGITS = frozenset("0123456789")
_WORD = frozenset(
    "abcdefghijklmnopqrstuvwxyzABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_")
_SPACE = frozenset(" \t\n\r\f\v")
_META = frozenset("\\.[]()|*+?{}^$")


class RegexError(ValueError):
    """Pattern rejected by the grammar regex subset."""


# ------------------------------------------------------------- AST ----


class _Node:
    __slots__ = ()


class _Lit(_Node):
    """One character drawn from a set (a literal is a 1-element set;
    classes/escapes are bigger sets; negations resolve at build time
    against the compile alphabet)."""

    __slots__ = ("chars", "negated")

    def __init__(self, chars: FrozenSet[str], negated: bool = False):
        self.chars = chars
        self.negated = negated


class _Cat(_Node):
    __slots__ = ("parts",)

    def __init__(self, parts: List[_Node]):
        self.parts = parts


class _Alt(_Node):
    __slots__ = ("options",)

    def __init__(self, options: List[_Node]):
        self.options = options


class _Repeat(_Node):
    """lo..hi copies of ``node``; ``hi`` None means unbounded."""

    __slots__ = ("node", "lo", "hi")

    def __init__(self, node: _Node, lo: int, hi):
        self.node = node
        self.lo = lo
        self.hi = hi


class _Parser:
    def __init__(self, pattern: str):
        self.pat = pattern
        self.pos = 0

    def _peek(self):
        return self.pat[self.pos] if self.pos < len(self.pat) else None

    def _next(self) -> str:
        if self.pos >= len(self.pat):
            raise RegexError(f"unexpected end of pattern: {self.pat!r}")
        ch = self.pat[self.pos]
        self.pos += 1
        return ch

    def parse(self) -> _Node:
        node = self._alternation()
        if self.pos != len(self.pat):
            raise RegexError(
                f"trailing {self.pat[self.pos:]!r} in {self.pat!r}")
        return node

    def _alternation(self) -> _Node:
        options = [self._concat()]
        while self._peek() == "|":
            self._next()
            options.append(self._concat())
        return options[0] if len(options) == 1 else _Alt(options)

    def _concat(self) -> _Node:
        parts: List[_Node] = []
        while self._peek() is not None and self._peek() not in "|)":
            parts.append(self._quantified())
        if not parts:
            return _Cat([])  # empty branch: matches ""
        return parts[0] if len(parts) == 1 else _Cat(parts)

    def _quantified(self) -> _Node:
        node = self._atom()
        ch = self._peek()
        if ch == "*":
            self._next()
            return _Repeat(node, 0, None)
        if ch == "+":
            self._next()
            return _Repeat(node, 1, None)
        if ch == "?":
            self._next()
            return _Repeat(node, 0, 1)
        if ch == "{":
            self._next()
            lo = self._int()
            hi: object = lo
            if self._peek() == ",":
                self._next()
                hi = self._int() if self._peek() != "}" else None
            if self._next() != "}":
                raise RegexError(f"unclosed {{}} in {self.pat!r}")
            if hi is not None and hi < lo:
                raise RegexError(f"bad repeat bounds in {self.pat!r}")
            return _Repeat(node, lo, hi)
        return node

    def _int(self) -> int:
        digits = ""
        while self._peek() is not None and self._peek().isdigit():
            digits += self._next()
        if not digits:
            raise RegexError(f"expected number in {self.pat!r}")
        return int(digits)

    def _atom(self) -> _Node:
        ch = self._next()
        if ch == "(":
            node = self._alternation()
            if self._next() != ")":
                raise RegexError(f"unclosed group in {self.pat!r}")
            return node
        if ch == "[":
            return self._char_class()
        if ch == ".":
            return _Lit(frozenset(), negated=True)  # anything in alphabet
        if ch == "\\":
            return _Lit(*self._escape())
        if ch in "*+?{":
            raise RegexError(f"dangling quantifier {ch!r} in {self.pat!r}")
        if ch in ")]|":
            raise RegexError(f"unbalanced {ch!r} in {self.pat!r}")
        return _Lit(frozenset(ch))

    def _escape(self) -> Tuple[FrozenSet[str], bool]:
        ch = self._next()
        if ch == "d":
            return _DIGITS, False
        if ch == "D":
            return _DIGITS, True
        if ch == "w":
            return _WORD, False
        if ch == "W":
            return _WORD, True
        if ch == "s":
            return _SPACE, False
        if ch == "S":
            return _SPACE, True
        if ch == "n":
            return frozenset("\n"), False
        if ch == "t":
            return frozenset("\t"), False
        if ch == "r":
            return frozenset("\r"), False
        if ch in _META or not ch.isalnum():
            return frozenset(ch), False
        raise RegexError(f"unsupported escape \\{ch} in {self.pat!r}")

    def _char_class(self) -> _Node:
        negated = self._peek() == "^"
        if negated:
            self._next()
        chars: Set[str] = set()
        first = True
        while True:
            ch = self._peek()
            if ch is None:
                raise RegexError(f"unclosed [] in {self.pat!r}")
            if ch == "]" and not first:
                self._next()
                break
            first = False
            ch = self._next()
            if ch == "\\":
                esc, esc_neg = self._escape()
                if esc_neg:
                    raise RegexError(
                        f"negated escape inside class in {self.pat!r}")
                chars |= esc
                continue
            if self._peek() == "-" and self.pos + 1 < len(self.pat) \
                    and self.pat[self.pos + 1] != "]":
                self._next()
                hi = self._next()
                if hi == "\\":
                    esc, _ = self._escape()
                    if len(esc) != 1:
                        raise RegexError(
                            f"bad range end in {self.pat!r}")
                    (hi,) = esc
                if ord(hi) < ord(ch):
                    raise RegexError(f"reversed range in {self.pat!r}")
                chars |= {chr(c) for c in range(ord(ch), ord(hi) + 1)}
            else:
                chars.add(ch)
        return _Lit(frozenset(chars), negated)


# ------------------------------------------------------------- NFA ----


class _NFA:
    def __init__(self):
        self.eps: List[Set[int]] = []
        self.trans: List[Dict[str, Set[int]]] = []

    def new_state(self) -> int:
        self.eps.append(set())
        self.trans.append({})
        return len(self.eps) - 1

    def add_eps(self, a: int, b: int):
        self.eps[a].add(b)

    def add_char(self, a: int, ch: str, b: int):
        self.trans[a].setdefault(ch, set()).add(b)


def _pattern_chars(node: _Node) -> Set[str]:
    """Every concrete character the AST names (negations contribute the
    characters they EXCLUDE — those must exist in the alphabet for the
    complement to be meaningful)."""
    out: Set[str] = set()
    stack = [node]
    while stack:
        n = stack.pop()
        if isinstance(n, _Lit):
            out |= n.chars
        elif isinstance(n, _Cat):
            stack.extend(n.parts)
        elif isinstance(n, _Alt):
            stack.extend(n.options)
        elif isinstance(n, _Repeat):
            stack.append(n.node)
    return out


def _build_nfa(node: _Node, nfa: _NFA, alphabet: FrozenSet[str],
               start: int) -> int:
    """Thompson construction; returns the fragment's accept state."""
    if isinstance(node, _Lit):
        chars = (alphabet - node.chars) if node.negated else \
            (node.chars & alphabet)
        end = nfa.new_state()
        for ch in chars:
            nfa.add_char(start, ch, end)
        return end
    if isinstance(node, _Cat):
        cur = start
        for part in node.parts:
            cur = _build_nfa(part, nfa, alphabet, cur)
        return cur
    if isinstance(node, _Alt):
        end = nfa.new_state()
        for opt in node.options:
            s = nfa.new_state()
            nfa.add_eps(start, s)
            nfa.add_eps(_build_nfa(opt, nfa, alphabet, s), end)
        return end
    if isinstance(node, _Repeat):
        cur = start
        for _ in range(node.lo):
            cur = _build_nfa(node.node, nfa, alphabet, cur)
        if node.hi is None:
            loop = nfa.new_state()
            nfa.add_eps(cur, loop)
            body_end = _build_nfa(node.node, nfa, alphabet, loop)
            nfa.add_eps(body_end, loop)
            return loop
        end = nfa.new_state()
        nfa.add_eps(cur, end)
        for _ in range(node.hi - node.lo):
            cur = _build_nfa(node.node, nfa, alphabet, cur)
            nfa.add_eps(cur, end)
        return end
    raise RegexError(f"unknown AST node {type(node).__name__}")


def _eps_closure(nfa: _NFA, states: Set[int]) -> FrozenSet[int]:
    out = set(states)
    stack = list(states)
    while stack:
        s = stack.pop()
        for t in nfa.eps[s]:
            if t not in out:
                out.add(t)
                stack.append(t)
    return frozenset(out)


class CharDFA:
    """Deterministic automaton over a finite character alphabet.

    ``trans[state]`` maps char -> next state (absent = reject);
    ``accepting`` / ``live`` are boolean-per-state lists, ``live[s]``
    true iff some accepting state is reachable from ``s``."""

    __slots__ = ("trans", "accepting", "live", "start", "alphabet")

    def __init__(self, trans, accepting, live, start, alphabet):
        self.trans = trans
        self.accepting = accepting
        self.live = live
        self.start = start
        self.alphabet = alphabet

    @property
    def n_states(self) -> int:
        return len(self.trans)

    def fullmatch(self, text: str) -> bool:
        cur = self.start
        for ch in text:
            cur = self.trans[cur].get(ch)
            if cur is None:
                return False
        return self.accepting[cur]


def compile_regex(pattern: str, alphabet) -> CharDFA:
    """Pattern + iterable of alphabet characters -> :class:`CharDFA`.

    The effective alphabet is the union of ``alphabet`` (the characters
    the vocabulary can emit) and the pattern's own literals, so a
    pattern naming characters no token contains still compiles — those
    branches are simply unreachable through the vocabulary."""
    ast = _Parser(pattern).parse()
    full_alphabet = frozenset(alphabet) | _pattern_chars(ast)
    nfa = _NFA()
    start = nfa.new_state()
    accept = _build_nfa(ast, nfa, full_alphabet, start)

    # subset construction
    start_set = _eps_closure(nfa, {start})
    ids: Dict[FrozenSet[int], int] = {start_set: 0}
    trans: List[Dict[str, int]] = [{}]
    accepting: List[bool] = [accept in start_set]
    work = [start_set]
    while work:
        cur = work.pop()
        cid = ids[cur]
        moves: Dict[str, Set[int]] = {}
        for s in cur:
            for ch, dests in nfa.trans[s].items():
                moves.setdefault(ch, set()).update(dests)
        for ch, dests in moves.items():
            closure = _eps_closure(nfa, dests)
            nid = ids.get(closure)
            if nid is None:
                nid = len(ids)
                ids[closure] = nid
                trans.append({})
                accepting.append(accept in closure)
                work.append(closure)
            trans[cid][ch] = nid

    # liveness: reverse reachability from accepting states
    n = len(trans)
    rev: List[Set[int]] = [set() for _ in range(n)]
    for s, moves in enumerate(trans):
        for d in moves.values():
            rev[d].add(s)
    live = [False] * n
    stack = [s for s in range(n) if accepting[s]]
    for s in stack:
        live[s] = True
    while stack:
        s = stack.pop()
        for p in rev[s]:
            if not live[p]:
                live[p] = True
                stack.append(p)
    return CharDFA(trans, accepting, live, 0, full_alphabet)
