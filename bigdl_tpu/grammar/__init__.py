"""Structured generation: grammar-constrained decoding (PR 20).

Host-side grammar compiler + token-level automata. A regex or JSON
schema lowers to a character DFA, then lifts to a token automaton over
the serving vocabulary with per-state legal-token sets precomputed as
packed vocab masks (Willard & Louf 2023, "Efficient Guided Generation
for Large Language Models"). The engine consumes the automaton through
``GenerationEngine.submit(grammar=...)``: the current state's mask row
enters the jitted decode step as a per-slot additive bias, and the
state advances on the host as tokens stream back.

    from bigdl_tpu.grammar import json_schema_grammar, compile_grammar
    g = compile_grammar(json_schema_grammar(schema), vocab, eos_id=eos)
    stream = engine.submit(prompt, max_new_tokens=64, grammar=g)
"""

from bigdl_tpu.grammar.automaton import (
    DEAD,
    NEG_BIAS,
    Grammar,
    TokenAutomaton,
    clear_compile_cache,
    compile_cache_stats,
    compile_grammar,
    json_schema_grammar,
    regex_grammar,
)
from bigdl_tpu.grammar.regex import CharDFA, RegexError, compile_regex
from bigdl_tpu.grammar.schema import SchemaError, json_schema_regex

__all__ = [
    "DEAD",
    "NEG_BIAS",
    "CharDFA",
    "Grammar",
    "GrammarViolation",
    "RegexError",
    "SchemaError",
    "TokenAutomaton",
    "clear_compile_cache",
    "compile_cache_stats",
    "compile_grammar",
    "compile_regex",
    "json_schema_grammar",
    "json_schema_regex",
    "regex_grammar",
]


def __getattr__(name):
    # GrammarViolation lives in serving.errors (it is a ServingError);
    # re-exported here for discoverability without a circular import
    if name == "GrammarViolation":
        from bigdl_tpu.serving.errors import GrammarViolation

        return GrammarViolation
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
