"""Training-to-serving handoff: watch a checkpoint directory, hot-reload.

The ckpt tier (``bigdl_tpu/ckpt``) commits verified manifest entries; a
:class:`CheckpointWatcher` polls ``MANIFEST.json`` and, on every NEW
committed entry, verifies the blob (size + sha256 — a half-written or
corrupt checkpoint is skipped, the old weights keep serving) and swaps
it into a running :class:`~bigdl_tpu.serving.service.InferenceService`
or :class:`~bigdl_tpu.serving.engine.GenerationEngine` via their atomic
``reload``. The serving process never restarts and a mid-flight batch
never sees torn params — the reload contract both backends enforce.

Polling (not inotify) is deliberate: checkpoint directories are
routinely on network filesystems where event APIs lie, and a manifest
commit is already atomic (``os.replace``), so a poll either sees the
old manifest or the new one — never a torn entry list.
"""

from __future__ import annotations

import logging
import threading
from typing import Any, Callable, Optional

from bigdl_tpu import faults
from bigdl_tpu.ckpt.manifest import load_manifest, verify_entry, verify_shards
from bigdl_tpu.faults import RetryPolicy
from bigdl_tpu.utils.checkpoint import deserialize_payload

log = logging.getLogger("bigdl_tpu.serving")


class CheckpointWatcher:
    """Background poller reloading ``service`` from new committed
    manifest entries. Use :func:`watch_checkpoints` to construct."""

    def __init__(self, service, directory: str,
                 poll_interval: float = 2.0, *,
                 template: Optional[dict] = None,
                 reload_existing: bool = True,
                 on_reload: Optional[Callable[[Any], None]] = None,
                 poll_backoff: Optional[RetryPolicy] = None):
        self.service = service
        self.directory = str(directory)
        self.poll_interval = float(poll_interval)
        self.reloads = 0
        self.last_entry = None
        self.last_error: "Exception | None" = None
        self._template = template
        self._on_reload = on_reload
        self._skip_tag: "str | None" = None
        # ERROR polls (unreadable manifest, transient reload failure)
        # back off on the shared poll schedule — base poll_interval,
        # doubling to the cap with deterministic jitter — instead of
        # re-reading a broken directory at full rate forever; one clean
        # poll resets the schedule
        self._poll_policy = poll_backoff or RetryPolicy.poll_schedule(
            self.poll_interval)
        self._error_polls = 0
        self._stop = threading.Event()
        if not reload_existing:
            # adopt the current tip as the baseline WITHOUT reloading it:
            # the server presumably restored it at startup
            entries = load_manifest(self.directory)
            if entries:
                self.last_entry = entries[-1]
        self._thread = threading.Thread(
            target=self._run, name="bigdl-serving-ckpt-watch", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                self.poll_once()
                self._error_polls = 0
            except Exception:
                # a bad poll (unreadable manifest, reload rejection) must
                # not kill the watcher: the NEXT commit may be fine
                self._error_polls += 1
                log.exception(
                    "checkpoint watch poll failed; retrying in %.1fs",
                    self._poll_policy.backoff(self._error_polls - 1))
            self._stop.wait(
                self.poll_interval if self._error_polls == 0
                else self._poll_policy.backoff(self._error_polls - 1))

    def poll_once(self) -> bool:
        """One poll: reload iff the manifest tip is a new committed entry
        whose blob verifies. Returns True when a reload happened."""
        # fault site: an armed OSError is exactly an unreadable-manifest
        # read (network fs hiccup); the watcher logs, backs off, retries
        faults.fire("ckpt.watch_manifest", directory=self.directory)
        entries = load_manifest(self.directory)
        if not entries:
            return False
        entry = entries[-1]
        if self.last_entry is not None and entry.tag == self.last_entry.tag:
            return False
        if entry.tag == self._skip_tag:
            return False  # known-bad tip: wait for a NEW commit
        # shards first: they fail cheap (per-shard chunked hash) and a
        # torn-shard tip is retried every poll until repaired — checking
        # them before verify_entry spares re-reading and re-hashing the
        # full main blob on each of those failing polls
        if not verify_shards(self.directory, entry):
            log.warning(
                "checkpoint '%s' has a missing or corrupt per-host shard; "
                "keeping the serving weights and waiting for the next "
                "commit (or the shard's repair)", entry.tag)
            return False
        blob = verify_entry(self.directory, entry)
        if blob is None:
            log.warning(
                "checkpoint '%s' failed verification during watch; keeping "
                "the serving weights and waiting for the next commit",
                entry.tag)
            return False
        try:
            payload = deserialize_payload(blob, self._template)
            self.service.reload(payload["params"],
                                payload.get("module_state") or None)
        except (ValueError, TypeError) as e:
            # deterministic rejection (structure/signature mismatch — e.g.
            # a retrained model with a different config): memo the tag so
            # we do not re-read + re-deserialize a multi-GB blob every
            # poll forever; a NEW commit clears the memo by changing the
            # tip
            self._skip_tag = entry.tag
            self.last_error = e
            log.exception(
                "checkpoint '%s' cannot be hot-reloaded; the serving "
                "weights are unchanged and this entry will be skipped "
                "until a new commit lands", entry.tag)
            return False
        except Exception as e:
            # anything else may be TRANSIENT — a device_put hiccup, or a
            # ReplicaSet roll aborted by one replica mid-sweep (siblings
            # already swapped; only a RETRY of this same tip can converge
            # the fleet back to one version) — so do NOT memoize: the
            # next poll tries the same entry again
            self.last_error = e
            log.exception(
                "checkpoint '%s' reload failed (possibly transient); "
                "will retry on the next poll", entry.tag)
            return False
        self._skip_tag = None
        self.last_error = None
        self.last_entry = entry
        self.reloads += 1
        log.info("hot-reloaded serving weights from checkpoint '%s' "
                 "(step %d)", entry.tag, entry.step)
        if self._on_reload is not None:
            self._on_reload(entry)
        return True

    def stop(self, timeout: Optional[float] = None) -> None:
        self._stop.set()
        self._thread.join(timeout)

    def __enter__(self) -> "CheckpointWatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()


def watch_checkpoints(service, directory: str, poll_interval: float = 2.0,
                      **kwargs) -> CheckpointWatcher:
    """Start watching ``directory``'s ``MANIFEST.json`` and hot-reload
    ``service`` on every new committed entry.

    ``reload_existing=True`` (default) also loads the newest committed
    entry already present at start — a server coming up mid-training
    picks up the latest weights immediately. ``template`` is forwarded
    to ``deserialize_payload`` (pass the params/state structure when the
    checkpoint format needs it); ``on_reload(entry)`` fires after each
    successful swap. Stop with ``watcher.stop()`` (or use it as a
    context manager).
    """
    return CheckpointWatcher(service, directory, poll_interval, **kwargs)
