"""Typed serving failures.

Both are delivered two ways: ``InferenceService.submit`` RAISES
``Overloaded`` (admission control happens on the caller's thread, before
a queue slot is taken), while ``DeadlineExceeded`` is set ON the
request's future (expiry is detected by the batcher worker when the
request would otherwise occupy a batch slot).
"""

from __future__ import annotations


class ServingError(RuntimeError):
    """Base class for serving-tier failures."""


class Overloaded(ServingError):
    """The request queue is at its configured bound; the request was
    rejected without being enqueued (backpressure, not buffering)."""

    def __init__(self, queue_depth: int, max_queue: int):
        super().__init__(
            f"serving queue full ({queue_depth}/{max_queue}); request rejected")
        self.queue_depth = queue_depth
        self.max_queue = max_queue


class DeadlineExceeded(ServingError):
    """The request's deadline expired while it waited in the queue; it was
    dropped before occupying a forward slot."""

    def __init__(self, waited_s: float, deadline_s: float):
        super().__init__(
            f"request deadline {deadline_s * 1e3:.1f} ms exceeded after "
            f"waiting {waited_s * 1e3:.1f} ms")
        self.waited_s = waited_s
        self.deadline_s = deadline_s
