"""Typed serving failures.

Delivery convention: admission-time failures (``Overloaded``,
``UnknownModel``) RAISE on the caller's thread, before a queue slot is
taken; in-flight failures (``DeadlineExceeded``, ``StreamCancelled``)
are set ON the request's future/stream, detected by the batcher or
generation-engine worker at the point the request would otherwise
occupy a forward slot or decode step.
"""

from __future__ import annotations


class ServingError(RuntimeError):
    """Base class for serving-tier failures."""


class Overloaded(ServingError):
    """The request queue (or a router's per-model in-flight quota) is at
    its configured bound; the request was rejected without being enqueued
    (backpressure, not buffering). ``model`` names the saturated backend
    when the rejection came from a :class:`ModelRouter` quota."""

    def __init__(self, queue_depth: int, max_queue: int,
                 model: "str | None" = None):
        where = f"model '{model}'" if model else "serving queue"
        super().__init__(
            f"{where} full ({queue_depth}/{max_queue}); request rejected")
        self.queue_depth = queue_depth
        self.max_queue = max_queue
        self.model = model


class UnknownModel(ServingError):
    """A router request named a model no backend is registered under."""

    def __init__(self, name: str, available):
        avail = ", ".join(sorted(available)) or "<none>"
        super().__init__(
            f"no model '{name}' registered (available: {avail})")
        self.name = name
        self.available = sorted(available)


class ReplicaUnavailable(ServingError):
    """Every replica in a :class:`~bigdl_tpu.serving.replica.ReplicaSet`
    is quarantined (or closed): there is no healthy backend to place the
    request on. Distinct from :class:`Overloaded` — overload is healthy
    backpressure, this is an availability failure the operator should
    page on."""

    def __init__(self, name: str, replicas):
        replicas = list(replicas)
        super().__init__(
            f"no healthy replica available for '{name}' "
            f"({len(replicas)} registered: {', '.join(replicas) or '<none>'})")
        self.name = name
        self.replicas = replicas


class StreamCancelled(ServingError):
    """The generation stream was cancelled by its consumer; the slot was
    retired at the next decode-step boundary. Tokens produced before the
    cancel are still available on the stream."""


class DeadlineExceeded(ServingError):
    """The request's deadline expired while it waited in the queue; it was
    dropped before occupying a forward slot."""

    def __init__(self, waited_s: float, deadline_s: float):
        super().__init__(
            f"request deadline {deadline_s * 1e3:.1f} ms exceeded after "
            f"waiting {waited_s * 1e3:.1f} ms")
        self.waited_s = waited_s
        self.deadline_s = deadline_s


class GrammarViolation(ServingError):
    """A grammar-constrained stream reached a terminal it cannot parse
    from: either the token budget ran out in a non-accepting automaton
    state, or the state has no legal continuation and no legal EOS
    (stuck). The contract is "every emitted stream parses" — so the
    stream FAILS with this error instead of delivering garbage. Tokens
    produced before the violation are preserved on the stream for
    debugging; ``state`` is the automaton state the stream died in."""

    def __init__(self, why: str, *, state: int, tokens_out: int,
                 grammar_key: "str | None" = None):
        what = f" for grammar '{grammar_key}'" if grammar_key else ""
        super().__init__(
            f"constrained stream cannot complete a parse{what}: {why} "
            f"(automaton state {state}, {tokens_out} tokens emitted)")
        self.why = why
        self.state = state
        self.tokens_out = tokens_out
        self.grammar_key = grammar_key


class TransportError(ServingError):
    """The RPC transport to a remote replica failed: connect refused, a
    send/recv died mid-frame, the peer vanished, or the connection-level
    circuit breaker is open. This is an ENGINE error in the front-door
    taxonomy — it indicts the replica, feeds the ReplicaSet's
    consecutive-failure eviction, and traffic fails over to siblings."""

    def __init__(self, message: str, *, endpoint: "str | None" = None):
        where = f" ({endpoint})" if endpoint else ""
        super().__init__(f"rpc transport failure{where}: {message}")
        self.endpoint = endpoint


class RemoteError(ServingError):
    """A remote backend raised an exception the wire codec could not
    reconstruct as its original type (an unknown class, or one whose
    constructor rejects the recorded args). The remote class name and
    message are preserved so the taxonomy loss is at least legible."""

    def __init__(self, remote_type: str, message: str):
        super().__init__(f"remote {remote_type}: {message}")
        self.remote_type = remote_type
