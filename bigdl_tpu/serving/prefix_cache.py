"""Content-addressed KV prefix sharing over the paged cache (PR 12).

Real serving traffic re-prefills the same prompt prefix thousands of
times — shared system prompts, few-shot templates, multi-turn chat.
With the paged cache those prefix K/V rows are ALREADY sitting in
physical pages when a sequence retires; the only missing piece is an
index that finds them again. This module is that index, following the
radix-tree KV reuse of vLLM/SGLang-style serving stacks (PAPERS.md):

- **entries are full, immutable pages.** A prompt's K/V writes depend
  only on the token ids and their absolute positions (prompts start at
  position 0), so a completely written page is a pure function of
  ``(model version, the page-aligned token prefix ending at it)``. Only
  FULL prompt pages are published — the page a prompt ends mid-way
  through keeps taking decode writes and is never shareable — and a
  lookup never matches the whole prompt (at least one tail token must
  re-prefill to produce the first-token logits), so a shared page is
  read-only BY CONSTRUCTION: attach lengths are page-aligned, every
  prefill/decode write of the attaching request lands at positions past
  the attached prefix, i.e. in pages it allocated itself. Copy-on-write
  therefore degenerates to the alignment assertion the engine makes at
  attach time — no device-side copy path exists to need.
- **the index is a radix tree of page-sized token chunks.** One node
  per cached page, keyed under its parent by the page's token tuple;
  matching walks chunk by chunk, so a hit is always a chain of
  ancestors (a page is only usable together with its whole prefix).
- **references, not copies.** The cache holds ONE
  :meth:`~bigdl_tpu.serving.paging.PagePool.share` reference per cached
  page; an attaching request adds its own. The pool frees a page only
  at refcount zero, so eviction and retirement can race in any order
  without a page ever reaching the free heap while referenced.
- **LRU leaf eviction under page pressure.** When an admission cannot
  reserve its pages, the engine evicts least-recently-used UNREFERENCED
  leaves (cache-only refcount, no children) before falling back to the
  FIFO head-of-line wait — cached prefixes are a cache, live requests
  are not.

All mutation happens on the engine loop thread (the same single-writer
discipline as :class:`~bigdl_tpu.serving.paging.PagePool`);
``snapshot()`` reads plain ints and is safe to scrape from the obs
:class:`~bigdl_tpu.obs.registry.MetricsRegistry`.
"""

from __future__ import annotations

import heapq
from typing import Callable, FrozenSet, List, Optional, Sequence, Tuple


class _PrefixNode:
    """One cached page: ``chunk`` is its page_size-token key under
    ``parent``, ``page`` the physical page id the cache holds a pool
    reference for, ``stamp`` the LRU clock of its last touch."""

    __slots__ = ("chunk", "parent", "page", "children", "stamp")

    def __init__(self, chunk, parent, page, stamp=0):
        self.chunk = chunk
        self.parent = parent
        self.page = page
        self.children = {}
        self.stamp = stamp


class PrefixCache:
    """Radix index over full, immutable KV pages of one paged lane.

    One instance per (engine, lane): a speculative engine keeps one for
    its target pools and one for the draft pools — the two models'
    pages hold different K/V for the same tokens and must never be
    shared across lanes. ``version`` folds the model identity into the
    keying: the engine bumps/clears on ``reload``, so pages written by
    retired params can never serve new ones.
    """

    def __init__(self, pool, *, name: str = "prefix"):
        self._pool = pool
        self.page_size = int(pool.page_size)
        self.name = name
        self.version = 0
        self._root = _PrefixNode(None, None, None)
        self._pages = 0          # nodes == cached pages (gauge)
        self._clock = 0          # LRU stamp source
        self.hits = 0            # admissions that attached >= 1 page
        self.misses = 0
        self.hit_tokens = 0      # prompt tokens served from the cache
        self.published_pages = 0
        self.evicted_pages = 0
        self.deduped_pages = 0   # duplicate physicals retired at publish

    # ------------------------------------------------------- queries ----

    @property
    def pages(self) -> int:
        """Pages the cache currently holds references for (gauge)."""
        return self._pages

    def lookup(self, prompt: Sequence[int]
               ) -> Tuple[int, List[int], List[_PrefixNode]]:
        """Longest cached page-aligned prefix of ``prompt`` that leaves
        at least ONE tail token to prefill (the final chunk must run to
        produce the first-token logits). Returns ``(matched token
        count, page ids, nodes)``; touches the matched chain's LRU
        stamps. Pure apart from the stamps — probing at the FIFO head
        check and again at admission sees the same answer."""
        ps = self.page_size
        limit = (len(prompt) - 1) // ps    # full pages, tail preserved
        node = self._root
        pages: List[int] = []
        nodes: List[_PrefixNode] = []
        for i in range(limit):
            child = node.children.get(
                tuple(int(t) for t in prompt[i * ps:(i + 1) * ps]))
            if child is None:
                break
            node = child
            pages.append(node.page)
            nodes.append(node)
        if nodes:
            self._clock += 1
            for nd in nodes:
                nd.stamp = self._clock
        return len(pages) * ps, pages, nodes

    def match_pages(self, prompt: Sequence[int], limit: int) -> List[int]:
        """Canonical cached page ids for the first ``limit`` full chunks
        of ``prompt`` (may return fewer — the walk stops at the first
        unindexed chunk). Unlike :meth:`lookup` this is a PURE reader:
        no LRU touch, no tail-token clamp — it serves publish-time
        dedup, not admission."""
        ps = self.page_size
        node = self._root
        pages: List[int] = []
        for i in range(limit):
            child = node.children.get(
                tuple(int(t) for t in prompt[i * ps:(i + 1) * ps]))
            if child is None:
                break
            node = child
            pages.append(node.page)
        return pages

    def record_probe(self, hit: bool, n_tokens: int = 0) -> None:
        """Count one admission's probe outcome (the engine calls this
        exactly once per admitted request per lane)."""
        if hit:
            self.hits += 1
            self.hit_tokens += int(n_tokens)
        else:
            self.misses += 1

    # ------------------------------------------------------ mutators ----

    def publish(self, prompt: Sequence[int], page_row) -> int:
        """Index the FULL prompt pages of a retiring sequence:
        ``page_row[i]`` is the physical page holding prompt tokens
        ``[i*ps, (i+1)*ps)``. Existing chains are descended (the pages
        the request itself attached, or a prefix someone published
        first — their duplicate physical pages simply drain with the
        request's own references); new nodes take one pool reference
        each. Returns the number of pages newly published."""
        ps = self.page_size
        self._clock += 1
        node = self._root
        added = 0
        for i in range(len(prompt) // ps):
            key = tuple(int(t) for t in prompt[i * ps:(i + 1) * ps])
            child = node.children.get(key)
            if child is None:
                page = int(page_row[i])
                self._pool.share([page])
                child = _PrefixNode(key, node, page, self._clock)
                node.children[key] = child
                self._pages += 1
                self.published_pages += 1
                added += 1
            child.stamp = self._clock
            node = child
        return added

    def node_prefix(self, nd: _PrefixNode) -> Tuple[int, ...]:
        """The page-aligned token prefix ending at ``nd`` — the chain's
        chunks root-to-node, flattened. This IS the node's radix key
        (together with ``version``), which is what the host tier files
        an offloaded page under."""
        chunks: List[Tuple[int, ...]] = []
        while nd is not None and nd is not self._root:
            chunks.append(nd.chunk)
            nd = nd.parent
        out: List[int] = []
        for chunk in reversed(chunks):
            out.extend(chunk)
        return tuple(out)

    def evict(self, n_pages: int,
              protect: FrozenSet[_PrefixNode] = frozenset(),
              on_evict: Optional[
                  Callable[[Tuple[int, ...], int], None]] = None) -> int:
        """Free up to ``n_pages`` pages by evicting least-recently-used
        UNREFERENCED leaves (pool refcount exactly the cache's own, no
        children — evicting an interior node would orphan its
        descendants' chains). ``protect`` shields the chain a pending
        admission just matched. Returns pages actually freed.

        Eviction is LEAF-FIRST, in rounds: one round drains the CURRENT
        evictable frontier in LRU order, and only when the shortfall
        survives a whole round do the parents that round exposed
        become the next frontier. The pre-PR-18 version pushed an exposed
        parent into the SAME heap under its own stamp — and because
        ``lookup``/``publish`` stamp a whole chain with one clock
        value, a parent is never younger than its coldest descendant,
        so one cold deep leaf let eviction climb its ancestor chain and
        drop the whole thing while OTHER chains' (younger-stamped)
        leaves survived untouched. An ancestor serves every branch
        below it; a leaf serves one. Round ordering makes the policy
        match that value: shorter shared prefixes outlive single-branch
        tails under equal pressure — and each evicted node leaves
        individually (shortest prefixes last), which is exactly the
        granularity the host tier wants its offload candidates in.

        ``on_evict(prefix_tokens, page)`` is invoked per victim BEFORE
        the page's reference is released — the engine's host-tier hook
        dispatches its device gather there, while the page still cannot
        be reallocated. The callback must not raise (the engine wraps
        its fault site); eviction proceeds regardless of what it does.
        """
        if n_pages <= 0 or not self._pages:
            return 0

        def _evictable(nd: _PrefixNode) -> bool:
            return (not nd.children and nd not in protect
                    and self._pool.refcount(nd.page) == 1)

        frontier: List[_PrefixNode] = []
        stack = list(self._root.children.values())
        while stack:
            nd = stack.pop()
            if nd.children:
                stack.extend(nd.children.values())
            elif _evictable(nd):
                frontier.append(nd)
        freed = 0
        while frontier and freed < n_pages:
            heap: List[Tuple[int, int, _PrefixNode]] = [
                (nd.stamp, id(nd), nd) for nd in frontier]
            heapq.heapify(heap)
            exposed: List[_PrefixNode] = []
            while heap and freed < n_pages:
                _, _, leaf = heapq.heappop(heap)
                if on_evict is not None:
                    on_evict(self.node_prefix(leaf), leaf.page)
                parent = leaf.parent
                del parent.children[leaf.chunk]
                self._pool.release([leaf.page])
                self._pages -= 1
                self.evicted_pages += 1
                freed += 1
                if parent is not self._root and _evictable(parent):
                    # next ROUND's candidate, never this round's: the
                    # leaf-first fix (see docstring)
                    exposed.append(parent)
            frontier = exposed
        return freed

    def clear(self) -> int:
        """Drop every cached page reference (engine close / failure /
        param reload — cached K/V keyed by the old params must never
        serve the new ones). Returns pages released; bumps ``version``
        so stale external references to this index are identifiable."""
        released = 0
        stack = list(self._root.children.values())
        while stack:
            nd = stack.pop()
            stack.extend(nd.children.values())
            self._pool.release([nd.page])
            released += 1
        self._root = _PrefixNode(None, None, None)
        self._pages = 0
        self.evicted_pages += released
        self.version += 1
        return released

    # ------------------------------------------------------- readers ----

    def snapshot(self) -> dict:
        """Plain-int gauges for the obs registry (``register("prefix",
        cache)``) — index size and probe/eviction counters."""
        probes = self.hits + self.misses
        return {
            "entries": self._pages,
            "shared_pages": self._pages,
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": self.hits / probes if probes else 0.0,
            "hit_tokens": self.hit_tokens,
            "published_pages": self.published_pages,
            "evicted_pages": self.evicted_pages,
            "deduped_pages": self.deduped_pages,
            "version": self.version,
        }
