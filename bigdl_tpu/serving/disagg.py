"""Prefill/decode disaggregation (PR 15): dedicated prefill engines
hand finished KV pages to decode engines.

The monolithic :class:`~bigdl_tpu.serving.engine.GenerationEngine`
interleaves prefill chunks with decode steps inside one scheduler loop,
so every long admitted prompt stalls every in-flight stream's next
token by a full chunk cost. This module removes the interference the
way production fleets do — by splitting the roles:

- a **prefill engine** (``role="prefill"``) runs only the
  ``prefill``/``chunk`` kernels. Its final prompt chunk, instead of
  flipping the slot to decode, gathers the request's finished KV pages
  into a device block and hands them off;
- a **decode engine** (``role="decode"``) runs only the ``decode``
  kernel and admits a request exclusively through
  ``submit_prefilled`` — pages already materialized, scattered into
  its own pool at adoption. Its inter-token latency therefore never
  pays for a neighbour's prompt.

:class:`DisaggregatedEngine` is the front door wiring the two: one
``submit`` that looks exactly like the monolithic engine's and produces
bit-identical streams (greedy and sampled, f32 and int8 KV, whole and
chunked prompts — the handoff payload carries the first token and the
POST-prefill PRNG key, so the decode side resumes the identical token
stream). Same-process handoff is a device-to-device gather/scatter of
owned page rows between the two pools (``PagePool.export_pages`` /
``adopt_pages`` keep the refcount/owner gauges byte-exact, and shared
prefix pages dedup to one copy on the decode side). Cross-process
handoff hosts a :class:`PrefillWorker` behind the PR-14 RPC fabric —
the KV block serializes over ``rpc.py`` npy frames through
``RemoteReplica`` and the front door re-stamps the deadline from its
own clock (monotonic time does not cross processes).

Failure semantics are request-scoped on both sides of the handoff: a
fault at the ``engine.page_handoff`` site (export or adopt stage) fails
only that stream with the injected error and drains BOTH pools'
per-owner gauges to zero — the chaos tier proves it for the local and
the RPC path.
"""

from __future__ import annotations

import time
from concurrent.futures import Future, InvalidStateError
from typing import Any, List, Optional, Sequence

import jax
import numpy as np

from bigdl_tpu.serving.engine import (
    GenerationEngine,
    GenerationStream,
    _cache_sharding_tree,
)
from bigdl_tpu.serving.metrics import ServingMetrics

__all__ = [
    "PageBlockMover",
    "DisaggregatedEngine",
    "PrefillWorker",
    "chaos_lm",
    "chaos_prefill_worker",
]


class PageBlockMover:
    """The jitted gather/scatter pair moving one request's page rows
    between role pools.

    ``gather(cache, idx)`` is a pure read: row ``i`` of every cache
    leaf's block is ``leaf[idx[i]]`` (the trash-padded tail rows gather
    trash-page garbage that the scatter routes straight back to the
    destination trash page — fixed shapes, no masking). ``scatter``
    donates the destination cache, exactly like the decode step, so
    adoption never reallocates pool buffers. Both work uniformly over
    f32 ``(K, V)`` and int8 ``(K, V, Ks, Vs)`` leaves because every
    pool is axis-0 page-indexed. ``gather_traces``/``scatter_traces``
    count actual XLA traces — the per-role compile-once tests pin them
    at one each.
    """

    def __init__(self, cache_sharding=None):
        self.cache_sharding = cache_sharding
        self.gather_traces = 0
        self.scatter_traces = 0

        def _gather(cache, idx):
            self.gather_traces += 1
            block = jax.tree_util.tree_map(lambda pool: pool[idx], cache)
            if cache_sharding is not None:
                block = jax.tree_util.tree_map(
                    jax.lax.with_sharding_constraint, block,
                    _cache_sharding_tree(block, cache_sharding))
            return block

        def _scatter(cache, block, idx):
            self.scatter_traces += 1
            out = jax.tree_util.tree_map(
                lambda pool, rows: pool.at[idx].set(rows), cache, block)
            if cache_sharding is not None:
                out = jax.tree_util.tree_map(
                    jax.lax.with_sharding_constraint, out,
                    _cache_sharding_tree(out, cache_sharding))
            return out

        self._gather = jax.jit(_gather)
        # donating the cache keeps adoption allocation-free; the block
        # is NOT donated (the local path may still hold it when a
        # retry-shaped caller re-dispatches)
        self._scatter = jax.jit(_scatter, donate_argnums=(0,))

    def gather(self, cache, idx):
        return self._gather(cache, np.asarray(idx, np.int32))

    def scatter(self, cache, block, idx):
        return self._scatter(cache, block, np.asarray(idx, np.int32))


class _FrontDoorStream(GenerationStream):
    """The consumer-facing stream of a disaggregated request. It is
    pushed by whichever role currently owns the request; ``cancel``
    additionally forwards to the prefill-role inner stream so a
    cancellation lands even before the handoff."""

    def __init__(self):
        super().__init__()
        self._inner: Optional[GenerationStream] = None

    def cancel(self) -> None:
        super().cancel()
        inner = self._inner
        if inner is not None:
            inner.cancel()


class DisaggregatedEngine:
    """Front door over a dedicated prefill engine and a dedicated
    decode engine: one monolithic-shaped ``submit``, bit-identical
    streams, no prefill/decode interference.

    ``**shared`` are :class:`GenerationEngine` kwargs applied to both
    roles, with three keys redirected where they belong:
    ``prefix_cache`` goes to the PREFILL role only (the radix index
    lives with the engine that writes prompt pages; attach-by-reference
    keeps working there), ``metrics`` goes to the DECODE role only (it
    is the front-door-visible sink — ITL, served/failed — while the
    prefill engine gets its own), and ``tracer`` rides with prefill
    (where requests are born). ``prefill_overrides`` /
    ``decode_overrides`` merge per-role on top (e.g. distinct modeled
    kernels, pool sizes, or a role-local metrics sink).

    Pass ``remote_prefill`` (a ``RemoteReplica`` hosting a
    :class:`PrefillWorker`, e.g. from
    ``start_replica_process("pkg.mod:worker_factory")``) instead of
    building a local prefill engine: prompts then prefill in the child
    process and pages arrive as npy frames over the PR-14 wire.
    """

    def __init__(self, model, params, *,
                 remote_prefill=None,
                 prefill_overrides: Optional[dict] = None,
                 decode_overrides: Optional[dict] = None,
                 **shared):
        shared.pop("role", None)
        metrics = shared.pop("metrics", None)
        tracer = shared.pop("tracer", None)
        prefix = bool(shared.pop("prefix_cache", False))
        cam = bool(shared.pop("cache_aware_admission", False))
        host_pages = shared.pop("host_pages", None)

        decode_kw = dict(shared)
        decode_kw["metrics"] = metrics or ServingMetrics()
        decode_kw.update(decode_overrides or {})
        self._decode = GenerationEngine(model, params, role="decode",
                                        **decode_kw)
        self.metrics = self._decode.metrics

        self._remote = remote_prefill
        self._prefill: Optional[GenerationEngine] = None
        if remote_prefill is None:
            prefill_kw = dict(shared)
            prefill_kw["prefix_cache"] = prefix
            prefill_kw["cache_aware_admission"] = cam
            prefill_kw["tracer"] = tracer
            if host_pages is not None:
                # the host tier hangs off the prefix index, which lives
                # with the prefill role in the disaggregated split
                prefill_kw["host_pages"] = host_pages
            prefill_kw.update(prefill_overrides or {})
            self._prefill = GenerationEngine(model, params, role="prefill",
                                             **prefill_kw)
            # the handoff consumer: runs ON the prefill loop thread
            # while the pages are still owned
            self._prefill._handoff_cb = self._on_handoff

    # ------------------------------------------------------ lifecycle ----

    def warmup(self) -> None:
        if self._prefill is not None:
            self._prefill.warmup()
        elif self._remote is not None:
            self._remote.warmup()
        self._decode.warmup()

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Prefill side first: its drain flushes every pending handoff
        into the decode queue, which the decode drain then finishes."""
        if self._prefill is not None:
            self._prefill.close(drain=drain, timeout=timeout)
        elif self._remote is not None:
            self._remote.close(drain=drain, timeout=timeout)
        self._decode.close(drain=drain, timeout=timeout)

    def __enter__(self) -> "DisaggregatedEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------ front door ----

    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: Optional[int] = None,
               deadline: Optional[float] = None,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0,
               seed: Optional[int] = None) -> GenerationStream:
        """Monolithic-shaped submit: route the prompt to the prefill
        role, continue the returned stream on the decode role once the
        pages hand off. The stream's tokens are bit-identical to a
        monolithic engine's for the same request (test-enforced)."""
        stream = _FrontDoorStream()
        ctx = {
            "stream": stream,
            "deadline": (None if deadline is None
                         else stream.t_submit + float(deadline)),
            "dispatched": False,
        }
        if self._prefill is not None:
            inner = self._prefill.submit(
                prompt, max_new_tokens=max_new_tokens, deadline=deadline,
                temperature=temperature, top_k=top_k, top_p=top_p,
                seed=seed, tag=ctx)
            stream._inner = inner
            inner.add_done_callback(self._make_relay(ctx))
        else:
            fut = self._remote.submit(
                np.asarray(prompt, np.int32), deadline=deadline,
                max_new_tokens=max_new_tokens, temperature=temperature,
                top_k=top_k, top_p=top_p, seed=seed)
            fut.add_done_callback(
                lambda f: self._on_remote_done(ctx, f))
        return stream

    def generate(self, prompt: Sequence[int], *,
                 timeout: Optional[float] = None, **kw) -> List[int]:
        return self.submit(prompt, **kw).result(timeout)

    # --------------------------------------------------------- handoff ----

    def _on_handoff(self, payload: dict) -> None:
        """Local handoff consumer (prefill loop thread, pages still
        owned): gather the KV block device-to-device off the prefill
        cache, then dispatch to the decode role. Raising here is the
        contract for failure — the prefill engine aborts the handoff,
        releases the pages and fails the inner stream."""
        payload["block"] = self._prefill._mover.gather(
            self._prefill._cache, payload["page_row"])
        self._dispatch(payload, reraise=True)

    def _on_remote_done(self, ctx: dict, fut) -> None:
        stream: GenerationStream = ctx["stream"]
        try:
            payload = fut.result()
        except BaseException as e:
            stream._finish(e)
            return
        if payload.get("complete"):
            # the request retired at its first token (mnt==1 / EOS /
            # deadline check) — nothing to decode, the worker returned
            # the finished tokens directly
            now = time.monotonic()
            for t in np.asarray(payload["tokens"]).reshape(-1):
                stream._push(int(t), now)
            stream._finish(None, now)
            return
        payload["tag"] = ctx
        self._dispatch(payload, reraise=False)

    def _dispatch(self, payload: dict, *, reraise: bool) -> None:
        """Hand one prefilled payload to the decode role. ``reraise``
        distinguishes the paths: locally the exception must propagate
        into the prefill engine's abort path (pages are still charged
        there); on the RPC path the worker already exported its pages,
        so failing the front stream is the whole cleanup."""
        ctx = payload.pop("tag")
        ctx["dispatched"] = True
        # the front door's clock owns the deadline: same-process this is
        # a no-op re-stamp, cross-process it replaces the worker's
        # meaningless monotonic value
        payload["deadline"] = ctx["deadline"]
        try:
            self._decode.submit_prefilled(payload, stream=ctx["stream"])
        except BaseException as e:
            ctx["stream"]._finish(e)
            if reraise:
                raise

    def _make_relay(self, ctx: dict):
        """Done-callback on the prefill-role inner stream: forward a
        prefill-phase failure (or a request that legitimately finished
        AT its first token, so no handoff fired) to the front stream.
        After a dispatch the decode role owns the stream and this is a
        no-op — ``_finish`` is idempotent besides."""

        def relay(inner: GenerationStream) -> None:
            stream: GenerationStream = ctx["stream"]
            if inner.error is not None:
                stream._finish(inner.error)
                return
            if ctx["dispatched"]:
                return
            now = time.monotonic()
            for t in inner.tokens:
                stream._push(int(t), now)
            stream._finish(None, now)

        return relay

    # -------------------------------------------------------- queries ----

    @property
    def prefill_engine(self) -> Optional[GenerationEngine]:
        return self._prefill

    @property
    def decode_engine(self) -> GenerationEngine:
        return self._decode

    def snapshot(self) -> dict:
        out: dict = {"decode": self._decode.metrics.snapshot(),
                     "decode_pool": self._decode._pool.snapshot()}
        if self._prefill is not None:
            out["prefill"] = self._prefill.metrics.snapshot()
            out["prefill_pool"] = self._prefill._pool.snapshot()
        elif self._remote is not None:
            out["prefill"] = self._remote.remote_snapshot()
        return out


class PrefillWorker:
    """RPC-hostable backend wrapping a prefill-role engine: ``submit``
    returns a Future that resolves with the handoff payload — the KV
    block converted to host npy leaves so it crosses the wire — for the
    client-side :class:`DisaggregatedEngine` to adopt. Satisfies the
    ``ReplicaServer`` backend contract (``submit``/``reload``/
    ``warmup``/``close`` plus the ``metrics``/``pages_in_use`` gauges
    its snapshot probes)."""

    def __init__(self, model, params, *, warm: bool = True, **engine_kw):
        engine_kw.pop("role", None)
        self.engine = GenerationEngine(model, params, role="prefill",
                                       **engine_kw)
        self.engine._handoff_cb = self._on_handoff
        if warm:
            self.engine.warmup()

    # ----------------------------------------------- backend contract ----

    def submit(self, x, deadline: Optional[float] = None,
               max_new_tokens: Optional[int] = None,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0, seed: Optional[int] = None,
               **kw) -> Future:
        fut: Future = Future()
        ctx = {"future": fut}
        inner = self.engine.submit(
            [int(t) for t in np.asarray(x).reshape(-1)],
            max_new_tokens=(None if max_new_tokens is None
                            else int(max_new_tokens)),
            deadline=deadline, temperature=float(temperature),
            top_k=int(top_k), top_p=float(top_p),
            seed=None if seed is None else int(seed), tag=ctx)

        def relay(s: GenerationStream) -> None:
            if fut.done():
                return  # the handoff already resolved it
            try:
                if s.error is not None:
                    fut.set_exception(s.error)
                else:
                    fut.set_result({"complete": True,
                                    "tokens": np.asarray(s.tokens,
                                                         np.int32)})
            except InvalidStateError:
                pass  # lost the race with the handoff resolution

        inner.add_done_callback(relay)
        return fut

    def _on_handoff(self, payload: dict) -> None:
        ctx = payload.pop("tag")
        # np-ify ON the loop thread while the pages are owned: the
        # export right after this may recycle them into another prompt
        payload["block"] = jax.tree_util.tree_map(
            np.asarray,
            self.engine._mover.gather(self.engine._cache,
                                      payload["page_row"]))
        # monotonic clocks don't cross processes — the front door
        # re-stamps from its own at dispatch
        payload["deadline"] = None
        fut: Future = ctx["future"]
        if not fut.done():
            try:
                fut.set_result(payload)
            except InvalidStateError:
                pass  # the relay resolved it between the check and here

    def reload(self, params, state=None) -> None:
        self.engine.reload(params, state)

    def warmup(self, *a, **kw) -> None:
        pass  # warmed in the constructor, before the RPC port opens

    def close(self, drain: bool = True, timeout=None) -> None:
        self.engine.close(drain=drain, timeout=timeout)

    # gauges the ReplicaServer snapshot probes
    @property
    def metrics(self) -> ServingMetrics:
        return self.engine.metrics

    @property
    def pages_in_use(self) -> int:
        return self.engine.pages_in_use


# ----------------------------------------------------- chaos factories ----


def chaos_lm():
    """Deterministic tiny LM both sides of a cross-process test build
    independently (``jax.random.key(0)`` init — bit-identical params in
    parent and child, nothing pickled)."""
    from bigdl_tpu.nn.layers.attention import Transformer

    model = Transformer(vocab_size=64, hidden_size=32, num_heads=2,
                        filter_size=64, num_hidden_layers=1)
    params, _ = model.init(jax.random.key(0))
    return model, params


def chaos_prefill_worker() -> PrefillWorker:
    """Zero-arg factory for ``start_replica_process`` — hosts the
    :func:`chaos_lm` prefill role for the RPC handoff tests and the
    chaos bench leg."""
    model, params = chaos_lm()
    return PrefillWorker(model, params, max_slots=2, max_len=48,
                         max_prompt_len=16, page_size=8, prefill_chunk=8)
