"""GenerationEngine — continuous-batching autoregressive generation.

PR 1's :class:`~bigdl_tpu.serving.service.InferenceService` batches
run-to-completion requests, the wrong shape for autoregressive decoding:
one long sequence holds the whole micro-batch hostage and new requests
wait for the full batch to finish. This module is the iteration-level
scheduler (Orca, OSDI '22; vLLM's slot-managed KV cache, SOSP '23 —
PAPERS.md): the unit of scheduling is ONE decode step, not one request.

Design, in XLA terms:

- **fixed-shape slot table** — the KV cache is ``(max_slots, heads,
  max_len, head_dim)`` per layer, built once by ``model.init_cache``.
  The jitted decode step closes over nothing dynamic: tokens ``(S,)``
  and positions ``(S,)`` are the only per-step inputs, so the loop
  compiles exactly once at warmup and NEVER recompiles, however
  admissions and retirements reshuffle the slots (test-enforced via the
  :class:`DecodeKernels` trace counters).
- **donated cache** — the cache pytree is donated to every prefill and
  decode call, so the steady-state loop allocates no new cache buffers.
- **admission between steps** — new requests prefill into free slots at
  decode-step boundaries (one bucket-padded prompt forward each);
  finished sequences (EOS, max-tokens, deadline expiry, cancel) retire
  mid-flight and free their slot immediately.
- **iterator-futures** — ``submit`` returns a :class:`GenerationStream`
  that yields tokens as the loop produces them; time-to-first-token and
  per-stream tokens/sec land in the shared
  :class:`~bigdl_tpu.serving.metrics.ServingMetrics`.

:func:`static_generate` is the run-to-completion baseline over the SAME
jitted kernels — ``bench.py --mode serving --generate`` and the CI smoke
gate measure continuous vs static tokens/sec with it (the win is
scheduling, so it shows even on one core).

PR 6 replaces the dense slot lanes with a **paged KV cache**
(:class:`PagedDecodeKernels`, the default for paged-capable models):
per layer the cache is a shared pool of fixed-size pages plus a per-slot
int32 page map, reserved/released by the host-side
:class:`~bigdl_tpu.serving.paging.PagePool` as sequences are admitted
and retire — KV memory scales with each request's actual token budget
instead of ``max_slots x max_len``, the direct capacity lever on
concurrent users. Riding on the paged step:

- **in-step sampling** — temperature / top-k / top-p run INSIDE the
  jitted decode step with per-request params batched as ``(max_slots,)``
  arrays and one raw threefry key per slot (``core.rng``); a request's
  stream depends only on its seed, so sampled output is deterministic
  across runs, admission orderings, and schedulers. Greedy
  (``temperature=0``, the default) stays bit-identical to the dense
  PR-5 engine — test-enforced.
- **chunked prefill** — prompts longer than ``prefill_chunk`` advance
  one chunk per engine iteration, interleaved with decode steps, so a
  max-length prompt no longer stalls every neighbour's next token; the
  ``max_prompt_len < max_len`` admission wall is gone (any prompt up to
  ``max_len - 1`` is admitted and chunked).

The dense :class:`DecodeKernels` path is kept verbatim as the PR-5
baseline (and for decode-capable models without the paged API); the
bit-identity acceptance tests decode the same prompts through both.

PR 12 adds **prefix caching** (``prefix_cache=True``, paged engines
only): retiring sequences publish their full prompt pages to a
host-side radix index (``serving.prefix_cache.PrefixCache``) keyed by
(model version, page-aligned token prefix); an admission whose prompt
matches attaches those pages by refcounted reference and chunked
prefill SKIPS the covered chunks entirely — only the divergent tail
runs the chunk/prefill kernels. Zero device-side changes: the kernels
already take page ids as data, so compile-once is untouched, and
because cached bits equal freshly-computed bits, output with the cache
on is bit-identical to off (test-enforced). Unreferenced cached
prefixes evict LRU under page pressure before the FIFO admission wait
triggers.
"""

from __future__ import annotations

import logging
import queue as _queue
import threading
import time
import uuid
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu import faults
from bigdl_tpu.core.rng import request_seed, threefry_key_data
from bigdl_tpu.faults import StallError, Watchdog
from bigdl_tpu.obs.timeline import StepTimeline
from bigdl_tpu.obs.trace import submit_trace
from bigdl_tpu.ops.sampling import (
    EXTRA_STREAM,
    draft_sample,
    filtered_probs,
    pick_token,
    position_uniform,
    sample_tokens,
    speculative_sample,
)
from bigdl_tpu.serving.batcher import bucket_sizes_for
from bigdl_tpu.utils.errors import fresh_exception
from bigdl_tpu.serving.errors import (
    DeadlineExceeded,
    GrammarViolation,
    Overloaded,
    StreamCancelled,
)
from bigdl_tpu.serving.kv_tiers import HostPageStore
from bigdl_tpu.serving.metrics import ServingMetrics
from bigdl_tpu.serving.paging import PagePool, page_bytes, pages_per_lane
from bigdl_tpu.serving.prefix_cache import PrefixCache

log = logging.getLogger("bigdl_tpu.serving")

_SENTINEL = object()


class _TraceCounts:
    """Mutable trace counters, deliberately a separate tiny object: the
    jitted closures capture THIS (and the model), never the object that
    owns the pjit executables — a closure capturing the owner would put
    it in a cycle through the C++ pjit object, which the GC cannot
    break, leaking model+params on an unclosed engine."""

    __slots__ = ("prefill", "decode", "chunk")

    def __init__(self):
        self.prefill = 0
        self.decode = 0
        self.chunk = 0


def _cache_pinner(cache_sharding):
    """Constraint applied to the new cache INSIDE every jitted kernel
    when the engine runs sharded: pins the output cache to the exact
    NamedSharding the input cache carries, so (a) donation of the sharded
    cache holds call after call (donor and result layouts match) and
    (b) GSPMD can never drift the cache layout between steps, which would
    miss the executable cache and break compile-once. ``None`` (the
    single-device engine) is the identity.

    An int8 paged cache passes a PAIR ``(page_sharding, scale_sharding)``
    — 4-D page pools pin to the heads-sharded spec, the 2-D per-token
    scale pools to the replicated one (``parallel.tp.kv_scale_pspec``)."""
    if cache_sharding is None:
        return lambda cache: cache

    def pin(cache):
        return jax.tree_util.tree_map(
            jax.lax.with_sharding_constraint, cache,
            _cache_sharding_tree(cache, cache_sharding))

    return pin


def _cache_sharding_tree(cache, cache_sharding):
    """Expand an engine cache sharding (a single sharding, or the int8
    (pages, scales) pair) into the per-leaf tree both ``jax.device_put``
    and the in-jit pinner consume — the ONE place the leaf-to-sharding
    dispatch rule lives (4-D leaves are page pools, 2-D leaves are
    per-token scale pools)."""
    if isinstance(cache_sharding, tuple):
        page_s, scale_s = cache_sharding
        return jax.tree_util.tree_map(
            lambda a: page_s if a.ndim == 4 else scale_s, cache)
    return jax.tree_util.tree_map(lambda _: cache_sharding, cache)


class DecodeKernels:
    """The jitted ``(prefill, decode)`` pair over a decode-capable model
    (one exposing ``init_cache`` / ``prefill`` / ``decode_step``, e.g.
    ``nn.Transformer`` in ``language_model`` mode).

    Greedy argmax sampling happens INSIDE the jitted step so only the
    ``int32`` next-token vector crosses to the host each iteration.
    ``prefill_traces`` / ``decode_traces`` increment only when XLA
    actually traces (= compiles) — the compile-count assertions in the
    tests read them. The cache argument is donated: the steady-state
    loop never reallocates cache buffers.

    ``cache_sharding`` (a ``NamedSharding``, typically
    ``parallel.tp.kv_cache_pspec`` over a serving mesh) turns the pair
    into pjit over tensor-parallel params: the returned cache is pinned
    to that sharding so donation and compile-once survive sharding.
    """

    def __init__(self, model, *, donate: bool = True, cache_sharding=None):
        self.model = model
        self.cache_sharding = cache_sharding
        self.counts = _TraceCounts()
        counts = self.counts
        pin = _cache_pinner(cache_sharding)

        def prefill(params, cache, slot, tokens, length):
            counts.prefill += 1
            logits, cache = model.prefill(params, cache, slot, tokens, length)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), pin(cache)

        def decode(params, cache, tokens, positions):
            counts.decode += 1
            logits, cache = model.decode_step(params, cache, tokens, positions)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), pin(cache)

        dn = (1,) if donate else ()
        self._prefill = jax.jit(prefill, donate_argnums=dn)
        self._decode = jax.jit(decode, donate_argnums=dn)

    @property
    def prefill_traces(self) -> int:
        return self.counts.prefill

    @property
    def decode_traces(self) -> int:
        return self.counts.decode

    def prefill(self, params, cache, slot: int, tokens, length: int):
        """-> (first generated token, new cache); donates ``cache``."""
        return self._prefill(params, cache, int(slot),
                             np.asarray(tokens, np.int32), int(length))

    def decode(self, params, cache, tokens, positions):
        """-> (next token per slot (S,), new cache); donates ``cache``."""
        return self._decode(params, cache, np.asarray(tokens, np.int32),
                            np.asarray(positions, np.int32))


class PagedDecodeKernels:
    """The jitted ``(prefill, chunk, decode)`` triple over a PAGED
    decode-capable model (one exposing ``init_paged_cache`` /
    ``prefill_paged`` / ``decode_step_paged``, e.g. ``nn.Transformer``).

    Differences from the dense :class:`DecodeKernels`:

    - the cache is the shared page pool; every call additionally takes
      int32 page ids (a ``(ppn,)`` row for prefill chunks, the full
      ``(max_slots, ppn)`` map for decode) — dynamic VALUES with static
      shapes, so the compile-once guarantee is untouched;
    - sampling runs inside the step: per-slot ``temperature`` / ``top_k``
      / ``top_p`` arrays plus one raw threefry key per slot, split once
      per call (``ops.sampling.sample_tokens``). ``temperature=0`` rows
      take the bitwise PR-5 greedy-argmax path;
    - ``chunk`` is prefill WITHOUT logits/sampling — the non-final
      pieces of a chunked prompt. It always runs at exactly
      ``prefill_chunk`` tokens, so it traces once.

    The cache is donated on every call; only token/key vectors cross to
    the host per step. ``use_kernel`` routes decode attention through
    the Pallas paged kernel (auto: TPU only). ``cache_sharding`` shards
    the page pools (heads axis) exactly like :class:`DecodeKernels`.
    """

    def __init__(self, model, *, donate: bool = True,
                 use_kernel: Optional[bool] = None, cache_sharding=None):
        self.model = model
        self.cache_sharding = cache_sharding
        self.counts = _TraceCounts()
        counts = self.counts
        pin = _cache_pinner(cache_sharding)

        def prefill(params, cache, pages, tokens, start, length, trash,
                    temp, top_k, top_p, key, bias):
            counts.prefill += 1
            logits, cache = model.prefill_paged(
                params, cache, pages, tokens, start, length, trash)
            toks, new_key = sample_tokens(logits[None], temp, top_k, top_p,
                                          key, bias)
            return toks[0], new_key, pin(cache)

        def chunk(params, cache, pages, tokens, start, length, trash):
            counts.chunk += 1
            return pin(model.prefill_paged(params, cache, pages, tokens,
                                           start, length, trash,
                                           need_logits=False))

        def decode(params, cache, tokens, positions, page_map,
                   temps, top_ks, top_ps, keys, bias):
            counts.decode += 1
            logits, cache = model.decode_step_paged(
                params, cache, tokens, positions, page_map,
                use_kernel=use_kernel)
            toks, new_keys = sample_tokens(logits, temps, top_ks, top_ps,
                                           keys, bias)
            return toks, new_keys, pin(cache)

        dn = (1,) if donate else ()
        self._prefill = jax.jit(prefill, donate_argnums=dn)
        self._chunk = jax.jit(chunk, donate_argnums=dn)
        self._decode = jax.jit(decode, donate_argnums=dn)

    @property
    def prefill_traces(self) -> int:
        return self.counts.prefill

    @property
    def chunk_traces(self) -> int:
        return self.counts.chunk

    @property
    def decode_traces(self) -> int:
        return self.counts.decode

    def prefill(self, params, cache, pages, tokens, start, length, trash,
                temperature=0.0, top_k=0, top_p=1.0, key=None, bias=None):
        """Final (or only) chunk of one prompt: writes its K/V rows and
        samples the first generated token (under the optional ``(1, V)``
        grammar mask ``bias``). -> ``(token, new_key (1, 2), new
        cache)``; donates ``cache``."""
        if key is None:
            key = np.zeros(2, np.uint32)
        return self._prefill(
            params, cache, np.asarray(pages, np.int32),
            np.asarray(tokens, np.int32), int(start), int(length),
            int(trash), np.asarray([temperature], np.float32),
            np.asarray([top_k], np.int32), np.asarray([top_p], np.float32),
            np.asarray(key, np.uint32).reshape(1, 2),
            None if bias is None else np.asarray(bias, np.float32))

    def chunk(self, params, cache, pages, tokens, start, length, trash):
        """Non-final prompt chunk: K/V writes only. -> new cache
        (donates the old one)."""
        return self._chunk(
            params, cache, np.asarray(pages, np.int32),
            np.asarray(tokens, np.int32), int(start), int(length),
            int(trash))

    def decode(self, params, cache, tokens, positions, page_map,
               temps, top_ks, top_ps, keys, bias=None):
        """One decode step for every slot (``bias``: optional ``(S, V)``
        grammar mask, a traced value — pass it consistently, None or
        array, to keep the one-executable contract). -> ``(next token
        per slot (S,), new keys (S, 2), new cache)``; donates
        ``cache``."""
        return self._decode(
            params, cache, np.asarray(tokens, np.int32),
            np.asarray(positions, np.int32),
            np.asarray(page_map, np.int32),
            np.asarray(temps, np.float32), np.asarray(top_ks, np.int32),
            np.asarray(top_ps, np.float32), np.asarray(keys, np.uint32),
            None if bias is None else np.asarray(bias, np.float32))


class _SpecTraceCounts:
    """Trace counters for the speculative kernel set (same GC discipline
    as :class:`_TraceCounts` — the jitted closures capture THIS, never
    the kernel owner)."""

    __slots__ = ("prefill", "chunk", "draft_write", "draft", "verify")

    def __init__(self):
        self.prefill = 0
        self.chunk = 0
        self.draft_write = 0
        self.draft = 0
        self.verify = 0


class SpeculativeKernels:
    """The jitted kernel set for draft-verified (speculative) generation
    over TWO paged decode-capable models sharing one positional
    contract: a cheap ``draft_model`` proposes candidate tokens with
    ordinary single-row decode steps, and the ``model`` (the target)
    scores all of them in ONE multi-token ``verify`` forward
    (``Transformer.decode_verify_paged``), whose logits feed the
    rejection sampler (``ops.sampling.speculative_sample``).

    Kernels (cache argument donated in every one):

    - ``prefill`` / ``chunk`` — the target's prompt path, as in
      :class:`PagedDecodeKernels`, except the first generated token is
      drawn with the speculative tier's per-(request, output-position)
      keys (position 0) instead of the per-step split chain — so a
      sampled stream is a pure function of its request seed under ANY
      acceptance history;
    - ``draft_write`` — the draft model's prompt path (K/V writes only,
      no logits): the draft needs the prompt in its own cache before it
      can propose;
    - ``draft`` — one draft decode step for every slot: the draft's
      logits are sampled into ``(tokens, dists)`` where ``dists`` is the
      draft's full filtered distribution per slot — the verify step
      needs it for the accept ratio and the residual;
    - ``verify`` — the target's multi-token step over ``[last_token,
      d_1..d_k]`` plus the rejection sampler: returns ``(n_accepted,
      emitted tokens, new cache)``.

    All shapes are fixed (``k`` is baked into the verify width), so each
    kernel compiles exactly once — the compile-once contract of the
    paged engine survives speculation, whatever the acceptance lengths
    do (trace-counter test-enforced). ``cache_sharding`` pins BOTH
    models' page pools (the leaf-shape dispatch in
    ``_cache_sharding_tree`` is dimension-based, so one sharding serves
    both caches)."""

    def __init__(self, model, draft_model, *, donate: bool = True,
                 use_kernel: Optional[bool] = None, cache_sharding=None):
        if not hasattr(draft_model, "decode_step_paged"):
            raise ValueError(
                "speculative decoding needs a PAGED draft model "
                "(decode_step_paged — see nn.Transformer)")
        if getattr(model, "vocab_size", None) != getattr(
                draft_model, "vocab_size", None):
            raise ValueError(
                f"draft and target models must share one vocabulary, got "
                f"{getattr(draft_model, 'vocab_size', None)} vs "
                f"{getattr(model, 'vocab_size', None)}")
        self.model = model
        self.draft_model = draft_model
        self.cache_sharding = cache_sharding
        self.counts = _SpecTraceCounts()
        counts = self.counts
        pin = _cache_pinner(cache_sharding)

        def prefill(params, cache, pages, tokens, start, length, trash,
                    temp, top_k, top_p, key, bias):
            counts.prefill += 1
            logits, cache = model.prefill_paged(
                params, cache, pages, tokens, start, length, trash)
            dist = filtered_probs(logits[None], temp, top_k, top_p, bias)
            u = position_uniform(key, EXTRA_STREAM,
                                 jnp.zeros((1,), jnp.int32))
            return pick_token(dist, u)[0], pin(cache)

        def chunk(params, cache, pages, tokens, start, length, trash):
            counts.chunk += 1
            return pin(model.prefill_paged(params, cache, pages, tokens,
                                           start, length, trash,
                                           need_logits=False))

        def draft_write(dparams, dcache, pages, tokens, start, length,
                        trash):
            counts.draft_write += 1
            return pin(draft_model.prefill_paged(
                dparams, dcache, pages, tokens, start, length, trash,
                need_logits=False))

        def draft(dparams, dcache, tokens, positions, page_map, temps,
                  top_ks, top_ps, keys, out_pos, bias):
            counts.draft += 1
            logits, dcache = draft_model.decode_step_paged(
                dparams, dcache, tokens, positions, page_map,
                use_kernel=use_kernel)
            toks, dists = draft_sample(logits, temps, top_ks, top_ps,
                                       keys, out_pos, bias)
            return toks, dists, pin(dcache)

        def verify(params, cache, last_tokens, draft_tokens, positions,
                   page_map, trash, temps, top_ks, top_ps, keys,
                   out_base, draft_dists, bias):
            counts.verify += 1
            tokens = jnp.stack((last_tokens,) + tuple(draft_tokens),
                               axis=1)
            logits, cache = model.decode_verify_paged(
                params, cache, tokens, positions, page_map, trash)
            if bias is not None:
                # grammar mask per verify position: masked tokens get
                # zero target probability, so speculative_sample itself
                # is untouched (an illegal draft is rejected w.p. 1)
                logits = logits.astype(jnp.float32) + bias
            n_acc, out = speculative_sample(
                logits, jnp.stack(tuple(draft_tokens), axis=1),
                jnp.stack(tuple(draft_dists), axis=1),
                temps, top_ks, top_ps, keys, out_base)
            return n_acc, out, pin(cache)

        dn = (1,) if donate else ()
        self._prefill = jax.jit(prefill, donate_argnums=dn)
        self._chunk = jax.jit(chunk, donate_argnums=dn)
        self._draft_write = jax.jit(draft_write, donate_argnums=dn)
        self._draft = jax.jit(draft, donate_argnums=dn)
        self._verify = jax.jit(verify, donate_argnums=dn)

    # trace counters (compile-once assertions read these)
    @property
    def prefill_traces(self) -> int:
        return self.counts.prefill

    @property
    def chunk_traces(self) -> int:
        return self.counts.chunk

    @property
    def draft_write_traces(self) -> int:
        return self.counts.draft_write

    @property
    def draft_traces(self) -> int:
        return self.counts.draft

    @property
    def verify_traces(self) -> int:
        return self.counts.verify

    # decode_traces aliases verify for surfaces (engine properties,
    # step-cost wrappers) that treat "the per-iteration kernel" uniformly
    @property
    def decode_traces(self) -> int:
        return self.counts.verify

    def prefill(self, params, cache, pages, tokens, start, length, trash,
                temperature=0.0, top_k=0, top_p=1.0, key=None, bias=None):
        """Final (or only) chunk of one prompt through the TARGET:
        writes its K/V rows and samples the first generated token (the
        EXTRA_STREAM draw at output position 0, under the optional
        ``(1, V)`` grammar mask). -> ``(token, new cache)``; donates
        ``cache``."""
        if key is None:
            key = np.zeros(2, np.uint32)
        return self._prefill(
            params, cache, np.asarray(pages, np.int32),
            np.asarray(tokens, np.int32), int(start), int(length),
            int(trash), np.asarray([temperature], np.float32),
            np.asarray([top_k], np.int32), np.asarray([top_p], np.float32),
            np.asarray(key, np.uint32).reshape(1, 2),
            None if bias is None else np.asarray(bias, np.float32))

    def chunk(self, params, cache, pages, tokens, start, length, trash):
        """Non-final prompt chunk through the TARGET: K/V writes only.
        -> new cache (donates the old one)."""
        return self._chunk(
            params, cache, np.asarray(pages, np.int32),
            np.asarray(tokens, np.int32), int(start), int(length),
            int(trash))

    def draft_write(self, dparams, dcache, pages, tokens, start, length,
                    trash):
        """Prompt chunk through the DRAFT (final or not — the draft
        never samples during prefill). -> new draft cache (donated)."""
        return self._draft_write(
            dparams, dcache, np.asarray(pages, np.int32),
            np.asarray(tokens, np.int32), int(start), int(length),
            int(trash))

    def draft(self, dparams, dcache, tokens, positions, page_map, temps,
              top_ks, top_ps, keys, out_pos, bias=None):
        """One draft decode step for every slot (``bias``: optional
        ``(S, V)`` grammar mask — the draft proposes only legal
        tokens). -> ``(tokens (S,), dists (S, V), new draft cache)``;
        donates ``dcache``."""
        return self._draft(
            dparams, dcache, np.asarray(tokens, np.int32),
            np.asarray(positions, np.int32),
            np.asarray(page_map, np.int32), np.asarray(temps, np.float32),
            np.asarray(top_ks, np.int32), np.asarray(top_ps, np.float32),
            np.asarray(keys, np.uint32), np.asarray(out_pos, np.int32),
            None if bias is None else np.asarray(bias, np.float32))

    def verify(self, params, cache, last_tokens, draft_tokens, positions,
               page_map, trash, temps, top_ks, top_ps, keys, out_base,
               draft_dists, bias=None):
        """The target's verify forward + rejection sampler.
        ``draft_tokens`` / ``draft_dists`` are the k-tuples of device
        arrays the draft steps returned; ``bias`` is the optional
        ``(S, k+1, V)`` stacked grammar mask added to the target logits
        before the sampler. -> ``(n_accepted (S,), tokens (S, k+1), new
        cache)``; donates ``cache``."""
        return self._verify(
            params, cache, np.asarray(last_tokens, np.int32),
            tuple(draft_tokens), np.asarray(positions, np.int32),
            np.asarray(page_map, np.int32), int(trash),
            np.asarray(temps, np.float32), np.asarray(top_ks, np.int32),
            np.asarray(top_ps, np.float32), np.asarray(keys, np.uint32),
            np.asarray(out_base, np.int32), tuple(draft_dists),
            None if bias is None else np.asarray(bias, np.float32))


class GenerationStream:
    """Iterator-future for one generation request.

    The engine pushes tokens as decode steps complete; the consumer
    either iterates (``for tok in stream`` — single-pass, yields each
    token once then raises the terminal error, if any) or blocks for the
    whole sequence with :meth:`result`. :meth:`cancel` asks the engine
    to retire the slot at the next step boundary (the stream then ends
    with :class:`StreamCancelled`; tokens produced so far stay readable
    via :attr:`tokens`).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._tokens: List[int] = []
        self._q: "_queue.SimpleQueue" = _queue.SimpleQueue()
        self._done = threading.Event()
        self._error: Optional[BaseException] = None
        self._cancelled = False
        self._callbacks: List[Callable[["GenerationStream"], None]] = []
        self.t_submit = time.monotonic()
        self.t_first: Optional[float] = None
        self.t_done: Optional[float] = None
        # per-request trace context (obs.RequestTrace); rides the stream
        # so routers/replica sets can annotate it without new signatures
        self.trace = None

    # ------------------------------------------------- engine side ----

    def _push(self, token: int, now: float) -> None:
        with self._lock:
            if self.t_first is None:
                self.t_first = now
            self._tokens.append(token)
        self._q.put(token)

    def _finish(self, error: Optional[BaseException] = None,
                now: Optional[float] = None) -> None:
        with self._lock:
            if self._done.is_set():
                return
            self._error = error
            self.t_done = now if now is not None else time.monotonic()
            callbacks = list(self._callbacks)
            self._done.set()
        self._q.put(_SENTINEL)
        for cb in callbacks:
            try:
                cb(self)
            except Exception:
                log.exception("GenerationStream done-callback failed")

    # ----------------------------------------------- consumer side ----

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is _SENTINEL:
                if self._error is not None:
                    # the stored terminal error may be raised again by any
                    # number of result()/__iter__ calls on other threads —
                    # raise a per-call copy so no raise mutates the
                    # __traceback__ a sibling already captured (GL001)
                    raise fresh_exception(self._error)
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the stream finishes; the full token list (raises
        the stream's terminal error instead, e.g. ``DeadlineExceeded``)."""
        if not self._done.wait(timeout):
            raise TimeoutError("generation stream did not finish in time")
        if self._error is not None:
            raise fresh_exception(self._error)  # per-call copy (GL001)
        return list(self._tokens)

    def cancel(self) -> None:
        """Ask the engine to retire this request at the next step
        boundary (no-op once the stream is done)."""
        self._cancelled = True

    def add_done_callback(self, fn: Callable[["GenerationStream"], None]) -> None:
        with self._lock:
            if not self._done.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    # ------------------------------------------------------ queries ----

    @property
    def tokens(self) -> List[int]:
        """Tokens produced so far (snapshot copy)."""
        with self._lock:
            return list(self._tokens)

    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def cancelled(self) -> bool:
        return self._cancelled

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    @property
    def ttft_s(self) -> Optional[float]:
        """Submit -> first token, seconds (None before the first token)."""
        return None if self.t_first is None else self.t_first - self.t_submit


def _start_host_copy(leaf):
    """Kick an async device->host transfer for one gathered block leaf
    (the offload double-buffer overlaps with decode steps; the drain
    poll reads it back with ``np.asarray`` once landed). Best-effort:
    backends without the API just pay the copy at read time."""
    try:
        leaf.copy_to_host_async()
    except (AttributeError, NotImplementedError, RuntimeError):
        pass
    return leaf


def _block_ready(block) -> bool:
    """True when every leaf of a gathered block has its data available
    (the non-blocking completion poll between scheduler iterations)."""
    for leaf in jax.tree_util.tree_leaves(block):
        ready = getattr(leaf, "is_ready", None)
        if ready is not None and not ready():
            return False
    return True


class _GenRequest:
    __slots__ = ("prompt", "max_new_tokens", "deadline", "stream",
                 "temperature", "top_k", "top_p", "seed", "tag", "handoff",
                 "priority", "grammar")

    def __init__(self, prompt: List[int], max_new_tokens: int,
                 deadline: Optional[float], stream: GenerationStream,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, seed: Optional[int] = None,
                 tag: Any = None, handoff: Optional[dict] = None,
                 priority: int = 0, grammar=None):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.deadline = deadline
        self.stream = stream
        self.temperature = temperature
        self.top_k = top_k
        self.top_p = top_p
        self.seed = seed
        self.tag = tag            # opaque caller context, rides the handoff
        self.handoff = handoff    # adopt payload (decode-role admission)
        self.priority = int(priority)  # QoS tier (PR 18): a page-blocked
        #                                higher-priority head may swap out
        #                                lower-priority active streams
        self.grammar = grammar    # compiled TokenAutomaton (PR 20) or None

    @property
    def sampled(self) -> bool:
        return self.temperature > 0.0


class _SlotState:
    """Host-side bookkeeping for one occupied slot. ``phase`` is
    "decode" for the dense engine always; the paged engine admits into
    "prefill" and flips to "decode" once the final prompt chunk has run
    (chunked prefill interleaves with neighbours' decode steps)."""

    __slots__ = ("req", "last_token", "position", "generated", "t_admit",
                 "phase", "pages", "page_row", "prefill_pos",
                 "draft_pages", "dpage_row", "cache_version", "t_last",
                 "grammar_state", "grammar_error")

    def __init__(self, req: _GenRequest, last_token: int, position: int,
                 generated: int, t_admit: float, phase: str = "decode",
                 pages: Optional[List[int]] = None,
                 page_row=None, prefill_pos: int = 0,
                 draft_pages: Optional[List[int]] = None,
                 dpage_row=None):
        self.req = req
        self.last_token = last_token
        self.position = position          # cache row the NEXT token writes
        self.generated = generated
        self.t_admit = t_admit
        self.phase = phase
        self.pages = pages                # reserved physical pages (paged)
        self.page_row = page_row          # (ppn,) int32 map row (paged)
        self.prefill_pos = prefill_pos    # next prompt index to prefill
        self.draft_pages = draft_pages    # draft-lane pages (speculative)
        self.dpage_row = dpage_row        # draft (ppn,) map row (spec)
        self.cache_version = 0            # prefix-index version at admit
        self.t_last = 0.0                 # last token's push time (ITL)
        self.grammar_state = None         # automaton state (None until armed)
        self.grammar_error = None         # pending GrammarViolation


class _StepTicket:
    """One in-flight async decode step (PR 19): the device futures plus
    the dispatch-time view the land needs. ``parts`` pins the exact
    ``(slot, _SlotState)`` pairs the step computed for — at land time a
    participant whose slot no longer maps to the SAME state (retired
    and re-admitted, swapped out, cancelled) is skipped: its token is
    the discarded rider token of the one-step scheduling lag.
    ``positions`` is the dispatched position snapshot (unclamped rows
    feed ``position + 1`` back into the live dispatch arrays)."""

    __slots__ = ("parts", "positions", "toks", "keys", "overlap_s")

    def __init__(self, parts: List[Tuple[int, "_SlotState"]],
                 positions: "np.ndarray", toks, keys):
        self.parts = parts
        self.positions = positions
        self.toks = toks          # device future: int32[max_slots]
        self.keys = keys          # device future (paged) or None (dense)
        self.overlap_s = 0.0      # host work done while in flight


class _Core:
    """State shared between the engine facade and the loop thread:
    request/stream bookkeeping only, nothing heavy — so the loop can
    fail every stream and exit even if the facade (holding params,
    cache, and the jitted kernels) has been garbage-collected."""

    __slots__ = ("cond", "pending", "active", "free", "closed", "drain")

    def __init__(self, max_slots: int):
        self.cond = threading.Condition()
        self.pending: "deque[_GenRequest]" = deque()
        self.active: Dict[int, _SlotState] = {}
        self.free: List[int] = list(range(max_slots))
        self.closed = False
        self.drain = True


def _fail_streams(core: _Core, error: BaseException,
                  engine: "Optional[GenerationEngine]" = None) -> None:
    """Fail every pending/active stream. Pass the engine (when a strong
    ref is still live) so a PAGED engine's reserved pages return to the
    pool — close(drain=False) and step-failure must not strand the
    ``pages_in_use`` gauge non-zero in a shared ServingMetrics. Callers
    are the loop thread or a post-join close(): never concurrent with a
    running step, so touching the pool here is safe."""
    with core.cond:
        reqs = list(core.pending) + [s.req for s in core.active.values()]
        states = list(core.active.items())
        core.pending.clear()
        core.free.extend(core.active.keys())
        core.active.clear()
    if engine is not None and engine.paged:
        for slot, st in states:
            engine._pool.release(st.pages or ())
            st.pages = None
            engine._page_map[slot] = engine._pool.trash
            if engine.speculative:
                # BOTH lanes of a speculative slot return to the pool —
                # a mid-verify failure must not strand the draft lane
                engine._pool.release(st.draft_pages or ())
                st.draft_pages = None
                engine._dpage_map[slot] = engine._pool.trash
        if engine._prefix is not None:
            # terminal path (step failure, close, GC): the prefix index
            # must drop its page references too, or a shared
            # ServingMetrics reports phantom shared_pages/pages_in_use
            # forever (chaos drain gate: shared_pages == 0)
            engine._prefix.clear()
            if engine._dprefix is not None:
                engine._dprefix.clear()
        if engine._host is not None:
            # the host tier drains with the device tier: in-flight
            # offload copies drop (their device pages already evicted
            # cleanly) and every resident entry/booking releases, so
            # both tiers' gauges reach zero together (chaos drain gate)
            engine._pending_offloads.clear()
            engine._host.clear()
        if states or engine._prefix is not None or engine._host is not None:
            engine._report_pages()
    for r in reqs:
        if not r.stream.done:
            r.stream._finish(error)
        tr = r.stream.trace
        if tr is not None and not tr.done:
            tr.finish(outcome="failed", error=type(error).__name__)


def _engine_loop(engine_ref: "weakref.ref[GenerationEngine]",
                 core: _Core) -> None:
    """Loop thread body. Holds only a weak ref to the engine while idle
    (same discipline as the batcher worker): an engine whose owner
    forgot ``close()`` becomes collectable and the loop exits, failing
    any stranded streams, instead of pinning params + KV cache forever."""
    try:
        _engine_loop_body(engine_ref, core)
    finally:
        # the LOOP owns watchdog retirement: close() skips it while the
        # loop is still alive (a wedged step outliving the join
        # timeout), so when the stuck step finally returns and the loop
        # exits, the watchdog thread — and its strong engine ref — must
        # be released here or they leak for the process lifetime
        engine = engine_ref()
        if engine is not None and engine._watchdog is not None:
            engine._watchdog.close(timeout=0)


def _notify_core(core: _Core) -> None:
    """``weakref.finalize`` callback registered on every engine: when
    the owner drops the last strong reference without ``close()``, the
    idle loop thread is parked in a PURE ``cond.wait()`` (no timeout —
    the last GL003 busy-wait left the hot loop in PR 19), so GC itself
    must deliver the wakeup that lets the loop observe the dead weakref
    and exit. Takes the core, never the engine: a strong engine ref in
    the finalizer's args would keep the engine alive forever."""
    try:
        with core.cond:
            core.cond.notify_all()
    except Exception:  # graftlint: disable=GL006
        # interpreter teardown can run finalizers after the lock
        # machinery is gone (nothing to log TO either); the daemon loop
        # thread dies with the process anyway, so swallowing is safe
        pass


def _engine_loop_body(engine_ref: "weakref.ref[GenerationEngine]",
                      core: _Core) -> None:
    while True:
        with core.cond:
            while not core.pending and not core.active and not core.closed:
                # check the weakref BEFORE waiting, under the lock: the
                # finalize hook notifies under this same lock, so a GC
                # that lands between iterations (the collector holds the
                # GIL, so the loop can be parked anywhere) is either seen
                # here or its notify arrives after wait() releases the
                # lock — the wakeup cannot be lost
                if engine_ref() is None:
                    break
                # pure wait: close() notifies, submit() notifies, and
                # engine GC notifies via the weakref.finalize hook —
                # every wake source is explicit, so no polling timeout
                core.cond.wait()
                if engine_ref() is None:
                    break
            if core.closed:
                if not core.drain:
                    _fail_streams(core, RuntimeError(
                        "generation engine closed before request ran"),
                        engine_ref())
                    return
                if not core.pending and not core.active:
                    return
        engine = engine_ref()
        if engine is None:
            _fail_streams(core, RuntimeError(
                "generation engine was garbage-collected with requests "
                "in flight"))
            return
        if engine._failed is not None:
            # the watchdog fired while a step was stuck; the streams are
            # already failed — now that the loop has control again, do
            # the slot/page reconciliation HERE (the only thread allowed
            # to touch them) and stop
            _fail_streams(core, engine._failed, engine)
            return
        wd = engine._watchdog
        if wd is not None:
            wd.arm("decode step")
        try:
            engine._step()
        except Exception as e:
            # a broken step cannot be retried: the donated cache may be
            # consumed — fail every stream loudly and stop the loop
            engine._failed = e
            log.exception("generation engine step failed; engine stopped")
            _fail_streams(core, e, engine)
            return
        finally:
            if wd is not None:
                wd.disarm()
        del engine


class GenerationEngine:
    """Continuous-batching generation front door over one decode-capable
    model (``init_cache`` / ``prefill`` / ``decode_step`` — see
    ``nn.Transformer``).

    ``submit(prompt, max_new_tokens=..., deadline=...)`` returns a
    :class:`GenerationStream`; a persistent loop thread admits pending
    prompts into free slots between decode steps, decodes every active
    slot per iteration, and retires finished sequences mid-flight.
    Admission control mirrors :class:`InferenceService`: a full pending
    queue raises :class:`Overloaded` on the caller's thread.

    ``warmup()`` compiles the decode step (once — its shapes never
    change) and every prompt bucket; call it before traffic so no
    request pays a compile. ``reload(params)`` swaps weights atomically
    between steps (see the hot-reload satellite).
    """

    def __init__(self, model, params, *, max_slots: int = 8,
                 max_len: int = 256, max_prompt_len: Optional[int] = None,
                 eos_id: Optional[int] = None, pad_id: int = 0,
                 max_queue: int = 64,
                 metrics: Optional[ServingMetrics] = None,
                 cache_dtype=jnp.float32,
                 kernels=None,
                 page_size: int = 16,
                 num_pages: Optional[int] = None,
                 prefill_chunk: Optional[int] = None,
                 seed: int = 0,
                 use_paged_kernel: Optional[bool] = None,
                 mesh=None,
                 param_pspecs=None,
                 shard_axis: str = "tp",
                 stall_timeout: Optional[float] = None,
                 quantize: Optional[str] = None,
                 speculate: Optional[tuple] = None,
                 prefix_cache: bool = False,
                 cache_aware_admission: bool = False,
                 host_pages: Optional[int] = None,
                 role: str = "both",
                 tracer=None,
                 timeline_capacity: int = 512,
                 profile_dir: Optional[str] = None,
                 profile_iters: int = 10,
                 async_scheduling: bool = False):
        if max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        if max_len < 2:
            raise ValueError("max_len must be >= 2 (prompt + 1 token)")
        self.model = model
        self.max_slots = int(max_slots)
        self.max_len = int(max_len)
        self.eos_id = None if eos_id is None else int(eos_id)
        self.pad_id = int(pad_id)
        self.max_queue = int(max_queue)
        self.metrics = metrics or ServingMetrics()
        self.seed = int(seed)
        # observability plane (PR 11): `tracer` (an obs.Tracer) turns on
        # per-request span traces — None (the default) costs one `is
        # None` test on the submit path and one attribute load per
        # decode step (the disarmed-fault-site budget, test-pinned).
        # `timeline` is the always-on bounded per-iteration breakdown
        # (host vs device, prefill/decode/verify split, queue depth and
        # occupancy); its aggregate feeds the metrics' engine_steps
        # block. `profile_dir` arms an opt-in jax.profiler trace
        # bracketing the first `profile_iters` scheduler iterations.
        # `async_scheduling` (PR 19) overlaps the host share of every
        # iteration with the in-flight decode step — same stream
        # bytes, one step of scheduling lag; see _step_async.
        self.tracer = tracer
        self.timeline = StepTimeline(timeline_capacity)
        self._profile_dir = profile_dir
        self._profile_iters = int(profile_iters)
        self._profile_state = 0   # 0 = armed/idle, 1 = tracing, 2 = done
        self._profile_count = 0
        # the int8 serving tier (PR 9): `quantize="int8"` rewrites the
        # GEMM weights to per-channel int8 ONCE here (and again inside
        # every reload, so checkpoint watchers keep feeding float
        # params); `cache_dtype="int8"` stores KV pages int8 with
        # per-token fp32 scale pools riding alongside. Both knobs keep
        # every standing contract: the quantized tree's shapes/dtypes
        # are a pure function of the float tree (reload never
        # recompiles), and the int8 cache donates/pins/shards exactly
        # like the float one.
        if quantize not in (None, "int8"):
            raise ValueError(f"quantize must be None or 'int8', "
                             f"got {quantize!r}")
        self.quantize = quantize
        # speculative decoding (PR 10): `speculate=(draft_model,
        # draft_params, k)` pairs the target with a cheap draft of the
        # same model family (same vocabulary). Each scheduler iteration
        # then runs k+1 draft decode steps (the +1 pre-writes the
        # would-be bonus row in the draft cache, so a full acceptance
        # leaves no K/V hole) and ONE target verify forward that scores
        # all k candidates at once — the memory-bandwidth-bound target
        # decode is amortized over up to k+1 emitted tokens per round.
        # Greedy speculative output is token-identical to plain greedy
        # decode (test-enforced); the draft and target reserve
        # side-by-side lanes in the ONE PagePool, tagged per owner.
        self.speculative = False
        self.spec_k = 0
        self.draft_model = None
        draft_params = None
        # prefix caching (PR 12): content-addressed sharing of full,
        # immutable prompt pages across requests over the one PagePool.
        # Off by default — the cache holds page references past request
        # lifetimes, so pool-drain invariants change shape with it on
        # (output does NOT: cache on vs off is bit-identical,
        # test-enforced). Built per lane below; a speculative engine
        # keeps separate target/draft indexes because the two models'
        # pages hold different K/V for the same tokens and must never
        # be shared across owners.
        self.prefix_caching = bool(prefix_cache)
        self._prefix: Optional[PrefixCache] = None
        self._dprefix: Optional[PrefixCache] = None
        self._prefix_flush = False
        # True after an eviction scan freed nothing; cleared whenever
        # pages release or publish (evictability can only change then),
        # so a page-blocked FIFO head does not re-walk the whole index
        # every scheduler iteration
        self._evict_stale = False
        # cache-aware admission (PR 14): when the FIFO head is
        # page-blocked, admit a LATER pending request that fits —
        # preferring resident prefixes (they allocate fewer fresh
        # pages) — instead of idling free pages behind the head. The
        # head's wait stays bounded: at most `_bypass_limit` bypasses
        # per blocked head, then strict FIFO resumes (fairness is
        # test-enforced). Off by default: it is a scheduling-order
        # change, never an output change.
        self.cache_aware_admission = bool(cache_aware_admission)
        self._bypass_limit = 4
        self._head_bypasses = 0   # consecutive bypasses of the current head
        self.admission_bypasses = 0  # total (snapshot counter)
        # prefill/decode disaggregation (PR 15): role="prefill" runs ONLY
        # the prefill/chunk kernels — the final chunk, instead of
        # flipping the slot to decode, gathers the finished KV pages into
        # a device block and invokes `_handoff_cb` (set by the
        # DisaggregatedEngine front door) with the handoff payload; the
        # slot's pages are then export_pages()d and the slot freed.
        # role="decode" runs ONLY the decode kernel and admits via
        # `submit_prefilled` — pages already materialized, scattered into
        # its own pool at adoption. role="both" (default) is the
        # monolithic engine, bit-identically untouched.
        if role not in ("both", "prefill", "decode"):
            raise ValueError(
                f"role must be 'both', 'prefill' or 'decode', got {role!r}")
        self.role = role
        self._handoff_cb: Optional[Callable[[dict], None]] = None
        # content-identity namespace for exported pages: unique per
        # engine INSTANCE across processes (adopt-side dedup keys on it,
        # and two prefill workers' page ids must never alias)
        self.handoff_source = f"prefill-{uuid.uuid4().hex[:12]}"
        self._mover = None
        if speculate is not None:
            try:
                self.draft_model, draft_params, self.spec_k = speculate
            except (TypeError, ValueError):
                raise ValueError(
                    "speculate must be a (draft_model, draft_params, k) "
                    "triple")
            self.spec_k = int(self.spec_k)
            if self.spec_k < 1:
                raise ValueError("speculate k must be >= 1")
            self.speculative = True
        if quantize == "int8":
            from bigdl_tpu.nn.quantized import (
                count_quantized_gemms,
                quantize_for_serving,
            )

            self._quantize_params = quantize_for_serving
            params = quantize_for_serving(params)
            if draft_params is not None:
                # the draft serves too: its GEMMs ride the same int8 tier
                draft_params = quantize_for_serving(draft_params)
            self.metrics.set_quantized_gemms(count_quantized_gemms(params))
        else:
            self._quantize_params = None
        self.cache_dtype_name = np.dtype(cache_dtype).name
        # sharded (tensor-parallel) mode: params placed per the Megatron
        # pspecs (parallel.tp), the KV cache — dense lanes or paged pools
        # — sharded on the HEADS axis; the jitted kernels become pjit and
        # GSPMD derives the collectives. Greedy decode stays bit-identical
        # to the single-device engine and compile-once survives because
        # every call sees the same input shardings (test-enforced).
        self.mesh = mesh
        self._param_shardings = None
        self._cache_sharding = None
        self._draft_param_shardings = None
        if mesh is not None:
            from jax.sharding import NamedSharding

            from bigdl_tpu.parallel.mesh import tree_shardings
            from bigdl_tpu.parallel.tp import (
                kv_cache_pspec,
                kv_scale_pspec,
                transformer_tp_pspecs,
            )

            if param_pspecs is None:
                param_pspecs = transformer_tp_pspecs(model, mesh,
                                                     axis=shard_axis,
                                                     params=params)
            self._param_shardings = tree_shardings(mesh, params, param_pspecs)
            params = jax.device_put(params, self._param_shardings)
            if draft_params is not None:
                # the draft shards on the same mesh with its OWN Megatron
                # pspecs (tp must divide its head count too); its page
                # pools reuse the target's heads-axis cache sharding
                dspecs = transformer_tp_pspecs(self.draft_model, mesh,
                                               axis=shard_axis,
                                               params=draft_params)
                self._draft_param_shardings = tree_shardings(
                    mesh, draft_params, dspecs)
                draft_params = jax.device_put(draft_params,
                                              self._draft_param_shardings)
            self._cache_sharding = NamedSharding(mesh,
                                                 kv_cache_pspec(shard_axis))
            if self.cache_dtype_name == "int8":
                # int8 pools carry 2-D per-token scale pools next to the
                # 4-D pages: pages shard on heads, scales replicate
                self._cache_sharding = (
                    self._cache_sharding,
                    NamedSharding(mesh, kv_scale_pspec()))
            if kernels is not None and getattr(
                    kernels, "cache_sharding",
                    None) != self._cache_sharding:
                # not just non-None: kernels pinned to a DIFFERENT mesh or
                # spec would return caches whose layout disagrees with the
                # engine's placement every step — donation mismatch and a
                # silent compile-once violation
                raise ValueError(
                    "a sharded engine needs kernels built with the engine's "
                    "exact cache_sharding (NamedSharding of this mesh + "
                    f"{kv_cache_pspec(shard_axis)}; int8 caches pair it "
                    "with a replicated scale-pool sharding); pass "
                    "kernels=None to build matching ones")
        # mode: the kernels pick it when given; otherwise paged whenever
        # the model speaks the paged API (the dense lanes are the PR-5
        # baseline, kept for bit-identity tests and plain-cache models).
        # `chunk` is the paged-triple discriminator so wrappers (fixed
        # step-cost shims, failure injectors) duck-type either flavour.
        if kernels is not None:
            if hasattr(kernels, "verify") != self.speculative:
                # a speculative engine needs the draft model/params from
                # `speculate=` AND kernels that carry the verify step;
                # half of either is a silent wrong-mode engine
                raise ValueError(
                    "speculate=(draft_model, draft_params, k) and "
                    "SpeculativeKernels go together: pass both or "
                    "neither")
            self.paged = hasattr(kernels, "chunk")
        else:
            self.paged = bool(page_size) and hasattr(model,
                                                    "decode_step_paged")
        if self.speculative and not (
                bool(page_size) and hasattr(model, "decode_step_paged")):
            raise ValueError(
                "speculative decoding needs the paged engine (the draft "
                "and target caches live side by side in one PagePool)")
        if self.cache_dtype_name == "int8" and not self.paged:
            raise ValueError(
                "cache_dtype='int8' needs the paged engine (int8 KV lives "
                "in the page pools with per-token scale pools; the dense "
                "slot-lane path is the float PR-5 baseline, kept bitwise "
                "untouched)")
        if self.role != "both":
            if not self.paged:
                raise ValueError(
                    "role='prefill'/'decode' needs the paged engine — the "
                    "handoff moves physical KV pages between pools")
            if self.speculative:
                raise ValueError(
                    "role='prefill'/'decode' excludes speculative decoding "
                    "(draft-lane pages do not cross the handoff yet)")
            if self.role == "decode" and self.prefix_caching:
                raise ValueError(
                    "the prefix index lives with the prefill role (pages "
                    "are published where prompts are written); pass "
                    "prefix_cache=True to the prefill engine instead")
        # two-tier KV (PR 18): host_pages=N backs the device pool with a
        # HostPageStore — prefix chains the device index would evict LRU
        # offload to host RAM instead (async device->host, double-
        # buffered, polled between iterations) and restore on a later
        # hit bit-identically; a page-blocked higher-priority head may
        # swap OUT a lower-priority active stream through the same tier.
        self._host: Optional[HostPageStore] = None
        self._pending_offloads: List[dict] = []
        self._offload_inflight_cap = 2   # double-buffer: never more
        #                                  in-flight copies than overlap
        self._swap_seq = 0               # swap booking ids (engine-local)
        if host_pages is not None:
            if not self.paged:
                raise ValueError(
                    "host_pages needs the paged engine (the host tier "
                    "stores physical KV pages; the dense slot-lane path "
                    "has none)")
            if self.speculative:
                raise ValueError(
                    "host_pages excludes speculative decoding (draft-"
                    "lane pages do not offload yet)")
            if self.role == "decode":
                raise ValueError(
                    "the host tier lives with the prefix index "
                    "(prefill/both roles — pages offload where prompts "
                    "are written); pass host_pages to the prefill "
                    "engine instead")
            if not self.prefix_caching:
                raise ValueError(
                    "host_pages needs prefix_cache=True — the host tier "
                    "is indexed by the same (version, prefix) radix keys "
                    "the device prefix index files pages under")
        if self.paged:
            # chunked prefill lifts the prompt-length wall: anything that
            # leaves room for one generated token is admitted and chunked
            self.max_prompt_len = int(max_prompt_len or (max_len - 1))
        else:
            self.max_prompt_len = int(max_prompt_len or max(1, max_len // 2))
        if not 1 <= self.max_prompt_len < self.max_len:
            raise ValueError(
                f"max_prompt_len {self.max_prompt_len} must be in "
                f"[1, max_len) = [1, {self.max_len})")
        if self.paged:
            self.page_size = int(page_size)
            self.prefill_chunk = int(
                prefill_chunk or min(64, self.max_prompt_len))
            if self.prefill_chunk < 1:
                raise ValueError("prefill_chunk must be >= 1")
            self.prompt_buckets = bucket_sizes_for(
                min(self.max_prompt_len, self.prefill_chunk))
            # dense-equivalent pool by default; shrink num_pages to trade
            # worst-case capacity for more concurrent typical requests.
            # A speculative engine reserves TWO lanes per slot (target +
            # draft) out of the one pool, so its default doubles — the
            # device pools of both models span the shared id space.
            ppn = pages_per_lane(self.max_len, self.page_size)
            self._lanes = lanes = 2 if self.speculative else 1
            self.num_pages = int(num_pages or self.max_slots * ppn * lanes)
            self._pool = PagePool(self.num_pages, self.page_size,
                                  self.max_len)
            if kernels is not None:
                self.kernels = kernels
            elif self.speculative:
                self.kernels = SpeculativeKernels(
                    model, self.draft_model, use_kernel=use_paged_kernel,
                    cache_sharding=self._cache_sharding)
            else:
                self.kernels = PagedDecodeKernels(
                    model, use_kernel=use_paged_kernel,
                    cache_sharding=self._cache_sharding)
            self._cache = model.init_paged_cache(
                self.num_pages + 1, self.page_size, cache_dtype)
            # per-slot step inputs, mutated on admission/retirement only
            self._page_map = np.full((self.max_slots, ppn),
                                     self._pool.trash, np.int32)
            self._temps = np.zeros((self.max_slots,), np.float32)
            self._top_ks = np.zeros((self.max_slots,), np.int32)
            self._top_ps = np.ones((self.max_slots,), np.float32)
            self._keys = np.zeros((self.max_slots, 2), np.uint32)
            # grammar-constrained decoding (PR 20): per-slot additive
            # mask rows, a traced (S, V) input of every sampling kernel.
            # Always the SAME kind of argument per engine (array, or
            # consistently None when the model exposes no vocab_size):
            # jit treats None as an empty pytree, so flip-flopping would
            # double the executable set. Unconstrained slots keep
            # all-zero rows — a constant shift, bitwise no-op.
            vocab = getattr(model, "vocab_size", None)
            self._bias = (np.zeros((self.max_slots, int(vocab)), np.float32)
                          if vocab else None)
            # distinct grammar keys seen by THIS engine: a submit whose
            # automaton key is already here shares the compiled tables
            # (the module compile cache made that sharing free) — the
            # grammar_compile_cache_hits metric counts those reuses
            self._grammars: set = set()
            if self.speculative:
                # the draft cache spans the same page-id space; its map
                # rows park on the shared trash page exactly like the
                # target's. In speculative mode `_keys` holds each
                # slot's REQUEST key (constant — draws are keyed by
                # output position, never by step).
                self._dcache = self.draft_model.init_paged_cache(
                    self.num_pages + 1, self.page_size, cache_dtype)
                self._dpage_map = np.full((self.max_slots, ppn),
                                          self._pool.trash, np.int32)
            # dtype-aware byte accounting for the kv_bytes_in_use gauge:
            # bytes one reserved page costs across ALL layers, scale
            # pools included (paging.page_bytes); 0 for models that do
            # not expose transformer dims (the gauge then stays silent)
            heads = getattr(model, "num_heads", 0)
            hidden = getattr(model, "hidden_size", 0)
            layers = getattr(model, "num_hidden_layers", 0)
            self._kv_page_bytes = (
                layers * page_bytes(self.page_size, heads, hidden // heads,
                                    self.cache_dtype_name)
                if heads and hidden and layers else 0)
            self._kv_dpage_bytes = 0
            if self.speculative:
                dheads = getattr(self.draft_model, "num_heads", 0)
                dhidden = getattr(self.draft_model, "hidden_size", 0)
                dlayers = getattr(self.draft_model, "num_hidden_layers", 0)
                self._kv_dpage_bytes = (
                    dlayers * page_bytes(self.page_size, dheads,
                                         dhidden // dheads,
                                         self.cache_dtype_name)
                    if dheads and dhidden and dlayers else 0)
            if self.prefix_caching:
                self._prefix = PrefixCache(self._pool, name="target")
                if self.speculative:
                    self._dprefix = PrefixCache(self._pool, name="draft")
            if host_pages is not None:
                self._host = HostPageStore(
                    int(host_pages), page_bytes=self._kv_page_bytes)
            if self.role != "both" or self._host is not None:
                # gather (prefill export / host offload) / scatter
                # (decode adopt / host restore) jits: one executable
                # each, counted like the kernel triples (compile-once is
                # test-pinned). Lazy import: disagg.py imports this
                # module at its top.
                from bigdl_tpu.serving.disagg import PageBlockMover

                self._mover = PageBlockMover(
                    cache_sharding=self._cache_sharding)
            self._report_pages()
        else:
            if self.prefix_caching:
                raise ValueError(
                    "prefix_cache=True needs the paged engine (shared "
                    "prefixes live in refcounted KV pages; the dense "
                    "slot-lane path has no pages to share)")
            self.prompt_buckets = bucket_sizes_for(self.max_prompt_len)
            self.kernels = kernels or DecodeKernels(
                model, cache_sharding=self._cache_sharding)
            self._cache = model.init_cache(self.max_slots, self.max_len,
                                           cache_dtype)
        if self._cache_sharding is not None:
            # heads-axis placement from step zero: the kernels' in-step
            # constraint then keeps every successive donated cache here
            self._cache = jax.device_put(
                self._cache,
                _cache_sharding_tree(self._cache, self._cache_sharding))
            if self.speculative:
                self._dcache = jax.device_put(
                    self._dcache,
                    _cache_sharding_tree(self._dcache,
                                         self._cache_sharding))
        self._params = params
        self._draft_params = draft_params
        self._failed: Optional[BaseException] = None
        self._core = _Core(self.max_slots)
        # stall watchdog: a decode/prefill call that makes no progress
        # past `stall_timeout` seconds (wedged device, hung collective)
        # fails every pending/active STREAM with a StallError diagnostic
        # instead of hanging their consumers forever; the loop thread
        # reconciles slots/pages when (if) the stuck step returns. NOTE:
        # a watchdog-armed engine must be close()d — the watchdog holds
        # a strong ref, so the forgot-to-close GC path applies only to
        # unwatched engines.
        self._watchdog = None
        if stall_timeout is not None:
            self._watchdog = Watchdog(
                f"engine@{id(self):x}", stall_timeout, self._on_stall)
        # async scheduling (PR 19): the loop lands step N's tokens,
        # immediately dispatches step N+1 from snapshot inputs, and does
        # ALL host work (delivery, retirement, admission, prefill
        # chunks, KV-tier polls) while N+1 runs on device. Scheduling
        # decisions lag one step — see _step_async. The speculative
        # round's accept count is a host decision gating the round's
        # FIRST draft input, so there is no overlap window to exploit
        # without changing the speculative contract: a speculative
        # engine keeps the sync path whatever the knob says.
        self.async_scheduling = bool(async_scheduling)
        self._async = self.async_scheduling and not self.speculative
        self._inflight: Optional[_StepTicket] = None
        # live per-slot dispatch inputs, the host half of the double
        # buffer: arming (admission / final prefill chunk) and landing
        # write here; every dispatch hands the kernels private COPIES,
        # so mutations for step N+2 can never race the in-flight N+1
        # (jax may alias a numpy argument's buffer on the CPU backend)
        self._step_tokens = np.zeros((self.max_slots,), np.int32)
        self._step_positions = np.zeros((self.max_slots,), np.int32)
        # slots armed since the last dispatch: the next land must NOT
        # fold the old ticket's rows over their fresh arming (a retired
        # slot re-admitted while its last step was still in flight)
        self._armed_dirty: set = set()
        # GC-liveness wakeup for the pure cond.wait() idle loop: when
        # the last strong engine ref drops, this finalizer (which holds
        # only the core) nudges the loop awake to observe the dead
        # weakref and exit
        weakref.finalize(self, _notify_core, self._core)
        self._thread = threading.Thread(
            target=_engine_loop, args=(weakref.ref(self), self._core),
            name="bigdl-serving-engine", daemon=True)
        self._thread.start()

    # ------------------------------------------------------ submission ----

    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: Optional[int] = None,
               deadline: Optional[float] = None,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0,
               seed: Optional[int] = None,
               tag: Any = None,
               priority: int = 0,
               grammar=None) -> GenerationStream:
        """Enqueue one prompt (sequence of token ids). ``max_new_tokens``
        caps generation (default: whatever fits in ``max_len``);
        ``deadline`` is seconds from now — an expired request retires
        mid-flight with :class:`DeadlineExceeded` on its stream. Raises
        :class:`Overloaded` when the pending queue is at its bound.

        Sampling (paged engine only): ``temperature > 0`` samples inside
        the jitted step, optionally filtered by ``top_k`` / nucleus
        ``top_p``; ``temperature=0`` (default) is greedy argmax. The
        stream's PRNG seed defaults to a pure function of the engine
        seed and the prompt bytes, so sampled output — like greedy — is
        identical across runs and admission orderings; pass ``seed`` to
        give byte-identical prompts distinct streams.

        ``tag`` is an opaque caller context that rides the request into
        a prefill-role engine's handoff payload (the DisaggregatedEngine
        threads its per-request routing state through it).

        ``priority`` (QoS, PR 18; meaningful on a host-tier engine —
        inert otherwise): when this request heads the FIFO queue
        page-blocked and nothing else frees room, active streams of
        STRICTLY lower priority may swap out through the host tier to
        admit it; they resume byte-exactly once pages free. Equal
        priorities never displace each other — default-0 traffic is
        plain FIFO.

        ``grammar`` (PR 20, paged engine only): a compiled
        :class:`~bigdl_tpu.grammar.TokenAutomaton` over this model's
        vocabulary. Every step of the stream then samples under the
        automaton's current-state mask (greedy = argmax over the LEGAL
        set), the state advances host-side per emitted token, and the
        stream is guaranteed to parse — a stream that cannot reach a
        parse (budget exhausted mid-grammar, or a stuck state) fails
        with :class:`GrammarViolation` instead of emitting garbage."""
        if self.role == "decode":
            raise RuntimeError(
                "a decode-role engine admits only prefilled requests "
                "(pages already materialized) — use submit_prefilled()")
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > self.max_prompt_len:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds max_prompt_len "
                f"{self.max_prompt_len}")
        temperature = float(temperature)
        if temperature > 0.0 and not self.paged:
            raise ValueError(
                "sampling (temperature > 0) needs the paged engine — the "
                "dense DecodeKernels path is the greedy PR-5 baseline")
        if temperature < 0.0:
            raise ValueError("temperature must be >= 0")
        if grammar is not None:
            if not self.paged:
                raise ValueError(
                    "grammar-constrained decoding needs the paged engine "
                    "(the mask rides the in-step sampler)")
            if self._bias is None:
                raise ValueError(
                    "grammar-constrained decoding needs a model exposing "
                    "vocab_size (the per-slot mask is (S, vocab))")
            if self.role != "both":
                raise ValueError(
                    "grammar-constrained decoding does not cross the "
                    "prefill/decode handoff yet — submit to a monolithic "
                    "(role='both') engine")
            if not hasattr(grammar, "bias_row"):
                raise TypeError(
                    "grammar must be a compiled TokenAutomaton — build "
                    "one with grammar.compile_grammar(regex_grammar(...) "
                    "or json_schema_grammar(...), vocab, eos_id)")
            if grammar.vocab_size != self._bias.shape[1]:
                raise ValueError(
                    f"grammar compiled over a {grammar.vocab_size}-token "
                    f"vocabulary, model has {self._bias.shape[1]}")
            if grammar.eos_id != self.eos_id:
                raise ValueError(
                    f"grammar compiled with eos_id={grammar.eos_id}, "
                    f"engine has eos_id={self.eos_id} — the EOS mask "
                    f"column is how a constrained stream terminates")
        room = self.max_len - len(prompt)
        mnt = room if max_new_tokens is None else min(int(max_new_tokens), room)
        if mnt < 1:
            raise ValueError("no room to generate even one token")
        if self.paged:
            # a prefill-role engine reserves prompt pages only — the
            # generation budget is the DECODE pool's problem
            need = self._lanes * (
                self._pool.pages_for(len(prompt))
                if self.role == "prefill"
                else self._pool.pages_for(
                    min(len(prompt) + mnt - 1, self.max_len)))
            if need > self.num_pages:
                # a reservation the pool can NEVER satisfy would block the
                # FIFO head forever (page pressure is allowed to delay, not
                # to deadlock) — reject it on the caller's thread instead
                raise ValueError(
                    f"request needs {need} KV pages but the pool holds "
                    f"{self.num_pages}; shrink the prompt/max_new_tokens "
                    f"or grow num_pages")
        stream = GenerationStream()
        now = stream.t_submit
        # trace context attaches BEFORE the request can reach the loop
        # thread (admission reads stream.trace); tracer=None is free
        tr = submit_trace(self.tracer, "generate", prompt_len=len(prompt),
                          max_new_tokens=mnt, sampled=temperature > 0.0)
        stream.trace = tr
        req = _GenRequest(prompt, mnt,
                          None if deadline is None else now + float(deadline),
                          stream, temperature=temperature, top_k=int(top_k),
                          top_p=float(top_p),
                          seed=None if seed is None else int(seed),
                          tag=tag, priority=int(priority), grammar=grammar)
        core = self._core
        try:
            with core.cond:
                if self._failed is not None:
                    raise RuntimeError(
                        "generation engine stopped after a step failure"
                    ) from self._failed
                if core.closed:
                    raise RuntimeError("generation engine is closed")
                if len(core.pending) >= self.max_queue:
                    self.metrics.record_rejected()
                    raise Overloaded(len(core.pending), self.max_queue)
                if tr is not None:
                    # BEFORE the enqueue: once the loop thread can see
                    # the request it may admit, run, and finish() the
                    # trace — a post-notify event would mutate a trace
                    # already retired into the finished ring
                    tr.event("submit", queue_depth=len(core.pending) + 1)
                if grammar is not None:
                    # shared-grammar accounting: a key this engine has
                    # already served means the compiled automaton (and
                    # its mask tables) were reused via the module
                    # compile cache rather than rebuilt
                    if grammar.key in self._grammars:
                        self.metrics.record_grammar_cache_hit()
                    else:
                        self._grammars.add(grammar.key)
                core.pending.append(req)
                depth = len(core.pending)
                core.cond.notify_all()
        except BaseException:
            if tr is not None:
                tr.finish(outcome="rejected")
            raise
        self.metrics.set_queue_depth(depth)
        return stream

    def generate(self, prompt: Sequence[int], *,
                 max_new_tokens: Optional[int] = None,
                 deadline: Optional[float] = None,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, seed: Optional[int] = None,
                 timeout: Optional[float] = None) -> List[int]:
        """Blocking convenience: ``submit(...).result(timeout)``."""
        return self.submit(prompt, max_new_tokens=max_new_tokens,
                           deadline=deadline, temperature=temperature,
                           top_k=top_k, top_p=top_p,
                           seed=seed).result(timeout)

    def submit_prefilled(self, payload: dict, *,
                         stream: Optional[GenerationStream] = None
                         ) -> GenerationStream:
        """Enqueue a request whose prompt a PREFILL-role engine already
        ran (decode-role engines only): ``payload`` is the handoff dict
        that engine's ``_handoff_cb`` produced — prompt, first token,
        post-prefill PRNG key, sampling params, the gathered KV block
        and the page manifest. Admission adopts the prompt pages into
        this engine's pool (shared prefixes dedup to one local copy),
        scatters the block, pushes the first token, and decodes on —
        the stream continues bit-identically to a monolithic engine's.

        ``payload["deadline"]`` is ABSOLUTE ``time.monotonic()`` time:
        meaningful same-process only, so a cross-process front door
        re-stamps it from its own clock before dispatching here. Pass
        ``stream`` to continue an existing consumer-facing stream (the
        front door's); omitted, a fresh one is returned."""
        if self.role != "decode":
            raise RuntimeError(
                "submit_prefilled() needs a role='decode' engine — "
                "monolithic engines prefill their own prompts")
        prompt = [int(t) for t in np.asarray(payload["prompt"]).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt in handoff payload")
        mnt = int(payload["max_new_tokens"])
        if mnt < 1:
            raise ValueError("handoff payload has no generation budget")
        need = self._pool.pages_for(min(len(prompt) + mnt - 1, self.max_len))
        if need > self.num_pages:
            raise ValueError(
                f"request needs {need} KV pages but the decode pool holds "
                f"{self.num_pages}; shrink the prompt/max_new_tokens or "
                f"grow num_pages")
        stream = stream or GenerationStream()
        deadline = payload.get("deadline")
        req = _GenRequest(prompt, mnt,
                          None if deadline is None else float(deadline),
                          stream,
                          temperature=float(payload.get("temperature", 0.0)),
                          top_k=int(payload.get("top_k", 0)),
                          top_p=float(payload.get("top_p", 1.0)),
                          handoff=payload,
                          priority=int(payload.get("priority", 0)))
        core = self._core
        with core.cond:
            if self._failed is not None:
                raise RuntimeError(
                    "generation engine stopped after a step failure"
                ) from self._failed
            if core.closed:
                raise RuntimeError("generation engine is closed")
            if len(core.pending) >= self.max_queue:
                self.metrics.record_rejected()
                raise Overloaded(len(core.pending), self.max_queue)
            core.pending.append(req)
            depth = len(core.pending)
            core.cond.notify_all()
        self.metrics.set_queue_depth(depth)
        return stream

    def _on_stall(self, err: StallError) -> None:
        """Watchdog callback (runs on the WATCHDOG thread): the loop is
        stuck inside a step past the deadline. Mark the engine failed so
        new submits are refused, and finish every pending/active stream
        with the diagnostic so their consumers unblock. Slot and page
        bookkeeping is deliberately NOT touched here — only the loop
        thread may mutate it, and it reconciles via ``_fail_streams``
        the moment the stuck step returns (see ``_engine_loop``)."""
        core = self._core
        with core.cond:
            if self._failed is not None:
                return
            self._failed = err
            reqs = list(core.pending)
            streams = [st.req.stream for st in core.active.values()]
            core.pending.clear()
            core.cond.notify_all()
        log.error("generation engine stalled: %s", err)
        for r in reqs:
            r.stream._finish(err)
        for s in streams:
            s._finish(err)

    # ------------------------------------------------- loop internals ----
    # Everything below here runs on the loop thread only (except warmup,
    # which the caller must run before traffic).

    def _step(self) -> None:
        """One scheduler iteration: admit pending prompts into free slots
        (paged: only while the pool can cover the head request's full
        reservation — FIFO, so page pressure delays rather than reorders),
        advance one prefill chunk per prefilling slot, then one decode
        step over every decoding slot. Each iteration lands one row in
        the step timeline (host vs device split) and the aggregate in
        the metrics' ``engine_steps`` block.

        With ``async_scheduling=True`` (and no speculative draft) the
        iteration runs :meth:`_step_async` instead: land step N,
        dispatch step N+1, then do the host work under the in-flight
        step — same stream bytes, same executables, one step of
        scheduling lag."""
        if self._async:
            return self._step_async()
        t_iter = time.monotonic()
        self._profile_tick()
        self._maybe_flush_prefix()
        if self._pending_offloads:
            # reap landed device->host offload copies between
            # iterations — a non-blocking poll; a copy still in flight
            # waits for the next iteration, never a decode step
            self._drain_offloads()
        decode_s = verify_s = 0.0
        core = self._core
        prefill_s = self._admit_and_prefill()
        with core.cond:
            active = sorted((s, st) for s, st in core.active.items()
                            if st.phase == "decode")
        if active:
            t0 = time.monotonic()
            if self.speculative:
                self._speculative_round(active)
                verify_s = time.monotonic() - t0
            else:
                self._decode_once(active)
                decode_s = time.monotonic() - t0
        with core.cond:
            depth = len(core.pending)
            n_active = len(core.active)
        device_s = prefill_s + decode_s + verify_s
        host_s = max(0.0, time.monotonic() - t_iter - device_s)
        self.timeline.record(
            host_s=host_s, prefill_s=prefill_s, decode_s=decode_s,
            verify_s=verify_s, active=n_active, queue_depth=depth,
            occupancy=n_active / self.max_slots,
            pages_in_use=self._pool.in_use if self.paged else 0)
        self.metrics.record_engine_step(host_s, device_s)

    def _maybe_flush_prefix(self) -> None:
        """Apply a pending ``reload()`` prefix flush on the loop thread
        (the only thread allowed to touch the pool)."""
        if self._prefix is not None and self._prefix_flush:
            # reload() ran on another thread: cached pages hold K/V the
            # OLD params wrote — drop them here before any admission
            # can probe
            self._prefix_flush = False
            self._prefix.clear()
            if self._dprefix is not None:
                self._dprefix.clear()
            if self._host is not None:
                # host entries are keyed by the OLD index version and
                # can never match again — drop them (and any copies
                # still in flight) so the tier gauge drains with the
                # device index
                self._pending_offloads.clear()
                self._host.clear()
            self._evict_stale = False
            self._report_pages()

    def _admit_and_prefill(self) -> float:
        """Admission + chunked-prefill pass shared by the sync and
        async iterations; returns the prefill wall share. In the async
        iteration this runs AFTER the next decode step was dispatched,
        i.e. inside the overlap window."""
        core = self._core
        while True:
            swap_head = None
            swap_need = 0
            with core.cond:
                if not core.pending or not core.free:
                    break
                take = 0
                if self.paged:
                    need_alloc, probes = self._admit_need(core.pending[0])
                    if not self._pool.can_reserve(need_alloc) and \
                            not self._evict_for(need_alloc, probes):
                        # page pressure: evict unreferenced cached
                        # prefixes (LRU) first; only when the cache
                        # cannot cover the shortfall does the FIFO
                        # head-of-line wait trigger — a delay, never a
                        # reorder, unless cache-aware admission is on
                        # and a LATER pending request fits as-is (then
                        # a bounded bypass keeps the pool busy while
                        # the head waits)
                        bypass = self._pick_bypass()
                        if bypass is None:
                            # last resort before the FIFO wait: a host-
                            # tier engine may swap OUT lower-priority
                            # active streams for the head (QoS, PR 18)
                            # — decided outside the lock below, then
                            # the head re-evaluates
                            swap_head = core.pending[0]
                            swap_need = need_alloc
                        else:
                            take = bypass
                if swap_head is None:
                    if take == 0:
                        self._head_bypasses = 0
                        req = core.pending.popleft()
                    else:
                        self._head_bypasses += 1
                        self.admission_bypasses += 1
                        req = core.pending[take]
                        del core.pending[take]
                    depth = len(core.pending)
            if swap_head is not None:
                if self._swap_out_for(swap_head, swap_need):
                    continue
                break
            self.metrics.set_queue_depth(depth)
            if req.handoff is not None:
                self._admit_prefilled(req)
            elif self.paged:
                self._admit_paged(req)
            else:
                self._admit(req)
        prefill_s = 0.0
        if self.paged:
            with core.cond:
                prefilling = sorted((s, st) for s, st in core.active.items()
                                    if st.phase == "prefill")
            if prefilling:
                t0 = time.monotonic()
                for slot, st in prefilling:
                    self._prefill_chunk_once(slot, st)
                prefill_s = time.monotonic() - t0
        return prefill_s

    def _step_async(self) -> None:
        """One ASYNC scheduler iteration (``async_scheduling=True``):

        1. LAND the in-flight step's token/key futures — the only
           device sync in the loop;
        2. DISPATCH the next decode step immediately, from the live
           step arrays (landed rows folded in, re-armed rows skipped),
           before ANY host bookkeeping runs;
        3. PROCESS the landed step under the in-flight one: token
           delivery, ITL, retirement — then admission, prefill chunks,
           and the KV-tier offload poll, all inside the overlap window.

        Scheduling decisions lag one step: a slot whose landed token
        hits EOS / max-tokens / the deadline already rides in the step
        dispatched at (2). Its extra token is discarded at the next
        land (the participant no longer maps to the same slot state),
        and its garbage K/V write goes to its own — by then possibly
        recycled — pages at a clamped position: device program order
        puts that write BEFORE any later owner's prefill, and causal
        masking hides whatever the prefill does not overwrite (the same
        recycled-page argument the sync engine already relies on).

        Stream bytes are identical to the sync path: decode is per-row
        independent (per-slot attention lanes, per-slot sampling keys),
        so rider rows and stale garbage rows cannot perturb a live
        row's token, and every dispatch input is a host numpy array
        exactly like the sync path's — same executable signature, so
        compile-once holds with zero new traces."""
        t_iter = time.monotonic()
        self._profile_tick()
        self._maybe_flush_prefix()
        core = self._core
        decode_s = 0.0
        ticket = self._inflight
        toks = None
        t_land_end = None
        if ticket is not None:
            self._inflight = None
            t0 = time.monotonic()
            toks = np.asarray(ticket.toks)
            keys = (np.asarray(ticket.keys) if ticket.keys is not None
                    else None)
            decode_s = time.monotonic() - t0
            t_land_end = time.monotonic()
            # fold the landed rows into the live dispatch arrays —
            # skipping rows armed since the ticket left: a slot retired
            # and re-admitted while its last step was still in flight
            # must keep its fresh arming, not the old ticket's output
            for slot, _st in ticket.parts:
                if slot in self._armed_dirty:
                    continue
                self._step_tokens[slot] = toks[slot]
                self._step_positions[slot] = ticket.positions[slot] + 1
                if keys is not None:
                    self._keys[slot] = keys[slot]
                # grammar (PR 20): the advance must land HERE, before
                # the next dispatch reads self._bias — the mask for
                # step N+1 reflects the token step N emitted. Verdicts
                # (stuck/violation) are recorded on the slot state and
                # surfaced by _process_landed below; the fold-in filter
                # (armed-dirty skip) matches _process_landed's identity
                # filter, so exactly the delivered slots advance.
                self._grammar_step(slot, _st, int(toks[slot]))
        # dispatch the next step BEFORE any host bookkeeping: from here
        # to the next land, the device and the host run concurrently
        with core.cond:
            active = sorted((s, st) for s, st in core.active.items()
                            if st.phase == "decode")
        step_gap_s = 0.0
        t_disp = None
        if active:
            t0 = time.monotonic()
            self._dispatch_decode(active)
            t_disp = time.monotonic()
            if t_land_end is not None:
                # host-side gap between landing step N and dispatching
                # step N+1 — a lower bound on device idle per step
                step_gap_s = t0 - t_land_end
        self._armed_dirty.clear()
        # ---- overlap window: everything below runs while the step
        # dispatched above is in flight on device ----
        if ticket is not None:
            self._process_landed(ticket, toks)
        if self._pending_offloads:
            # KV-tier poll (PR 18), relocated into the overlap window:
            # reap landed device->host offload copies while the decode
            # step runs instead of serializing before the next dispatch
            self._drain_offloads()
        prefill_s = self._admit_and_prefill()
        with core.cond:
            depth = len(core.pending)
            n_active = len(core.active)
        t_end = time.monotonic()
        overlapped_s = 0.0
        if t_disp is not None:
            # host share of the iteration spent under the in-flight
            # step (the prefill-chunk device waits are not host work)
            overlapped_s = max(0.0, t_end - t_disp - prefill_s)
            if self._inflight is not None:
                self._inflight.overlap_s = overlapped_s
        device_s = prefill_s + decode_s
        host_s = max(0.0, t_end - t_iter - device_s)
        self.timeline.record(
            host_s=host_s, prefill_s=prefill_s, decode_s=decode_s,
            step_gap_s=step_gap_s, host_overlapped_s=overlapped_s,
            active=n_active, queue_depth=depth,
            occupancy=n_active / self.max_slots,
            pages_in_use=self._pool.in_use if self.paged else 0)
        self.metrics.record_engine_step(host_s, device_s,
                                        overlapped=overlapped_s > 0)

    def _dispatch_decode(self, active: List[Tuple[int, _SlotState]]) -> None:
        """Launch one decode step without waiting for it (async path).
        Inputs are freshly built / copied host arrays — the device-side
        half of the double buffer: the engine may mutate the live
        arrays for step N+2 the moment this returns. Positions clamp at
        the lane end for rider rows (the speculative round's clamp
        precedent); a rider's write lands in its own lane/pages and is
        causally invisible to every later owner."""
        faults.fire("engine.decode", engine=self)
        tokens = np.zeros((self.max_slots,), np.int32)
        positions = np.zeros((self.max_slots,), np.int32)
        for slot, _st in active:
            tokens[slot] = self._step_tokens[slot]
            positions[slot] = min(int(self._step_positions[slot]),
                                  self.max_len - 1)
        if self.paged:
            toks_dev, keys_dev, self._cache = self.kernels.decode(
                self._params, self._cache, tokens, positions,
                self._page_map.copy(), self._temps.copy(),
                self._top_ks.copy(), self._top_ps.copy(),
                self._keys.copy(),
                bias=None if self._bias is None else self._bias.copy())
        else:
            toks_dev, self._cache = self.kernels.decode(
                self._params, self._cache, tokens, positions)
            keys_dev = None
        self._inflight = _StepTicket(list(active), positions, toks_dev,
                                     keys_dev)

    def _arm_async_slot(self, slot: int, st: _SlotState) -> None:
        """Arm a slot's live dispatch inputs (async path). Every site
        that hands a slot its first decodable token — dense admission,
        the final prefill chunk, a decode-role / swap-resume admission
        — writes the token and position HERE; the dispatch side reads
        only these rows, because the slot state itself is updated by
        the landing side one step late. Marking the row dirty keeps an
        in-flight ticket's land from folding stale output over a fresh
        arming (the slot retired and was re-admitted mid-flight)."""
        if not self._async:
            return
        self._step_tokens[slot] = st.last_token
        self._step_positions[slot] = st.position
        self._armed_dirty.add(slot)

    def _process_landed(self, ticket: _StepTicket,
                        toks: "np.ndarray") -> None:
        """Deliver a landed async step: push tokens, tick traces, record
        ITL, retire — the sync `_decode_once` tail, one step late.
        Participants whose slot no longer maps to the SAME state
        (retired rider, swapped-out victim, re-admitted slot) are
        skipped: their token is discarded, their stream untouched."""
        core = self._core
        with core.cond:
            live = [(slot, st) for slot, st in ticket.parts
                    if core.active.get(slot) is st]
        now = time.monotonic()
        self.metrics.record_decode_step(len(ticket.parts), self.max_slots)
        sampled = 0
        retired = []
        for slot, st in live:
            tok = int(toks[slot])
            st.last_token = tok
            st.position += 1
            st.generated += 1
            sampled += st.req.sampled
            tr = st.req.stream.trace
            if tr is not None:
                tr.tick("decode")
            if st.t_last:
                self.metrics.record_itl(now - st.t_last)
            st.t_last = now
            st.req.stream._push(tok, now)
            # the automaton already advanced in the fold-in (the mask
            # had to be live before the dispatch above) — only the
            # verdict is read here
            why = self._grammar_why(st, self._retire_why(st, st.req, now))
            if why is not None:
                retired.append((slot, st, why))
        if sampled:
            self.metrics.record_sampled(sampled)
        for slot, st, why in retired:
            self._release_slot(slot, st)
            self._finish_slot(st, why, now)

    def _profile_tick(self) -> None:
        """Opt-in ``jax.profiler`` bracket: with ``profile_dir`` set,
        start a device trace at the first scheduler iteration and stop
        it after ``profile_iters`` — the on-chip step breakdown the
        BENCH/MFU round reads. Never lets a profiler failure (no
        backend support, a second concurrent trace) break serving."""
        if self._profile_dir is None or self._profile_state == 2:
            return
        try:
            if self._profile_state == 0:
                jax.profiler.start_trace(self._profile_dir)
                self._profile_state = 1
                return
            self._profile_count += 1
            if self._profile_count >= self._profile_iters:
                jax.profiler.stop_trace()
                self._profile_state = 2
        except Exception:
            log.exception("engine profiler bracket failed; disabled")
            self._profile_state = 2

    def _report_pages(self) -> None:
        """Publish page occupancy plus the dtype-aware byte gauge (the
        same reserved pages, priced in the cache's ACTUAL dtype with
        scale pools included; a speculative engine prices target and
        draft lanes at their own models' per-page cost)."""
        self.metrics.set_pages(self._pool.in_use, self._pool.num_pages)
        if self._prefix is not None:
            self.metrics.set_shared_pages(
                self._prefix.pages
                + (self._dprefix.pages if self._dprefix is not None
                   else 0))
        if self._host is not None:
            self.metrics.set_host_pages(self._host.pages,
                                        self._host.bytes_used)
        if not self._kv_page_bytes:
            return
        if self.speculative:
            in_bytes = (self._pool.in_use_by("target")
                        * self._kv_page_bytes
                        + self._pool.in_use_by("draft")
                        * self._kv_dpage_bytes)
        else:
            in_bytes = self._pool.in_use * self._kv_page_bytes
        self.metrics.set_kv_cache(in_bytes, self.cache_dtype_name)

    def _pages_needed(self, req: _GenRequest) -> int:
        # PER-LANE pages: rows written = prompt + generated - 1 (the
        # final token is returned but never written back before the slot
        # retires). A speculative slot reserves this many for EACH of
        # its two lanes (`_lanes` — the draft writes the same positions).
        # A prefill-role slot writes prompt rows only — generation pages
        # are reserved by the adopting decode pool.
        if self.role == "prefill":
            return self._pool.pages_for(len(req.prompt))
        return self._pool.pages_for(
            min(len(req.prompt) + req.max_new_tokens - 1, self.max_len))

    # --------------------------------------------- prefix-cache hooks ----

    def _prefix_probe(self, req: _GenRequest):
        """Probe the per-lane prefix indexes for ``req``'s page-aligned
        prompt prefix. Returns ``(cached token count, [(pages, nodes)
        per lane])``; a speculative engine clamps to the COMMON hit
        depth of both lanes — the chunk skip is shared, so a page one
        lane lost to eviction forces the other to re-prefill it too.

        A pending reload flush (``_prefix_flush``) forces a MISS: the
        reload already swapped the params this request will decode
        with, so every cached entry is stale even though the loop has
        not cleared the index yet (that happens at the next ``_step``
        top — admissions run after that check, but reload can land
        between the check and this probe)."""
        if self._prefix_flush:
            empty = ([], [])
            return 0, [empty, empty] if self._dprefix is not None \
                else [empty]
        n_tok, pages, nodes = self._prefix.lookup(req.prompt)
        if self._dprefix is None:
            return n_tok, [(pages, nodes)]
        dn_tok, dpages, dnodes = self._dprefix.lookup(req.prompt)
        k = min(n_tok, dn_tok) // self.page_size
        return k * self.page_size, [(pages[:k], nodes[:k]),
                                    (dpages[:k], dnodes[:k])]

    def _admit_need(self, req: _GenRequest):
        """Pages the pool must ALLOCATE to admit ``req`` (cache-attached
        prefix pages are shared, not allocated), plus the probe result
        protecting the matched chains from eviction."""
        need = self._lanes * self._pages_needed(req)
        if self._prefix is None or req.handoff is not None:
            # handoff admissions never probe the prefix index (it lives
            # with the prefill role); adopt-side dedup may still make
            # some of `need` shares instead of allocs — gating on the
            # full count is the conservative bound
            return need, None
        cached_len, probes = self._prefix_probe(req)
        return need - self._lanes * (cached_len // self.page_size), probes

    def _evict_for(self, need_alloc: int, probes) -> bool:
        """Try to free enough cached pages for an admission short by
        ``need_alloc - free`` pages: LRU leaf eviction per lane, never
        touching the chains the admission itself matched. True when the
        pool can now cover the reservation."""
        if self._prefix is None or self._evict_stale:
            # a prior scan found nothing evictable and no release or
            # publish has happened since — the answer cannot have
            # changed, skip the index walk
            return False
        protect = set()
        for pr in probes or ():
            protect.update(pr[1])
        shortfall = need_alloc - self._pool.free_pages
        freed = 0
        # host tier (PR 18): target-lane victims offload instead of
        # vanishing — the hook dispatches each page's device gather
        # BEFORE evict() releases it (speculative engines never have a
        # host tier, so the draft lane below stays hook-less)
        on_evict = self._offload_page if self._host is not None else None
        for cache in (self._prefix, self._dprefix):
            if cache is None or shortfall <= freed:
                break
            freed += cache.evict(shortfall - freed, frozenset(protect),
                                 on_evict=on_evict)
            on_evict = None
        if freed == 0:
            self._evict_stale = True
        return self._pool.can_reserve(need_alloc)

    # ------------------------------------------------ host tier (PR 18) ----

    def _offload_page(self, prefix: Tuple[int, ...], page: int) -> None:
        """Prefix-eviction hook (``PrefixCache.evict`` ``on_evict``):
        gather the victim page into a fixed-shape device block — the
        SAME jitted gather the disaggregation handoff compiles, row 0
        real, the rest trash — and start its async device->host copy.
        Runs BEFORE evict() releases the page, so the pure-read gather
        can never race the page's next owner (donation waits on pending
        readers). Completion is polled between scheduler iterations
        (``_drain_offloads``); at most ``_offload_inflight_cap`` copies
        are ever in flight — past the cap the page just evicts (the
        pre-PR-18 behaviour), counted as a drop. Must not raise: the
        eviction proceeds regardless."""
        if len(self._pending_offloads) >= self._offload_inflight_cap:
            self._drain_offloads()   # non-blocking: reap what landed
        if len(self._pending_offloads) >= self._offload_inflight_cap:
            self._host.record_drop()
            self.metrics.record_offload_dropped()
            return
        try:
            faults.fire("kv.offload", engine=self, kind="prefix")
        except BaseException as exc:
            # fault-injected copy failure: the page evicts plainly
            # (never strands in either tier), only this entry is lost
            log.debug("kv.offload copy faulted; entry dropped: %s", exc)
            self._host.record_drop()
            self.metrics.record_offload_dropped()
            return
        idx = np.full((self._pool.pages_per_slot,), self._pool.trash,
                      np.int32)
        idx[0] = page
        block = self._mover.gather(self._cache, idx)
        jax.tree_util.tree_map(_start_host_copy, block)
        self._pending_offloads.append({
            "kind": "prefix", "key": tuple(prefix),
            "version": self._prefix.version, "block": block})

    def _drain_offloads(self, wait: bool = False) -> None:
        """Reap finished device->host offload copies, FIFO. Non-blocking
        by default (one poll per scheduler iteration — an unfinished
        copy waits, a decode step never does); ``wait=True`` blocks
        until everything lands (tests and drain paths only)."""
        host = self._host
        drained = False
        while self._pending_offloads:
            entry = self._pending_offloads[0]
            block = (entry["block"] if entry["kind"] == "prefix"
                     else entry["payload"]["block"])
            if not wait and not _block_ready(block):
                break
            self._pending_offloads.pop(0)
            drained = True
            if entry["kind"] == "prefix":
                if entry["version"] != self._prefix.version:
                    # a reload flush raced the copy: bytes the OLD
                    # params wrote must not enter the host index
                    host.record_drop()
                    self.metrics.record_offload_dropped()
                    continue
                rows = jax.tree_util.tree_map(
                    lambda leaf: np.asarray(leaf[0]), entry["block"])
                host.put_prefix(entry["version"], entry["key"], rows)
                self.metrics.record_offload(1)
            else:
                # swap payload: the block's device buffers release once
                # the rows live host-side (the payload itself already
                # rides the re-queued resume request; device_put at
                # adoption uploads np leaves identically)
                payload = entry["payload"]
                payload["block"] = jax.tree_util.tree_map(
                    lambda leaf: np.asarray(leaf), payload["block"])
        if drained:
            self._report_pages()

    def _restore_prefix(self, req: _GenRequest, cached_len: int
                        ) -> Tuple[List[int], int]:
        """Extend a device prefix hit from the HOST tier: consecutive
        page-aligned chunks past the device hit whose bytes were
        offloaded come back host->device — fresh pages allocate, ONE
        batched scatter (the warmed executable) writes them, and the
        chunks REPUBLISH into the device index, so the attach in
        ``_admit_paged`` sees them exactly as never-evicted entries
        (the copy is a memcpy both ways — bit-identity is free, int8
        scale pools ride as ordinary leaves). Returns ``(restored
        pages, new cached_len)``; the restored pages replace tail
        allocations one for one, so the admission gate's reservation
        arithmetic is unchanged. An injected ``kv.restore`` fault
        degrades the affected entries to a plain miss (they leave the
        host store; the request re-prefills; the stream is unharmed)."""
        host = self._host
        ps = self.page_size
        prompt = req.prompt
        version = self._prefix.version
        start_k = cached_len // ps
        hits: List[Tuple[int, ...]] = []
        for k in range(start_k, (len(prompt) - 1) // ps):
            key = tuple(prompt[:(k + 1) * ps])
            if not host.has_prefix(version, key):
                break
            hits.append(key)
        if not hits:
            return [], cached_len
        try:
            faults.fire("kv.restore", engine=self, kind="prefix")
        except BaseException:
            for key in hits:
                host.drop_prefix(version, key)
            self._report_pages()
            return [], cached_len
        pages = self._pool.alloc(len(hits), owner="target")
        ppn = self._pool.pages_per_slot
        idx = np.full((ppn,), self._pool.trash, np.int32)
        idx[:len(pages)] = pages
        rows = [host.take_prefix(version, key) for key in hits]

        def _fill(leaf, *page_rows):
            out = np.zeros((ppn,) + leaf.shape[1:], leaf.dtype)
            for i, r in enumerate(page_rows):
                out[i] = r
            return out

        block = jax.tree_util.tree_map(_fill, self._cache, *rows)
        if self._cache_sharding is not None:
            block = jax.device_put(
                block, _cache_sharding_tree(block, self._cache_sharding))
        else:
            block = jax.device_put(block)
        self._cache = self._mover.scatter(self._cache, block, idx)
        # republish: the restored chunks re-enter the device index with
        # their own cache references (request ref + cache ref, the
        # never-evicted end state). Rows before start_k descend the
        # live device chain — publish only reads the row for NEW nodes.
        end = (start_k + len(hits)) * ps
        pub_row = np.full((ppn,), self._pool.trash, np.int32)
        pub_row[start_k:start_k + len(pages)] = pages
        self._prefix.publish(prompt[:end], pub_row)
        self._evict_stale = False
        self.metrics.record_restore(len(pages))
        self._report_pages()
        return pages, end

    def _swap_out_for(self, head: _GenRequest, need_alloc: int) -> bool:
        """QoS swap (PR 18): the FIFO head is page-blocked and neither
        eviction nor bypass helped — swap OUT lowest-priority, longest-
        idle active decode streams (pages + PRNG key + position through
        the host tier; the stream parks on a re-queued resume request)
        until the head's reservation fits. Only STRICTLY lower priority
        yields, so a swap chain terminates and equal-priority traffic
        never thrashes. False leaves the plain FIFO wait in place."""
        if self._host is None or self.role != "both":
            return False
        core = self._core
        swapped = False
        while not self._pool.can_reserve(need_alloc):
            with core.cond:
                victims = [
                    (st.req.priority, st.t_last, slot, st)
                    for slot, st in core.active.items()
                    if st.phase == "decode" and st.pages
                    and st.req.priority < head.priority
                    and st.req.grammar is None
                    # constrained streams never swap: the resume payload
                    # carries no automaton state, and replaying the
                    # advance through the host tier buys nothing — the
                    # head waits for a different victim instead
                    and st.generated < st.req.max_new_tokens
                    and st.position < self.max_len]
            if not victims:
                return swapped and self._pool.can_reserve(need_alloc)
            victims.sort(key=lambda v: (v[0], v[1]))
            _, _, slot, st = victims[0]
            if not self._swap_out_slot(slot, st):
                return False
            swapped = True
        return True

    def _swap_out_slot(self, slot: int, st: _SlotState) -> bool:
        """Export one active decode stream to the host tier: gather its
        whole lane (the handoff gather), start the async host copy,
        export the pages, park the slot, and re-queue a resume request
        carrying the handoff-shaped payload — adoption replays it
        byte-exactly (the PRNG key splits once per emitted token while
        resident, so park/resume never skews a sampled stream). A
        faulted swap-out aborts BEFORE anything moves: the victim stays
        resident with all its pages."""
        try:
            faults.fire("kv.offload", engine=self, kind="swap")
        except BaseException:
            self._host.record_drop()
            self.metrics.record_offload_dropped()
            return False
        req = st.req
        self._swap_seq += 1
        swap_id = self._swap_seq
        ps = self.page_size
        plen = len(req.prompt)
        meta = np.asarray(
            [(int(p), self._pool.generation(p), int((i + 1) * ps <= plen))
             for i, p in enumerate(st.pages)], np.int64).reshape(-1, 3)
        block = self._mover.gather(self._cache, st.page_row)
        jax.tree_util.tree_map(_start_host_copy, block)
        payload = {
            "prompt": np.asarray(req.prompt, np.int32),
            "first_token": int(st.last_token),
            "key": self._keys[slot].copy(),
            "plen": plen,
            "max_new_tokens": int(req.max_new_tokens),
            "temperature": float(req.temperature),
            "top_k": int(req.top_k),
            "top_p": float(req.top_p),
            "deadline": req.deadline,
            "page_row": st.page_row.copy(),
            "page_meta": meta,
            "source": self.handoff_source,
            "tag": req.tag,
            "block": block,
            "swap": True,
            "swap_id": swap_id,
            "position": int(st.position),
            "generated": int(st.generated),
            "priority": int(req.priority),
            "t_admit": float(st.t_admit),
        }
        self._pending_offloads.append({"kind": "swap", "payload": payload})
        core = self._core
        with core.cond:
            core.active.pop(slot, None)
            core.free.append(slot)
        # the request's references leave through handoff accounting: the
        # gather above captured the bytes (a pure read the pages' next
        # owner must wait on), the ids free for the head. No publish —
        # nothing may newly enter the device index off a parked stream.
        self._pool.export_pages(st.pages or ())
        st.pages = None
        self._page_map[slot] = self._pool.trash
        self._temps[slot] = 0.0
        self._top_ks[slot] = 0
        self._top_ps[slot] = 1.0
        self._keys[slot] = 0
        if self._bias is not None:
            self._bias[slot] = 0.0
        self._evict_stale = False
        self._host.park_stream(swap_id, len(meta))
        self.metrics.record_swap_out()
        resume = _GenRequest(req.prompt, req.max_new_tokens, req.deadline,
                             req.stream, temperature=req.temperature,
                             top_k=req.top_k, top_p=req.top_p,
                             seed=req.seed, tag=req.tag, handoff=payload,
                             priority=req.priority)
        with core.cond:
            # FIFO tail: the resumed stream waits its turn like any
            # arrival — fairness under repeated pressure is bounded by
            # the strict-priority rule, not by queue position
            core.pending.append(resume)
        self._report_pages()
        return True

    def _chunk_invocations(self, n_tokens: int) -> int:
        """Kernel invocations (non-final chunks + the final prefill) a
        prompt tail of ``n_tokens`` costs — the unit the
        ``prefill_chunks_skipped`` saving is counted in."""
        if n_tokens <= 0:
            return 0
        return (n_tokens - 1) // self.prefill_chunk + 1

    def _request_key(self, req: _GenRequest) -> np.ndarray:
        seed = req.seed
        if seed is None:
            seed = request_seed(
                self.seed, np.asarray(req.prompt, np.int32).tobytes(),
                len(req.prompt))
        return threefry_key_data(seed)

    def _admit_paged(self, req: _GenRequest) -> None:
        """Paged admission is bookkeeping only: reserve the slot and its
        full page budget. The prompt itself runs as chunks inside the
        iteration loop so a long prompt interleaves with neighbours'
        decode steps.

        CRITICAL ordering: the slot's row of ``self._page_map`` stays
        parked on the trash page (and its sampling params/key stay
        disarmed) until the FINAL chunk completes — interleaved decode
        steps scatter a pad-token K/V row for every slot in the batch,
        prefilling ones included, and split every slot's PRNG key. Expose
        the real pages or the request key early and those decode steps
        would corrupt the prompt's first page and make the sampled
        stream depend on neighbour traffic. The chunk/prefill kernels
        take the page row as an explicit argument instead."""
        now = time.monotonic()
        why = self._retire_why(None, req, now)
        if why is not None:
            self._finish_request(req, why, now, queue_wait=None)
            return
        if req.grammar is not None:
            self.metrics.record_constrained_stream()
        core = self._core
        with core.cond:
            core.free.sort()
            slot = core.free.pop(0)
        need = self._pages_needed(req)
        tr = req.stream.trace
        reserve_sp = None
        if tr is not None:
            tr.span("queue_wait", tr.t0)
            reserve_sp = tr.begin_span("page_reserve")
        # prefix-cache probe: hit pages attach by REFERENCE (share) and
        # their tokens never re-prefill; only the divergent tail and the
        # generation budget allocate fresh pages. The attach is what
        # copy-on-write protects — and because hits are page-ALIGNED and
        # always leave >= 1 tail token, every write the request will
        # ever issue (tail chunks, decode rows) lands at positions past
        # the attached prefix, in pages it allocated itself: CoW
        # reduces to the alignment assertion below.
        cached_len = 0
        hit_k = 0
        shared_pages: List[int] = []
        dshared_pages: List[int] = []
        restored: List[int] = []
        if self._prefix is not None:
            cached_len, probes = self._prefix_probe(req)
            assert cached_len % self.page_size == 0 \
                and cached_len < len(req.prompt), \
                "prefix attach must be page-aligned with a live tail"
            hit_k = cached_len // self.page_size
            if hit_k:
                shared_pages = list(probes[0][0])
                self._pool.share(shared_pages)
                if self._dprefix is not None:
                    dshared_pages = list(probes[1][0])
                    self._pool.share(dshared_pages)
            if self._host is not None and not self._prefix_flush:
                # host tier (PR 18): chains the device index evicted may
                # live one tier down — restored pages slot in right
                # after the device hit and count as cached from here on
                restored, cached_len = self._restore_prefix(req,
                                                            cached_len)
                hit_k = cached_len // self.page_size
            skipped = (self._chunk_invocations(len(req.prompt))
                       - self._chunk_invocations(len(req.prompt)
                                                 - cached_len))
            self._prefix.record_probe(hit_k > 0, cached_len)
            if self._dprefix is not None:
                self._dprefix.record_probe(hit_k > 0, cached_len)
            self.metrics.record_prefix_probe(hit_k > 0,
                                             skipped * self._lanes)
        pages = shared_pages + restored + self._pool.alloc(
            need - hit_k, owner="target")
        row = np.full((self._pool.pages_per_slot,), self._pool.trash,
                      np.int32)
        row[:len(pages)] = pages
        draft_pages = None
        drow = None
        if self.speculative:
            # the draft lane reserves the same row budget side by side
            # (one pool, owner-tagged so the drain invariants are
            # assertable per lane)
            draft_pages = dshared_pages + self._pool.alloc(
                need - hit_k, owner="draft")
            drow = np.full((self._pool.pages_per_slot,), self._pool.trash,
                           np.int32)
            drow[:len(draft_pages)] = draft_pages
        if tr is not None:
            tr.end_span(reserve_sp, pages=need * self._lanes, slot=slot,
                        prefix_pages=hit_k * self._lanes)
        st = _SlotState(req, self.pad_id, cached_len, 0, now,
                        phase="prefill", pages=pages, page_row=row,
                        prefill_pos=cached_len, draft_pages=draft_pages,
                        dpage_row=drow)
        if self._prefix is not None:
            # stamp the index version the prompt is prefilled under:
            # a retirement after a reload flush (version bumped) must
            # NOT publish its old-params pages into the fresh index.
            # One stamp covers both lanes — they flush in lockstep.
            st.cache_version = self._prefix.version
        with core.cond:
            core.active[slot] = st
        self._report_pages()
        if self._prefix is not None:
            # fault site: an armed exception lands between the prefix
            # attach (references taken) and the first prefill/decode
            # step — the loop's failure path must release every
            # refcount and leak zero shared pages (chaos-gated)
            faults.fire("engine.prefix_attach", engine=self)

    def _admit_prefilled(self, req: _GenRequest) -> None:
        """Decode-role admission: the prompt's KV rows arrive as a
        gathered device block plus a page manifest instead of running
        prefill here. Adopt the pages (shared prefixes dedup to one
        local copy), scatter the block into this pool's cache, arm the
        slot exactly as a monolithic final chunk would — same last
        token, position, sampling params and post-prefill PRNG key, so
        the decode continuation is bit-identical — and push the first
        token. A failure between adopt and scatter is REQUEST-scoped:
        the cache is untouched until the scatter lands, so only this
        stream fails and its pages release; the engine keeps serving."""
        payload = req.handoff
        swap = bool(payload.get("swap"))
        if swap:
            # the parked booking ends the moment the resume admission
            # runs, whatever its outcome — expiry, cancellation, an
            # injected fault, or a clean adoption; the payload is the
            # only thing that survives a failed resume, and it dies
            # with the request
            self._host.unpark_stream(int(payload["swap_id"]))
            self.metrics.record_swap_in()
            self._report_pages()
        now = time.monotonic()
        why = self._retire_why(None, req, now)
        if why is not None:
            self._finish_request(req, why, now, queue_wait=None)
            return
        core = self._core
        with core.cond:
            core.free.sort()
            slot = core.free.pop(0)
        meta = np.asarray(payload["page_meta"]).reshape(-1, 3)
        need = self._pages_needed(req)
        k_p = len(meta)
        pages: List[int] = []
        try:
            if swap:
                # fault site: before a parked stream's resume adoption —
                # an injected fault fails ONLY this stream (the except
                # below releases its pages); the engine keeps serving
                faults.fire("kv.restore", engine=self, kind="swap")
            # fault site: between the prefill engine's export and this
            # pool's adopt — the chaos gate proves a mid-handoff fault
            # drains BOTH pools' per-owner gauges to zero
            faults.fire("engine.page_handoff", engine=self, stage="adopt")
            pages = self._pool.adopt_pages(
                [(int(m[0]), int(m[1]), bool(m[2])) for m in meta],
                source=str(payload["source"]), owner="target")
            pages = pages + self._pool.alloc(need - k_p, owner="target")
            row = np.full((self._pool.pages_per_slot,), self._pool.trash,
                          np.int32)
            row[:len(pages)] = pages
            idx = np.full((self._pool.pages_per_slot,), self._pool.trash,
                          np.int32)
            idx[:k_p] = pages[:k_p]
            # identity for committed arrays (the local gather's output,
            # wherever it is sharded), an upload for the RPC path's np
            # leaves — both land as ONE committed executable signature
            block = jax.device_put(payload["block"])
            self._cache = self._mover.scatter(self._cache, block, idx)
        except BaseException as e:
            self._pool.release(pages)
            with core.cond:
                core.free.append(slot)
            self._report_pages()
            self.metrics.record_failed()
            req.stream._finish(e, time.monotonic())
            return
        self._temps[slot] = req.temperature
        self._top_ks[slot] = req.top_k
        self._top_ps[slot] = req.top_p
        self._keys[slot] = np.asarray(payload["key"], np.uint32)
        self._page_map[slot] = row
        tok = int(payload["first_token"])
        now = time.monotonic()
        if swap:
            # a resumed stream continues MID-generation: position,
            # progress and the queue-wait base restore from the payload,
            # and the consumer already holds every pushed token — push
            # nothing, decode on from the parked key (which split once
            # per emitted token while resident: byte-exact resume)
            st = _SlotState(req, tok, int(payload["position"]),
                            int(payload["generated"]),
                            float(payload["t_admit"]), phase="decode",
                            pages=pages, page_row=row)
        else:
            st = _SlotState(req, tok, len(req.prompt), 1, now,
                            phase="decode", pages=pages, page_row=row)
        st.t_last = now
        with core.cond:
            core.active[slot] = st
        self._arm_async_slot(slot, st)
        self._report_pages()
        if not swap:
            req.stream._push(tok, now)
        why = self._retire_why(st, req, now)
        if why is not None:
            self._release_slot(slot, st)
            self._finish_slot(st, why, now)

    def _handoff_payload(self, slot: int, st: _SlotState,
                         tok: int) -> dict:
        """Everything a decode-role engine needs to continue ``st``'s
        stream bit-identically: the first token, the POST-prefill PRNG
        key (sampled token i draws from split i whatever engine holds
        the slot), sampling params, and the page manifest —
        ``(page id, write generation, shareable)`` rows naming each
        prompt page's content under this engine's ``handoff_source``
        namespace (full prompt pages are shareable; the partial tail
        page keeps taking decode writes and always fresh-copies). The
        KV block itself is gathered by the handoff callback while the
        pages are still owned. np-typed throughout so the payload
        crosses rpc.py npy frames unchanged."""
        req = st.req
        ps = self.page_size
        plen = len(req.prompt)
        meta = np.asarray(
            [(int(p), self._pool.generation(p), int((i + 1) * ps <= plen))
             for i, p in enumerate(st.pages)], np.int64).reshape(-1, 3)
        return {
            "prompt": np.asarray(req.prompt, np.int32),
            "first_token": int(tok),
            "key": self._keys[slot].copy(),
            "plen": plen,
            "max_new_tokens": int(req.max_new_tokens),
            "temperature": float(req.temperature),
            "top_k": int(req.top_k),
            "top_p": float(req.top_p),
            "deadline": req.deadline,
            "page_row": st.page_row.copy(),
            "page_meta": meta,
            "source": self.handoff_source,
            "tag": req.tag,
            "priority": int(req.priority),
        }

    def _handoff_slot(self, slot: int, st: _SlotState) -> None:
        """Retire a prefill-role slot whose pages were handed off:
        publish the full prompt pages to the prefix index (it lives with
        THIS role — the next same-prefix prompt attaches by reference
        and skips its covered chunks), then export the request's
        references and free the slot. Mirrors ``_release_slot`` except
        the pages leave through ``export_pages`` accounting."""
        core = self._core
        with core.cond:
            core.active.pop(slot, None)
            core.free.append(slot)
        if (self._prefix is not None and st.pages
                and st.cache_version == self._prefix.version):
            self._prefix.publish(st.req.prompt, st.page_row)
            self._evict_stale = False
            self._dedup_after_publish()
        self._pool.export_pages(st.pages or ())
        st.pages = None
        self._page_map[slot] = self._pool.trash
        self._temps[slot] = 0.0
        self._top_ks[slot] = 0
        self._top_ps[slot] = 1.0
        self._keys[slot] = 0
        self._evict_stale = False
        self._report_pages()

    def _abort_handoff(self, slot: int, st: _SlotState,
                       err: BaseException) -> None:
        """A handoff failed before its pages left this pool: release
        them (no publish — the stream is failing, nothing should newly
        enter the index off its back), free the slot, fail the stream
        with the error. REQUEST-scoped on purpose: the gather is a pure
        read, the cache was never touched, so the engine keeps serving
        its other slots."""
        core = self._core
        with core.cond:
            core.active.pop(slot, None)
            core.free.append(slot)
        self._pool.release(st.pages or ())
        st.pages = None
        self._page_map[slot] = self._pool.trash
        self._temps[slot] = 0.0
        self._top_ks[slot] = 0
        self._top_ps[slot] = 1.0
        self._keys[slot] = 0
        self._evict_stale = False
        self._report_pages()
        self.metrics.record_failed()
        now = time.monotonic()
        st.req.stream._finish(err, now)
        tr = st.req.stream.trace
        if tr is not None:
            tr.finish(outcome="failed", tokens=st.generated)

    def _prefill_chunk_once(self, slot: int, st: _SlotState) -> None:
        """Advance one prompt chunk for a prefilling slot. Non-final
        chunks are always exactly ``prefill_chunk`` tokens (one compiled
        shape); the final chunk is bucket-padded and samples the first
        generated token."""
        req = st.req
        now = time.monotonic()
        why = self._retire_why(None, req, now)
        if why is not None:
            self._release_slot(slot, st)
            self._finish_slot(st, why, now)
            return
        faults.fire("engine.prefill", engine=self)
        prompt = req.prompt
        start = st.prefill_pos
        remaining = len(prompt) - start
        pages_row = st.page_row  # NOT self._page_map: see _admit_paged
        tr = req.stream.trace
        if remaining > self.prefill_chunk:
            sp = (tr.begin_span("prefill_chunk") if tr is not None
                  else None)
            tokens = np.asarray(prompt[start:start + self.prefill_chunk],
                                np.int32)
            self._cache = self.kernels.chunk(
                self._params, self._cache, pages_row, tokens, start,
                self.prefill_chunk, self._pool.trash)
            if self.speculative:
                # the draft needs the prompt in its own cache before it
                # can propose: same chunk, draft lane
                self._dcache = self.kernels.draft_write(
                    self._draft_params, self._dcache, st.dpage_row,
                    tokens, start, self.prefill_chunk, self._pool.trash)
            st.prefill_pos += self.prefill_chunk
            st.position = st.prefill_pos
            self.metrics.record_chunk(self.prefill_chunk, self.prefill_chunk)
            if tr is not None:
                tr.end_span(sp, tokens=self.prefill_chunk, final=False)
            return
        final_sp = tr.begin_span("prefill_chunk") if tr is not None else None
        bucket = next(b for b in self.prompt_buckets if b >= remaining)
        padded = np.full((bucket,), self.pad_id, np.int32)
        padded[:remaining] = prompt[start:]
        # the final chunk arms the slot's step inputs: sampling params,
        # the request's PRNG key (fresh HERE, so token i always draws
        # from split i whatever decode traffic ran during the prefill),
        # the grammar start-state mask row (a stale async fold-in may
        # have scribbled a retired owner's row — reset then arm), and —
        # after the K/V writes land — the live page-map row
        self._temps[slot] = req.temperature
        self._top_ks[slot] = req.top_k
        self._top_ps[slot] = req.top_p
        if self._bias is not None:
            self._bias[slot] = 0.0
            self._grammar_arm(slot, st)
        bias1 = (None if self._bias is None
                 else self._bias[slot:slot + 1].copy())
        if self.speculative:
            # speculative sampling is keyed by (request, output
            # position), never by step — `_keys[slot]` holds the CONSTANT
            # request key and the kernels fold positions in
            key = self._request_key(req)
            tok_dev, self._cache = self.kernels.prefill(
                self._params, self._cache, pages_row, padded, start,
                remaining, self._pool.trash, self._temps[slot],
                self._top_ks[slot], self._top_ps[slot], key, bias=bias1)
            self._dcache = self.kernels.draft_write(
                self._draft_params, self._dcache, st.dpage_row, padded,
                start, remaining, self._pool.trash)
            self._keys[slot] = key
            self._dpage_map[slot] = st.dpage_row
        else:
            tok_dev, key_dev, self._cache = self.kernels.prefill(
                self._params, self._cache, pages_row, padded, start,
                remaining, self._pool.trash, self._temps[slot],
                self._top_ks[slot], self._top_ps[slot],
                self._request_key(req), bias=bias1)
            self._keys[slot] = np.asarray(key_dev)[0]
        tok = int(np.asarray(tok_dev))
        self._page_map[slot] = pages_row
        now = time.monotonic()
        self.metrics.record_prefill(remaining, bucket,
                                    now - req.stream.t_submit)
        if req.sampled:
            self.metrics.record_sampled(1)
        if tr is not None:
            tr.end_span(final_sp, tokens=remaining, final=True)
            tr.event("first_token")
        req.stream._push(tok, now)
        st.phase = "decode"
        st.last_token = tok
        st.position = len(prompt)
        st.generated = 1
        st.t_last = now
        self._grammar_step(slot, st, tok)
        self._arm_async_slot(slot, st)
        why = self._grammar_why(st, self._retire_why(st, req, now))
        if why is not None:
            self._release_slot(slot, st)
            self._finish_slot(st, why, now)
            return
        if self.role == "prefill":
            # the whole prompt is written (phase just flipped) and the
            # request still wants tokens: hand the finished pages to the
            # decode role instead of decoding here. The callback gathers
            # the block from this cache ON THIS THREAD while the pages
            # are still owned, then routes it; only after it returns do
            # the pages export and the slot free. A fault or callback
            # failure is request-scoped — pages release, stream fails,
            # the engine keeps prefilling its other slots.
            try:
                faults.fire("engine.page_handoff", engine=self,
                            stage="export")
                cb = self._handoff_cb
                if cb is None:
                    raise RuntimeError(
                        "prefill-role engine has no handoff consumer "
                        "(set by DisaggregatedEngine / PrefillWorker)")
                cb(self._handoff_payload(slot, st, tok))
            except BaseException as e:
                self._abort_handoff(slot, st, e)
                return
            self._handoff_slot(slot, st)
            self._finish_slot(st, "done", now)

    def _release_slot(self, slot: int, st: _SlotState) -> None:
        """Return a slot (and, paged, its pages + step-input rows) to the
        free state. The page-map row parks on the trash page so the
        still-running decode step can neither read nor clobber a page the
        next owner gets."""
        core = self._core
        with core.cond:
            core.active.pop(slot, None)
            core.free.append(slot)
        if self.paged:
            if (self._prefix is not None and st.pages
                    and st.phase == "decode"
                    and st.cache_version == self._prefix.version):
                # publish the sequence's FULL prompt pages back to the
                # index (phase=="decode" means the whole prompt is
                # written; a mid-prefill retirement has nothing whole
                # to share). New nodes take their own pool references
                # BEFORE the request's are dropped below, so the pages
                # never graze the free heap in between. The version
                # check drops retirements that straddled a reload
                # flush: their pages hold K/V the OLD params wrote and
                # must never re-enter the fresh index.
                self._prefix.publish(st.req.prompt, st.page_row)
                if self._dprefix is not None:
                    self._dprefix.publish(st.req.prompt, st.dpage_row)
                self._evict_stale = False
                # publish-time dedup (PR 14): concurrent same-prefix
                # prefills that all missed the index each wrote their
                # own physical copies of these now-canonical pages —
                # repoint still-active duplicates and free the copies
                self._dedup_after_publish()
            self._pool.release(st.pages or ())
            st.pages = None
            self._page_map[slot] = self._pool.trash
            if self.speculative:
                self._pool.release(st.draft_pages or ())
                st.draft_pages = None
                self._dpage_map[slot] = self._pool.trash
            self._temps[slot] = 0.0
            self._top_ks[slot] = 0
            self._top_ps[slot] = 1.0
            self._keys[slot] = 0
            if self._bias is not None:
                self._bias[slot] = 0.0  # unconstrained no-op row
            st.grammar_state = None
            self._evict_stale = False   # released pages: re-scan is live
            self._report_pages()

    def _dedup_after_publish(self) -> None:
        """Repoint every still-active decode slot whose full prompt
        pages now have canonical cached twins (same chunk chain in the
        index, different physical page) at the cached pages, releasing
        its private duplicates. Bit-identity is free: a FULL prompt
        page is a pure function of ``(params, its page-aligned token
        prefix)``, and a decode slot only ever writes at positions
        ``>= len(prompt)`` — pages past index ``len(prompt) //
        page_size``, never the repointed ones. Loop-thread only, like
        every pool/index mutation."""
        core = self._core
        with core.cond:
            slots = [(s, st) for s, st in core.active.items()
                     if st.phase == "decode" and st.pages]
        for slot, st in slots:
            if st.cache_version != self._prefix.version:
                continue
            n_full = len(st.req.prompt) // self.page_size
            if not n_full:
                continue
            canon = self._prefix.match_pages(st.req.prompt, n_full)
            self._dedup_row(self._prefix, st.pages, st.page_row,
                            self._page_map[slot], canon)
            if self._dprefix is not None and st.draft_pages:
                dcanon = self._dprefix.match_pages(st.req.prompt, n_full)
                self._dedup_row(self._dprefix, st.draft_pages,
                                st.dpage_row, self._dpage_map[slot],
                                dcanon)

    def _dedup_row(self, cache: PrefixCache, pages: List[int], row,
                   map_row, canon: List[int]) -> None:
        swapped = 0
        for i, page in enumerate(canon):
            if i >= len(pages) or pages[i] == page:
                continue
            # order matters: take the cached page's reference BEFORE
            # dropping the duplicate's, the same never-graze-the-free-
            # heap discipline as publish/attach. BOTH rows must repoint:
            # st.page_row feeds publish at retirement, but the decode
            # kernels read the engine's live _page_map row (a separate
            # array — _admit_paged copies values in), and a decoding
            # slot left reading the released duplicate would see the
            # page's NEXT owner overwrite it
            self._pool.share([page])
            self._pool.release([pages[i]])
            pages[i] = page
            row[i] = page
            map_row[i] = page
            swapped += 1
        if swapped:
            cache.deduped_pages += swapped
            self._evict_stale = False  # freed pages: re-scan is live

    def _pick_bypass(self) -> Optional[int]:
        """Cache-aware admission (PR 14): index into ``core.pending``
        of a later request to admit while the page-blocked FIFO head
        waits, or ``None`` (strict FIFO wait). Caller holds the core
        lock. A candidate must fit the pool AS-IS — no eviction runs
        on its behalf, freed pages belong to the head. Among fitting
        candidates the longest resident prefix wins (it allocates the
        fewest fresh pages and strictly extends the pool's runway);
        FIFO position breaks ties. At most ``_bypass_limit``
        consecutive bypasses per blocked head, so the head's wait is
        bounded by construction."""
        if (not self.cache_aware_admission
                or self._head_bypasses >= self._bypass_limit):
            return None
        best: Optional[Tuple[int, int]] = None   # (cached_len, index)
        pending = self._core.pending
        for j in range(1, len(pending)):
            need, _ = self._admit_need(pending[j])
            if not self._pool.can_reserve(need):
                continue
            cached = 0
            if self._prefix is not None:
                cached, _ = self._prefix_probe(pending[j])
            if best is None or cached > best[0]:
                best = (cached, j)
        return None if best is None else best[1]

    def _admit(self, req: _GenRequest) -> None:
        now = time.monotonic()
        why = self._retire_why(None, req, now)
        if why is not None:
            self._finish_request(req, why, now, queue_wait=None)
            return
        faults.fire("engine.prefill", engine=self)
        core = self._core
        with core.cond:
            core.free.sort()
            slot = core.free.pop(0)
        tr = req.stream.trace
        sp = None
        if tr is not None:
            tr.span("queue_wait", tr.t0)
            sp = tr.begin_span("prefill_chunk", slot=slot)
        n = len(req.prompt)
        bucket = next(b for b in self.prompt_buckets if b >= n)
        padded = np.full((bucket,), self.pad_id, np.int32)
        padded[:n] = req.prompt
        tok_dev, self._cache = self.kernels.prefill(
            self._params, self._cache, slot, padded, n)
        tok = int(np.asarray(tok_dev))
        now = time.monotonic()
        self.metrics.record_prefill(n, bucket, now - req.stream.t_submit)
        if tr is not None:
            tr.end_span(sp, tokens=n, final=True)
            tr.event("first_token")
        req.stream._push(tok, now)
        st = _SlotState(req, tok, n, 1, now)
        st.t_last = now
        why = self._retire_why(st, req, now)
        if why is None:
            with core.cond:
                core.active[slot] = st
            self._arm_async_slot(slot, st)
        else:
            with core.cond:
                core.free.append(slot)
            self._finish_slot(st, why, now)

    def _decode_once(self, active: List[Tuple[int, _SlotState]]) -> None:
        # fault site: an armed exception is exactly a kernel/step failure
        # (the loop fails every stream and stops); armed latency models a
        # slow or wedged device for the stall watchdog
        faults.fire("engine.decode", engine=self)
        tokens = np.zeros((self.max_slots,), np.int32)
        positions = np.zeros((self.max_slots,), np.int32)
        for slot, st in active:
            tokens[slot] = st.last_token
            positions[slot] = st.position
        if self.paged:
            toks_dev, keys_dev, self._cache = self.kernels.decode(
                self._params, self._cache, tokens, positions,
                self._page_map, self._temps, self._top_ks, self._top_ps,
                self._keys, bias=self._bias)
            self._keys = np.array(keys_dev)  # writable copy (host-mutated)
        else:
            toks_dev, self._cache = self.kernels.decode(
                self._params, self._cache, tokens, positions)
        toks = np.asarray(toks_dev)
        now = time.monotonic()
        self.metrics.record_decode_step(len(active), self.max_slots)
        sampled = 0
        retired = []
        for slot, st in active:
            tok = int(toks[slot])
            st.last_token = tok
            st.position += 1
            st.generated += 1
            sampled += st.req.sampled
            tr = st.req.stream.trace
            if tr is not None:
                tr.tick("decode")
            if st.t_last:
                # gap since this stream's previous token — the decode
                # stall gauge prefill interference inflates (PR 15)
                self.metrics.record_itl(now - st.t_last)
            st.t_last = now
            st.req.stream._push(tok, now)
            self._grammar_step(slot, st, tok)
            why = self._grammar_why(st, self._retire_why(st, st.req, now))
            if why is not None:
                retired.append((slot, st, why))
        if sampled:
            self.metrics.record_sampled(sampled)
        for slot, st, why in retired:
            self._release_slot(slot, st)
            self._finish_slot(st, why, now)

    def _speculative_round(self, active: List[Tuple[int, _SlotState]]) -> None:
        """One speculative iteration over every decoding slot: k+1 draft
        decode steps (each feeding the previous step's device-resident
        tokens straight back in — the +1 pre-writes the bonus token's
        K/V row in the draft cache so a full acceptance leaves no hole),
        then ONE target verify forward scoring all k candidates, then
        host-side accept/rollback bookkeeping.

        Rollback is free by construction: a rejection just leaves the
        slot's position at the last accepted row, and the rejected
        candidates' K/V rows sit causally masked past it until the next
        round overwrites them — the same recycled-page bit-cleanliness
        the paged cache already guarantees."""
        faults.fire("engine.draft", engine=self)
        k = self.spec_k
        tokens = np.zeros((self.max_slots,), np.int32)
        positions = np.zeros((self.max_slots,), np.int32)
        out_base = np.zeros((self.max_slots,), np.int32)
        for slot, st in active:
            tokens[slot] = st.last_token
            positions[slot] = st.position
            out_base[slot] = st.generated
        # grammar (PR 20): draft step i and verify position i share one
        # mask — the automaton state after the first i draft proposals,
        # walked on a per-round SCRATCH copy of each constrained slot's
        # state (the canonical state only advances on EMITTED tokens,
        # below). The accepted prefix always equals the draft prefix, so
        # verify's residual resample at the first rejection is masked by
        # exactly its true predecessor state; rows past a terminal go
        # through a dead scratch state, whose all-zero bias row is the
        # uniform-shift no-op the emit cap discards anyway.
        gslots = [(slot, st) for slot, st in active
                  if st.req.grammar is not None]
        g_scratch = {slot: st.grammar_state for slot, st in gslots}
        d_tokens = []
        d_dists = []
        bias_list = []
        cur = tokens
        for i in range(k + 1):
            if self._bias is None:
                bias_i = None
            elif gslots:
                bias_i = self._bias.copy()
                for slot, st in gslots:
                    bias_i[slot] = st.req.grammar.bias_row(g_scratch[slot])
            else:
                bias_i = self._bias
            # positions clamp at the lane end: a slot about to retire at
            # max_len keeps fixed shapes (garbage proposals there are
            # rejected or discarded by the room cap below)
            pos_i = np.minimum(positions + i, self.max_len - 1)
            cur, dist, self._dcache = self.kernels.draft(
                self._draft_params, self._dcache, cur, pos_i,
                self._dpage_map, self._temps, self._top_ks, self._top_ps,
                self._keys, out_base + i, bias=bias_i)
            # host round trip on purpose: feeding the committed device
            # output straight back would key a SECOND pjit executable
            # (committed vs uncommitted int32[S]) — compile-once pins
            # exactly one entry per kernel
            cur = np.asarray(cur)
            for slot, st in gslots:
                g_scratch[slot] = st.req.grammar.advance(
                    g_scratch[slot], int(cur[slot]))
            bias_list.append(bias_i)
            if i < k:
                d_tokens.append(cur)
                d_dists.append(dist)
        faults.fire("engine.verify", engine=self)
        bias_v = (None if self._bias is None
                  else np.stack(bias_list, axis=1))  # (S, k+1, V)
        n_dev, out_dev, self._cache = self.kernels.verify(
            self._params, self._cache, tokens, d_tokens, positions,
            self._page_map, self._pool.trash, self._temps, self._top_ks,
            self._top_ps, self._keys, out_base, d_dists, bias=bias_v)
        n_acc = np.asarray(n_dev)
        outs = np.asarray(out_dev)
        now = time.monotonic()
        self.metrics.record_decode_step(len(active), self.max_slots)
        accepted_total = 0
        pushed_total = 0
        sampled = 0
        retired = []
        for slot, st in active:
            room = min(st.req.max_new_tokens - st.generated,
                       self.max_len - st.position)
            emit = min(int(n_acc[slot]) + 1, room)
            pushed = 0
            for j in range(emit):
                tok = int(outs[slot, j])
                st.req.stream._push(tok, now)
                pushed += 1
                if self.eos_id is not None and tok == self.eos_id:
                    break
                # canonical advance per EMITTED token (the scratch walk
                # above covered proposals); a stuck verdict stops the
                # emission — nothing unparseable streams past it
                self._grammar_step(slot, st, tok)
                if st.grammar_error is not None:
                    break
            accepted_total += min(int(n_acc[slot]), pushed)
            pushed_total += pushed
            tr = st.req.stream.trace
            if tr is not None:
                tr.tick("verify_round")
            if pushed and st.t_last:
                # one amortized sample per emitted token: the round's
                # wall gap spread over everything it pushed
                self.metrics.record_itl((now - st.t_last) / pushed, pushed)
            st.t_last = now
            st.last_token = int(outs[slot, pushed - 1])
            st.position += pushed
            st.generated += pushed
            sampled += pushed if st.req.sampled else 0
            why = self._grammar_why(st, self._retire_why(st, st.req, now))
            if why is not None:
                retired.append((slot, st, why))
        self.metrics.record_verify_step(k * len(active), accepted_total,
                                        pushed_total - len(active))
        if sampled:
            self.metrics.record_sampled(sampled)
        for slot, st, why in retired:
            self._release_slot(slot, st)
            self._finish_slot(st, why, now)

    def _retire_why(self, st: Optional[_SlotState], req: _GenRequest,
                    now: float) -> Optional[str]:
        """Retirement disposition, or None to keep decoding. Order:
        explicit cancel wins, a normally-completed sequence beats a
        deadline that expired on the same step."""
        if req.stream.cancelled:
            return "cancelled"
        if st is not None:
            if self.eos_id is not None and st.last_token == self.eos_id:
                return "done"
            if st.generated >= req.max_new_tokens:
                return "done"
            if st.position >= self.max_len:
                return "done"
        if req.deadline is not None and now > req.deadline:
            return "expired"
        return None

    # ---------------------------------------- grammar (PR 20) hooks ----

    def _grammar_arm(self, slot: int, st: _SlotState) -> None:
        """Arm a constrained slot's mask row for its FIRST sampled token
        (the final prefill chunk): the automaton begins at its start
        state, and the start state's bias row must be live in
        ``self._bias`` BEFORE the prefill kernel samples."""
        g = st.req.grammar
        if g is None:
            return
        st.grammar_state = g.start_state
        self._bias[slot] = g.bias_row(st.grammar_state)
        self.metrics.record_masked_frac(g.masked_frac(st.grammar_state))

    def _grammar_step(self, slot: int, st: _SlotState, tok: int) -> None:
        """Advance a constrained slot's automaton on one emitted token
        and re-arm ``self._bias[slot]`` for the NEXT step. A verdict
        (stuck terminal, or the defensive illegal-token case) is
        recorded on ``st.grammar_error`` — surfaced by
        :meth:`_grammar_why` at the retirement decision, never raised
        here (this runs inside the scheduler loop / the async fold-in,
        where an exception would take down every stream)."""
        g = st.req.grammar
        if g is None or st.grammar_error is not None:
            return
        if self.eos_id is not None and tok == self.eos_id:
            # the EOS column is legal only in ACCEPTING states, so
            # sampling it IS the parse — nothing left to re-arm
            return
        state = g.advance(st.grammar_state, tok)
        st.grammar_state = state
        if state < 0:
            # defensive: the mask makes illegal tokens unsampleable
            # (exp(-1e9) underflows to exact f32 zero), so a dead state
            # here means the mask was not applied — fail the stream
            # rather than emit unparseable text
            st.grammar_error = GrammarViolation(
                f"token {tok} is not legal from the previous state",
                state=state, tokens_out=st.generated, grammar_key=g.key)
            return
        if not g.has_continuation(state) and not g.is_accepting(state):
            st.grammar_error = GrammarViolation(
                "stuck state: no legal continuation and no legal EOS "
                "over this vocabulary", state=state,
                tokens_out=st.generated, grammar_key=g.key)
            return
        self._bias[slot] = g.bias_row(state)
        self.metrics.record_masked_frac(g.masked_frac(state))

    def _grammar_why(self, st: _SlotState,
                     why: Optional[str]) -> Optional[str]:
        """Fold the grammar verdict into the retirement disposition:

        - a recorded violation (stuck state, defensive illegal token)
          always fails the stream;
        - a budget/length ``done`` in a NON-accepting state is a
          violation — the emitted text does not parse (an EOS-sampled
          ``done`` always lands accepting: EOS is only legal there);
        - with no EOS id configured, an accepting state with nothing
          legal left retires ``done`` — the parse is complete and the
          next mask row would be the all-illegal no-op.
        Cancel/expired dispositions pass through: their own errors win.
        """
        g = st.req.grammar
        if g is None:
            return why
        if st.grammar_error is not None:
            return "grammar"
        if why == "done" and not g.is_accepting(st.grammar_state):
            st.grammar_error = GrammarViolation(
                "token budget exhausted before the grammar could "
                "complete", state=st.grammar_state,
                tokens_out=st.generated, grammar_key=g.key)
            return "grammar"
        if (why is None and self.eos_id is None
                and g.is_accepting(st.grammar_state)
                and not g.has_continuation(st.grammar_state)):
            return "done"
        return why

    def _finish_slot(self, st: _SlotState, why: str, now: float) -> None:
        if why == "grammar":
            err = st.grammar_error
            self.metrics.record_failed()
            st.req.stream._finish(err, now)
            tr = st.req.stream.trace
            if tr is not None:
                tr.finish(outcome="grammar_violation", tokens=st.generated)
            return
        self._finish_request(st.req, why, now,
                             queue_wait=st.t_admit - st.req.stream.t_submit,
                             generated=st.generated)

    def _finish_request(self, req: _GenRequest, why: str, now: float, *,
                        queue_wait: Optional[float],
                        generated: int = 0) -> None:
        stream = req.stream
        dur = now - stream.t_submit
        if why == "expired":
            self.metrics.record_expired()
            stream._finish(DeadlineExceeded(
                dur, req.deadline - stream.t_submit), now)
        elif why == "cancelled":
            stream._finish(StreamCancelled(
                "generation stream cancelled by its consumer"), now)
        else:
            self.metrics.record_served(dur, queue_wait or 0.0)
            self.metrics.record_stream(generated, dur)
            stream._finish(None, now)
        tr = stream.trace
        if tr is not None:
            tr.finish(outcome=why, tokens=generated)

    # -------------------------------------------------------- lifecycle ----

    def warmup(self) -> None:
        """Compile the decode step and every prompt-bucket prefill BEFORE
        traffic arrives. Must run before the first submit (it touches the
        cache from the caller's thread); the garbage keys it writes are
        causally invisible and overwritten by real admissions."""
        core = self._core
        with core.cond:
            if core.pending or core.active:
                raise RuntimeError("warmup() must run before traffic")
        zeros = np.zeros((self.max_slots,), np.int32)
        if self.paged and self.speculative:
            # every write routes to the trash page (the map rows are
            # parked there). One call per kernel shape: the draft step
            # and the verify step each have exactly ONE shape however
            # the acceptance lengths vary at runtime.
            trash_row = np.full((self._pool.pages_per_slot,),
                                self._pool.trash, np.int32)
            k = self.spec_k
            # grammar bias rows warm as the same argument KIND traffic
            # passes (host arrays when the model has a vocab, else
            # consistently None) — a kind flip would key a second pjit
            # executable per kernel and break the compile-once pins
            wb = self._bias
            wb1 = None if wb is None else wb[:1]
            wbv = (None if wb is None else
                   np.zeros((self.max_slots, k + 1, wb.shape[1]),
                            np.float32))
            _, wd, self._dcache = self.kernels.draft(
                self._draft_params, self._dcache, zeros, zeros,
                self._dpage_map, self._temps, self._top_ks, self._top_ps,
                self._keys, zeros, bias=wb)
            # verify must see the RUNTIME argument kinds: draft tokens
            # arrive as host arrays (the round's committed-output
            # normalization) but dists stay device-resident — a numpy
            # dist here would warm a second executable for the same
            # trace (pjit keys on committed-ness, not just shape)
            zt = [np.zeros((self.max_slots,), np.int32)] * k
            zd = [wd] * k
            _, _, self._cache = self.kernels.verify(
                self._params, self._cache, zeros, zt, zeros,
                self._page_map, self._pool.trash, self._temps,
                self._top_ks, self._top_ps, self._keys, zeros, zd,
                bias=wbv)
            if self.max_prompt_len > self.prefill_chunk:
                chunk_pad = np.full((self.prefill_chunk,), self.pad_id,
                                    np.int32)
                self._cache = self.kernels.chunk(
                    self._params, self._cache, trash_row, chunk_pad, 0,
                    self.prefill_chunk, self._pool.trash)
                self._dcache = self.kernels.draft_write(
                    self._draft_params, self._dcache, trash_row,
                    chunk_pad, 0, self.prefill_chunk, self._pool.trash)
            for bucket in self.prompt_buckets:
                pad = np.full((bucket,), self.pad_id, np.int32)
                _, self._cache = self.kernels.prefill(
                    self._params, self._cache, trash_row, pad, 0, bucket,
                    self._pool.trash, bias=wb1)
                self._dcache = self.kernels.draft_write(
                    self._draft_params, self._dcache, trash_row, pad, 0,
                    bucket, self._pool.trash)
            jax.block_until_ready(self._dcache)
        elif self.paged:
            # every write below routes to the trash page (the map rows
            # are parked there), so warmup garbage can never surface.
            # Role-split engines warm ONLY their role's kernels: the
            # compile-once contract is per role (a prefill engine never
            # traces decode and vice versa — trace-counter-pinned).
            trash_row = np.full((self._pool.pages_per_slot,),
                                self._pool.trash, np.int32)
            # grammar bias rows warm as the same argument KIND traffic
            # passes (arrays when the model has a vocab, else None) —
            # a kind flip would key a second pjit executable
            wb = self._bias
            wb1 = None if wb is None else wb[:1]
            if self.role != "prefill":
                _, self._keys, self._cache = self.kernels.decode(
                    self._params, self._cache, zeros, zeros,
                    self._page_map, self._temps, self._top_ks,
                    self._top_ps, self._keys, bias=wb)
                self._keys = np.asarray(self._keys)
            if self.role != "decode":
                if self.max_prompt_len > self.prefill_chunk:
                    self._cache = self.kernels.chunk(
                        self._params, self._cache, trash_row,
                        np.full((self.prefill_chunk,), self.pad_id,
                                np.int32),
                        0, self.prefill_chunk, self._pool.trash)
                for bucket in self.prompt_buckets:
                    _, _, self._cache = self.kernels.prefill(
                        self._params, self._cache, trash_row,
                        np.full((bucket,), self.pad_id, np.int32), 0,
                        bucket, self._pool.trash, bias=wb1)
            if self.role == "prefill":
                # the export gather (pure read off the trash rows)
                jax.block_until_ready(
                    self._mover.gather(self._cache, trash_row))
            elif self.role == "decode":
                # the adopt scatter: a zero block routed to the trash
                # page, placed exactly as runtime blocks are (the
                # device_put the adopt path applies) so ONE executable
                # serves warmup and traffic
                block = jax.tree_util.tree_map(
                    lambda leaf: np.zeros(
                        (self._pool.pages_per_slot,) + leaf.shape[1:],
                        leaf.dtype), self._cache)
                if self._cache_sharding is not None:
                    block = jax.device_put(
                        block,
                        _cache_sharding_tree(block, self._cache_sharding))
                else:
                    block = jax.device_put(block)
                self._cache = self._mover.scatter(self._cache, block,
                                                  trash_row)
            if self._host is not None:
                # host tier (PR 18): the offload/swap gather and the
                # restore scatter warm exactly like the role-split
                # engines' — ONE executable each, runtime calls place
                # their blocks identically (compile-once is test-pinned)
                if self.role != "prefill":
                    jax.block_until_ready(
                        self._mover.gather(self._cache, trash_row))
                block = jax.tree_util.tree_map(
                    lambda leaf: np.zeros(
                        (self._pool.pages_per_slot,) + leaf.shape[1:],
                        leaf.dtype), self._cache)
                if self._cache_sharding is not None:
                    block = jax.device_put(
                        block,
                        _cache_sharding_tree(block, self._cache_sharding))
                else:
                    block = jax.device_put(block)
                self._cache = self._mover.scatter(self._cache, block,
                                                  trash_row)
            # warmup consumed one split per slot key: re-arm the zeros so
            # the first real admission starts from its request seed (it
            # overwrites the row anyway; this keeps the invariant obvious)
            self._keys = np.zeros((self.max_slots, 2), np.uint32)
        else:
            _, self._cache = self.kernels.decode(
                self._params, self._cache, zeros, zeros)
            for bucket in self.prompt_buckets:
                _, self._cache = self.kernels.prefill(
                    self._params, self._cache, 0,
                    np.full((bucket,), self.pad_id, np.int32), bucket)
        jax.block_until_ready(self._cache)

    def reload(self, params, state: Any = None) -> None:
        """Swap decode params atomically between steps: a decode/prefill
        call reads ``self._params`` exactly once, so every step sees one
        consistent tree — never torn halves. Signature-checked: matching
        shapes/dtypes mean the jitted step is NOT recompiled. ``state``
        is accepted for :func:`watch_checkpoints` symmetry but must be
        empty — incremental decode is stateless."""
        from bigdl_tpu.serving.service import require_matching_signature

        if state:
            raise ValueError(
                "GenerationEngine.reload takes params only: incremental "
                "decode runs stateless (no BN-style buffers)")
        if self._quantize_params is not None:
            # a quantized engine reloads from FLOAT checkpoints: the
            # transform is a pure function of shapes, so the quantized
            # tree's signature matches the serving one and the jitted
            # step is NOT recompiled (pjit-cache test-enforced)
            params = self._quantize_params(params)
        require_matching_signature("params", self._params, params)
        # device_put once: host arrays would re-transfer every step and
        # miss the jit cache (uncommitted args key a different executable).
        # A sharded engine re-places with the ORIGINAL shardings for the
        # same reason: differently-placed params key a fresh executable.
        if self._param_shardings is not None:
            self._params = jax.device_put(params, self._param_shardings)
        else:
            self._params = jax.device_put(params)
        if self._prefix is not None:
            # cached pages are keyed by (model version, prefix): pages
            # the OLD params wrote must never serve the new ones. The
            # pool is loop-thread-only, so flag the flush and let the
            # loop clear the index at its next iteration (the same
            # between-steps granularity the param swap itself has).
            self._prefix_flush = True
        self.metrics.record_reload()

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Stop admitting; with ``drain`` (default) the loop keeps
        stepping until every pending and in-flight stream finishes,
        otherwise they fail with ``RuntimeError``."""
        core = self._core
        with core.cond:
            core.closed = True
            core.drain = drain
            core.cond.notify_all()
        self._thread.join(timeout)
        if self._profile_state == 1:
            # a profile bracket wider than the traffic that ran: close
            # it rather than leak an open device trace
            try:
                jax.profiler.stop_trace()
            except Exception:
                log.exception("stopping engine profiler trace failed")
            self._profile_state = 2
        if self._watchdog is not None and not self._thread.is_alive():
            self._watchdog.close()
        if not self._thread.is_alive():
            # the loop has exited: a request that raced the close flag in
            # must fail rather than strand its consumer. NOT safe while
            # the loop lives (a timed-out drain join) — it would fail
            # streams the loop is still legitimately serving and
            # double-free their slots mid-step.
            _fail_streams(core, RuntimeError(
                "generation engine closed before request ran"), self)

    def __enter__(self) -> "GenerationEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --------------------------------------------------------- queries ----

    @property
    def failed(self) -> Optional[BaseException]:
        """The error that stopped the engine loop (``None`` while
        healthy). A fleet heal pass probes this instead of waiting for
        the next placement attempt to trip over the dead loop."""
        with self._core.cond:
            return self._failed

    @property
    def active_slots(self) -> int:
        with self._core.cond:
            return len(self._core.active)

    @property
    def pending_requests(self) -> int:
        with self._core.cond:
            return len(self._core.pending)

    @property
    def free_slots(self) -> List[int]:
        with self._core.cond:
            return sorted(self._core.free)

    @property
    def decode_compilations(self) -> int:
        return self.kernels.decode_traces

    @property
    def prefill_compilations(self) -> int:
        return self.kernels.prefill_traces

    @property
    def chunk_compilations(self) -> int:
        return getattr(self.kernels, "chunk_traces", 0)

    @property
    def draft_compilations(self) -> int:
        return getattr(self.kernels, "draft_traces", 0)

    @property
    def verify_compilations(self) -> int:
        return getattr(self.kernels, "verify_traces", 0)

    @property
    def handoff_gather_compilations(self) -> int:
        return self._mover.gather_traces if self._mover is not None else 0

    @property
    def handoff_scatter_compilations(self) -> int:
        return self._mover.scatter_traces if self._mover is not None else 0

    @property
    def pages_in_use(self) -> int:
        return self._pool.in_use if self.paged else 0

    @property
    def free_pages(self) -> int:
        return self._pool.free_pages if self.paged else 0

    @property
    def shared_pages(self) -> int:
        """Pages the prefix index(es) currently hold references for
        (0 without prefix caching) — the chaos drain gate's gauge."""
        if self._prefix is None:
            return 0
        return self._prefix.pages + (self._dprefix.pages
                                     if self._dprefix is not None else 0)

    @property
    def host_pages_in_use(self) -> int:
        """Pages resident in the host tier — offloaded prefix entries
        plus parked-stream bookings (0 without ``host_pages``); the
        second gauge the two-tier drain gate asserts reaches zero."""
        return self._host.pages if self._host is not None else 0

    @property
    def host_store(self) -> Optional[HostPageStore]:
        """The host tier itself (``None`` without ``host_pages``) —
        snapshot()-able like the PagePool, for registry scrapes."""
        return self._host


def _static_grammar_step(g, state, tok, eos_id, n_out):
    """``static_generate``'s per-token automaton advance — the engine's
    ``_grammar_step`` semantics, raising :class:`GrammarViolation`
    instead of failing a stream (the static baseline has no stream)."""
    if g is None or (eos_id is not None and tok == eos_id):
        return state
    state = g.advance(state, tok)
    if state < 0:
        raise GrammarViolation(
            f"token {tok} is not legal from the previous state",
            state=state, tokens_out=n_out, grammar_key=g.key)
    if not g.has_continuation(state) and not g.is_accepting(state):
        raise GrammarViolation(
            "stuck state: no legal continuation and no legal EOS over "
            "this vocabulary", state=state, tokens_out=n_out,
            grammar_key=g.key)
    return state


def _static_grammar_finish(g, state, tok, eos_id, n_out):
    """Completion check at a static stream's retirement: a budget /
    length ``done`` must land in an accepting state, or the emitted
    text does not parse (an EOS-terminated stream always does — the
    EOS column is only legal in accepting states)."""
    if g is None or (eos_id is not None and tok == eos_id):
        return
    if not g.is_accepting(state):
        raise GrammarViolation(
            "token budget exhausted before the grammar could complete",
            state=state, tokens_out=n_out, grammar_key=g.key)


def static_generate(model, params, requests, *, max_slots: int,
                    max_len: int, eos_id: Optional[int] = None,
                    pad_id: int = 0, cache_dtype=jnp.float32,
                    kernels=None,
                    prompt_buckets: Optional[Sequence[int]] = None,
                    page_size: int = 16, num_pages: Optional[int] = None,
                    prefill_chunk: Optional[int] = None, seed: int = 0,
                    sampling: Optional[Sequence[dict]] = None,
                    quantize: Optional[str] = None,
                    speculate: Optional[tuple] = None):
    """Run-to-completion static batching BASELINE over the same jitted
    kernels the engine uses: admit ``max_slots`` requests, decode until
    EVERY one finishes (the longest sequence holds the whole batch
    hostage), only then admit the next group. ``requests`` is a sequence
    of ``(prompt, max_new_tokens)``; returns ``(token lists, decode
    steps executed)``. This is the comparison the bench/CI smoke gate
    runs — continuous batching must beat it on mixed lengths because it
    retires short sequences mid-flight instead of idling their slots.

    With :class:`PagedDecodeKernels` (the default for paged-capable
    models) the baseline runs over the SAME paged + sampling kernels as
    the engine — apples to apples stays apples. ``sampling`` is an
    optional per-request list of dicts (``temperature`` / ``top_k`` /
    ``top_p`` / ``seed``); seeds derive exactly like the engine's, so a
    sampled run produces IDENTICAL streams under either scheduler.

    ``quantize="int8"`` / ``cache_dtype="int8"`` mirror the engine knobs
    (the transform is deterministic, so an int8 engine and an int8
    static run still emit identical tokens — the bench mismatch gate
    covers the quantized tier too).

    ``speculate=(draft_model, draft_params, k)`` mirrors the engine's
    draft-verified mode over :class:`SpeculativeKernels`: the same
    position-keyed draws make a speculative static run emit the
    ENGINE's exact streams (greedy and sampled), which is the
    schedule-invariance gate the speculative bench leans on."""
    draft_model = draft_params = None
    spec_k = 0
    if speculate is not None:
        draft_model, draft_params, spec_k = speculate
        spec_k = int(spec_k)
    if quantize == "int8":
        from bigdl_tpu.nn.quantized import quantize_for_serving

        params = quantize_for_serving(params)
        if draft_params is not None:
            draft_params = quantize_for_serving(draft_params)
    elif quantize is not None:
        raise ValueError(f"quantize must be None or 'int8', got {quantize!r}")
    if np.dtype(cache_dtype) == np.int8 and not (
            hasattr(kernels, "chunk") if kernels is not None
            else page_size and hasattr(model, "decode_step_paged")):
        # same guard the engine applies: the dense slot-lane path has no
        # scale pools, so an int8 cache there would truncate K/V to
        # zeros and decode garbage without a single error
        raise ValueError(
            "cache_dtype='int8' needs the paged kernels (int8 KV lives in "
            "the page pools with per-token scale pools)")
    if kernels is None:
        if speculate is not None:
            kernels = SpeculativeKernels(model, draft_model)
        else:
            kernels = (PagedDecodeKernels(model)
                       if page_size and hasattr(model, "decode_step_paged")
                       else DecodeKernels(model))
    requests = [([int(t) for t in p], int(m)) for p, m in requests]
    if hasattr(kernels, "verify"):  # speculative set (or a wrapper)
        if speculate is None:
            raise ValueError(
                "SpeculativeKernels need speculate=(draft_model, "
                "draft_params, k)")
        return _static_generate_spec(
            model, params, requests, kernels, draft_params, spec_k,
            max_slots=max_slots, max_len=max_len, eos_id=eos_id,
            pad_id=pad_id, cache_dtype=cache_dtype,
            prompt_buckets=prompt_buckets, page_size=page_size,
            num_pages=num_pages, prefill_chunk=prefill_chunk, seed=seed,
            sampling=sampling, draft_model=draft_model)
    if speculate is not None:
        raise ValueError(
            "speculate= needs SpeculativeKernels (pass kernels=None to "
            "build them)")
    if hasattr(kernels, "chunk"):  # paged triple (or a wrapper around one)
        return _static_generate_paged(
            model, params, requests, kernels, max_slots=max_slots,
            max_len=max_len, eos_id=eos_id, pad_id=pad_id,
            cache_dtype=cache_dtype, prompt_buckets=prompt_buckets,
            page_size=page_size, num_pages=num_pages,
            prefill_chunk=prefill_chunk, seed=seed, sampling=sampling)
    if sampling is not None:
        raise ValueError("sampling needs PagedDecodeKernels")
    buckets = list(prompt_buckets
                   or bucket_sizes_for(max(len(p) for p, _ in requests)))
    cache = model.init_cache(max_slots, max_len, cache_dtype)
    outputs: List[Optional[List[int]]] = [None] * len(requests)
    total_steps = 0
    for base in range(0, len(requests), max_slots):
        group = requests[base:base + max_slots]
        states = []
        for slot, (prompt, mnt) in enumerate(group):
            n = len(prompt)
            bucket = next(b for b in buckets if b >= n)
            padded = np.full((bucket,), pad_id, np.int32)
            padded[:n] = prompt
            tok_dev, cache = kernels.prefill(params, cache, slot, padded, n)
            tok = int(np.asarray(tok_dev))
            target = min(mnt, max_len - n)
            states.append({
                "tokens": [tok], "last": tok, "pos": n,
                "target": target,
                "done": (eos_id is not None and tok == eos_id) or target <= 1,
            })
        while not all(s["done"] for s in states):
            tokens = np.zeros((max_slots,), np.int32)
            positions = np.zeros((max_slots,), np.int32)
            for slot, s in enumerate(states):
                tokens[slot] = s["last"]
                positions[slot] = s["pos"]
            toks_dev, cache = kernels.decode(params, cache, tokens, positions)
            toks = np.asarray(toks_dev)
            total_steps += 1
            for slot, s in enumerate(states):
                if s["done"]:
                    continue
                tok = int(toks[slot])
                s["tokens"].append(tok)
                s["last"] = tok
                s["pos"] += 1
                if ((eos_id is not None and tok == eos_id)
                        or len(s["tokens"]) >= s["target"]
                        or s["pos"] >= max_len):
                    s["done"] = True
        for i, s in enumerate(states):
            outputs[base + i] = s["tokens"]
    return outputs, total_steps


def _static_generate_spec(model, params, requests, kernels, draft_params,
                          spec_k, *, max_slots, max_len, eos_id, pad_id,
                          cache_dtype, prompt_buckets, page_size,
                          num_pages, prefill_chunk, seed, sampling,
                          draft_model):
    """Speculative body of :func:`static_generate`: group-at-a-time
    run-to-completion over the SAME draft/verify kernels the engine
    runs. Draws are keyed by (request, output position), so the emitted
    streams are identical to the engine's under any grouping — the
    speculative analogue of the paged body's schedule invariance.
    Returns ``(token lists, verify rounds executed)``."""
    from bigdl_tpu.core.rng import request_seed as _request_seed
    from bigdl_tpu.core.rng import threefry_key_data as _tkd

    k = int(spec_k)
    chunk = int(prefill_chunk or min(64, max_len - 1))
    longest = max(len(p) for p, _ in requests)
    buckets = list(prompt_buckets or bucket_sizes_for(min(longest, chunk)))
    num_pages = int(num_pages
                    or max_slots * 2 * pages_per_lane(max_len, page_size))
    pool = PagePool(num_pages, page_size, max_len)
    cache = model.init_paged_cache(num_pages + 1, page_size, cache_dtype)
    dcache = draft_model.init_paged_cache(num_pages + 1, page_size,
                                          cache_dtype)
    ppn = pool.pages_per_slot
    page_map = np.full((max_slots, ppn), pool.trash, np.int32)
    dpage_map = np.full((max_slots, ppn), pool.trash, np.int32)
    temps = np.zeros((max_slots,), np.float32)
    top_ks = np.zeros((max_slots,), np.int32)
    top_ps = np.ones((max_slots,), np.float32)
    keys = np.zeros((max_slots, 2), np.uint32)
    # grammar (PR 20): same bias-kind rule as the engine (arrays iff the
    # model exposes a vocab — one executable per kernel, shared or not)
    vocab = getattr(model, "vocab_size", None)
    bias = (np.zeros((max_slots, int(vocab)), np.float32)
            if vocab else None)

    outputs: List[Optional[List[int]]] = [None] * len(requests)
    total_rounds = 0
    for base in range(0, len(requests), max_slots):
        group = requests[base:base + max_slots]
        states = []
        for slot, (prompt, mnt) in enumerate(group):
            n = len(prompt)
            target = min(mnt, max_len - n)
            spec = dict(sampling[base + slot] or {}) if sampling else {}
            req_seed = spec.get("seed")
            if req_seed is None:
                req_seed = _request_seed(
                    seed, np.asarray(prompt, np.int32).tobytes(), n)
            temps[slot] = float(spec.get("temperature", 0.0))
            top_ks[slot] = int(spec.get("top_k", 0))
            top_ps[slot] = float(spec.get("top_p", 1.0))
            keys[slot] = _tkd(req_seed)
            g = spec.get("grammar")
            gstate = None
            if g is not None:
                if bias is None:
                    raise ValueError(
                        "sampling['grammar'] needs a model exposing "
                        "vocab_size")
                gstate = g.start_state
                bias[slot] = g.bias_row(gstate)
            need = pool.pages_for(min(n + target - 1, max_len))
            if not pool.can_reserve(2 * need):
                raise ValueError(
                    f"num_pages={num_pages} cannot hold a speculative "
                    f"static group (needs {2 * need} more pages) — grow "
                    f"the pool or shrink max_slots")
            pages = pool.alloc(need, owner="target")
            dpages = pool.alloc(need, owner="draft")
            page_map[slot, :] = pool.trash
            page_map[slot, :len(pages)] = pages
            dpage_map[slot, :] = pool.trash
            dpage_map[slot, :len(dpages)] = dpages
            start = 0
            while n - start > chunk:
                piece = np.asarray(prompt[start:start + chunk], np.int32)
                cache = kernels.chunk(params, cache, page_map[slot],
                                      piece, start, chunk, pool.trash)
                dcache = kernels.draft_write(
                    draft_params, dcache, dpage_map[slot], piece, start,
                    chunk, pool.trash)
                start += chunk
            remaining = n - start
            bucket = next(b for b in buckets if b >= remaining)
            padded = np.full((bucket,), pad_id, np.int32)
            padded[:remaining] = prompt[start:]
            tok_dev, cache = kernels.prefill(
                params, cache, page_map[slot], padded, start, remaining,
                pool.trash, temps[slot], top_ks[slot], top_ps[slot],
                keys[slot],
                bias=None if bias is None else bias[slot:slot + 1].copy())
            dcache = kernels.draft_write(
                draft_params, dcache, dpage_map[slot], padded, start,
                remaining, pool.trash)
            tok = int(np.asarray(tok_dev))
            gstate = _static_grammar_step(g, gstate, tok, eos_id, 1)
            if g is not None:
                bias[slot] = g.bias_row(gstate)
            done = (eos_id is not None and tok == eos_id) or target <= 1
            if done:
                _static_grammar_finish(g, gstate, tok, eos_id, 1)
            states.append({
                "tokens": [tok], "last": tok, "pos": n,
                "target": target, "pages": pages, "dpages": dpages,
                "grammar": g, "gstate": gstate,
                "done": done,
            })
        while not all(s["done"] for s in states):
            tokens = np.zeros((max_slots,), np.int32)
            positions = np.zeros((max_slots,), np.int32)
            out_base = np.zeros((max_slots,), np.int32)
            for slot, s in enumerate(states):
                tokens[slot] = s["last"]
                positions[slot] = s["pos"]
                out_base[slot] = len(s["tokens"])
            # draft step i and verify position i share one mask, walked
            # on a per-round scratch copy of each live grammar state —
            # the engine's _speculative_round discipline exactly
            glive = [(slot, s) for slot, s in enumerate(states)
                     if not s["done"] and s["grammar"] is not None]
            g_scratch = {slot: s["gstate"] for slot, s in glive}
            d_tokens = []
            d_dists = []
            bias_list = []
            cur = tokens
            for i in range(k + 1):
                if bias is None:
                    bias_i = None
                elif glive:
                    bias_i = bias.copy()
                    for slot, s in glive:
                        bias_i[slot] = s["grammar"].bias_row(
                            g_scratch[slot])
                else:
                    bias_i = bias
                pos_i = np.minimum(positions + i, max_len - 1)
                cur, dist, dcache = kernels.draft(
                    draft_params, dcache, cur, pos_i, dpage_map, temps,
                    top_ks, top_ps, keys, out_base + i, bias=bias_i)
                cur = np.asarray(cur)   # one executable: see engine loop
                for slot, s in glive:
                    g_scratch[slot] = s["grammar"].advance(
                        g_scratch[slot], int(cur[slot]))
                bias_list.append(bias_i)
                if i < k:
                    d_tokens.append(cur)
                    d_dists.append(dist)
            n_dev, out_dev, cache = kernels.verify(
                params, cache, tokens, d_tokens, positions, page_map,
                pool.trash, temps, top_ks, top_ps, keys, out_base,
                d_dists,
                bias=None if bias is None else np.stack(bias_list, axis=1))
            n_acc = np.asarray(n_dev)
            outs = np.asarray(out_dev)
            total_rounds += 1
            for slot, s in enumerate(states):
                if s["done"]:
                    continue
                room = min(s["target"] - len(s["tokens"]),
                           max_len - s["pos"])
                emit = min(int(n_acc[slot]) + 1, room)
                g = s["grammar"]
                pushed = 0
                for j in range(emit):
                    tok = int(outs[slot, j])
                    s["tokens"].append(tok)
                    pushed += 1
                    if eos_id is not None and tok == eos_id:
                        break
                    s["gstate"] = _static_grammar_step(
                        g, s["gstate"], tok, eos_id, len(s["tokens"]))
                if g is not None:
                    bias[slot] = g.bias_row(s["gstate"])
                s["last"] = int(outs[slot, pushed - 1])
                s["pos"] += pushed
                if ((eos_id is not None and s["last"] == eos_id)
                        or len(s["tokens"]) >= s["target"]
                        or s["pos"] >= max_len):
                    s["done"] = True
                    _static_grammar_finish(g, s["gstate"], s["last"],
                                           eos_id, len(s["tokens"]))
        for i, s in enumerate(states):
            outputs[base + i] = s["tokens"]
            pool.release(s["pages"])
            pool.release(s["dpages"])
        page_map[:] = pool.trash
        dpage_map[:] = pool.trash
        temps[:] = 0.0
        top_ks[:] = 0
        top_ps[:] = 1.0
        keys[:] = 0
        if bias is not None:
            bias[:] = 0.0
    return outputs, total_rounds


def _static_generate_paged(model, params, requests, kernels, *, max_slots,
                           max_len, eos_id, pad_id, cache_dtype,
                           prompt_buckets, page_size, num_pages,
                           prefill_chunk, seed, sampling):
    """Paged body of :func:`static_generate`: same group-at-a-time
    run-to-completion schedule, over the paged + sampling kernels. Each
    group reserves its pages up front and releases them when the whole
    group finishes — which is exactly the capacity pathology the paged
    ENGINE fixes by releasing per sequence."""
    chunk = int(prefill_chunk or min(64, max_len - 1))
    longest = max(len(p) for p, _ in requests)
    buckets = list(prompt_buckets or bucket_sizes_for(min(longest, chunk)))
    num_pages = int(num_pages
                    or max_slots * pages_per_lane(max_len, page_size))
    pool = PagePool(num_pages, page_size, max_len)
    cache = model.init_paged_cache(num_pages + 1, page_size, cache_dtype)
    page_map = np.full((max_slots, pool.pages_per_slot), pool.trash,
                       np.int32)
    temps = np.zeros((max_slots,), np.float32)
    top_ks = np.zeros((max_slots,), np.int32)
    top_ps = np.ones((max_slots,), np.float32)
    keys = np.zeros((max_slots, 2), np.uint32)
    # grammar (PR 20): same bias-kind rule as the engine — arrays iff
    # the model exposes a vocab, so a kernels set shared with an engine
    # keeps its one executable per kernel
    vocab = getattr(model, "vocab_size", None)
    bias = (np.zeros((max_slots, int(vocab)), np.float32)
            if vocab else None)

    outputs: List[Optional[List[int]]] = [None] * len(requests)
    total_steps = 0
    for base in range(0, len(requests), max_slots):
        group = requests[base:base + max_slots]
        states = []
        for slot, (prompt, mnt) in enumerate(group):
            n = len(prompt)
            target = min(mnt, max_len - n)
            spec = dict(sampling[base + slot] or {}) if sampling else {}
            req_seed = spec.get("seed")
            if req_seed is None:
                req_seed = request_seed(
                    seed, np.asarray(prompt, np.int32).tobytes(), n)
            temps[slot] = float(spec.get("temperature", 0.0))
            top_ks[slot] = int(spec.get("top_k", 0))
            top_ps[slot] = float(spec.get("top_p", 1.0))
            keys[slot] = threefry_key_data(req_seed)
            g = spec.get("grammar")
            gstate = None
            if g is not None:
                if bias is None:
                    raise ValueError(
                        "sampling['grammar'] needs a model exposing "
                        "vocab_size")
                gstate = g.start_state
                bias[slot] = g.bias_row(gstate)
            need = pool.pages_for(min(n + target - 1, max_len))
            if not pool.can_reserve(need):
                raise ValueError(
                    f"num_pages={num_pages} cannot hold a static group "
                    f"(needs {need} more pages) — grow the pool or "
                    f"shrink max_slots")
            pages = pool.alloc(need)
            page_map[slot, :] = pool.trash
            page_map[slot, :len(pages)] = pages
            start = 0
            while n - start > chunk:
                cache = kernels.chunk(
                    params, cache, page_map[slot],
                    np.asarray(prompt[start:start + chunk], np.int32),
                    start, chunk, pool.trash)
                start += chunk
            remaining = n - start
            bucket = next(b for b in buckets if b >= remaining)
            padded = np.full((bucket,), pad_id, np.int32)
            padded[:remaining] = prompt[start:]
            tok_dev, key_dev, cache = kernels.prefill(
                params, cache, page_map[slot], padded, start, remaining,
                pool.trash, temps[slot], top_ks[slot], top_ps[slot],
                keys[slot],
                bias=None if bias is None else bias[slot:slot + 1].copy())
            tok = int(np.asarray(tok_dev))
            keys[slot] = np.asarray(key_dev)[0]
            gstate = _static_grammar_step(g, gstate, tok, eos_id, 1)
            if g is not None:
                bias[slot] = g.bias_row(gstate)
            done = (eos_id is not None and tok == eos_id) or target <= 1
            if done:
                _static_grammar_finish(g, gstate, tok, eos_id, 1)
            if (not done and g is not None and eos_id is None
                    and not g.has_continuation(gstate)):
                done = True  # parse complete, nothing legal remains
            states.append({
                "tokens": [tok], "last": tok, "pos": n,
                "target": target, "pages": pages,
                "grammar": g, "gstate": gstate,
                "done": done,
            })
        while not all(s["done"] for s in states):
            tokens = np.zeros((max_slots,), np.int32)
            positions = np.zeros((max_slots,), np.int32)
            for slot, s in enumerate(states):
                tokens[slot] = s["last"]
                positions[slot] = s["pos"]
            toks_dev, keys_dev, cache = kernels.decode(
                params, cache, tokens, positions, page_map, temps, top_ks,
                top_ps, keys, bias=bias)
            toks = np.asarray(toks_dev)
            keys = np.array(keys_dev)
            total_steps += 1
            for slot, s in enumerate(states):
                if s["done"]:
                    continue
                tok = int(toks[slot])
                s["tokens"].append(tok)
                s["last"] = tok
                s["pos"] += 1
                g = s["grammar"]
                s["gstate"] = _static_grammar_step(
                    g, s["gstate"], tok, eos_id, len(s["tokens"]))
                if g is not None:
                    bias[slot] = g.bias_row(s["gstate"])
                if ((eos_id is not None and tok == eos_id)
                        or len(s["tokens"]) >= s["target"]
                        or s["pos"] >= max_len):
                    s["done"] = True
                    _static_grammar_finish(g, s["gstate"], tok, eos_id,
                                           len(s["tokens"]))
                elif (g is not None and eos_id is None
                        and not g.has_continuation(s["gstate"])):
                    s["done"] = True
        for i, s in enumerate(states):
            outputs[base + i] = s["tokens"]
            pool.release(s["pages"])
        page_map[:] = pool.trash
        temps[:] = 0.0
        top_ks[:] = 0
        top_ps[:] = 1.0
        keys[:] = 0
        if bias is not None:
            bias[:] = 0.0
    return outputs, total_steps
