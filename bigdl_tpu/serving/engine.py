"""GenerationEngine — continuous-batching autoregressive generation.

PR 1's :class:`~bigdl_tpu.serving.service.InferenceService` batches
run-to-completion requests, the wrong shape for autoregressive decoding:
one long sequence holds the whole micro-batch hostage and new requests
wait for the full batch to finish. This module is the iteration-level
scheduler (Orca, OSDI '22; vLLM's slot-managed KV cache, SOSP '23 —
PAPERS.md): the unit of scheduling is ONE decode step, not one request.

Design, in XLA terms:

- **fixed-shape slot table** — the KV cache is ``(max_slots, heads,
  max_len, head_dim)`` per layer, built once by ``model.init_cache``.
  The jitted decode step closes over nothing dynamic: tokens ``(S,)``
  and positions ``(S,)`` are the only per-step inputs, so the loop
  compiles exactly once at warmup and NEVER recompiles, however
  admissions and retirements reshuffle the slots (test-enforced via the
  :class:`DecodeKernels` trace counters).
- **donated cache** — the cache pytree is donated to every prefill and
  decode call, so the steady-state loop allocates no new cache buffers.
- **admission between steps** — new requests prefill into free slots at
  decode-step boundaries (one bucket-padded prompt forward each);
  finished sequences (EOS, max-tokens, deadline expiry, cancel) retire
  mid-flight and free their slot immediately.
- **iterator-futures** — ``submit`` returns a :class:`GenerationStream`
  that yields tokens as the loop produces them; time-to-first-token and
  per-stream tokens/sec land in the shared
  :class:`~bigdl_tpu.serving.metrics.ServingMetrics`.

:func:`static_generate` is the run-to-completion baseline over the SAME
jitted kernels — ``bench.py --mode serving --generate`` and the CI smoke
gate measure continuous vs static tokens/sec with it (the win is
scheduling, so it shows even on one core).

Sampling is greedy (argmax inside the jitted step): deterministic for a
fixed model+prompt regardless of admission order or slot assignment,
which the tests rely on. Swap :class:`DecodeKernels` for a sampling
variant when temperature is needed.
"""

from __future__ import annotations

import logging
import queue as _queue
import threading
import time
import weakref
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.serving.batcher import bucket_sizes_for
from bigdl_tpu.serving.errors import (
    DeadlineExceeded,
    Overloaded,
    StreamCancelled,
)
from bigdl_tpu.serving.metrics import ServingMetrics

log = logging.getLogger("bigdl_tpu.serving")

_SENTINEL = object()


class _TraceCounts:
    """Mutable trace counters, deliberately a separate tiny object: the
    jitted closures capture THIS (and the model), never the object that
    owns the pjit executables — a closure capturing the owner would put
    it in a cycle through the C++ pjit object, which the GC cannot
    break, leaking model+params on an unclosed engine."""

    __slots__ = ("prefill", "decode")

    def __init__(self):
        self.prefill = 0
        self.decode = 0


class DecodeKernels:
    """The jitted ``(prefill, decode)`` pair over a decode-capable model
    (one exposing ``init_cache`` / ``prefill`` / ``decode_step``, e.g.
    ``nn.Transformer`` in ``language_model`` mode).

    Greedy argmax sampling happens INSIDE the jitted step so only the
    ``int32`` next-token vector crosses to the host each iteration.
    ``prefill_traces`` / ``decode_traces`` increment only when XLA
    actually traces (= compiles) — the compile-count assertions in the
    tests read them. The cache argument is donated: the steady-state
    loop never reallocates cache buffers.
    """

    def __init__(self, model, *, donate: bool = True):
        self.model = model
        self.counts = _TraceCounts()
        counts = self.counts

        def prefill(params, cache, slot, tokens, length):
            counts.prefill += 1
            logits, cache = model.prefill(params, cache, slot, tokens, length)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

        def decode(params, cache, tokens, positions):
            counts.decode += 1
            logits, cache = model.decode_step(params, cache, tokens, positions)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), cache

        dn = (1,) if donate else ()
        self._prefill = jax.jit(prefill, donate_argnums=dn)
        self._decode = jax.jit(decode, donate_argnums=dn)

    @property
    def prefill_traces(self) -> int:
        return self.counts.prefill

    @property
    def decode_traces(self) -> int:
        return self.counts.decode

    def prefill(self, params, cache, slot: int, tokens, length: int):
        """-> (first generated token, new cache); donates ``cache``."""
        return self._prefill(params, cache, int(slot),
                             np.asarray(tokens, np.int32), int(length))

    def decode(self, params, cache, tokens, positions):
        """-> (next token per slot (S,), new cache); donates ``cache``."""
        return self._decode(params, cache, np.asarray(tokens, np.int32),
                            np.asarray(positions, np.int32))


class GenerationStream:
    """Iterator-future for one generation request.

    The engine pushes tokens as decode steps complete; the consumer
    either iterates (``for tok in stream`` — single-pass, yields each
    token once then raises the terminal error, if any) or blocks for the
    whole sequence with :meth:`result`. :meth:`cancel` asks the engine
    to retire the slot at the next step boundary (the stream then ends
    with :class:`StreamCancelled`; tokens produced so far stay readable
    via :attr:`tokens`).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._tokens: List[int] = []
        self._q: "_queue.SimpleQueue" = _queue.SimpleQueue()
        self._done = threading.Event()
        self._error: Optional[BaseException] = None
        self._cancelled = False
        self._callbacks: List[Callable[["GenerationStream"], None]] = []
        self.t_submit = time.monotonic()
        self.t_first: Optional[float] = None
        self.t_done: Optional[float] = None

    # ------------------------------------------------- engine side ----

    def _push(self, token: int, now: float) -> None:
        with self._lock:
            if self.t_first is None:
                self.t_first = now
            self._tokens.append(token)
        self._q.put(token)

    def _finish(self, error: Optional[BaseException] = None,
                now: Optional[float] = None) -> None:
        with self._lock:
            if self._done.is_set():
                return
            self._error = error
            self.t_done = now if now is not None else time.monotonic()
            callbacks = list(self._callbacks)
            self._done.set()
        self._q.put(_SENTINEL)
        for cb in callbacks:
            try:
                cb(self)
            except Exception:
                log.exception("GenerationStream done-callback failed")

    # ----------------------------------------------- consumer side ----

    def __iter__(self):
        while True:
            item = self._q.get()
            if item is _SENTINEL:
                if self._error is not None:
                    raise self._error
                return
            yield item

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Block until the stream finishes; the full token list (raises
        the stream's terminal error instead, e.g. ``DeadlineExceeded``)."""
        if not self._done.wait(timeout):
            raise TimeoutError("generation stream did not finish in time")
        if self._error is not None:
            raise self._error
        return list(self._tokens)

    def cancel(self) -> None:
        """Ask the engine to retire this request at the next step
        boundary (no-op once the stream is done)."""
        self._cancelled = True

    def add_done_callback(self, fn: Callable[["GenerationStream"], None]) -> None:
        with self._lock:
            if not self._done.is_set():
                self._callbacks.append(fn)
                return
        fn(self)

    # ------------------------------------------------------ queries ----

    @property
    def tokens(self) -> List[int]:
        """Tokens produced so far (snapshot copy)."""
        with self._lock:
            return list(self._tokens)

    @property
    def done(self) -> bool:
        return self._done.is_set()

    @property
    def cancelled(self) -> bool:
        return self._cancelled

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    @property
    def ttft_s(self) -> Optional[float]:
        """Submit -> first token, seconds (None before the first token)."""
        return None if self.t_first is None else self.t_first - self.t_submit


class _GenRequest:
    __slots__ = ("prompt", "max_new_tokens", "deadline", "stream")

    def __init__(self, prompt: List[int], max_new_tokens: int,
                 deadline: Optional[float], stream: GenerationStream):
        self.prompt = prompt
        self.max_new_tokens = max_new_tokens
        self.deadline = deadline
        self.stream = stream


class _SlotState:
    """Host-side bookkeeping for one occupied slot."""

    __slots__ = ("req", "last_token", "position", "generated", "t_admit")

    def __init__(self, req: _GenRequest, last_token: int, position: int,
                 generated: int, t_admit: float):
        self.req = req
        self.last_token = last_token
        self.position = position          # cache row the NEXT token writes
        self.generated = generated
        self.t_admit = t_admit


class _Core:
    """State shared between the engine facade and the loop thread:
    request/stream bookkeeping only, nothing heavy — so the loop can
    fail every stream and exit even if the facade (holding params,
    cache, and the jitted kernels) has been garbage-collected."""

    __slots__ = ("cond", "pending", "active", "free", "closed", "drain")

    def __init__(self, max_slots: int):
        self.cond = threading.Condition()
        self.pending: "deque[_GenRequest]" = deque()
        self.active: Dict[int, _SlotState] = {}
        self.free: List[int] = list(range(max_slots))
        self.closed = False
        self.drain = True


def _fail_streams(core: _Core, error: BaseException) -> None:
    with core.cond:
        reqs = list(core.pending) + [s.req for s in core.active.values()]
        core.pending.clear()
        core.free.extend(core.active.keys())
        core.active.clear()
    for r in reqs:
        if not r.stream.done:
            r.stream._finish(error)


def _engine_loop(engine_ref: "weakref.ref[GenerationEngine]",
                 core: _Core) -> None:
    """Loop thread body. Holds only a weak ref to the engine while idle
    (same discipline as the batcher worker): an engine whose owner
    forgot ``close()`` becomes collectable and the loop exits, failing
    any stranded streams, instead of pinning params + KV cache forever."""
    while True:
        with core.cond:
            while not core.pending and not core.active and not core.closed:
                core.cond.wait(timeout=0.05)
                if engine_ref() is None:
                    break
            if core.closed:
                if not core.drain:
                    _fail_streams(core, RuntimeError(
                        "generation engine closed before request ran"))
                    return
                if not core.pending and not core.active:
                    return
        engine = engine_ref()
        if engine is None:
            _fail_streams(core, RuntimeError(
                "generation engine was garbage-collected with requests "
                "in flight"))
            return
        try:
            engine._step()
        except Exception as e:
            # a broken step cannot be retried: the donated cache may be
            # consumed — fail every stream loudly and stop the loop
            engine._failed = e
            log.exception("generation engine step failed; engine stopped")
            _fail_streams(core, e)
            return
        del engine


class GenerationEngine:
    """Continuous-batching generation front door over one decode-capable
    model (``init_cache`` / ``prefill`` / ``decode_step`` — see
    ``nn.Transformer``).

    ``submit(prompt, max_new_tokens=..., deadline=...)`` returns a
    :class:`GenerationStream`; a persistent loop thread admits pending
    prompts into free slots between decode steps, decodes every active
    slot per iteration, and retires finished sequences mid-flight.
    Admission control mirrors :class:`InferenceService`: a full pending
    queue raises :class:`Overloaded` on the caller's thread.

    ``warmup()`` compiles the decode step (once — its shapes never
    change) and every prompt bucket; call it before traffic so no
    request pays a compile. ``reload(params)`` swaps weights atomically
    between steps (see the hot-reload satellite).
    """

    def __init__(self, model, params, *, max_slots: int = 8,
                 max_len: int = 256, max_prompt_len: Optional[int] = None,
                 eos_id: Optional[int] = None, pad_id: int = 0,
                 max_queue: int = 64,
                 metrics: Optional[ServingMetrics] = None,
                 cache_dtype=jnp.float32,
                 kernels: Optional[DecodeKernels] = None):
        if max_slots < 1:
            raise ValueError("max_slots must be >= 1")
        if max_len < 2:
            raise ValueError("max_len must be >= 2 (prompt + 1 token)")
        self.model = model
        self.max_slots = int(max_slots)
        self.max_len = int(max_len)
        self.max_prompt_len = int(max_prompt_len or max(1, max_len // 2))
        if not 1 <= self.max_prompt_len < self.max_len:
            raise ValueError(
                f"max_prompt_len {self.max_prompt_len} must be in "
                f"[1, max_len) = [1, {self.max_len})")
        self.eos_id = None if eos_id is None else int(eos_id)
        self.pad_id = int(pad_id)
        self.max_queue = int(max_queue)
        self.metrics = metrics or ServingMetrics()
        self.prompt_buckets = bucket_sizes_for(self.max_prompt_len)
        self.kernels = kernels or DecodeKernels(model)
        self._params = params
        self._cache = model.init_cache(self.max_slots, self.max_len,
                                       cache_dtype)
        self._failed: Optional[BaseException] = None
        self._core = _Core(self.max_slots)
        self._thread = threading.Thread(
            target=_engine_loop, args=(weakref.ref(self), self._core),
            name="bigdl-serving-engine", daemon=True)
        self._thread.start()

    # ------------------------------------------------------ submission ----

    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: Optional[int] = None,
               deadline: Optional[float] = None) -> GenerationStream:
        """Enqueue one prompt (sequence of token ids). ``max_new_tokens``
        caps generation (default: whatever fits in ``max_len``);
        ``deadline`` is seconds from now — an expired request retires
        mid-flight with :class:`DeadlineExceeded` on its stream. Raises
        :class:`Overloaded` when the pending queue is at its bound."""
        prompt = [int(t) for t in np.asarray(prompt).reshape(-1)]
        if not prompt:
            raise ValueError("empty prompt")
        if len(prompt) > self.max_prompt_len:
            raise ValueError(
                f"prompt length {len(prompt)} exceeds max_prompt_len "
                f"{self.max_prompt_len}")
        room = self.max_len - len(prompt)
        mnt = room if max_new_tokens is None else min(int(max_new_tokens), room)
        if mnt < 1:
            raise ValueError("no room to generate even one token")
        stream = GenerationStream()
        now = stream.t_submit
        req = _GenRequest(prompt, mnt,
                          None if deadline is None else now + float(deadline),
                          stream)
        core = self._core
        with core.cond:
            if self._failed is not None:
                raise RuntimeError(
                    "generation engine stopped after a step failure"
                ) from self._failed
            if core.closed:
                raise RuntimeError("generation engine is closed")
            if len(core.pending) >= self.max_queue:
                self.metrics.record_rejected()
                raise Overloaded(len(core.pending), self.max_queue)
            core.pending.append(req)
            depth = len(core.pending)
            core.cond.notify_all()
        self.metrics.set_queue_depth(depth)
        return stream

    def generate(self, prompt: Sequence[int], *,
                 max_new_tokens: Optional[int] = None,
                 deadline: Optional[float] = None,
                 timeout: Optional[float] = None) -> List[int]:
        """Blocking convenience: ``submit(...).result(timeout)``."""
        return self.submit(prompt, max_new_tokens=max_new_tokens,
                           deadline=deadline).result(timeout)

    # ------------------------------------------------- loop internals ----
    # Everything below here runs on the loop thread only (except warmup,
    # which the caller must run before traffic).

    def _step(self) -> None:
        """One scheduler iteration: admit pending prompts into free slots,
        then one decode step over every active slot."""
        core = self._core
        while True:
            with core.cond:
                if not core.pending or not core.free:
                    break
                req = core.pending.popleft()
                depth = len(core.pending)
            self.metrics.set_queue_depth(depth)
            self._admit(req)
        with core.cond:
            active = sorted(core.active.items())
        if active:
            self._decode_once(active)

    def _admit(self, req: _GenRequest) -> None:
        now = time.monotonic()
        why = self._retire_why(None, req, now)
        if why is not None:
            self._finish_request(req, why, now, queue_wait=None)
            return
        core = self._core
        with core.cond:
            core.free.sort()
            slot = core.free.pop(0)
        n = len(req.prompt)
        bucket = next(b for b in self.prompt_buckets if b >= n)
        padded = np.full((bucket,), self.pad_id, np.int32)
        padded[:n] = req.prompt
        tok_dev, self._cache = self.kernels.prefill(
            self._params, self._cache, slot, padded, n)
        tok = int(np.asarray(tok_dev))
        now = time.monotonic()
        self.metrics.record_prefill(n, bucket, now - req.stream.t_submit)
        req.stream._push(tok, now)
        st = _SlotState(req, tok, n, 1, now)
        why = self._retire_why(st, req, now)
        if why is None:
            with core.cond:
                core.active[slot] = st
        else:
            with core.cond:
                core.free.append(slot)
            self._finish_slot(st, why, now)

    def _decode_once(self, active: List[Tuple[int, _SlotState]]) -> None:
        tokens = np.zeros((self.max_slots,), np.int32)
        positions = np.zeros((self.max_slots,), np.int32)
        for slot, st in active:
            tokens[slot] = st.last_token
            positions[slot] = st.position
        toks_dev, self._cache = self.kernels.decode(
            self._params, self._cache, tokens, positions)
        toks = np.asarray(toks_dev)
        now = time.monotonic()
        self.metrics.record_decode_step(len(active), self.max_slots)
        retired = []
        for slot, st in active:
            tok = int(toks[slot])
            st.last_token = tok
            st.position += 1
            st.generated += 1
            st.req.stream._push(tok, now)
            why = self._retire_why(st, st.req, now)
            if why is not None:
                retired.append((slot, st, why))
        if retired:
            core = self._core
            with core.cond:
                for slot, _, _ in retired:
                    core.active.pop(slot, None)
                    core.free.append(slot)
            for _, st, why in retired:
                self._finish_slot(st, why, now)

    def _retire_why(self, st: Optional[_SlotState], req: _GenRequest,
                    now: float) -> Optional[str]:
        """Retirement disposition, or None to keep decoding. Order:
        explicit cancel wins, a normally-completed sequence beats a
        deadline that expired on the same step."""
        if req.stream.cancelled:
            return "cancelled"
        if st is not None:
            if self.eos_id is not None and st.last_token == self.eos_id:
                return "done"
            if st.generated >= req.max_new_tokens:
                return "done"
            if st.position >= self.max_len:
                return "done"
        if req.deadline is not None and now > req.deadline:
            return "expired"
        return None

    def _finish_slot(self, st: _SlotState, why: str, now: float) -> None:
        self._finish_request(st.req, why, now,
                             queue_wait=st.t_admit - st.req.stream.t_submit,
                             generated=st.generated)

    def _finish_request(self, req: _GenRequest, why: str, now: float, *,
                        queue_wait: Optional[float],
                        generated: int = 0) -> None:
        stream = req.stream
        dur = now - stream.t_submit
        if why == "expired":
            self.metrics.record_expired()
            stream._finish(DeadlineExceeded(
                dur, req.deadline - stream.t_submit), now)
        elif why == "cancelled":
            stream._finish(StreamCancelled(
                "generation stream cancelled by its consumer"), now)
        else:
            self.metrics.record_served(dur, queue_wait or 0.0)
            self.metrics.record_stream(generated, dur)
            stream._finish(None, now)

    # -------------------------------------------------------- lifecycle ----

    def warmup(self) -> None:
        """Compile the decode step and every prompt-bucket prefill BEFORE
        traffic arrives. Must run before the first submit (it touches the
        cache from the caller's thread); the garbage keys it writes are
        causally invisible and overwritten by real admissions."""
        core = self._core
        with core.cond:
            if core.pending or core.active:
                raise RuntimeError("warmup() must run before traffic")
        _, self._cache = self.kernels.decode(
            self._params, self._cache,
            np.zeros((self.max_slots,), np.int32),
            np.zeros((self.max_slots,), np.int32))
        for bucket in self.prompt_buckets:
            _, self._cache = self.kernels.prefill(
                self._params, self._cache, 0,
                np.full((bucket,), self.pad_id, np.int32), bucket)
        jax.block_until_ready(self._cache)

    def reload(self, params, state: Any = None) -> None:
        """Swap decode params atomically between steps: a decode/prefill
        call reads ``self._params`` exactly once, so every step sees one
        consistent tree — never torn halves. Signature-checked: matching
        shapes/dtypes mean the jitted step is NOT recompiled. ``state``
        is accepted for :func:`watch_checkpoints` symmetry but must be
        empty — incremental decode is stateless."""
        from bigdl_tpu.serving.service import require_matching_signature

        if state:
            raise ValueError(
                "GenerationEngine.reload takes params only: incremental "
                "decode runs stateless (no BN-style buffers)")
        require_matching_signature("params", self._params, params)
        # device_put once: host arrays would re-transfer every step and
        # miss the jit cache (uncommitted args key a different executable)
        self._params = jax.device_put(params)
        self.metrics.record_reload()

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Stop admitting; with ``drain`` (default) the loop keeps
        stepping until every pending and in-flight stream finishes,
        otherwise they fail with ``RuntimeError``."""
        core = self._core
        with core.cond:
            core.closed = True
            core.drain = drain
            core.cond.notify_all()
        self._thread.join(timeout)
        if not self._thread.is_alive():
            # the loop has exited: a request that raced the close flag in
            # must fail rather than strand its consumer. NOT safe while
            # the loop lives (a timed-out drain join) — it would fail
            # streams the loop is still legitimately serving and
            # double-free their slots mid-step.
            _fail_streams(core, RuntimeError(
                "generation engine closed before request ran"))

    def __enter__(self) -> "GenerationEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # --------------------------------------------------------- queries ----

    @property
    def active_slots(self) -> int:
        with self._core.cond:
            return len(self._core.active)

    @property
    def pending_requests(self) -> int:
        with self._core.cond:
            return len(self._core.pending)

    @property
    def free_slots(self) -> List[int]:
        with self._core.cond:
            return sorted(self._core.free)

    @property
    def decode_compilations(self) -> int:
        return self.kernels.decode_traces

    @property
    def prefill_compilations(self) -> int:
        return self.kernels.prefill_traces


def static_generate(model, params, requests, *, max_slots: int,
                    max_len: int, eos_id: Optional[int] = None,
                    pad_id: int = 0, cache_dtype=jnp.float32,
                    kernels: Optional[DecodeKernels] = None,
                    prompt_buckets: Optional[Sequence[int]] = None):
    """Run-to-completion static batching BASELINE over the same jitted
    kernels the engine uses: admit ``max_slots`` requests, decode until
    EVERY one finishes (the longest sequence holds the whole batch
    hostage), only then admit the next group. ``requests`` is a sequence
    of ``(prompt, max_new_tokens)``; returns ``(token lists, decode
    steps executed)``. This is the comparison the bench/CI smoke gate
    runs — continuous batching must beat it on mixed lengths because it
    retires short sequences mid-flight instead of idling their slots."""
    kernels = kernels or DecodeKernels(model)
    requests = [([int(t) for t in p], int(m)) for p, m in requests]
    buckets = list(prompt_buckets
                   or bucket_sizes_for(max(len(p) for p, _ in requests)))
    cache = model.init_cache(max_slots, max_len, cache_dtype)
    outputs: List[Optional[List[int]]] = [None] * len(requests)
    total_steps = 0
    for base in range(0, len(requests), max_slots):
        group = requests[base:base + max_slots]
        states = []
        for slot, (prompt, mnt) in enumerate(group):
            n = len(prompt)
            bucket = next(b for b in buckets if b >= n)
            padded = np.full((bucket,), pad_id, np.int32)
            padded[:n] = prompt
            tok_dev, cache = kernels.prefill(params, cache, slot, padded, n)
            tok = int(np.asarray(tok_dev))
            target = min(mnt, max_len - n)
            states.append({
                "tokens": [tok], "last": tok, "pos": n,
                "target": target,
                "done": (eos_id is not None and tok == eos_id) or target <= 1,
            })
        while not all(s["done"] for s in states):
            tokens = np.zeros((max_slots,), np.int32)
            positions = np.zeros((max_slots,), np.int32)
            for slot, s in enumerate(states):
                tokens[slot] = s["last"]
                positions[slot] = s["pos"]
            toks_dev, cache = kernels.decode(params, cache, tokens, positions)
            toks = np.asarray(toks_dev)
            total_steps += 1
            for slot, s in enumerate(states):
                if s["done"]:
                    continue
                tok = int(toks[slot])
                s["tokens"].append(tok)
                s["last"] = tok
                s["pos"] += 1
                if ((eos_id is not None and tok == eos_id)
                        or len(s["tokens"]) >= s["target"]
                        or s["pos"] >= max_len):
                    s["done"] = True
        for i, s in enumerate(states):
            outputs[base + i] = s["tokens"]
    return outputs, total_steps
