"""ModelRouter — the multi-model serving front door.

One ``submit(model_name, x)`` surface dispatching to N registered
backends — :class:`~bigdl_tpu.serving.service.InferenceService` for
run-to-completion prediction, :class:`~bigdl_tpu.serving.engine.
GenerationEngine` for continuous-batching generation, or anything
duck-typing their ``submit``/``metrics``/``close`` trio. Each backend
keeps its own queue, batching policy, and compiled executables; the
router adds the cross-model concerns:

- **per-model in-flight quotas** — a saturated model rejects with
  :class:`Overloaded` (tagged with the model name) while every other
  model keeps serving; quotas are decremented when the future/stream
  completes, so they bound true in-flight work, not just queue depth;
- **typed routing errors** — an unregistered name raises
  :class:`UnknownModel` listing what IS available;
- **aggregate observability** — ``snapshot()`` and ``format_table()``
  fold every backend's :class:`ServingMetrics` into one per-model view.

The reference's analogue is one ``PredictionService`` per model with
client-side routing; here routing is server-side so quotas, metrics,
and lifecycle live in one place.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

from bigdl_tpu.serving.errors import Overloaded, UnknownModel
from bigdl_tpu.serving.replica import ReplicaSet

_SNAP_COLS = ("served", "rejected", "expired", "failed", "tokens_out")


class _Backend:
    __slots__ = ("backend", "max_inflight", "inflight", "owned")

    def __init__(self, backend, max_inflight: Optional[int], owned: bool):
        self.backend = backend
        self.max_inflight = max_inflight
        self.inflight = 0
        self.owned = owned


class ModelRouter:
    """Multi-model front door over named serving backends.

    ``register`` is cheap and can happen while traffic flows to other
    models; ``close()`` closes every backend registered with
    ``owned=True`` (the default) — pass ``owned=False`` for backends
    whose lifecycle someone else manages.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._backends: Dict[str, _Backend] = {}
        self._closed = False

    # ----------------------------------------------------- registry ----

    def register(self, name: str, backend, *,
                 max_inflight: Optional[int] = None,
                 owned: bool = True, **replica_kw) -> "ModelRouter":
        """Add a backend under ``name``. ``max_inflight`` bounds
        concurrently outstanding requests for THIS model (None =
        unbounded at the router; the backend's own queue still applies).
        A LIST of backends registers as one
        :class:`~bigdl_tpu.serving.replica.ReplicaSet` transparently —
        the model name then resolves to N replicas behind the same
        ``submit`` signature (extra keyword args configure the set, e.g.
        ``max_failures`` / ``probe``). Returns self for chaining."""
        if max_inflight is not None and max_inflight < 1:
            raise ValueError("max_inflight must be >= 1 (or None)")
        if isinstance(backend, (list, tuple)):
            if not owned:
                # the router builds the set right here, so "someone else
                # manages its lifecycle" can't be true: an unowned set
                # would leak its prober thread and member engines forever
                raise ValueError(
                    "a list of backends registers as a router-owned "
                    "ReplicaSet; construct the ReplicaSet yourself to "
                    "manage its lifecycle (owned=False)")
            backend = ReplicaSet(list(backend), name=name, **replica_kw)
        elif replica_kw:
            raise TypeError(
                f"unexpected arguments {sorted(replica_kw)}: replica "
                f"options apply only when registering a list of backends")
        with self._lock:
            if self._closed:
                raise RuntimeError("router is closed")
            if name in self._backends:
                raise ValueError(f"model '{name}' already registered")
            self._backends[name] = _Backend(backend, max_inflight, owned)
        return self

    def unregister(self, name: str, *, close: bool = False):
        """Remove ``name``; with ``close`` also close the backend.
        In-flight requests already submitted keep running."""
        with self._lock:
            b = self._backends.pop(name, None)
        if b is None:
            raise UnknownModel(name, self.names())
        if close:
            b.backend.close()
        return b.backend

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._backends)

    def backend(self, name: str):
        with self._lock:
            b = self._backends.get(name)
        if b is None:
            raise UnknownModel(name, self.names())
        return b.backend

    # ----------------------------------------------------- dispatch ----

    def submit(self, model_name: str, x, **kwargs):
        """Route one request: returns whatever the backend's ``submit``
        returns (a ``Future`` for an InferenceService, a
        ``GenerationStream`` for a GenerationEngine) — extra kwargs
        (``deadline``, ``max_new_tokens``, ...) pass straight through.
        Raises :class:`UnknownModel` for unregistered names and
        :class:`Overloaded` (with the model name) at the quota."""
        with self._lock:
            if self._closed:
                raise RuntimeError("router is closed")
            b = self._backends.get(model_name)
            if b is None:
                raise UnknownModel(model_name, sorted(self._backends))
            if b.max_inflight is not None and b.inflight >= b.max_inflight:
                metrics = getattr(b.backend, "metrics", None)
                if metrics is not None:
                    # the backend never sees a quota-shed request: count
                    # it here so `rejected` means "shed load" regardless
                    # of WHICH bound (queue or quota) did the shedding
                    metrics.record_rejected()
                raise Overloaded(b.inflight, b.max_inflight,
                                 model=model_name)
            # count BEFORE submitting: two racing submits must not both
            # slip under the quota, and the done-callback may fire on
            # another thread the instant submit returns
            b.inflight += 1

        # idempotent, exception-safe release: exactly one decrement per
        # submission, whoever fires it and however often. A backend whose
        # close(drain=False) races a completion (replica eviction fails
        # the same futures the loop is finishing) may invoke done
        # callbacks more than once, and a broken handle may reject the
        # callback outright — neither may leak or double-release the
        # quota slot, or the model jams shut / overshoots its bound.
        released = [False]

        def release_once(_h=None):
            with self._lock:
                if released[0]:
                    return
                released[0] = True
                b.inflight -= 1

        try:
            handle = b.backend.submit(x, **kwargs)
        except BaseException:
            release_once()
            raise
        # trace context rides the handle (obs tier): the router stamps
        # the model name onto whatever trace the backend started
        tr = getattr(handle, "trace", None)
        if tr is not None:
            tr.annotate(model=model_name)
        try:
            handle.add_done_callback(release_once)
        except BaseException:
            release_once()
            raise
        return handle

    def predict(self, model_name: str, x,
                timeout: Optional[float] = None, **kwargs):
        """Blocking convenience: ``submit(...).result(timeout)`` —
        works for both futures and generation streams."""
        return self.submit(model_name, x, **kwargs).result(timeout)

    # ------------------------------------------------ observability ----

    def inflight(self, name: str) -> int:
        with self._lock:
            b = self._backends.get(name)
        if b is None:
            raise UnknownModel(name, self.names())
        return b.inflight

    def snapshot(self) -> Dict[str, dict]:
        """Per-model dict: router-level in-flight/quota plus the
        backend's full metrics snapshot."""
        with self._lock:
            items = list(self._backends.items())
        out: Dict[str, dict] = {}
        for name, b in items:
            snap = b.backend.metrics.snapshot()
            snap["inflight"] = b.inflight
            snap["max_inflight"] = b.max_inflight
            out[name] = snap
        return out

    def format_table(self) -> str:
        """One row per model: the cross-model counters plus p99 latency
        (per-backend detail lives in each backend's own table)."""
        snaps = self.snapshot()
        header = (f"{'model':<16} {'inflight':>8} {'quota':>6} "
                  + " ".join(f"{c:>9}" for c in _SNAP_COLS)
                  + f" {'p99_ms':>9}")
        lines = [header]
        for name in sorted(snaps):
            s = snaps[name]
            quota = s["max_inflight"]
            lat = s.get("latency_ms") or {}
            lines.append(
                f"{name:<16} {s['inflight']:>8} "
                f"{'-' if quota is None else quota:>6} "
                + " ".join(f"{s.get(c, 0):>9}" for c in _SNAP_COLS)
                + f" {lat.get('p99', float('nan')):>9.3f}")
        return "\n".join(lines)

    # ----------------------------------------------------- lifecycle ----

    def close(self, drain: bool = True) -> None:
        """Close every OWNED backend (drain by default) and refuse new
        traffic. Foreign (``owned=False``) backends are left running."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
            backends = list(self._backends.values())
        for b in backends:
            if b.owned:
                b.backend.close(drain=drain)

    def __enter__(self) -> "ModelRouter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
