"""Host-side page accounting for the paged KV cache.

The device side is dumb on purpose — per layer, K/V pools of shape
``(num_pages + 1, heads, page_size, head_dim)`` and int32 page-id arrays
(see ``nn.Transformer.init_paged_cache``). ALL allocation policy lives
here, on the host, between decode steps: which physical pages a sequence
owns, when they are reserved, when they return to the free list. That
split keeps the jitted kernels shape-stable (compile-once survives any
allocation pattern) and makes the allocator trivially testable.

Policy notes:

- **full reservation at admission.** A request needs pages for
  ``prompt_len + max_new_tokens - 1`` rows (the last generated token is
  never written back); all of them are reserved up front. Memory still
  scales with the request's ACTUAL budget instead of ``max_len`` — the
  capacity lever — while mid-flight page exhaustion (which would force
  vLLM-style preemption/recompute) becomes impossible by construction.
  Early retirement (EOS, deadline, cancel) returns the unused tail.
- **smallest-id-first.** Frees push onto a heap, allocations pop the
  smallest ids: the allocation sequence is a pure function of the
  admission/retirement sequence, which the determinism tests lean on
  (and fragmented maps stay cheap to eyeball in a debugger).
- **one trash page.** Physical page ``num_pages`` exists in the pools
  but never in the free list: bucket-padding writes and freed slots'
  map rows point there, so garbage can never land in a page another
  sequence owns. Its contents are arbitrary and always masked.
- **refcounted read-only sharing (PR 12).** Every reserved page carries
  a reference count: ``alloc`` starts it at 1, :meth:`share` adds a
  reference (the prefix cache publishing a page, or a request attaching
  a cached prefix page), ``release`` drops one — a page returns to the
  free heap ONLY when its last reference goes, so a shared page can
  never be handed to a new owner while somebody still reads it.
  ``in_use`` and the per-owner gauges count DISTINCT pages (a page
  shared by three requests is charged once, to its original alloc
  owner), which keeps every drain invariant byte-exact under sharing.
- **cross-pool handoff (PR 15).** Prefill/decode disaggregation moves
  finished prompt pages between two pools. :meth:`export_pages` is the
  sending side: it drops the exporting request's references (a prefix
  cache holding its own reference keeps the page alive for the NEXT
  request) and counts the handoff. :meth:`adopt_pages` is the receiving
  side: each source page is identified by ``(source tag, page id, write
  generation)`` — the generation bumps on every ``alloc``, so a source
  page id that was freed and refilled with different tokens can never
  alias a stale import. The first adoption of an identity allocates a
  fresh local page (the caller copies the rows in); a repeat adoption
  while that local page is still live just :meth:`share`\\ s it, which
  is how shared prefix pages cross the handoff WITHOUT being charged
  twice in ``in_use``. The import index is unwound eagerly when the
  local page's last reference goes, so it can never point at a freed
  or recycled page.
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np  # module-level on purpose: page_bytes sits on the
# hot metrics path (one call per kv_bytes_in_use gauge read) — a
# function-local import would re-run the sys.modules lookup per read


def page_bytes(page_size: int, num_heads: int, head_dim: int,
               cache_dtype="float32") -> int:
    """Bytes ONE physical KV page costs per layer: the K and V pages
    plus, for ``int8``, the per-token fp32 scale-pool rows that ride
    alongside them (``nn.Transformer.init_paged_cache``). The ONE place
    the dtype-aware byte accounting lives — the engine's
    ``kv_bytes_in_use`` gauge and the bench capacity column both read
    it, so int8-vs-bf16 capacity claims price the scale overhead
    honestly instead of pretending pages are free to describe."""
    if np.dtype(cache_dtype) == np.int8:
        per_row = num_heads * head_dim * 1 + 4       # int8 row + f32 scale
    else:
        per_row = num_heads * head_dim * np.dtype(cache_dtype).itemsize
    return 2 * page_size * per_row                   # K and V


def pages_per_lane(max_len: int, page_size: int) -> int:
    """Logical pages covering one full-length lane (ceil division). The
    ONE place this rounding lives — the engine, static baseline, and
    bench capacity math all read it from here (or from a pool's
    ``pages_per_slot``), so the allocator and its accountants can never
    disagree."""
    if page_size < 1:
        raise ValueError("page_size must be >= 1")
    return -(-int(max_len) // int(page_size))


class PagePool:
    """Free-list allocator over ``num_pages`` usable KV pages."""

    def __init__(self, num_pages: int, page_size: int, max_len: int):
        if num_pages < 1:
            raise ValueError("num_pages must be >= 1")
        if page_size < 1:
            raise ValueError("page_size must be >= 1")
        self.num_pages = int(num_pages)
        self.page_size = int(page_size)
        # logical pages per slot: every page map row has this many ids
        self.pages_per_slot = pages_per_lane(max_len, self.page_size)
        # the extra physical page all masked writes are routed to
        self.trash = self.num_pages
        self._free: List[int] = list(range(self.num_pages))
        heapq.heapify(self._free)
        self.in_use = 0  # peak tracking lives in ServingMetrics.set_pages
        # owner tag per reserved page id (only for tagged allocs): one
        # slot may hold SEVERAL reservations — a speculative engine
        # reserves a target lane and a draft lane side by side — and the
        # drain invariants ("every lane returned") need to be assertable
        # per owner, not just in aggregate. release() looks the tag up
        # by page id, so callers cannot desync the per-owner gauges by
        # forgetting to repeat the tag.
        self._page_owner: Dict[int, str] = {}
        self._owner_counts: Dict[str, int] = {}
        # reference count per RESERVED page (absent = free). alloc sets
        # 1; share() adds; release() subtracts and frees at zero — the
        # prefix cache's read-only page sharing rides on this.
        self._refs: Dict[int, int] = {}
        # write generation per page id: bumped on every alloc. Part of
        # the cross-pool page identity — a freed-and-refilled page gets
        # a new generation, so adopt-side dedup can never match stale
        # content under a recycled id.
        self._generation: Dict[int, int] = {}
        # adopt-side import index: (source tag, source page, source
        # generation) -> local page, plus the reverse map release()
        # uses to unwind entries the moment the local page frees.
        self._imports: Dict[Tuple[str, int, int], int] = {}
        self._import_by_dst: Dict[int, Tuple[str, int, int]] = {}
        self.pages_exported = 0      # pages handed to another pool
        self.pages_adopted = 0       # fresh local pages from adoption
        self.pages_adopt_shared = 0  # adoptions served by a live import

    def pages_for(self, n_tokens: int) -> int:
        """Pages needed to hold ``n_tokens`` KV rows (>= 1)."""
        return max(1, -(-int(n_tokens) // self.page_size))

    def can_reserve(self, n: int) -> bool:
        return len(self._free) >= n

    def alloc(self, n: int, owner: Optional[str] = None) -> List[int]:
        """Reserve ``n`` pages (smallest ids first), optionally tagged
        with an ``owner`` label (e.g. ``"target"`` / ``"draft"`` lanes).
        Raises if the pool cannot satisfy the request — callers gate on
        :meth:`can_reserve` at admission, so this firing means an
        accounting bug."""
        if n > len(self._free):
            raise RuntimeError(
                f"page pool exhausted: want {n}, have {len(self._free)} "
                f"of {self.num_pages}")
        pages = [heapq.heappop(self._free) for _ in range(n)]
        self.in_use += n
        for p in pages:
            self._refs[p] = 1
            self._generation[p] = self._generation.get(p, 0) + 1
        if owner is not None:
            for p in pages:
                self._page_owner[p] = owner
            self._owner_counts[owner] = (
                self._owner_counts.get(owner, 0) + n)
        return pages

    def share(self, pages: Sequence[int]) -> None:
        """Add one reference to each (already reserved) page. The page
        keeps its original owner tag and stays charged ONCE in
        ``in_use`` / the per-owner gauges — sharing is free to account.
        Raises on a free page: a reference to memory nobody reserved is
        exactly the use-after-free the refcount exists to prevent."""
        for p in pages:
            p = int(p)
            refs = self._refs.get(p, 0)
            if refs < 1:
                raise RuntimeError(
                    f"page {p} is not reserved; share() can only add "
                    f"references to live pages")
            self._refs[p] = refs + 1

    def release(self, pages: Sequence[int]) -> None:
        """Drop one reference per page; a page returns to the free heap
        (and its owner gauge) only when the LAST reference goes — a
        shared page is never handed back while referenced."""
        for p in pages:
            p = int(p)
            refs = self._refs.get(p)
            if refs is None:
                raise RuntimeError(
                    f"page {p} released while not reserved (double "
                    f"release, or a page id that never came from alloc)")
            if refs > 1:
                self._refs[p] = refs - 1
                continue
            del self._refs[p]
            heapq.heappush(self._free, p)
            self.in_use -= 1
            owner = self._page_owner.pop(p, None)
            if owner is not None:
                self._owner_counts[owner] -= 1
            key = self._import_by_dst.pop(p, None)
            if key is not None:
                del self._imports[key]

    def generation(self, page: int) -> int:
        """Write generation of ``page`` (0 = never allocated). Bumped on
        every :meth:`alloc`, so ``(pool tag, page id, generation)``
        names the page's CONTENT, not just its slot — the identity
        :meth:`adopt_pages` dedups on across a role handoff."""
        return self._generation.get(int(page), 0)

    def export_pages(self, pages: Sequence[int]) -> None:
        """Hand ``pages`` to another pool: the exporting request's rows
        have already been gathered device-side, so its references are
        dropped exactly like :meth:`release` — a prefix cache that also
        references a page keeps it alive for the next attach; everything
        else returns to the free heap. Only the ``pages_exported``
        counter distinguishes a handoff from a plain retirement."""
        self.pages_exported += len(pages)
        self.release(pages)

    def adopt_pages(self, meta: Sequence[Tuple[int, int, int]], *,
                    source: str, owner: Optional[str] = None) -> List[int]:
        """Receive exported pages described by ``meta`` rows of
        ``(source page id, source write generation, shareable)`` and
        return the local page per row, in order. A ``shareable`` row
        (a FULL prompt page — partial tail pages keep taking decode
        writes and are never dedupable) first probes the import index:
        a live hit is :meth:`share`\\ d — charged once in ``in_use``, to
        its original adopter — which is how a prefix shared by N
        requests crosses the handoff as ONE local page. Misses (and
        non-shareable rows) allocate fresh pages for the caller to
        scatter the rows into; scattering a dedup hit again is benign
        by construction — pages are pure functions of their tokens, so
        the rewrite is bit-identical. Callers gate admission on
        :meth:`can_reserve` for the FULL page count, so the partial
        allocation inside cannot fail mid-way."""
        out: List[int] = []
        for src_page, src_gen, shareable in meta:
            key = (str(source), int(src_page), int(src_gen))
            dst = self._imports.get(key) if shareable else None
            if dst is not None:
                # eager unwind at free keeps the index live-only, so a
                # hit is always a reserved page holding matching rows
                self.share([dst])
                self.pages_adopt_shared += 1
            else:
                dst = self.alloc(1, owner=owner)[0]
                self.pages_adopted += 1
                if shareable:
                    self._imports[key] = dst
                    self._import_by_dst[dst] = key
            out.append(dst)
        return out

    def refcount(self, page: int) -> int:
        """Live references on ``page`` (0 = free). The prefix cache's
        eviction gate: only pages it alone references (refcount == 1
        from the cache's own share) may be evicted to the free heap."""
        return self._refs.get(int(page), 0)

    def in_use_by(self, owner: str) -> int:
        """Reserved pages currently tagged ``owner`` (0 for unknown
        owners) — the per-lane drain gauge the speculative tests pin."""
        return self._owner_counts.get(owner, 0)

    def snapshot(self) -> dict:
        """Occupancy + per-owner gauges for the obs registry — the
        host-side allocator truth the engine's metrics gauges derive
        from. Read-only over plain ints/dicts (the engine loop owns all
        mutation), so a scrape from another thread is safe."""
        return {
            "pages_total": self.num_pages,
            "pages_in_use": self.in_use,
            "pages_free": len(self._free),
            "page_size": self.page_size,
            "pages_per_slot": self.pages_per_slot,
            "by_owner": {k: v for k, v in sorted(self._owner_counts.items())
                         if v},
            # pages currently multi-referenced (prefix-cache sharing);
            # appended after every earlier key (append-only contract)
            "pages_shared": sum(1 for r in self._refs.values() if r >= 2),
            # PR 15 disaggregation handoff counters (append-only)
            "pages_exported": self.pages_exported,
            "pages_adopted": self.pages_adopted,
            "pages_adopt_shared": self.pages_adopt_shared,
            # PR 18 tier dimension (append-only): this pool is always
            # the DEVICE side of the two-tier hierarchy; the host side
            # (serving.kv_tiers.HostPageStore) reports the same gauge
            # shape with tier="host", so per-owner/occupancy scrapes
            # join on it
            "tier": "hbm",
        }

    @property
    def free_pages(self) -> int:
        return len(self._free)
