"""Length-prefixed socket wire format for the cross-process serving
fabric (PR 14).

One frame carries one message — a JSON-able header tree plus zero or
more binary segments the header references by index:

``uint8 fmt | uint32 header_len | header | uint32 nseg | (uint64 len + bytes)*``

``fmt`` selects the header codec: ``1`` = msgpack when the baked-in
wheel is importable, ``0`` = json otherwise (a msgpack client can talk
to a json server and vice versa — the receiver honours the frame's own
byte, so mixed fleets never negotiate). All integers are big-endian,
the same ``struct`` framing discipline as
:class:`~bigdl_tpu.dataset.feeder.SocketFeedDataSet`.

The header tree is the uniform encoding of an arbitrary payload pytree:

- numpy arrays (and anything ``__array__``-able: jax arrays, scalars
  with dtype) become ``{"__a__": i}`` referencing segment ``i``, an
  ``.npy`` blob (``allow_pickle=False`` both ways — the wire never
  executes pickle), so tensors round-trip BIT-identically;
- raw ``bytes`` become ``{"__b__": i}``;
- tuples become ``{"__t__": [...]}`` (json would flatten them to
  lists, and pytree structure is part of the serving signature);
- EVERY dict becomes ``{"__m__": [[k, v], ...]}`` — uniform, so user
  dicts can never collide with the marker keys and non-string keys
  survive json;
- ``None``/bool/int/float/str pass through, lists recurse, numpy
  scalars decay to Python scalars.

Exceptions cross the wire as ``{"__exc__": ...}`` records holding the
class name, module, and the constructor args needed to REBUILD the
original type: the serving taxonomy (:class:`Overloaded`,
:class:`DeadlineExceeded`, ...), :class:`~bigdl_tpu.faults.InjectedFault`,
and plain builtins (``ValueError`` et al.) all reconstruct exactly, so
the front-door error contract survives process boundaries. Anything
unknown (or whose constructor rejects the recorded args) degrades to
:class:`~bigdl_tpu.serving.errors.RemoteError` — legible, never lossy
about the remote class name, never a pickle."""

from __future__ import annotations

import builtins
import io
import socket
import struct
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu.serving.errors import (
    DeadlineExceeded,
    Overloaded,
    RemoteError,
    ReplicaUnavailable,
    ServingError,
    StreamCancelled,
    TransportError,
    UnknownModel,
)

try:  # baked into the image; json is the always-there fallback
    import msgpack as _msgpack
except ImportError:  # pragma: no cover - exercised via _FMT_JSON paths
    _msgpack = None
import json as _json

MAGIC = b"BTRP\x01"          # handshake: 4-byte tag + wire version
_FMT_JSON = 0
_FMT_MSGPACK = 1
_U8 = struct.Struct(">B")
_U32 = struct.Struct(">I")
_U64 = struct.Struct(">Q")
MAX_HEADER = 64 << 20        # a corrupt length prefix fails fast,
MAX_SEGMENT = 1 << 32        # not as a multi-GB allocation


# ------------------------------------------------------------ payloads ----

def _encode(obj, segments: List[bytes]):
    """Payload tree -> json/msgpack-safe header tree + binary segments."""
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, (bytes, bytearray, memoryview)):
        segments.append(bytes(obj))
        return {"__b__": len(segments) - 1}
    if isinstance(obj, np.ndarray) or hasattr(obj, "__array__"):
        buf = io.BytesIO()
        np.save(buf, np.asarray(obj), allow_pickle=False)
        segments.append(buf.getvalue())
        return {"__a__": len(segments) - 1}
    if isinstance(obj, tuple):
        return {"__t__": [_encode(v, segments) for v in obj]}
    if isinstance(obj, list):
        return [_encode(v, segments) for v in obj]
    if isinstance(obj, dict):
        return {"__m__": [[_encode(k, segments), _encode(v, segments)]
                          for k, v in obj.items()]}
    if isinstance(obj, BaseException):
        return {"__exc__": _encode_exception(obj, segments)}
    raise TypeError(f"cannot encode {type(obj).__name__} for the rpc wire")


def _decode(obj, segments: List[bytes]):
    if isinstance(obj, list):
        return [_decode(v, segments) for v in obj]
    if isinstance(obj, dict):
        if "__a__" in obj:
            buf = io.BytesIO(segments[obj["__a__"]])
            return np.load(buf, allow_pickle=False)
        if "__b__" in obj:
            return segments[obj["__b__"]]
        if "__t__" in obj:
            return tuple(_decode(v, segments) for v in obj["__t__"])
        if "__m__" in obj:
            return {_decode(k, segments): _decode(v, segments)
                    for k, v in obj["__m__"]}
        if "__exc__" in obj:
            return decode_exception(obj["__exc__"], segments)
    return obj


# ---------------------------------------------------------- exceptions ----

# taxonomy classes whose __init__ signatures differ from their
# formatted-message args: record the REAL constructor args so the
# rebuilt instance carries the structured attributes, not just a string
_EXC_CTOR_ARGS = {
    "Overloaded": lambda e: (e.queue_depth, e.max_queue, e.model),
    "UnknownModel": lambda e: (e.name, e.available),
    "ReplicaUnavailable": lambda e: (e.name, e.replicas),
    "DeadlineExceeded": lambda e: (e.waited_s, e.deadline_s),
    "TransportError": lambda e: (str(e),),
    "RemoteError": lambda e: (e.remote_type, str(e)),
    "InjectedFault": lambda e: (e.site, e.call_index),
}


def _known_classes() -> Dict[str, type]:
    from bigdl_tpu.faults import InjectedFault, StallError

    known = {c.__name__: c for c in (
        ServingError, Overloaded, UnknownModel, ReplicaUnavailable,
        StreamCancelled, DeadlineExceeded, RemoteError, InjectedFault)}
    known["StallError"] = StallError
    return known


def _encode_exception(exc: BaseException, segments: List[bytes]) -> dict:
    name = type(exc).__name__
    extract = _EXC_CTOR_ARGS.get(name)
    if extract is not None:
        try:
            args = extract(exc)
        except AttributeError:
            extract, args = None, None
    if extract is None:
        args = exc.args
    try:
        enc_args = _encode(list(args), segments)
    except TypeError:
        enc_args = [str(exc)]
    return {"cls": name, "module": type(exc).__module__,
            "args": enc_args, "msg": str(exc)}


def decode_exception(rec: dict, segments: Optional[List[bytes]] = None
                     ) -> BaseException:
    """Rebuild a wire exception record as its original type where the
    type is trusted (serving taxonomy, InjectedFault/StallError, builtin
    exceptions); otherwise as :class:`RemoteError`. TransportError is
    deliberately NOT rebuilt as itself: a transport failure reported BY
    the peer is not a failure OF this hop's transport."""
    cls_name = rec.get("cls", "Exception")
    args = _decode(rec.get("args", []), segments or [])
    if not isinstance(args, list):
        args = [args]
    cls = None
    if cls_name != "TransportError":
        cls = _known_classes().get(cls_name)
    if cls is None and rec.get("module") == "builtins":
        cand = getattr(builtins, cls_name, None)
        if isinstance(cand, type) and issubclass(cand, Exception):
            cls = cand
    if cls is not None:
        try:
            return cls(*args)
        except Exception:
            pass
    return RemoteError(cls_name, rec.get("msg", ""))


def encode_exception(exc: BaseException) -> Tuple[dict, List[bytes]]:
    segments: List[bytes] = []
    return _encode_exception(exc, segments), segments


# -------------------------------------------------------------- frames ----

def pack_frame(tree: Any) -> bytes:
    """One message -> one length-prefixed byte string (ready for
    ``sendall``, or for the server's idempotency cache to replay)."""
    segments: List[bytes] = []
    header = _encode(tree, segments)
    if _msgpack is not None:
        fmt, raw = _FMT_MSGPACK, _msgpack.packb(header, use_bin_type=True)
    else:
        fmt, raw = _FMT_JSON, _json.dumps(header).encode("utf-8")
    parts = [_U8.pack(fmt), _U32.pack(len(raw)), raw,
             _U32.pack(len(segments))]
    for seg in segments:
        parts.append(_U64.pack(len(seg)))
        parts.append(seg)
    return b"".join(parts)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    """Read exactly n bytes or raise ConnectionError (same discipline as
    the feeder: a short read mid-frame is a dead peer, not data)."""
    chunks = []
    while n:
        chunk = sock.recv(min(n, 1 << 20))
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def recv_frame(sock: socket.socket) -> Any:
    """Read one frame and decode it back to the payload tree. Raises
    ``ConnectionError`` on EOF/short reads and ``TransportError`` on a
    malformed frame (bad codec byte, absurd lengths)."""
    fmt = _U8.unpack(_recv_exact(sock, 1))[0]
    hlen = _U32.unpack(_recv_exact(sock, 4))[0]
    if hlen > MAX_HEADER:
        raise TransportError(f"header length {hlen} exceeds {MAX_HEADER}")
    raw = _recv_exact(sock, hlen)
    if fmt == _FMT_MSGPACK:
        if _msgpack is None:
            raise TransportError("peer sent msgpack but msgpack is not "
                                 "importable here")
        header = _msgpack.unpackb(raw, raw=False, strict_map_key=False)
    elif fmt == _FMT_JSON:
        header = _json.loads(raw.decode("utf-8"))
    else:
        raise TransportError(f"unknown wire codec byte {fmt}")
    nseg = _U32.unpack(_recv_exact(sock, 4))[0]
    segments: List[bytes] = []
    for _ in range(nseg):
        slen = _U64.unpack(_recv_exact(sock, 8))[0]
        if slen > MAX_SEGMENT:
            raise TransportError(f"segment length {slen} exceeds "
                                 f"{MAX_SEGMENT}")
        segments.append(_recv_exact(sock, slen))
    return _decode(header, segments)


def send_frame(sock: socket.socket, tree: Any) -> None:
    sock.sendall(pack_frame(tree))


def client_handshake(sock: socket.socket) -> None:
    sock.sendall(MAGIC)
    echo = _recv_exact(sock, len(MAGIC))
    if echo != MAGIC:
        raise TransportError(f"bad handshake echo {echo!r}")


def server_handshake(sock: socket.socket) -> None:
    tag = _recv_exact(sock, len(MAGIC))
    if tag != MAGIC:
        raise TransportError(f"bad handshake tag {tag!r}")
    sock.sendall(MAGIC)
