"""SLO metrics for the serving tier.

Lock-protected counters plus bounded-reservoir latency histograms — the
serving analogue of ``utils/profiling.py``'s per-module wall-time table:
cheap enough to stay on in production (O(1) per request, fixed memory),
rich enough for the BENCH serving column (requests/sec, p50/p95/p99,
batch-size distribution, padding waste).
"""

from __future__ import annotations

import random
import threading
from typing import Dict, List, Optional

import numpy as np


class _Reservoir:
    """Fixed-size uniform sample of a stream (Vitter's algorithm R): the
    percentiles stay unbiased however long the service runs, with memory
    bounded at ``size`` floats. Caller holds the metrics lock."""

    def __init__(self, size: int, seed: int = 0):
        self.size = size
        self.seen = 0
        self.values: List[float] = []
        self._rng = random.Random(seed)

    def add(self, v: float) -> None:
        self.seen += 1
        if len(self.values) < self.size:
            self.values.append(v)
        else:
            j = self._rng.randrange(self.seen)
            if j < self.size:
                self.values[j] = v

    def percentiles(self, qs) -> Optional[List[float]]:
        if not self.values:
            return None
        return [float(p) for p in np.percentile(self.values, qs)]


class ServingMetrics:
    """Counters + histograms for one :class:`InferenceService`.

    All mutators take the internal lock; ``snapshot()`` returns a plain
    dict (JSON-able) and ``format_table()`` a fixed-width dump in the
    style of ``utils/profiling.format_times``.
    """

    LATENCY_QS = (50, 95, 99)

    def __init__(self, reservoir_size: int = 2048):
        self._lock = threading.Lock()
        self.served = 0
        self.rejected = 0
        self.expired = 0
        self.failed = 0          # forward raised; futures got the exception
        self.forwards = 0        # executed forward calls (batches)
        self.batched_rows = 0    # real rows that went through a forward
        self.padded_rows = 0     # padding rows added to reach a bucket size
        self.queue_depth = 0
        self._batch_sizes: Dict[int, int] = {}   # real rows per forward
        self._latency = _Reservoir(reservoir_size)      # end-to-end seconds
        self._queue_wait = _Reservoir(reservoir_size)   # submit -> drain

    # ------------------------------------------------------- mutators ----

    def record_batch(self, n_real: int, n_padded: int) -> None:
        with self._lock:
            self.forwards += 1
            self.batched_rows += n_real
            self.padded_rows += n_padded - n_real
            self._batch_sizes[n_real] = self._batch_sizes.get(n_real, 0) + 1

    def record_served(self, latency_s: float, queue_wait_s: float) -> None:
        with self._lock:
            self.served += 1
            self._latency.add(latency_s)
            self._queue_wait.add(queue_wait_s)

    def record_rejected(self) -> None:
        with self._lock:
            self.rejected += 1

    def record_expired(self) -> None:
        with self._lock:
            self.expired += 1

    def record_failed(self, n: int = 1) -> None:
        with self._lock:
            self.failed += n

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = depth

    # -------------------------------------------------------- readers ----

    def snapshot(self) -> dict:
        """Point-in-time dict of every counter and distribution."""
        with self._lock:
            padded_total = self.batched_rows + self.padded_rows
            lat = self._latency.percentiles(self.LATENCY_QS)
            wait = self._queue_wait.percentiles(self.LATENCY_QS)
            return {
                "served": self.served,
                "rejected": self.rejected,
                "expired": self.expired,
                "failed": self.failed,
                "forwards": self.forwards,
                "queue_depth": self.queue_depth,
                "batch_size_dist": dict(sorted(self._batch_sizes.items())),
                "mean_batch_size": (self.batched_rows / self.forwards
                                    if self.forwards else 0.0),
                # fraction of executed rows that were padding
                "padding_waste": (self.padded_rows / padded_total
                                  if padded_total else 0.0),
                "latency_ms": None if lat is None else {
                    f"p{q}": round(v * 1e3, 3)
                    for q, v in zip(self.LATENCY_QS, lat)},
                "queue_wait_ms": None if wait is None else {
                    f"p{q}": round(v * 1e3, 3)
                    for q, v in zip(self.LATENCY_QS, wait)},
                "latency_samples": self._latency.seen,
            }

    def format_table(self) -> str:
        """Pretty table like ``profiling.format_times``'s getTimes dump."""
        s = self.snapshot()
        lines = [f"{'metric':<26} {'value':>18}"]

        def row(name, value):
            lines.append(f"{name:<26} {value:>18}")

        for k in ("served", "rejected", "expired", "failed", "forwards",
                  "queue_depth"):
            row(k, s[k])
        row("mean_batch_size", f"{s['mean_batch_size']:.2f}")
        row("padding_waste", f"{s['padding_waste'] * 100:.1f}%")
        dist = " ".join(f"{k}:{v}" for k, v in s["batch_size_dist"].items())
        row("batch_size_dist", dist or "-")
        for key in ("latency_ms", "queue_wait_ms"):
            if s[key]:
                for q, v in s[key].items():
                    row(f"{key[:-3]}_{q}(ms)", f"{v:.3f}")
        return "\n".join(lines)
