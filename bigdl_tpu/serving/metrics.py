"""SLO metrics for the serving tier.

Lock-protected counters plus bounded-reservoir latency histograms — the
serving analogue of ``utils/profiling.py``'s per-module wall-time table:
cheap enough to stay on in production (O(1) per request, fixed memory),
rich enough for the BENCH serving column (requests/sec, p50/p95/p99,
batch-size distribution, padding waste).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from bigdl_tpu.core.rng import uniform01


class _Window:
    """Time-windowed sample buffer: percentiles over only the last
    ``window_s`` seconds (bounded at ``cap`` samples). The lifetime
    :class:`_Reservoir` is the right record for a BENCH column, but a
    control loop steering on it would never see a burst END — a p99
    poisoned by a ten-second storm stays high for the life of the
    process. The autoscaler reads these instead. Caller holds the
    metrics lock."""

    def __init__(self, window_s: float = 30.0, cap: int = 4096):
        self.window_s = float(window_s)
        self.values: "deque" = deque(maxlen=cap)

    def add(self, v: float, now: Optional[float] = None) -> None:
        self.values.append(
            (time.monotonic() if now is None else now, float(v)))

    def percentiles(self, qs, now: Optional[float] = None):
        cut = (time.monotonic() if now is None else now) - self.window_s
        vals = [v for t, v in self.values if t >= cut]
        if not vals:
            return None
        return [float(p) for p in np.percentile(vals, qs)]


class _Reservoir:
    """Fixed-size uniform sample of a stream (Vitter's algorithm R): the
    percentiles stay unbiased however long the service runs, with memory
    bounded at ``size`` floats. Caller holds the metrics lock."""

    def __init__(self, size: int, seed: int = 0):
        self.size = size
        self.seen = 0
        self.values: List[float] = []
        self._seed = seed

    def add(self, v: float) -> None:
        self.seen += 1
        if len(self.values) < self.size:
            self.values.append(v)
        else:
            # keyed splitmix64 draw on (seed, element index): which slot
            # element N displaces is a pure function of the seed and N —
            # the reservoir replays exactly, with no stateful RNG (GL004)
            j = int(uniform01(self._seed, self.seen) * self.seen)
            if j < self.size:
                self.values[j] = v

    def percentiles(self, qs) -> Optional[List[float]]:
        if not self.values:
            return None
        return [float(p) for p in np.percentile(self.values, qs)]


class ServingMetrics:
    """Counters + histograms for one :class:`InferenceService`.

    All mutators take the internal lock; ``snapshot()`` returns a plain
    dict (JSON-able) and ``format_table()`` a fixed-width dump in the
    style of ``utils/profiling.format_times``.
    """

    LATENCY_QS = (50, 95, 99)

    def __init__(self, reservoir_size: int = 2048,
                 recent_window_s: float = 30.0):
        self._lock = threading.Lock()
        self.served = 0
        self.rejected = 0
        self.expired = 0
        self.failed = 0          # forward raised; futures got the exception
        self.forwards = 0        # executed forward calls (batches)
        self.batched_rows = 0    # real rows that went through a forward
        self.padded_rows = 0     # padding rows added to reach a bucket size
        self.queue_depth = 0
        self._batch_sizes: Dict[int, int] = {}   # real rows per forward
        self._latency = _Reservoir(reservoir_size)      # end-to-end seconds
        self._queue_wait = _Reservoir(reservoir_size)   # submit -> drain
        # token-level generation counters (GenerationEngine); zero for a
        # plain InferenceService, whose snapshot/table keep PR-1 shape
        self.prefills = 0        # admitted prompts (one prefill forward each)
        self.prefill_tokens = 0  # real prompt tokens prefetched into caches
        self.prefill_padded = 0  # pad tokens added to reach a prompt bucket
        self.decode_steps = 0    # executed decode iterations
        self.decode_active = 0   # sum over steps of slots actually serving
        self.decode_slot_rows = 0  # sum over steps of total slots (capacity)
        self.tokens_out = 0      # generated tokens streamed to consumers
        self.reloads = 0         # hot param swaps (reload/watch_checkpoints)
        self._ttft = _Reservoir(reservoir_size)         # submit -> 1st token
        self._stream_rate = _Reservoir(reservoir_size)  # per-stream tokens/s
        # paged-KV / sampling / chunked-prefill counters (PR 6); zero for
        # a dense engine or plain InferenceService — their snapshot/table
        # keep the earlier shapes (append-only, golden-order-enforced)
        self.prefill_chunks = 0  # non-final chunk forwards (chunked prefill)
        self.sampled_tokens = 0  # tokens produced by temperature > 0 slots
        self.pages_in_use = 0    # KV pool pages currently reserved (gauge)
        self.pages_total = 0     # KV pool size (gauge; 0 = not paged)
        self.pages_peak = 0      # high-water reserved pages
        # replica-group counters (ReplicaSet); zero for a single backend —
        # its snapshot/table keep the earlier shapes (append-only contract)
        self.replicas_total = 0      # registered replicas (gauge)
        self.replicas_healthy = 0    # replicas currently placeable (gauge)
        self.replica_evictions = 0   # consecutive-failure quarantines
        self.replica_rejoins = 0     # probe-verified returns to service
        self.rolling_reloads = 0     # completed rolling reload sweeps
        self._replica_inflight: Dict[str, int] = {}  # per-replica gauge
        # quantized-serving fields (PR 9); unset for an unquantized /
        # non-paged backend — snapshot/table keep the earlier shapes
        # (the same append-only golden contract as every block above)
        self.kv_bytes_in_use = 0     # reserved KV bytes, scale pools incl.
        self.kv_cache_dtype = ""     # "" until a paged engine reports one
        self.quantized_gemms = 0     # int8 GEMMs in the serving params
        # speculative-decoding counters (PR 10); zero for a
        # non-speculative engine — snapshot/table keep the earlier
        # shapes (same append-only golden contract as every block above)
        self.draft_tokens = 0        # candidate tokens the draft proposed
        self.accepted_tokens = 0     # candidates the verify step accepted
        self.verify_steps = 0        # executed target verify forwards
        # engine step-timeline counters (PR 11); zero until an engine
        # scheduler loop actually iterates — snapshot/table keep the
        # earlier shapes (same append-only golden contract as every
        # block above). The per-iteration detail lives in the engine's
        # obs.StepTimeline ring; these are the aggregate split.
        self.engine_steps = 0        # scheduler loop iterations
        self.step_host_s = 0.0       # host scheduling/bookkeeping time
        self.step_device_s = 0.0     # kernel-call wait (all phases)
        # prefix-cache counters (PR 12); zero until a prefix-caching
        # paged engine actually probes — snapshot/table keep the
        # earlier shapes (same append-only golden contract as every
        # block above)
        self.prefix_hits = 0         # admissions that attached cached pages
        self.prefix_misses = 0       # admissions with no cached prefix
        self.shared_pages = 0        # pages the prefix index holds (gauge)
        self.prefill_chunks_skipped = 0  # chunk/prefill calls not executed
        # inter-token latency (PR 15): gap between consecutive tokens of
        # ONE stream, one sample per decode token. TTFT covers the first
        # token; this is the decode-stall gauge — the number prefill
        # interference inflates and disaggregation exists to protect.
        # Empty for a non-generating service — snapshot/table keep the
        # earlier shapes (same append-only golden contract as above).
        self._itl = _Reservoir(reservoir_size)          # seconds per gap
        # recent-window twins (PR 16): the lifetime reservoirs above are
        # the BENCH record; these time-windowed views are the
        # autoscaler's control signals — a burst's tail latency must
        # DECAY out of them once the burst (or a scale-up) resolves it,
        # or the controller could never see its own action take effect.
        # Appended at the snapshot tail per the golden contract.
        self.recent_window_s = float(recent_window_s)
        self._ttft_recent = _Window(recent_window_s)
        self._itl_recent = _Window(recent_window_s)
        # KV-tier counters (PR 18); zero for an engine without a host
        # tier — snapshot/table keep the earlier shapes (same
        # append-only golden contract as every block above)
        self.kv_offload_pages = 0    # device->host prefix copies landed
        self.kv_restore_pages = 0    # host->device prefix copies
        self.kv_offload_dropped = 0  # offload candidates abandoned
        self.kv_swaps_out = 0        # streams parked under QoS pressure
        self.kv_swaps_in = 0         # parked streams resumed
        self.host_pages = 0          # host-tier resident pages (gauge)
        self.host_bytes = 0          # host-tier resident bytes (gauge)
        self.host_pages_peak = 0
        # async-scheduling counters (PR 19); zero for a sync engine —
        # snapshot/table keep the earlier shapes (same append-only
        # golden contract as every block above). A step is "overlapped"
        # when host scheduling work ran while it was in flight on
        # device (the engine dispatched step N+1 before processing
        # step N's tokens).
        self.overlapped_steps = 0
        # structured-generation counters (PR 20); zero without grammar
        # traffic — snapshot/table keep the earlier shapes (same
        # append-only golden contract as every block above).
        self.constrained_streams = 0        # grammar requests admitted
        self.grammar_compile_cache_hits = 0  # automaton reuses at submit
        self._masked_frac_sum = 0.0  # mean masked-vocab fraction over
        self._masked_frac_n = 0      # every armed constrained step

    # ------------------------------------------------------- mutators ----

    def record_batch(self, n_real: int, n_padded: int) -> None:
        with self._lock:
            self.forwards += 1
            self.batched_rows += n_real
            self.padded_rows += n_padded - n_real
            self._batch_sizes[n_real] = self._batch_sizes.get(n_real, 0) + 1

    def record_served(self, latency_s: float, queue_wait_s: float) -> None:
        with self._lock:
            self.served += 1
            self._latency.add(latency_s)
            self._queue_wait.add(queue_wait_s)

    def record_rejected(self) -> None:
        with self._lock:
            self.rejected += 1

    def record_expired(self) -> None:
        with self._lock:
            self.expired += 1

    def record_failed(self, n: int = 1) -> None:
        with self._lock:
            self.failed += n

    def set_queue_depth(self, depth: int) -> None:
        with self._lock:
            self.queue_depth = depth

    # ------------------------------------------ generation mutators ----

    def record_prefill(self, n_prompt: int, n_padded: int,
                       ttft_s: Optional[float] = None) -> None:
        """One admitted prompt: ``n_prompt`` real tokens padded up to the
        ``n_padded`` bucket, plus the first generated token (prefill emits
        it); ``ttft_s`` is submit -> first token."""
        with self._lock:
            self.prefills += 1
            self.prefill_tokens += n_prompt
            self.prefill_padded += n_padded - n_prompt
            self.tokens_out += 1
            if ttft_s is not None:
                self._ttft.add(ttft_s)
                self._ttft_recent.add(ttft_s)

    def record_decode_step(self, n_active: int, n_slots: int) -> None:
        """One decode iteration serving ``n_active`` of ``n_slots`` slots
        (each active slot emits one token)."""
        with self._lock:
            self.decode_steps += 1
            self.decode_active += n_active
            self.decode_slot_rows += n_slots
            self.tokens_out += n_active

    def record_stream(self, n_tokens: int, duration_s: float) -> None:
        """One finished stream's token rate (generated / submit->done)."""
        with self._lock:
            if duration_s > 0:
                self._stream_rate.add(n_tokens / duration_s)

    def record_itl(self, gap_s: float, n: int = 1) -> None:
        """``n`` decode tokens of one stream arrived ``gap_s`` after the
        stream's previous token each (n > 1 = a speculative round's
        amortized per-token gap). One sample per generated token past
        the first — the first token's wait is TTFT, not ITL."""
        with self._lock:
            for _ in range(int(n)):
                self._itl.add(gap_s)
                self._itl_recent.add(gap_s)

    def record_reload(self) -> None:
        with self._lock:
            self.reloads += 1

    def record_chunk(self, n_real: int, n_padded: int) -> None:
        """One NON-final prompt chunk forward (chunked prefill); its
        tokens count toward the prompt totals, the admission itself is
        recorded by ``record_prefill`` when the final chunk runs."""
        with self._lock:
            self.prefill_chunks += 1
            self.prefill_tokens += n_real
            self.prefill_padded += n_padded - n_real

    def record_sampled(self, n: int) -> None:
        """``n`` tokens this step came from temperature-sampled slots
        (the rest of ``tokens_out`` is greedy)."""
        with self._lock:
            self.sampled_tokens += n

    def set_pages(self, in_use: int, total: int) -> None:
        """KV page-pool occupancy gauge (paged engine only)."""
        with self._lock:
            self.pages_in_use = in_use
            self.pages_total = total
            self.pages_peak = max(self.pages_peak, in_use)

    # ----------------------------------------- quantization mutators ----

    def set_kv_cache(self, bytes_in_use: int, dtype: str) -> None:
        """KV byte-occupancy gauge (paged engine): bytes the reserved
        pages cost in the cache's ACTUAL dtype, scale pools included —
        dtype-aware so int8 and bf16 engines report comparable numbers."""
        with self._lock:
            self.kv_bytes_in_use = int(bytes_in_use)
            self.kv_cache_dtype = str(dtype)

    def set_quantized_gemms(self, n: int) -> None:
        """How many GEMMs of the serving params run int8 (0 = float)."""
        with self._lock:
            self.quantized_gemms = int(n)

    # ------------------------------------------ speculative mutators ----

    def record_verify_step(self, n_draft: int, n_accepted: int,
                           n_extra_tokens: int = 0) -> None:
        """One speculative round's target verify forward: the draft
        proposed ``n_draft`` candidate tokens across the batch and
        ``n_accepted`` of them were accepted AND emitted.
        ``n_extra_tokens`` is the round's emitted tokens beyond the
        one-per-active-slot that ``record_decode_step`` already counted
        (speculation's whole win) — they fold into ``tokens_out``.
        ``acceptance_rate`` is a property of the draft's proposals
        alone: accepted / drafted."""
        with self._lock:
            self.verify_steps += 1
            self.draft_tokens += int(n_draft)
            self.accepted_tokens += int(n_accepted)
            self.tokens_out += int(n_extra_tokens)

    # ------------------------------------------- step-timeline mutators ----

    def record_engine_step(self, host_s: float, device_s: float,
                           overlapped: bool = False) -> None:
        """One engine scheduler iteration: ``host_s`` spent on host-side
        scheduling/bookkeeping, ``device_s`` inside the iteration's
        kernel-call regions (prefill chunks + decode/verify).
        ``overlapped`` marks an async-scheduling iteration whose host
        work ran under an in-flight device step (PR 19)."""
        with self._lock:
            self.engine_steps += 1
            self.step_host_s += float(host_s)
            self.step_device_s += float(device_s)
            if overlapped:
                self.overlapped_steps += 1

    # ----------------------------------- structured-generation mutators ----

    def record_constrained_stream(self) -> None:
        """One grammar-constrained request reached admission (PR 20)."""
        with self._lock:
            self.constrained_streams += 1

    def record_grammar_cache_hit(self) -> None:
        """A submit reused a grammar key this engine already served —
        the compiled automaton came from the module compile cache
        instead of a fresh regex->DFA->token-lift compilation."""
        with self._lock:
            self.grammar_compile_cache_hits += 1

    def record_masked_frac(self, frac: float) -> None:
        """Fraction of the vocabulary the just-armed mask row excludes
        (one sample per constrained-step arming; the snapshot reports
        the running mean — how tight the grammar squeezes sampling)."""
        with self._lock:
            self._masked_frac_sum += float(frac)
            self._masked_frac_n += 1

    # ----------------------------------------- prefix-cache mutators ----

    def record_prefix_probe(self, hit: bool,
                            chunks_skipped: int = 0) -> None:
        """One paged admission's prefix-cache probe: ``hit`` when cached
        pages were attached, ``chunks_skipped`` the chunk/prefill kernel
        invocations the attach made unnecessary (the prefill-FLOPs
        saving, counted against the cache-off invocation count)."""
        with self._lock:
            if hit:
                self.prefix_hits += 1
            else:
                self.prefix_misses += 1
            self.prefill_chunks_skipped += int(chunks_skipped)

    def set_shared_pages(self, n: int) -> None:
        """Prefix-index size gauge: pages the cache currently holds
        references for (drains to 0 on eviction/clear/close)."""
        with self._lock:
            self.shared_pages = int(n)

    # -------------------------------------------------- KV-tier mutators ----

    def record_offload(self, n_pages: int) -> None:
        """``n_pages`` prefix pages crossed device->host (the async copy
        landed and the host store filed them)."""
        with self._lock:
            self.kv_offload_pages += int(n_pages)

    def record_restore(self, n_pages: int) -> None:
        """``n_pages`` prefix pages crossed host->device (a later
        admission hit the host tier and re-attached them)."""
        with self._lock:
            self.kv_restore_pages += int(n_pages)

    def record_offload_dropped(self, n_pages: int = 1) -> None:
        """``n_pages`` offload candidates were abandoned instead of
        copied (an injected ``kv.offload`` fault, the in-flight copy
        cap, or host-capacity pressure) — the pages evicted plainly."""
        with self._lock:
            self.kv_offload_dropped += int(n_pages)

    def record_swap_out(self) -> None:
        """One active stream exported its pages and parked (QoS swap)."""
        with self._lock:
            self.kv_swaps_out += 1

    def record_swap_in(self) -> None:
        """One parked stream re-adopted its pages and resumed."""
        with self._lock:
            self.kv_swaps_in += 1

    def set_host_pages(self, pages: int, bytes_used: int) -> None:
        """Host-tier residency gauges (prefix entries + parked streams);
        drain to zero on engine close exactly like the device pool's."""
        with self._lock:
            self.host_pages = int(pages)
            self.host_bytes = int(bytes_used)
            self.host_pages_peak = max(self.host_pages_peak, int(pages))

    # --------------------------------------------- replica mutators ----

    def set_replicas(self, healthy: int, total: int,
                     inflight: Optional[Dict[str, int]] = None) -> None:
        """Replica-group occupancy gauges (ReplicaSet only): how many
        replicas are placeable and each replica's in-flight depth."""
        with self._lock:
            self.replicas_healthy = int(healthy)
            self.replicas_total = int(total)
            if inflight is not None:
                self._replica_inflight = dict(inflight)

    def record_eviction(self) -> None:
        """One replica quarantined after consecutive failures."""
        with self._lock:
            self.replica_evictions += 1

    def record_rejoin(self) -> None:
        """One quarantined replica returned to service after a probe."""
        with self._lock:
            self.replica_rejoins += 1

    def record_rolling_reload(self) -> None:
        """One completed rolling reload sweep (every replica drained and
        swapped in turn; individual swaps also count in ``reloads``)."""
        with self._lock:
            self.rolling_reloads += 1

    # -------------------------------------------------------- readers ----

    def snapshot(self) -> dict:
        """Point-in-time dict of every counter and distribution."""
        with self._lock:
            padded_total = self.batched_rows + self.padded_rows
            lat = self._latency.percentiles(self.LATENCY_QS)
            wait = self._queue_wait.percentiles(self.LATENCY_QS)
            return {
                "served": self.served,
                "rejected": self.rejected,
                "expired": self.expired,
                "failed": self.failed,
                "forwards": self.forwards,
                "queue_depth": self.queue_depth,
                "batch_size_dist": dict(sorted(self._batch_sizes.items())),
                "mean_batch_size": (self.batched_rows / self.forwards
                                    if self.forwards else 0.0),
                # fraction of executed rows that were padding
                "padding_waste": (self.padded_rows / padded_total
                                  if padded_total else 0.0),
                "latency_ms": None if lat is None else {
                    f"p{q}": round(v * 1e3, 3)
                    for q, v in zip(self.LATENCY_QS, lat)},
                "queue_wait_ms": None if wait is None else {
                    f"p{q}": round(v * 1e3, 3)
                    for q, v in zip(self.LATENCY_QS, wait)},
                "latency_samples": self._latency.seen,
                # token-level generation fields: NEW KEYS ONLY (PR-1
                # consumers index by key, so additions are compatible)
                "prefills": self.prefills,
                "decode_steps": self.decode_steps,
                "tokens_out": self.tokens_out,
                "reloads": self.reloads,
                "slot_occupancy": (self.decode_active / self.decode_slot_rows
                                   if self.decode_slot_rows else 0.0),
                "prompt_padding_waste": (
                    self.prefill_padded
                    / (self.prefill_tokens + self.prefill_padded)
                    if self.prefill_tokens + self.prefill_padded else 0.0),
                "ttft_ms": None if (t := self._ttft.percentiles(
                    self.LATENCY_QS)) is None else {
                    f"p{q}": round(v * 1e3, 3)
                    for q, v in zip(self.LATENCY_QS, t)},
                "stream_tokens_per_sec": None if (r := self._stream_rate.
                                                  percentiles((50,))) is None
                else round(r[0], 2),
                # paged-KV / sampling / chunked-prefill fields (PR 6):
                # appended after every earlier key, never reordered
                "prefill_chunks": self.prefill_chunks,
                "sampled_tokens": self.sampled_tokens,
                "pages_in_use": self.pages_in_use,
                "pages_total": self.pages_total,
                "pages_peak": self.pages_peak,
                "page_occupancy": (self.pages_in_use / self.pages_total
                                   if self.pages_total else 0.0),
                # replica-group fields (PR 7): appended after every
                # earlier key, never reordered
                "replicas_total": self.replicas_total,
                "replicas_healthy": self.replicas_healthy,
                "replica_evictions": self.replica_evictions,
                "replica_rejoins": self.replica_rejoins,
                "rolling_reloads": self.rolling_reloads,
                "replica_inflight": dict(self._replica_inflight),
                # quantized-serving fields (PR 9): appended after every
                # earlier key, never reordered
                "kv_bytes_in_use": self.kv_bytes_in_use,
                "kv_cache_dtype": self.kv_cache_dtype,
                "quantized_gemms": self.quantized_gemms,
                # speculative-decoding fields (PR 10): appended after
                # every earlier key, never reordered
                "draft_tokens": self.draft_tokens,
                "accepted_tokens": self.accepted_tokens,
                "acceptance_rate": (self.accepted_tokens
                                    / self.draft_tokens
                                    if self.draft_tokens else 0.0),
                "verify_steps": self.verify_steps,
                # engine step-timeline fields (PR 11): appended after
                # every earlier key, never reordered
                "engine_steps": self.engine_steps,
                "step_host_ms": round(self.step_host_s * 1e3, 3),
                "step_device_ms": round(self.step_device_s * 1e3, 3),
                "step_host_frac": (
                    self.step_host_s
                    / (self.step_host_s + self.step_device_s)
                    if self.step_host_s + self.step_device_s else 0.0),
                # prefix-cache fields (PR 12): appended after every
                # earlier key, never reordered
                "prefix_hits": self.prefix_hits,
                "prefix_misses": self.prefix_misses,
                "prefix_hit_rate": (
                    self.prefix_hits
                    / (self.prefix_hits + self.prefix_misses)
                    if self.prefix_hits + self.prefix_misses else 0.0),
                "shared_pages": self.shared_pages,
                "prefill_chunks_skipped": self.prefill_chunks_skipped,
                # inter-token-latency fields (PR 15): appended after
                # every earlier key, never reordered
                "itl_ms": None if (g := self._itl.percentiles(
                    self.LATENCY_QS)) is None else {
                    f"p{q}": round(v * 1e3, 3)
                    for q, v in zip(self.LATENCY_QS, g)},
                "itl_samples": self._itl.seen,
                # recent-window fields (PR 16): appended after every
                # earlier key, never reordered. None when the window is
                # empty — an idle engine's tail latency is "no data",
                # which the autoscaler's scale-down rules treat as
                # quiet, not as breach.
                "ttft_recent_ms": None if (tr := self._ttft_recent.
                                           percentiles(self.LATENCY_QS)
                                           ) is None else {
                    f"p{q}": round(v * 1e3, 3)
                    for q, v in zip(self.LATENCY_QS, tr)},
                "itl_recent_ms": None if (gr := self._itl_recent.
                                          percentiles(self.LATENCY_QS)
                                          ) is None else {
                    f"p{q}": round(v * 1e3, 3)
                    for q, v in zip(self.LATENCY_QS, gr)},
                "recent_window_s": self.recent_window_s,
                # KV-tier fields (PR 18): appended after every earlier
                # key, never reordered
                "kv_offload_pages": self.kv_offload_pages,
                "kv_restore_pages": self.kv_restore_pages,
                "kv_offload_dropped": self.kv_offload_dropped,
                "kv_swaps_out": self.kv_swaps_out,
                "kv_swaps_in": self.kv_swaps_in,
                "host_pages": self.host_pages,
                "host_bytes": self.host_bytes,
                "host_pages_peak": self.host_pages_peak,
                # async-scheduling fields (PR 19): appended after every
                # earlier key, never reordered
                "overlapped_steps": self.overlapped_steps,
                "step_overlap_frac": (self.overlapped_steps
                                      / self.engine_steps
                                      if self.engine_steps else 0.0),
                # structured-generation fields (PR 20): appended after
                # every earlier key, never reordered
                "constrained_streams": self.constrained_streams,
                "grammar_compile_cache_hits":
                    self.grammar_compile_cache_hits,
                "masked_vocab_frac": (self._masked_frac_sum
                                      / self._masked_frac_n
                                      if self._masked_frac_n else 0.0),
            }

    def format_table(self) -> str:
        """Pretty table like ``profiling.format_times``'s getTimes dump."""
        s = self.snapshot()
        lines = [f"{'metric':<26} {'value':>18}"]

        def row(name, value):
            lines.append(f"{name:<26} {value:>18}")

        for k in ("served", "rejected", "expired", "failed", "forwards",
                  "queue_depth"):
            row(k, s[k])
        row("mean_batch_size", f"{s['mean_batch_size']:.2f}")
        row("padding_waste", f"{s['padding_waste'] * 100:.1f}%")
        dist = " ".join(f"{k}:{v}" for k, v in s["batch_size_dist"].items())
        row("batch_size_dist", dist or "-")
        for key in ("latency_ms", "queue_wait_ms"):
            if s[key]:
                for q, v in s[key].items():
                    row(f"{key[:-3]}_{q}(ms)", f"{v:.3f}")
        # token-level rows are APPENDED, and only when generation actually
        # happened: a plain InferenceService table stays byte-identical to
        # the PR-1 golden output (extend, don't reorder — test-enforced)
        if s["prefills"] or s["decode_steps"] or s["tokens_out"]:
            row("tokens_out", s["tokens_out"])
            row("prefills", s["prefills"])
            row("decode_steps", s["decode_steps"])
            row("slot_occupancy", f"{s['slot_occupancy'] * 100:.1f}%")
            row("prompt_padding_waste",
                f"{s['prompt_padding_waste'] * 100:.1f}%")
            if s["ttft_ms"]:
                for q, v in s["ttft_ms"].items():
                    row(f"ttft_{q}(ms)", f"{v:.3f}")
            if s["stream_tokens_per_sec"] is not None:
                row("stream_tokens/s_p50", f"{s['stream_tokens_per_sec']:.2f}")
        # paged-KV rows: appended strictly after the generation block and
        # only when a paged engine actually ran (same append-only golden
        # contract as above — a dense engine's table is byte-identical
        # to its PR-5 output)
        if s["pages_total"] or s["prefill_chunks"] or s["sampled_tokens"]:
            row("pages_in_use", s["pages_in_use"])
            row("pages_total", s["pages_total"])
            row("pages_peak", s["pages_peak"])
            row("page_occupancy", f"{s['page_occupancy'] * 100:.1f}%")
            row("prefill_chunks", s["prefill_chunks"])
            row("sampled_tokens", s["sampled_tokens"])
        if s["reloads"]:
            row("reloads", s["reloads"])
        # replica-group rows: appended strictly LAST (after the reloads
        # row) and only when a ReplicaSet is actually reporting — every
        # earlier table stays a byte-identical strict prefix of this one
        # (the append-only golden contract, test-enforced)
        if s["replicas_total"]:
            row("replicas_healthy", f"{s['replicas_healthy']}"
                                    f"/{s['replicas_total']}")
            row("replica_evictions", s["replica_evictions"])
            row("replica_rejoins", s["replica_rejoins"])
            row("rolling_reloads", s["rolling_reloads"])
            dist = " ".join(f"{k}:{v}" for k, v in
                            sorted(s["replica_inflight"].items()))
            row("replica_inflight", dist or "-")
        # quantized-serving rows: appended strictly after the replica
        # block and only when an engine actually reported a KV dtype or
        # quantized GEMMs — every earlier table stays a byte-identical
        # strict prefix (append-only golden contract, test-enforced)
        if s["kv_cache_dtype"] or s["quantized_gemms"]:
            row("kv_bytes_in_use", s["kv_bytes_in_use"])
            row("kv_cache_dtype", s["kv_cache_dtype"] or "-")
            row("quantized_gemms", s["quantized_gemms"])
        # speculative rows: appended strictly after the quantized block
        # and only when a speculative engine actually verified — every
        # earlier table stays a byte-identical strict prefix
        # (append-only golden contract, test-enforced)
        if s["verify_steps"]:
            row("draft_tokens", s["draft_tokens"])
            row("accepted_tokens", s["accepted_tokens"])
            row("acceptance_rate", f"{s['acceptance_rate'] * 100:.1f}%")
            row("verify_steps", s["verify_steps"])
        # step-timeline rows: appended strictly after the speculative
        # block and only when an engine scheduler loop actually
        # iterated — every earlier table stays a byte-identical strict
        # prefix (append-only golden contract, test-enforced)
        if s["engine_steps"]:
            row("engine_steps", s["engine_steps"])
            row("step_host_ms", f"{s['step_host_ms']:.3f}")
            row("step_device_ms", f"{s['step_device_ms']:.3f}")
            row("step_host_frac", f"{s['step_host_frac'] * 100:.1f}%")
        # prefix-cache rows: appended strictly after the step-timeline
        # block and only when a prefix-caching engine actually probed —
        # every earlier table stays a byte-identical strict prefix
        # (append-only golden contract, test-enforced)
        if s["prefix_hits"] or s["prefix_misses"] or s["shared_pages"]:
            row("prefix_hits", s["prefix_hits"])
            row("prefix_misses", s["prefix_misses"])
            row("prefix_hit_rate", f"{s['prefix_hit_rate'] * 100:.1f}%")
            row("shared_pages", s["shared_pages"])
            row("prefill_chunks_skipped", s["prefill_chunks_skipped"])
        # inter-token-latency rows: appended strictly after the prefix
        # block and only when decode gaps were actually sampled — every
        # earlier table stays a byte-identical strict prefix
        # (append-only golden contract, test-enforced)
        if s["itl_samples"]:
            for q, v in s["itl_ms"].items():
                row(f"itl_{q}(ms)", f"{v:.3f}")
            row("itl_samples", s["itl_samples"])
        # KV-tier rows: appended strictly after the ITL block and only
        # when a host tier actually moved or held pages — every earlier
        # table stays a byte-identical strict prefix (append-only
        # golden contract, test-enforced)
        if (s["kv_offload_pages"] or s["kv_restore_pages"]
                or s["kv_swaps_out"] or s["host_pages"]
                or s["kv_offload_dropped"]):
            row("kv_offload_pages", s["kv_offload_pages"])
            row("kv_restore_pages", s["kv_restore_pages"])
            row("kv_offload_dropped", s["kv_offload_dropped"])
            row("kv_swaps_out", s["kv_swaps_out"])
            row("kv_swaps_in", s["kv_swaps_in"])
            row("host_pages", s["host_pages"])
            row("host_bytes", s["host_bytes"])
            row("host_pages_peak", s["host_pages_peak"])
        # async-scheduling rows: appended strictly after the KV-tier
        # block and only when the engine actually overlapped a step —
        # every earlier table stays a byte-identical strict prefix
        # (append-only golden contract, test-enforced)
        if s["overlapped_steps"]:
            row("overlapped_steps", s["overlapped_steps"])
            row("step_overlap_frac",
                f"{s['step_overlap_frac'] * 100:.1f}%")
        # structured-generation rows: appended strictly after the
        # async-scheduling block and only when constrained streams
        # actually ran — every earlier table stays a byte-identical
        # strict prefix (append-only golden contract, test-enforced)
        if s["constrained_streams"]:
            row("constrained_streams", s["constrained_streams"])
            row("grammar_compile_cache_hits",
                s["grammar_compile_cache_hits"])
            row("masked_vocab_frac",
                f"{s['masked_vocab_frac'] * 100:.1f}%")
        return "\n".join(lines)
