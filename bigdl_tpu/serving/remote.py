"""Cross-process serving fabric (PR 14): host any serving backend in a
child process behind the ReplicaSet contract.

Two halves, one wire (:mod:`bigdl_tpu.serving.rpc`):

- :class:`ReplicaServer` wraps a backend (GenerationEngine,
  InferenceService, or any duck-typed stub) behind a listening socket.
  Requests are fully asynchronous — ``submit`` registers the backend
  handle's done-callback and the response frame goes out whenever the
  work finishes, so one slow stream never head-of-line-blocks the
  connection. Responses are cached by request id (bounded LRU), so a
  hedged or retried duplicate is answered from the cache instead of
  re-executed — idempotency is the server's job, not the client's hope.
- :class:`RemoteReplica` is the client proxy: ``submit`` returns a
  future-shaped handle (``result``/``exception``/``add_done_callback``/
  ``cancel``), exactly what :class:`~bigdl_tpu.serving.replica
  .ReplicaSet` tracks, so a remote process drops into a set next to
  in-process engines with no adapter.

The robustness layer is the point of the PR:

- **deadlines propagate.** The remaining budget rides the request
  header; the server fails an already-expired request immediately and
  otherwise hands the budget to the backend (engines/services natively
  retire expired work — no zombie in-flight). The client keeps a local
  backstop: at ``deadline + grace`` a pending future fails with
  :class:`DeadlineExceeded` even if the remote is wedged.
- **circuit breaker.** Consecutive transport failures open the breaker
  for a cooldown; while open, ``submit`` fast-fails with
  :class:`TransportError` — which the ReplicaSet counts as an engine
  error, so the breaker FEEDS the existing consecutive-failure
  eviction instead of duplicating it. Probes go through half-open.
- **reconnect under RetryPolicy.** Connects are paced by the shared
  :class:`~bigdl_tpu.faults.RetryPolicy` (deterministic jitter), and
  every failure mode is injectable at the seeded ``rpc.*`` fault sites.
- **draining disconnects.** ``close(drain=True)`` waits for in-flight
  responses before the socket drops, and the server's draining close
  waits for its backend — rolling reloads never drop work.

``python -m bigdl_tpu.serving.remote --factory pkg.mod:fn`` is the
child-process entry (prints ``RPC_READY host port`` once listening);
:func:`start_replica_process` wraps the spawn/handshake and
``RemoteReplica.revive()`` relaunches a SIGKILLed child so the
ReplicaSet prober drives the whole death-and-rejoin cycle."""

from __future__ import annotations

import argparse
import collections
import heapq
import importlib
import logging
import os
import signal
import socket
import subprocess
import sys
import threading
import time
import uuid
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FutureTimeout
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from bigdl_tpu import faults
from bigdl_tpu.faults import RetryPolicy
from bigdl_tpu.obs.recorder import record_event
from bigdl_tpu.serving import rpc
from bigdl_tpu.serving.errors import DeadlineExceeded, TransportError

log = logging.getLogger("bigdl_tpu.serving")


def _handle_outcome(handle) -> Tuple[Any, Optional[BaseException]]:
    """(result, error) of a COMPLETED backend handle — the same probing
    order as ReplicaSet._handle_error (``.error`` streams first, then
    future ``.exception()``)."""
    err = getattr(handle, "error", None)
    if err is None and hasattr(handle, "exception"):
        try:
            err = handle.exception(timeout=0)
        except TypeError:
            err = handle.exception()
        except BaseException as e:
            err = e
    if err is not None:
        return None, err
    try:
        return handle.result(timeout=5), None
    except BaseException as e:
        return None, e


# ================================================================ server ==

class _Conn:
    """One accepted client connection: socket + a send lock (responses
    come from backend callback threads; frames must not interleave)."""

    __slots__ = ("sock", "lock", "alive")

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self.lock = threading.Lock()
        self.alive = True

    def send_bytes(self, packed: bytes) -> bool:
        try:
            with self.lock:
                self.sock.sendall(packed)
            return True
        except OSError:
            self.alive = False
            return False

    def close(self) -> None:
        self.alive = False
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


class ReplicaServer:
    """Serve one backend over the rpc wire. Listening starts in the
    constructor (``port=0`` binds an ephemeral port — read ``.port``);
    ``hard_exit=True`` (the ``__main__`` entry sets it) makes an
    injected ``rpc.peer_kill`` fault hard-exit the PROCESS — the
    in-band, seeded equivalent of SIGKILL; thread-hosted servers
    instead drop every socket without drain, which is what the peer
    observes either way."""

    def __init__(self, backend, *, host: str = "127.0.0.1", port: int = 0,
                 name: str = "remote", idempotency_cap: int = 256,
                 hard_exit: bool = False):
        self.backend = backend
        self.name = name
        self._hard_exit = hard_exit
        self._lock = threading.Lock()
        self._drain_cond = threading.Condition(self._lock)
        self._inflight: Dict[str, dict] = {}     # rid -> {handle, conns}
        self._done_cache: "collections.OrderedDict[str, bytes]" = \
            collections.OrderedDict()
        self._idem_cap = int(idempotency_cap)
        self._req_count = 0
        self.served = 0
        self.duplicates = 0                       # answered from the cache
        self._conns: List[_Conn] = []
        self._closed = threading.Event()
        self._aborted = False
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, int(port)))
        self._listener.listen(16)
        self.host, self.port = self._listener.getsockname()[:2]
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="bigdl-rpc-accept", daemon=True)
        self._accept_thread.start()

    # ------------------------------------------------------- socket IO ----

    def _accept_loop(self) -> None:
        # the listener is closed HERE, after the loop: closing a socket
        # another thread is blocked in accept() on does not reliably
        # release the kernel listen queue (the in-flight syscall pins
        # the file), so close()/abort() instead set _closed, poke the
        # port awake, and let this thread do the real close
        while not self._closed.is_set():
            try:
                sock, _ = self._listener.accept()
            except OSError:
                break
            if self._closed.is_set():
                try:
                    sock.close()
                except OSError:
                    pass
                break
            conn = _Conn(sock)
            with self._lock:
                self._conns.append(conn)
            threading.Thread(target=self._client_loop, args=(conn,),
                             name="bigdl-rpc-serve", daemon=True).start()
        try:
            self._listener.close()
        except OSError:
            pass

    def _client_loop(self, conn: _Conn) -> None:
        try:
            conn.sock.setsockopt(socket.IPPROTO_TCP,
                                 socket.TCP_NODELAY, 1)
            rpc.server_handshake(conn.sock)
            while conn.alive:
                msg = rpc.recv_frame(conn.sock)
                self._handle(conn, msg)
        except (OSError, ConnectionError, TransportError):
            pass  # peer went away; in-flight work keeps running and its
            #       responses stay in the idempotency cache for a retry
        finally:
            conn.close()
            with self._lock:
                if conn in self._conns:
                    self._conns.remove(conn)

    def _stop_listening(self) -> None:
        """Set _closed, wake a blocked accept with a throwaway connect,
        and wait for the accept thread to close the listener itself."""
        self._closed.set()
        try:
            poke = socket.create_connection((self.host, self.port),
                                            timeout=0.5)
            poke.close()
        except OSError:
            pass  # already released
        self._accept_thread.join(timeout=5)
        try:
            self._listener.close()
        except OSError:
            pass

    def _reply(self, conn: _Conn, rid, ok: bool, payload) -> None:
        tree = {"id": rid, "ok": ok,
                ("result" if ok else "error"): payload}
        try:
            packed = rpc.pack_frame(tree)
        except TypeError as e:
            # un-encodable RESULT: degrade to a typed error, never a
            # silent hang on the client's pending future
            packed = rpc.pack_frame(
                {"id": rid, "ok": False,
                 "error": TransportError(f"unencodable response: {e}")})
        conn.send_bytes(packed)

    # ------------------------------------------------------- dispatch ----

    def _handle(self, conn: _Conn, msg: dict) -> None:
        rid = msg.get("id")
        method = msg.get("method")
        with self._lock:
            self._req_count += 1
            idx = self._req_count
        try:
            faults.fire("rpc.peer_kill", key=idx, method=method)
        except BaseException:
            # the seeded SIGKILL: a child process dies for real; a
            # thread-hosted server drops every socket without drain
            # (exactly what the peer of a killed process observes)
            if self._hard_exit:
                os._exit(137)
            self.abort()
            return
        try:
            if method == "submit":
                self._handle_submit(conn, rid, msg)
                return
            if method == "ping":
                result = "pong"
            elif method == "snapshot":
                result = self.snapshot()
            elif method == "reload":
                state = msg.get("state")
                if state is None:
                    self.backend.reload(msg["params"])
                else:
                    self.backend.reload(msg["params"], state)
                result = "reloaded"
            elif method == "warmup":
                self.backend.warmup(*(msg.get("args") or []),
                                    **(msg.get("kwargs") or {}))
                result = "warm"
            elif method == "arm_fault":
                spec = faults.arm(msg["site"], **(msg.get("spec") or {}))
                result = {"site": spec.site}
            elif method == "disarm_fault":
                faults.disarm(msg["site"])
                result = "disarmed"
            elif method == "reset_faults":
                faults.reset()
                result = "reset"
            elif method == "fault_snapshot":
                result = faults.snapshot()
            elif method == "recorder_count":
                from bigdl_tpu.obs import flight_recorder

                result = flight_recorder().count(msg["kind"])
            elif method == "close":
                self._handle_close(conn, rid, msg)
                return
            else:
                raise ValueError(f"unknown rpc method {method!r}")
        except BaseException as e:
            self._reply(conn, rid, False, e)
            return
        self._reply(conn, rid, True, result)

    def _handle_submit(self, conn: _Conn, rid, msg: dict) -> None:
        kwargs = dict(msg.get("kwargs") or {})
        deadline_ms = msg.get("deadline_ms")
        if deadline_ms is not None:
            if deadline_ms <= 0:
                # expired in flight: abandon BEFORE the backend sees it
                self._reply(conn, rid, False,
                            DeadlineExceeded(0.0, deadline_ms / 1e3))
                return
            kwargs["deadline"] = deadline_ms / 1e3
        with self._lock:
            cached = self._done_cache.get(rid)
            if cached is not None:
                self._done_cache.move_to_end(rid)
                self.duplicates += 1
            else:
                rec = self._inflight.get(rid)
                if rec is not None:
                    # duplicate of RUNNING work (a hedge retry): attach
                    # this connection, never execute twice
                    self.duplicates += 1
                    if conn not in rec["conns"]:
                        rec["conns"].append(conn)
                    return
        if cached is not None:
            conn.send_bytes(cached)
            return
        try:
            handle = self.backend.submit(msg.get("x"), **kwargs)
        except BaseException as e:
            self._reply(conn, rid, False, e)
            return
        with self._lock:
            self._inflight[rid] = {"handle": handle, "conns": [conn]}
        handle.add_done_callback(lambda h: self._finish_submit(rid, h))

    def _finish_submit(self, rid, handle) -> None:
        result, err = _handle_outcome(handle)
        tree = {"id": rid, "ok": err is None,
                ("result" if err is None else "error"):
                    result if err is None else err}
        try:
            packed = rpc.pack_frame(tree)
        except TypeError as e:
            packed = rpc.pack_frame(
                {"id": rid, "ok": False,
                 "error": TransportError(f"unencodable response: {e}")})
        with self._drain_cond:
            rec = self._inflight.pop(rid, None)
            self._done_cache[rid] = packed
            while len(self._done_cache) > self._idem_cap:
                self._done_cache.popitem(last=False)
            if err is None:
                self.served += 1
            conns = list(rec["conns"]) if rec else []
            self._drain_cond.notify_all()
        for conn in conns:
            conn.send_bytes(packed)

    def _handle_close(self, conn: _Conn, rid, msg: dict) -> None:
        drain = bool(msg.get("drain", True))
        timeout = msg.get("timeout")
        if drain:
            self.drain(timeout)
        self._reply(conn, rid, True, "closing")
        threading.Thread(target=self.close, kwargs={"drain": False},
                         name="bigdl-rpc-shutdown", daemon=True).start()

    # ------------------------------------------------------ lifecycle ----

    def drain(self, timeout: Optional[float] = None) -> bool:
        """Wait for every in-flight backend handle to finish."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._drain_cond:
            while self._inflight:
                left = None if deadline is None \
                    else deadline - time.monotonic()
                if left is not None and left <= 0:
                    return False
                self._drain_cond.wait(timeout=left if left is not None
                                      else 0.5)
            return True

    def abort(self) -> None:
        """Drop the listener and every connection WITHOUT drain — the
        thread-hosted stand-in for a killed process."""
        self._aborted = True
        self._stop_listening()
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            c.close()

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        if drain:
            self.drain(timeout)
        self._stop_listening()
        with self._lock:
            conns = list(self._conns)
        for c in conns:
            c.close()

    def wait_closed(self, timeout: Optional[float] = None) -> bool:
        return self._closed.wait(timeout)

    @property
    def inflight(self) -> int:
        with self._lock:
            return len(self._inflight)

    def snapshot(self) -> dict:
        with self._lock:
            out = {"name": self.name, "inflight": len(self._inflight),
                   "served": self.served, "duplicates": self.duplicates,
                   "requests": self._req_count,
                   "connections": len(self._conns)}
        pages = getattr(self.backend, "pages_in_use", None)
        if pages is not None:
            out["pages_in_use"] = pages
        m = getattr(self.backend, "metrics", None)
        if m is not None:
            out["backend"] = m.snapshot()
        return out


# ================================================================ client ==

class _RemoteHandle(Future):
    """Future-shaped handle for one remote submit (``request_id`` rides
    along so hedged re-dispatch can reuse it)."""

    def __init__(self, request_id: str):
        super().__init__()
        self.request_id = request_id


def _safe_fail(fut: Future, exc: BaseException) -> None:
    try:
        if not fut.cancelled():
            fut.set_exception(exc)
    except Exception:
        pass  # already resolved (a race with the receiver) — first wins


def _safe_resolve(fut: Future, value) -> None:
    try:
        if not fut.cancelled():
            fut.set_result(value)
    except Exception:
        pass


class _Pending:
    """One outstanding request id; ``futs`` is a LIST because a
    duplicate submit with the same id (hedge retry on this client)
    attaches to the outstanding request instead of re-sending."""

    __slots__ = ("futs", "t_submit", "rel_deadline", "abs_deadline")

    def __init__(self, fut, t_submit, rel_deadline):
        self.futs = [fut]
        self.t_submit = t_submit
        self.rel_deadline = rel_deadline
        self.abs_deadline = None if rel_deadline is None \
            else t_submit + rel_deadline

    def fail_all(self, exc: BaseException) -> None:
        for f in self.futs:
            _safe_fail(f, exc)

    def resolve_all(self, value) -> None:
        for f in self.futs:
            _safe_resolve(f, value)


class RemoteReplica:
    """Client proxy for one :class:`ReplicaServer` — a drop-in
    ReplicaSet backend whose engine lives across a socket (and usually
    a process). See the module docstring for the robustness contract.

    ``connect_policy`` paces reconnects (default 3 attempts, 50 ms
    doubling, deterministic jitter); ``breaker_threshold`` consecutive
    transport failures open the breaker for ``breaker_cooldown``
    seconds; ``deadline_grace`` is the slack the local backstop gives
    the server to answer a deadline itself before the client fails the
    future locally."""

    accepts_request_id = True  # ReplicaSet hedging reuses request ids

    def __init__(self, address: Tuple[str, int], *, name: str = "remote",
                 proc: Optional[subprocess.Popen] = None,
                 launch: Optional[dict] = None,
                 connect_policy: Optional[RetryPolicy] = None,
                 connect_timeout: float = 5.0,
                 breaker_threshold: int = 3,
                 breaker_cooldown: float = 1.0,
                 deadline_grace: float = 0.25):
        self.host, self.port = address[0], int(address[1])
        self.name = name
        self._proc = proc
        self._launch = launch
        self._policy = connect_policy or RetryPolicy(
            max_attempts=3, base_delay=0.05, max_delay=2.0,
            transient=(OSError, ConnectionError, TransportError))
        self._connect_timeout = float(connect_timeout)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown = float(breaker_cooldown)
        self.deadline_grace = float(deadline_grace)
        self._lock = threading.Lock()
        self._connect_lock = threading.Lock()
        self._send_lock = threading.Lock()
        self._sock: Optional[socket.socket] = None
        self._pending: Dict[str, _Pending] = {}
        self._closed = False
        self._closing = False  # deliberate close: disconnects are not
        #                        failures, keep the gauges honest
        self._send_count = 0
        # transport gauges (scraped via snapshot() -> MetricsRegistry)
        self._connects = 0
        self.rpc_reconnects = 0
        self.rpc_deadline_exceeded = 0
        self.rpc_hedges_won = 0
        self.breaker_trips = 0
        self._consec_failures = 0
        self._breaker_open_until = 0.0
        # deadline backstop: one heap, one thread, started on first use
        self._dl_cond = threading.Condition()
        self._dl_heap: List[Tuple[float, str]] = []
        self._dl_thread: Optional[threading.Thread] = None

    @property
    def endpoint(self) -> str:
        return f"{self.host}:{self.port}"

    # -------------------------------------------------------- breaker ----

    def _breaker_failure(self) -> None:
        with self._lock:
            self._consec_failures += 1
            if self._consec_failures >= self.breaker_threshold \
                    and time.monotonic() >= self._breaker_open_until:
                self._breaker_open_until = (time.monotonic()
                                            + self.breaker_cooldown)
                self.breaker_trips += 1
                record_event("rpc.breaker_open", endpoint=self.endpoint,
                             failures=self._consec_failures,
                             cooldown_s=self.breaker_cooldown)

    def _breaker_success(self) -> None:
        with self._lock:
            self._consec_failures = 0
            self._breaker_open_until = 0.0

    @property
    def breaker_state(self) -> str:
        with self._lock:
            return ("open" if time.monotonic() < self._breaker_open_until
                    else "closed")

    # ----------------------------------------------------- connection ----

    def _connect_once(self) -> socket.socket:
        faults.fire("rpc.connect", endpoint=self.endpoint)
        s = socket.create_connection((self.host, self.port),
                                     timeout=self._connect_timeout)
        try:
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            s.settimeout(None)
            rpc.client_handshake(s)
        except BaseException:
            s.close()
            raise
        return s

    def _ensure_conn(self, half_open: bool = False) -> socket.socket:
        with self._lock:
            if self._closed:
                raise TransportError("replica client is closed",
                                     endpoint=self.endpoint)
            if self._sock is not None:
                return self._sock
            if not half_open \
                    and time.monotonic() < self._breaker_open_until:
                raise TransportError(
                    f"circuit breaker open after "
                    f"{self._consec_failures} consecutive failures",
                    endpoint=self.endpoint)
        with self._connect_lock:
            with self._lock:
                if self._sock is not None:
                    return self._sock
            try:
                s = self._policy.call(
                    self._connect_once,
                    describe=f"rpc connect {self.endpoint}")
            except (OSError, ConnectionError, TransportError) as e:
                self._breaker_failure()
                if isinstance(e, TransportError):
                    raise
                raise TransportError(f"connect failed: {e}",
                                     endpoint=self.endpoint) from e
            with self._lock:
                self._sock = s
                self._connects += 1
                if self._connects > 1:
                    self.rpc_reconnects += 1
            self._breaker_success()
            threading.Thread(target=self._recv_loop, args=(s,),
                             name="bigdl-rpc-client-recv",
                             daemon=True).start()
            record_event("rpc.connected", endpoint=self.endpoint,
                         connects=self._connects)
            return s

    def _recv_loop(self, sock: socket.socket) -> None:
        try:
            while True:
                msg = rpc.recv_frame(sock)
                # latency-oriented site; an exc arm is a poisoned pipe
                faults.fire("rpc.recv_delay", endpoint=self.endpoint)
                self._dispatch(msg)
        except BaseException as e:
            self._conn_lost(sock, e)

    def _dispatch(self, msg: dict) -> None:
        rid = msg.get("id")
        with self._lock:
            ent = self._pending.pop(rid, None)
            # any response frame proves the transport: close the breaker
            self._consec_failures = 0
            self._breaker_open_until = 0.0
        if ent is None:
            return  # deadline backstop (or a cancel) got there first
        if msg.get("ok"):
            ent.resolve_all(msg.get("result"))
        else:
            err = msg.get("error")
            if not isinstance(err, BaseException):
                err = TransportError(f"malformed error frame: {err!r}",
                                     endpoint=self.endpoint)
            if isinstance(err, DeadlineExceeded):
                with self._lock:
                    self.rpc_deadline_exceeded += 1
            ent.fail_all(err)

    def _conn_lost(self, sock: socket.socket,
                   error: BaseException, count: bool = True) -> None:
        with self._lock:
            if self._sock is not sock:
                return  # a newer connection already took over
            self._sock = None
            pend = list(self._pending.values())
            self._pending.clear()
            closing = self._closed or self._closing
        try:
            sock.shutdown(socket.SHUT_RDWR)  # wake a blocked receiver
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass
        if count and not closing:
            self._breaker_failure()
        if not closing:
            record_event("rpc.disconnected", endpoint=self.endpoint,
                         error=type(error).__name__, pending=len(pend))
        terr = TransportError(f"connection lost: {error}",
                              endpoint=self.endpoint)
        for ent in pend:
            ent.fail_all(terr)

    # ----------------------------------------------- deadline backstop ----

    def _watch_deadline(self, rid: str, ent: _Pending) -> None:
        with self._dl_cond:
            heapq.heappush(self._dl_heap,
                           (ent.abs_deadline + self.deadline_grace, rid))
            if self._dl_thread is None or not self._dl_thread.is_alive():
                self._dl_thread = threading.Thread(
                    target=self._deadline_loop,
                    name="bigdl-rpc-deadline", daemon=True)
                self._dl_thread.start()
            self._dl_cond.notify_all()

    def _deadline_loop(self) -> None:
        while True:
            with self._dl_cond:
                while True:
                    if self._closed and not self._dl_heap:
                        return
                    now = time.monotonic()
                    if self._dl_heap and self._dl_heap[0][0] <= now:
                        _, rid = heapq.heappop(self._dl_heap)
                        break
                    if self._closed:
                        self._dl_heap.clear()
                        return
                    self._dl_cond.wait(
                        timeout=None if not self._dl_heap
                        else max(self._dl_heap[0][0] - now, 0.005))
            with self._lock:
                ent = self._pending.pop(rid, None)
                if ent is not None:
                    self.rpc_deadline_exceeded += 1
            if ent is None:
                continue  # the server answered in time
            waited = time.monotonic() - ent.t_submit
            record_event("rpc.deadline_backstop", endpoint=self.endpoint,
                         waited_ms=round(waited * 1e3, 1))
            ent.fail_all(DeadlineExceeded(waited, ent.rel_deadline))

    # -------------------------------------------------------- requests ----

    def _send(self, sock: socket.socket, msg: dict, method: str) -> None:
        with self._lock:
            self._send_count += 1
            idx = self._send_count
        try:
            faults.fire("rpc.send", key=idx, endpoint=self.endpoint,
                        method=method)
            with self._send_lock:
                rpc.send_frame(sock, msg)
        except BaseException as e:
            self._breaker_failure()
            self._conn_lost(sock, e, count=False)
            raise TransportError(f"send failed: {e}",
                                 endpoint=self.endpoint) from e

    def submit(self, x, request_id: Optional[str] = None,
               deadline: Optional[float] = None, **kwargs):
        """Place one request on the remote backend; returns a
        future-shaped handle. ``deadline`` is seconds from now and
        propagates in the header; transport failures raise
        :class:`TransportError` (an engine error — the ReplicaSet
        evicts and fails over)."""
        sock = self._ensure_conn()
        rid = request_id or uuid.uuid4().hex
        fut = _RemoteHandle(rid)
        rel = None if deadline is None else float(deadline)
        ent = _Pending(fut, time.monotonic(), rel)
        with self._lock:
            if self._closed:
                raise TransportError("replica client is closed",
                                     endpoint=self.endpoint)
            existing = self._pending.get(rid)
            if existing is not None:
                # duplicate id while the original is outstanding:
                # attach, don't re-send — one wire request, N futures
                existing.futs.append(fut)
                return fut
            self._pending[rid] = ent
        msg = {"id": rid, "method": "submit", "x": x, "kwargs": kwargs,
               "deadline_ms": None if rel is None else rel * 1e3}
        try:
            self._send(sock, msg, "submit")
        except BaseException:
            with self._lock:
                self._pending.pop(rid, None)
            raise
        if ent.abs_deadline is not None:
            self._watch_deadline(rid, ent)
        return fut

    def _call(self, method: str, extra: Optional[dict] = None,
              timeout: float = 60.0, half_open: bool = False):
        sock = self._ensure_conn(half_open=half_open)
        rid = uuid.uuid4().hex
        fut: Future = Future()
        ent = _Pending(fut, time.monotonic(), None)
        with self._lock:
            self._pending[rid] = ent
        msg = {"id": rid, "method": method}
        if extra:
            msg.update(extra)
        try:
            self._send(sock, msg, method)
        except BaseException:
            with self._lock:
                self._pending.pop(rid, None)
            raise
        try:
            return fut.result(timeout)
        except (_FutureTimeout, TimeoutError):
            with self._lock:
                self._pending.pop(rid, None)
            raise TransportError(f"{method} timed out after {timeout}s",
                                 endpoint=self.endpoint)

    def predict(self, x, timeout: Optional[float] = None, **kwargs):
        return self.submit(x, **kwargs).result(timeout)

    def ping(self, timeout: float = 5.0) -> str:
        """Liveness probe; goes through the breaker HALF-OPEN (a probe
        is allowed to test a tripped endpoint; success closes it)."""
        return self._call("ping", timeout=timeout, half_open=True)

    def reload(self, params, state=None, *, timeout: float = 120.0):
        extra = {"params": params}
        if state is not None:
            extra["state"] = state
        return self._call("reload", extra, timeout=timeout)

    def warmup(self, *args, timeout: float = 300.0, **kwargs):
        return self._call("warmup", {"args": list(args), "kwargs": kwargs},
                          timeout=timeout)

    def remote_snapshot(self, timeout: float = 10.0) -> dict:
        """The SERVER's view (in-flight count, backend metrics) — a
        network call, unlike :meth:`snapshot`."""
        return self._call("snapshot", timeout=timeout)

    # fault-plane plumbing: chaos harnesses arm the CHILD's injector and
    # reconcile its counts, keeping cross-process schedules replayable
    def arm_fault(self, site: str, **spec):
        return self._call("arm_fault", {"site": site, "spec": spec})

    def disarm_fault(self, site: str):
        return self._call("disarm_fault", {"site": site})

    def reset_faults(self):
        return self._call("reset_faults")

    def fault_snapshot(self) -> dict:
        return self._call("fault_snapshot")

    def recorder_count(self, kind: str) -> int:
        return self._call("recorder_count", {"kind": kind})

    def record_hedge_win(self) -> None:
        with self._lock:
            self.rpc_hedges_won += 1

    # ------------------------------------------------------ lifecycle ----

    def kill(self) -> None:
        """SIGKILL the owned child process (chaos harness hook)."""
        if self._proc is not None and self._proc.poll() is None:
            os.kill(self._proc.pid, signal.SIGKILL)
            self._proc.wait(timeout=10)

    def revive(self, timeout: float = 10.0) -> str:
        """Probe hook for a process-owning replica: relaunch the child
        if it died, then ping. Wire this as the ReplicaSet ``probe`` and
        the prober drives the whole SIGKILL-to-rejoin cycle."""
        if self._proc is not None and self._proc.poll() is not None \
                and self._launch is not None:
            proc, (host, port) = _spawn_replica(**self._launch)
            with self._lock:
                self._proc = proc
                self.host, self.port = host, int(port)
                self._consec_failures = 0
                self._breaker_open_until = 0.0
            record_event("rpc.respawned", endpoint=self.endpoint)
        return self.ping(timeout=timeout)

    @property
    def process_alive(self) -> Optional[bool]:
        return None if self._proc is None else self._proc.poll() is None

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        budget = 10.0 if timeout is None else float(timeout)
        deadline = time.monotonic() + budget
        with self._lock:
            if self._closed:
                return
            self._closing = True
        if drain:
            while time.monotonic() < deadline:
                with self._lock:
                    if not self._pending:
                        break
                time.sleep(0.01)
        with self._lock:
            has_conn = self._sock is not None
        if has_conn:
            try:
                self._call("close", {"drain": drain,
                                     "timeout": max(
                                         deadline - time.monotonic(), 0.1)},
                           timeout=max(deadline - time.monotonic(), 0.5))
            except Exception:
                pass  # a dead server is already closed
        with self._lock:
            self._closed = True
            sock, self._sock = self._sock, None
            pend = list(self._pending.values())
            self._pending.clear()
        terr = TransportError("replica client closed",
                              endpoint=self.endpoint)
        for ent in pend:
            ent.fail_all(terr)
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass
        with self._dl_cond:
            self._dl_cond.notify_all()
        if self._proc is not None:
            try:
                self._proc.wait(timeout=max(deadline - time.monotonic(),
                                            0.5))
            except subprocess.TimeoutExpired:
                self._proc.terminate()
                try:
                    self._proc.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    self._proc.kill()
                    self._proc.wait(timeout=5)

    def __enter__(self) -> "RemoteReplica":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -------------------------------------------------------- queries ----

    def snapshot(self) -> dict:
        """LOCAL transport gauges (no network — registry-scrape safe)."""
        with self._lock:
            state = ("open"
                     if time.monotonic() < self._breaker_open_until
                     else "closed")
            return {
                "endpoint": self.endpoint,
                "connected": self._sock is not None,
                "process_alive": self.process_alive,
                "inflight": len(self._pending),
                "rpc_connects": self._connects,
                "rpc_reconnects": self.rpc_reconnects,
                "rpc_deadline_exceeded": self.rpc_deadline_exceeded,
                "rpc_hedges_won": self.rpc_hedges_won,
                "breaker": {"state": state,
                            "consecutive_failures": self._consec_failures,
                            "trips": self.breaker_trips,
                            "threshold": self.breaker_threshold},
                "connect_policy": self._policy.snapshot(),
            }

    transport_snapshot = snapshot  # ReplicaSet.snapshot() looks for this


# ============================================================= launcher ==

def _spawn_replica(factory: str, host: str = "127.0.0.1",
                   env: Optional[dict] = None,
                   startup_timeout: float = 60.0
                   ) -> Tuple[subprocess.Popen, Tuple[str, int]]:
    # -c instead of -m: the package __init__ imports this module, so
    # `-m` would re-execute it under runpy and warn about the stale
    # sys.modules entry on every child start
    cmd = [sys.executable, "-c",
           "import sys; from bigdl_tpu.serving import remote; "
           "sys.exit(remote.main(sys.argv[1:]))",
           "--factory", factory, "--host", host, "--port", "0"]
    full_env = dict(os.environ)
    if env:
        full_env.update({str(k): str(v) for k, v in env.items()})
    proc = subprocess.Popen(cmd, stdout=subprocess.PIPE, text=True,
                            bufsize=1, env=full_env)
    ready = threading.Event()
    addr: List[Any] = [None]

    def _pump():
        # keep draining stdout for the child's whole life so it can
        # never block on a full pipe; only the READY line matters
        for line in proc.stdout:
            if line.startswith("RPC_READY "):
                _, h, p = line.split()
                addr[0] = (h, int(p))
                ready.set()
        ready.set()  # EOF: child died before (or after) ready

    threading.Thread(target=_pump, name="bigdl-rpc-stdout",
                     daemon=True).start()
    if not ready.wait(startup_timeout) or addr[0] is None:
        rc = proc.poll()
        if rc is None:
            proc.kill()
            proc.wait(timeout=10)
        raise TransportError(
            f"replica process {factory!r} did not report RPC_READY "
            f"(rc={rc})")
    return proc, addr[0]


def start_replica_process(factory: str, *, host: str = "127.0.0.1",
                          env: Optional[dict] = None,
                          startup_timeout: float = 60.0,
                          name: Optional[str] = None,
                          **replica_kw) -> RemoteReplica:
    """Spawn ``python -m bigdl_tpu.serving.remote --factory mod:fn`` and
    return the connected-on-demand :class:`RemoteReplica` that OWNS the
    child (``close`` reaps it, ``revive`` relaunches it). ``factory``
    is a ``module:function`` path resolving to a zero-arg callable that
    builds the backend INSIDE the child — nothing is pickled."""
    launch = {"factory": factory, "host": host, "env": env,
              "startup_timeout": startup_timeout}
    proc, addr = _spawn_replica(**launch)
    return RemoteReplica(addr, proc=proc, launch=launch,
                         name=name or factory, **replica_kw)


# ========================================================= toy backend ==

class ToyBackend:
    """Dependency-free deterministic backend for transport tests and
    demos: ``submit(x)`` answers ``2 * x`` after ``delay`` seconds on a
    worker thread, honouring the ``deadline`` contract (late work fails
    the future with :class:`DeadlineExceeded` instead of returning)."""

    def __init__(self, delay: float = 0.0):
        self.delay = float(delay)
        self.calls = 0
        self.reloads = 0
        self.warmups = 0

    def submit(self, x, deadline: Optional[float] = None, **kw):
        self.calls += 1
        fut: Future = Future()
        t0 = time.monotonic()
        delay = float(kw.pop("delay", self.delay))

        def run():
            if delay:
                time.sleep(delay)
            waited = time.monotonic() - t0
            if deadline is not None and waited > deadline:
                _safe_fail(fut, DeadlineExceeded(waited, deadline))
                return
            _safe_resolve(fut, np.asarray(x) * 2)

        threading.Thread(target=run, name="bigdl-rpc-toy",
                         daemon=True).start()
        return fut

    def reload(self, params, state=None):
        self.reloads += 1

    def warmup(self, *a, **kw):
        self.warmups += 1

    def close(self, drain: bool = True, timeout=None):
        pass


def toy_backend():
    return ToyBackend()


def slow_toy_backend():
    return ToyBackend(delay=0.2)


# ========================================================== child entry ==

def _resolve_factory(spec: str):
    mod, _, fn = spec.partition(":")
    module = importlib.import_module(mod)
    return getattr(module, fn or "create_backend")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="host a serving backend behind the rpc wire")
    ap.add_argument("--factory", required=True,
                    help="module:function building the backend")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--name", default=None)
    args = ap.parse_args(argv)
    backend = _resolve_factory(args.factory)()
    server = ReplicaServer(backend, host=args.host, port=args.port,
                           name=args.name or args.factory, hard_exit=True)
    print(f"RPC_READY {server.host} {server.port}", flush=True)
    server.wait_closed()
    try:
        backend.close()
    except Exception:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
