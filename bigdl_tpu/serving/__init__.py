"""Serving tier: dynamic batching, continuous-batching generation, and
multi-model routing with admission control, deadlines, and SLO metrics.

The reference's serving story is ``PredictionService.scala:56`` — a
blocking-queue pool of cloned models, one request per forward. On a TPU
that wastes nearly all the hardware: throughput lives in batch
occupancy, and a jitted executable recompiles per input shape. This
package supplies the TPU-native translation:

- :class:`InferenceService` — ``submit``/``predict`` front door with
  bounded-queue backpressure, per-request deadlines, warmup, atomic
  hot-reload, and graceful close;
- :class:`DynamicBatcher` — worker thread aggregating requests into
  bucket-padded micro-batches (bounded compiled-executable set);
- :class:`GenerationEngine` — continuous-batching autoregressive
  decoding over a fixed-shape KV slot table: admission and retirement
  happen BETWEEN decode steps, per-request tokens stream through
  :class:`GenerationStream` iterator-futures;
- :class:`ModelRouter` — one ``submit(model, x)`` front door over N
  registered backends with per-model quotas;
- :class:`ReplicaSet` — N replicas of one model on disjoint device sets
  behind one ``submit``: least-loaded placement, consecutive-failure
  eviction with probe-driven rejoin, and draining rolling reloads (a
  model name registered with a LIST of backends resolves to one
  transparently); pair with ``parallel.serving_meshes`` /
  ``parallel.tp.transformer_tp_pspecs`` for tensor-parallel replicas;
- :func:`watch_checkpoints` — poll a ckpt-tier ``MANIFEST.json`` and
  hot-reload a running service on each new committed entry;
- :class:`ServingMetrics` — served/rejected/expired counters, batch and
  latency distributions, padding waste, and the token-level generation
  fields (TTFT, tokens/sec, slot occupancy).

Prefix caching (PR 12) rides the paged engine: ``prefix_cache=True``
shares FULL, immutable prompt pages across requests by refcounted
reference (:class:`PrefixCache` radix index over the ``PagePool``) —
repeated system prompts / few-shot templates prefill once and every
later request skips the covered chunks, bit-identically (see README
"Prefix caching").

The int8 fast tier rides the same surfaces: ``quantize="int8"`` on
:class:`GenerationEngine` / :class:`InferenceService` runs every GEMM
as a true ``s8 x s8 -> s32`` MXU dot (``nn.quantized
.quantize_for_serving``), and ``cache_dtype="int8"`` stores KV pages
int8 with per-token fp32 scale pools — ~2x the concurrent sequences
per KV byte on top of paging's win, with compile-once, donation,
sharding, and hot-reload contracts intact (see README "Quantized
serving").

The cross-process fabric (PR 14) extends the same front door over
process boundaries: :class:`RemoteReplica` proxies any backend hosted
in a child process behind a length-prefixed msgpack/json socket wire
(``serving.rpc``) — deadlines propagate in the request header, the
error taxonomy round-trips intact, a connection-level circuit breaker
feeds the ReplicaSet's eviction, and ``ReplicaSet(hedge=True)`` adds
p99-delayed tail-latency hedging with request-id idempotency (see
README "Running a multi-process fleet").

Prefill/decode disaggregation (PR 15) splits the engine's roles:
:class:`DisaggregatedEngine` fronts a dedicated prefill-role engine
(only ``prefill``/``chunk`` kernels; its final chunk gathers the
request's finished KV pages into a device block) and a dedicated
decode-role engine (only ``decode``; admits exclusively via
``submit_prefilled`` with pages materialized), so decode inter-token
latency never pays for a neighbour's prompt. Same-process handoff is a
jitted gather/scatter between pools (``PagePool.export_pages`` /
``adopt_pages``); cross-process hosts a :class:`PrefillWorker` behind
the RPC fabric. Streams are bit-identical to the monolithic engine
(see README "Disaggregated prefill/decode").

The KV memory hierarchy (PR 18) adds a host tier beneath the device
``PagePool``: ``host_pages=N`` on a paged, prefix-cached engine spills
LRU-evicted prefix pages to a :class:`HostPageStore` (async,
double-buffered device→host copies overlapped with decode) and
restores them bit-identically on a later hit; ``submit(priority=)``
lets the engine swap out a low-priority idle stream's pages to host to
admit a blocked higher-priority request, resuming the parked stream
byte-exact (see README "KV memory hierarchy").

Structured generation (PR 20) makes a grammar a property of the
request: ``submit(grammar=...)`` takes a token-level automaton compiled
once per distinct regex/JSON-schema grammar (``bigdl_tpu.grammar``,
cached and shared across requests), and every decode step of that
stream samples under the automaton's current-state mask — delivered as
the per-slot additive-bias argument the jitted step already traces, so
compile-once and schedule invariance survive, and constrained/
unconstrained slots share one executable. Every emitted stream parses;
a budget-exhausted or wedged stream fails with a typed
:class:`GrammarViolation`. Composes with chunked prefill, int8,
tensor parallelism, and speculative decoding (masked tokens carry zero
target probability, so the rejection sampler needs no changes — see
README "Structured generation").

``optim.predictor.PredictionService`` is now a thin compatibility shim
over :class:`InferenceService`.
"""

from bigdl_tpu.serving.autoscale import (
    AutoscaleController,
    DisaggregatedFleet,
    EnginePool,
    ReplicaPool,
    ScalingPolicy,
)
from bigdl_tpu.serving.batcher import DynamicBatcher, bucket_sizes_for
from bigdl_tpu.serving.disagg import (
    DisaggregatedEngine,
    PageBlockMover,
    PrefillWorker,
)
from bigdl_tpu.serving.engine import (
    DecodeKernels,
    GenerationEngine,
    GenerationStream,
    PagedDecodeKernels,
    SpeculativeKernels,
    static_generate,
)
from bigdl_tpu.serving.kv_tiers import HostPageStore
from bigdl_tpu.serving.paging import PagePool
from bigdl_tpu.serving.prefix_cache import PrefixCache
from bigdl_tpu.serving.errors import (
    DeadlineExceeded,
    GrammarViolation,
    Overloaded,
    RemoteError,
    ReplicaUnavailable,
    ServingError,
    StreamCancelled,
    TransportError,
    UnknownModel,
)
from bigdl_tpu.serving.hot_reload import CheckpointWatcher, watch_checkpoints
from bigdl_tpu.serving.metrics import ServingMetrics
from bigdl_tpu.serving.remote import (
    RemoteReplica,
    ReplicaServer,
    start_replica_process,
)
from bigdl_tpu.serving.replica import ReplicaSet
from bigdl_tpu.serving.router import ModelRouter
from bigdl_tpu.serving.service import InferenceService

__all__ = [
    "AutoscaleController",
    "CheckpointWatcher",
    "DeadlineExceeded",
    "DecodeKernels",
    "DisaggregatedEngine",
    "DisaggregatedFleet",
    "DynamicBatcher",
    "EnginePool",
    "GenerationEngine",
    "GrammarViolation",
    "PageBlockMover",
    "PrefillWorker",
    "GenerationStream",
    "HostPageStore",
    "InferenceService",
    "ModelRouter",
    "Overloaded",
    "PagePool",
    "PagedDecodeKernels",
    "PrefixCache",
    "RemoteError",
    "RemoteReplica",
    "ReplicaPool",
    "ReplicaServer",
    "ReplicaSet",
    "ReplicaUnavailable",
    "ScalingPolicy",
    "ServingError",
    "ServingMetrics",
    "SpeculativeKernels",
    "StreamCancelled",
    "TransportError",
    "UnknownModel",
    "start_replica_process",
    "bucket_sizes_for",
    "static_generate",
    "watch_checkpoints",
]
