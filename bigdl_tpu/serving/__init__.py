"""Serving tier: dynamic-batching inference with admission control,
deadlines, and SLO metrics.

The reference's serving story is ``PredictionService.scala:56`` — a
blocking-queue pool of cloned models, one request per forward. On a TPU
that wastes nearly all the hardware: throughput lives in batch
occupancy, and a jitted executable recompiles per input shape. This
package supplies the TPU-native translation:

- :class:`InferenceService` — ``submit``/``predict`` front door with
  bounded-queue backpressure, per-request deadlines, warmup, and
  graceful close;
- :class:`DynamicBatcher` — worker thread aggregating requests into
  bucket-padded micro-batches (bounded compiled-executable set);
- :class:`ServingMetrics` — served/rejected/expired counters, batch and
  latency distributions, padding waste.

``optim.predictor.PredictionService`` is now a thin compatibility shim
over :class:`InferenceService`.
"""

from bigdl_tpu.serving.batcher import DynamicBatcher, bucket_sizes_for
from bigdl_tpu.serving.errors import DeadlineExceeded, Overloaded, ServingError
from bigdl_tpu.serving.metrics import ServingMetrics
from bigdl_tpu.serving.service import InferenceService

__all__ = [
    "DynamicBatcher",
    "DeadlineExceeded",
    "InferenceService",
    "Overloaded",
    "ServingError",
    "ServingMetrics",
    "bucket_sizes_for",
]
