"""Host-RAM KV tier beneath the device ``PagePool`` (PR 18).

Device HBM is the scarcest resource in the stack, and until this PR the
``PagePool`` treated it as the ONLY tier: a prefix page that lost the
LRU race was gone (its next request re-prefills from scratch) and page
pressure was a hard admission wall. Host RAM is 10-100x HBM on every
TPU host, and the PR-15 handoff machinery — ``PageBlockMover``
gather/scatter plus ``export_pages``/``adopt_pages`` accounting — is
already exactly the device half of a tier boundary. This module is the
host half, in the spirit of tiered-KV serving systems (Mooncake,
InfiniGen — PAPERS.md):

- **offloaded prefixes.** When the prefix cache would evict an
  unreferenced page chain, the engine gathers each victim page into a
  fixed-shape device block (the SAME jitted gather the disaggregation
  handoff compiled), starts an async device->host copy, and — once the
  copy lands, polled between scheduler iterations (under async
  scheduling the poll runs inside the overlap window, while the
  dispatched decode step is still in flight on device), never
  blocking a step — files the page's host bytes here under the same
  ``(model version, page-aligned token prefix)`` radix key the device
  index used. A later admission that misses the device index probes
  this store; a hit allocates fresh device pages, scatters the host
  rows back (one jitted scatter, bit-identical bytes — the copy is a
  memcpy in both directions, int8 scale pools ride along as ordinary
  leaves), and republishes the chain. Restore MOVES the entry back to
  the device tier: a page lives in exactly one tier at a time, which
  keeps the drain invariants first-order ("both tiers reach zero").
- **parked streams.** Stream swap-out (the QoS path: a low-priority
  active stream yields its device pages to a higher-priority waiter)
  books its exported pages here while the stream is parked; the
  payload itself rides the re-queued request. Accounting only — the
  store never owns a ``GenerationStream``.
- **bounded, LRU.** ``capacity_pages`` caps the prefix side; inserting
  past it evicts the oldest host entries (beyond the last tier there
  is only the floor). Parked streams are never evicted — a parked
  stream is a live request, not a cache entry.

Single-writer like the ``PagePool``: all mutation happens on the
engine loop thread; ``snapshot()`` reads plain ints and is safe to
scrape from any thread.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

__all__ = ["HostPageStore"]

# a prefix entry's radix key: (prefix-index version, the page-aligned
# token prefix ending at the stored page)
_PrefixKey = Tuple[int, Tuple[int, ...]]


class _HostEntry:
    """One offloaded page: host-side numpy rows per cache leaf (shape
    ``leaf.shape[1:]`` — one page of K/V, scale-pool rows included for
    int8 lanes) plus the LRU stamp of its last touch."""

    __slots__ = ("rows", "stamp")

    def __init__(self, rows: Any, stamp: int):
        self.rows = rows
        self.stamp = stamp


class HostPageStore:
    """Host-RAM page store: the tier beneath one device ``PagePool``.

    ``capacity_pages`` bounds the PREFIX side (parked-stream pages are
    live requests and never count against it); ``page_bytes`` prices
    one page across all layers (``paging.page_bytes`` x num_layers, the
    engine's ``_kv_page_bytes``) so the byte gauges agree with the
    device tier's accounting.
    """

    def __init__(self, capacity_pages: int, *, page_bytes: int = 0,
                 name: str = "host"):
        if capacity_pages < 1:
            raise ValueError("capacity_pages must be >= 1")
        self.capacity_pages = int(capacity_pages)
        self.page_bytes = int(page_bytes)
        self.name = name
        self._prefix: Dict[_PrefixKey, _HostEntry] = {}
        self._streams: Dict[int, int] = {}   # swap id -> parked pages
        self._clock = 0
        # counters (monotonic; gauges derive from the dicts above)
        self.offloaded_pages = 0   # device -> host prefix copies landed
        self.restored_pages = 0    # host -> device prefix copies
        self.dropped_pages = 0     # offloads abandoned (fault / in-flight cap)
        self.evicted_pages = 0     # host-side LRU evictions (capacity)
        self.stream_swaps_out = 0  # streams parked here
        self.stream_swaps_in = 0   # parked streams resumed

    # ------------------------------------------------------- queries ----

    @property
    def prefix_pages(self) -> int:
        return len(self._prefix)

    @property
    def stream_pages(self) -> int:
        return sum(self._streams.values())

    @property
    def pages(self) -> int:
        """Pages currently resident in the host tier (gauge): offloaded
        prefix entries plus every parked stream's exported pages."""
        return len(self._prefix) + self.stream_pages

    @property
    def bytes_used(self) -> int:
        return self.pages * self.page_bytes

    def has_prefix(self, version: int, prefix: Tuple[int, ...]) -> bool:
        """Pure membership probe (no LRU touch) — the admission path
        counts its consecutive host hits before committing pages."""
        return (int(version), tuple(prefix)) in self._prefix

    # ------------------------------------------------------ mutators ----

    def put_prefix(self, version: int, prefix: Tuple[int, ...],
                   rows: Any) -> bool:
        """File one offloaded page under its radix key, LRU-evicting the
        oldest host entries past ``capacity_pages`` (the floor below the
        last tier is the floor). Re-offloading a live key refreshes it
        in place. Returns False when the page was dropped instead
        (capacity zero-sum against newer entries never happens — the
        incoming page is always the newest)."""
        key = (int(version), tuple(prefix))
        self._clock += 1
        hit = self._prefix.get(key)
        if hit is not None:
            hit.rows = rows
            hit.stamp = self._clock
            return True
        while len(self._prefix) >= self.capacity_pages:
            oldest = min(self._prefix.items(), key=lambda kv: kv[1].stamp)
            del self._prefix[oldest[0]]
            self.evicted_pages += 1
        self._prefix[key] = _HostEntry(rows, self._clock)
        self.offloaded_pages += 1
        return True

    def take_prefix(self, version: int,
                    prefix: Tuple[int, ...]) -> Optional[Any]:
        """Restore hit: remove the entry and return its host rows (MOVE
        semantics — the page re-enters the device tier; a later
        eviction re-offloads it). None on miss."""
        entry = self._prefix.pop((int(version), tuple(prefix)), None)
        if entry is None:
            return None
        self.restored_pages += 1
        return entry.rows

    def drop_prefix(self, version: int, prefix: Tuple[int, ...]) -> bool:
        """Discard one entry without restoring it (a faulted restore
        degrades the affected entry to a miss — it must not strand in
        the host tier)."""
        if self._prefix.pop((int(version), tuple(prefix)), None) is None:
            return False
        self.dropped_pages += 1
        return True

    def record_drop(self, n: int = 1) -> None:
        """Count ``n`` offload candidates abandoned BEFORE reaching the
        store (an injected ``kv.offload`` fault, or the in-flight copy
        cap) — the pages simply evicted, nothing strands."""
        self.dropped_pages += int(n)

    def park_stream(self, swap_id: int, n_pages: int) -> None:
        """Book a swapped-out stream's exported pages in the host tier.
        The swap payload itself rides the re-queued request — the store
        holds accounting only, so a failed resume can never strand
        device state here."""
        self._streams[int(swap_id)] = int(n_pages)
        self.stream_swaps_out += 1

    def unpark_stream(self, swap_id: int) -> int:
        """Drop a parked stream's booking (resume admission, expiry, or
        a faulted swap-in — every exit path). Returns the pages it
        held (0 if unknown — idempotent on purpose)."""
        n = self._streams.pop(int(swap_id), None)
        if n is None:
            return 0
        self.stream_swaps_in += 1
        return n

    def clear(self) -> int:
        """Drop everything (engine close, reload flush, terminal
        failure paths) so both tiers drain to zero together. Returns
        pages released."""
        released = self.pages
        self._prefix.clear()
        self._streams.clear()
        return released

    # ------------------------------------------------------- readers ----

    def snapshot(self) -> dict:
        """Plain-int gauges/counters for the obs registry — the host
        half of the two-tier accounting, shaped like the PagePool's
        with ``tier`` naming which side of the boundary it reports."""
        return {
            "tier": "host",
            "pages_total": self.capacity_pages,
            "pages_in_use": self.pages,
            "prefix_pages": self.prefix_pages,
            "stream_pages": self.stream_pages,
            "bytes_in_use": self.bytes_used,
            "by_owner": {k: v for k, v in (("prefix", self.prefix_pages),
                                           ("stream", self.stream_pages))
                         if v},
            "offloaded_pages": self.offloaded_pages,
            "restored_pages": self.restored_pages,
            "dropped_pages": self.dropped_pages,
            "evicted_pages": self.evicted_pages,
            "stream_swaps_out": self.stream_swaps_out,
            "stream_swaps_in": self.stream_swaps_in,
        }
