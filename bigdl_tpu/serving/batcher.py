"""Dynamic micro-batching: the throughput engine of the serving tier.

A single worker thread drains a bounded request queue into micro-batches
under a ``(max_batch_size, max_wait_ms)`` policy: the first request opens
a batch window, the window closes when either the batch is full or the
wait budget is spent, and one jitted forward serves the whole batch.

Shape discipline: a jitted forward recompiles per input shape, so
batches are padded UP to the nearest **bucket** size (powers of two up
to ``max_batch_size``) — the compiled-executable set is bounded at
``len(bucket_sizes)`` per feature shape, however traffic fluctuates.
Padding rows repeat row 0 (any valid row works; padding outputs are
sliced off before scatter) and are charged to the padding-waste metric.

The reference's ``PredictionService.scala:56`` answer to concurrency is
a blocking-queue pool of cloned models, one request per forward; here
the pool collapses to one compiled executable and concurrency becomes
batch occupancy — the TPU-native translation.
"""

from __future__ import annotations

import queue as _queue
import threading
import time
import weakref
from concurrent.futures import Future
from typing import Any, Callable, List, Optional

import numpy as np
import jax

from bigdl_tpu.optim.predictor import _split_batch
from bigdl_tpu.serving.errors import DeadlineExceeded, Overloaded
from bigdl_tpu.serving.metrics import ServingMetrics


class _Request:
    """One enqueued inference request: an UNBATCHED feature tree, the
    future its row lands in, and its timing/deadline bookkeeping
    (``deadline`` is an absolute ``time.monotonic()`` instant)."""

    __slots__ = ("x", "future", "t_submit", "deadline")

    def __init__(self, x: Any, future: Future,
                 t_submit: float, deadline: Optional[float]):
        self.x = x
        self.future = future
        self.t_submit = t_submit
        self.deadline = deadline


def _worker_loop(batcher_ref: "weakref.ref[DynamicBatcher]",
                 q: _queue.Queue) -> None:
    """Batcher worker body. While IDLE it holds only the queue and a weak
    ref — never the batcher — so a batcher whose owner forgot ``close()``
    becomes collectable and its worker exits, instead of leaking a thread
    pinning the model and params forever. The strong ref is taken only
    for the duration of processing one batch."""
    while True:
        try:
            first = q.get(timeout=0.05)
        except _queue.Empty:
            batcher = batcher_ref()
            if batcher is None or batcher._closed:
                return
            del batcher
            continue
        batcher = batcher_ref()
        if batcher is None:
            # owner was collected with requests still queued: nobody will
            # ever run them — fail their futures rather than strand them
            for r in _drain(q, first):
                if not r.future.done():
                    r.future.set_exception(RuntimeError(
                        "serving batcher was garbage-collected with "
                        "requests in flight"))
            return
        batcher._consume(first)
        del batcher


def _drain(q: _queue.Queue, first: "_Request") -> List["_Request"]:
    reqs = [first]
    while True:
        try:
            reqs.append(q.get_nowait())
        except _queue.Empty:
            return reqs


def bucket_sizes_for(max_batch_size: int) -> List[int]:
    """Powers of two up to ``max_batch_size`` (which is always included
    as the top bucket, power of two or not)."""
    if max_batch_size < 1:
        raise ValueError("max_batch_size must be >= 1")
    sizes, b = [], 1
    while b < max_batch_size:
        sizes.append(b)
        b *= 2
    sizes.append(max_batch_size)
    return sizes


class DynamicBatcher:
    """Queue + worker thread turning request streams into bucket-padded
    micro-batches.

    ``forward`` takes one BATCHED feature tree and returns the batched
    output tree (it closes over params/state — see
    :class:`~bigdl_tpu.serving.service.InferenceService`).
    """

    def __init__(self, forward: Callable[[Any], Any], *,
                 max_batch_size: int = 8, max_wait_ms: float = 2.0,
                 max_queue: int = 64,
                 metrics: Optional[ServingMetrics] = None):
        if max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        self.forward = forward
        self.max_batch_size = int(max_batch_size)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.max_queue = int(max_queue)
        self.bucket_sizes = bucket_sizes_for(self.max_batch_size)
        self.metrics = metrics or ServingMetrics()
        self._q: _queue.Queue = _queue.Queue(maxsize=self.max_queue)
        self._closed = False
        # serializes the closed-check-then-put against close() setting the
        # flag: without it a submit could land a request AFTER close()'s
        # final drain, stranding its future forever
        self._admit_lock = threading.Lock()
        # the thread targets a module function holding only a WEAK ref:
        # a bound-method target would keep an unclosed batcher (and the
        # model/params its forward closes over) alive forever
        self._worker = threading.Thread(
            target=_worker_loop, args=(weakref.ref(self), self._q),
            name="bigdl-serving-batcher", daemon=True)
        self._worker.start()

    # ------------------------------------------------------ admission ----

    def submit(self, req: _Request) -> None:
        """Enqueue or reject-now: a full queue raises :class:`Overloaded`
        on the CALLER's thread (backpressure, never unbounded buffering)."""
        with self._admit_lock:
            if self._closed:
                raise RuntimeError("serving batcher is closed")
            try:
                self._q.put_nowait(req)
            except _queue.Full:
                self.metrics.record_rejected()
                raise Overloaded(self._q.qsize(), self.max_queue) from None
        self.metrics.set_queue_depth(self._q.qsize())

    # --------------------------------------------------------- worker ----

    def _consume(self, first: _Request) -> None:
        """Collect one batch window starting from ``first``, then execute."""
        reqs = [first]
        t_open = time.monotonic()
        while len(reqs) < self.max_batch_size:
            remaining = self.max_wait_s - (time.monotonic() - t_open)
            if remaining <= 0:
                break
            try:
                reqs.append(self._q.get(timeout=remaining))
            except _queue.Empty:
                break
        self.metrics.set_queue_depth(self._q.qsize())
        try:
            self._execute(reqs)
        except Exception as e:  # never let the worker die silently:
            # a dead worker strands every future forever
            self.metrics.record_failed(len(reqs))
            for r in reqs:
                if not r.future.done():
                    r.future.set_exception(e)

    def bucket(self, n: int) -> int:
        """Smallest bucket >= n."""
        for b in self.bucket_sizes:
            if b >= n:
                return b
        return self.max_batch_size

    def _execute(self, reqs: List[_Request]) -> None:
        now = time.monotonic()
        live: List[_Request] = []
        for r in reqs:
            if r.deadline is not None and now > r.deadline:
                # dropped BEFORE taking a batch slot: an expired request
                # must never displace a servable one
                self.metrics.record_expired()
                r.future.set_exception(DeadlineExceeded(
                    now - r.t_submit, r.deadline - r.t_submit))
            elif r.future.set_running_or_notify_cancel():
                live.append(r)
        if not live:
            return

        flat0, treedef = jax.tree_util.tree_flatten(live[0].x)
        ok: List[_Request] = [live[0]]
        rows: List[List[Any]] = [flat0]
        for r in live[1:]:
            flat, td = jax.tree_util.tree_flatten(r.x)
            if td != treedef or any(
                    np.shape(a) != np.shape(b) for a, b in zip(flat, flat0)):
                r.future.set_exception(ValueError(
                    "request feature tree structure/shape differs from the "
                    "batch it was grouped with; one InferenceService serves "
                    "one input signature"))
                self.metrics.record_failed()
                continue
            ok.append(r)
            rows.append(flat)
        live = ok

        n = len(rows)
        b = self.bucket(n)
        pad = b - n
        batched = jax.tree_util.tree_unflatten(treedef, [
            np.stack(list(col) + [col[0]] * pad)
            for col in zip(*rows)
        ])
        t_exec = time.monotonic()
        try:
            out = self.forward(batched)
        except Exception as e:  # compile/runtime failure: fail the batch
            self.metrics.record_failed(len(live))
            for r in live:
                if not r.future.done():
                    r.future.set_exception(e)
            return
        self.metrics.record_batch(n, b)
        per_row = _split_batch(out, n)
        t_done = time.monotonic()
        for r, row in zip(live, per_row):
            if not r.future.done():
                r.future.set_result(row)
                self.metrics.record_served(
                    t_done - r.t_submit, t_exec - r.t_submit)

    # -------------------------------------------------------- shutdown ----

    def close(self, drain: bool = True, timeout: Optional[float] = None) -> None:
        """Stop accepting; with ``drain`` (default) the worker finishes
        every queued request before exiting, otherwise queued futures fail
        with ``RuntimeError``."""
        with self._admit_lock:
            # under the lock, every admitted request is in the queue BEFORE
            # the flag flips: the worker (or the final sweep below) sees it
            self._closed = True

        def _fail_queued():
            while True:
                try:
                    r = self._q.get_nowait()
                except _queue.Empty:
                    return
                if not r.future.done():
                    r.future.set_exception(
                        RuntimeError("serving batcher closed before request ran"))

        if not drain:
            _fail_queued()
        self._worker.join(timeout)
        # the worker's idle branch can observe Empty just before a
        # pre-close put landed and then exit on the closed flag — sweep
        # the queue once more rather than strand such a future
        _fail_queued()
