"""Elastic fleet (PR 16): SLO-driven autoscaling over the serving
fabric, with per-role scaling of the disaggregated prefill/decode
pools.

Every prerequisite already exists in the stack — PR-11 gauges
(``MetricsRegistry.collect()``), PR-14 process spawning
(``start_replica_process``), PR-7 drain machinery (ReplicaSet draining
rolling reloads), PR-15 roles (prefill/decode engines) — but nothing
closes the loop: fleet size is fixed at wiring time, exactly like the
reference BigDL's static Spark executor allocation. This module is the
missing control plane:

- **Rules** (:func:`above` / :func:`below` / :func:`all_of` /
  :func:`any_of`) — tiny predicates over one flat metrics sample, the
  vocabulary scaling policies are written in. A missing key means the
  signal has no data (an idle reservoir window): :func:`above` reads
  that as "no breach" and :func:`below` as "quiet" by default, so an
  idle fleet scales down and never flaps up.
- **:class:`ScalingPolicy`** — per-pool bounds plus hysteresis: a
  scale-up needs ``breach_up`` CONSECUTIVE breaching polls and respects
  ``cooldown_up_s`` since the last scale-up; scale-down is deliberately
  stickier (``breach_down`` polls, ``cooldown_down_s`` since the last
  action in EITHER direction — growing and immediately shrinking is the
  classic flap).
- **Pools** — what the controller grows and shrinks. :class:`ReplicaPool`
  wraps a :class:`~bigdl_tpu.serving.replica.ReplicaSet` and a backend
  factory (an in-process engine builder or a
  ``start_replica_process`` closure): scale-up builds a backend, adds
  it WARMING (visible, unplaceable), warms it, then activates; scale-
  down drains the least-loaded member through the PR-7 gate (a busy
  member bounces the scale-down rather than failing a stream — the
  fleet never drops below N-1 serving). :class:`EnginePool` adapts one
  role of a :class:`DisaggregatedFleet` to the same protocol.
- **:class:`DisaggregatedFleet`** — the PR-15 front door generalised
  from 1 prefill + 1 decode engine to N + M: least-loaded placement
  across the prefill pool, per-request KV handoff to the least-loaded
  decode member, member death contained to ``ReplicaUnavailable`` on
  the affected streams. Prefill and decode pools scale INDEPENDENTLY —
  the canonical production win of disaggregation (prompt-heavy traffic
  grows the prefill pool on TTFT/queue pressure while the decode pool
  idles, and vice versa for long-generation traffic).
- **:class:`AutoscaleController`** — the poll loop: each tick heals
  dead members (a SIGKILLed replica is replaced, not mourned), samples
  the registry once, evaluates every pool's policy against it, and
  applies at most one membership change per pool per tick. Determinism
  for tests: ``poll_once(now=...)`` with an injected clock drives the
  whole state machine without threads or sleeps.

The controller never touches engine internals — it reads the same
``/metrics`` surface an external operator would and acts through the
same membership API, so everything it does is reproducible by hand.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from bigdl_tpu.serving.engine import GenerationStream
from bigdl_tpu.serving.errors import (
    DeadlineExceeded,
    Overloaded,
    ReplicaUnavailable,
    StreamCancelled,
    UnknownModel,
)

log = logging.getLogger("bigdl_tpu.serving.autoscale")

from bigdl_tpu.obs.recorder import record_event

__all__ = [
    "above",
    "below",
    "all_of",
    "any_of",
    "ScalingPolicy",
    "ReplicaPool",
    "EnginePool",
    "DisaggregatedFleet",
    "AutoscaleController",
]

#: Request-scoped failures a fleet member may surface to a caller
#: as-is; anything else from a member means the MEMBER broke, and the
#: front door translates it to :class:`ReplicaUnavailable`.
_CLIENT_ERRORS = (Overloaded, DeadlineExceeded, StreamCancelled,
                  UnknownModel, ValueError, TypeError)

Rule = Callable[[Dict[str, Any]], bool]


# ------------------------------------------------------------- rules ----


def _lookup(sample: Dict[str, Any], key: str) -> Optional[float]:
    """Resolve ``key`` in a metrics sample: flat dot-joined hit first
    (the ``MetricsRegistry.collect()`` shape), else a dot-path descent
    into nested dicts (a raw ``snapshot()``). Non-numeric and missing
    both resolve to None — "no data", which each rule interprets."""
    if key in sample:
        v = sample[key]
    else:
        v: Any = sample
        for part in key.split("."):
            if not isinstance(v, dict) or part not in v:
                return None
            v = v[part]
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v)


def above(key: str, threshold: float, *, missing: bool = False) -> Rule:
    """True when ``sample[key] > threshold``. A missing/idle signal is
    NOT a breach by default (an empty reservoir window must not grow
    the fleet)."""

    def rule(sample: Dict[str, Any]) -> bool:
        v = _lookup(sample, key)
        return missing if v is None else v > threshold

    rule.describe = f"{key} > {threshold:g}"  # type: ignore[attr-defined]
    return rule


def below(key: str, threshold: float, *, missing: bool = True) -> Rule:
    """True when ``sample[key] < threshold``. A missing/idle signal IS
    quiet by default (no recent latency samples = no load = eligible
    for scale-down)."""

    def rule(sample: Dict[str, Any]) -> bool:
        v = _lookup(sample, key)
        return missing if v is None else v < threshold

    rule.describe = f"{key} < {threshold:g}"  # type: ignore[attr-defined]
    return rule


def _combine(rules: Sequence[Rule], op: str) -> Rule:
    fn = all if op == "and" else any

    def rule(sample: Dict[str, Any]) -> bool:
        return fn(r(sample) for r in rules)

    joiner = f" {op} "
    rule.describe = "(" + joiner.join(  # type: ignore[attr-defined]
        getattr(r, "describe", "<rule>") for r in rules) + ")"
    return rule


def all_of(*rules: Rule) -> Rule:
    """Every rule must hold (scale-down guards compose with this)."""
    return _combine(rules, "and")


def any_of(*rules: Rule) -> Rule:
    """Any one rule suffices (scale-up pressure composes with this)."""
    return _combine(rules, "or")


# ------------------------------------------------------------ policy ----


class ScalingPolicy:
    """Bounds + rules + hysteresis for one pool.

    ``up_when`` / ``down_when`` are :data:`Rule` predicates over the
    controller's per-tick metrics sample. Hysteresis has three layers,
    all of which must agree before the pool moves:

    - **streaks** — the rule must hold for ``breach_up`` (resp.
      ``breach_down``) CONSECUTIVE polls; one noisy sample moves
      nothing, and any non-breaching poll resets the streak;
    - **cooldowns** — at least ``cooldown_up_s`` since the last
      scale-up (a new member needs time to absorb load before its
      absence from the gauges can justify another); scale-down
      additionally waits ``cooldown_down_s`` since the last action in
      EITHER direction, so the fleet never shrinks on the quiet gauges
      a just-added member created;
    - **bounds** — ``min_replicas`` / ``max_replicas`` clamp hard,
      whatever the rules say.
    """

    def __init__(self, *, min_replicas: int = 1, max_replicas: int = 4,
                 up_when: Optional[Rule] = None,
                 down_when: Optional[Rule] = None,
                 breach_up: int = 2, breach_down: int = 3,
                 cooldown_up_s: float = 5.0,
                 cooldown_down_s: float = 15.0):
        if min_replicas < 0 or max_replicas < max(min_replicas, 1):
            raise ValueError(
                f"bad bounds: min={min_replicas} max={max_replicas}")
        if breach_up < 1 or breach_down < 1:
            raise ValueError("breach thresholds must be >= 1")
        self.min_replicas = int(min_replicas)
        self.max_replicas = int(max_replicas)
        self.up_when = up_when
        self.down_when = down_when
        self.breach_up = int(breach_up)
        self.breach_down = int(breach_down)
        self.cooldown_up_s = float(cooldown_up_s)
        self.cooldown_down_s = float(cooldown_down_s)

    def describe(self) -> Dict[str, Any]:
        return {
            "min": self.min_replicas, "max": self.max_replicas,
            "up_when": getattr(self.up_when, "describe", None),
            "down_when": getattr(self.down_when, "describe", None),
            "breach_up": self.breach_up, "breach_down": self.breach_down,
            "cooldown_up_s": self.cooldown_up_s,
            "cooldown_down_s": self.cooldown_down_s,
        }


# ------------------------------------------------------------- pools ----


class ReplicaPool:
    """Scalable-pool adapter over a :class:`ReplicaSet` + a backend
    factory.

    ``factory`` is a zero-arg callable returning a fresh backend — an
    in-process engine builder for same-host elasticity, or a closure
    over :func:`~bigdl_tpu.serving.remote.start_replica_process` for a
    real child process per member. Scale-up runs warm-before-rotation:
    the backend joins the set WARMING (visible in gauges and healthz
    ``total``, unplaceable), compiles via ``warmup()``, then activates —
    traffic never lands on a cold engine. Scale-down picks the
    least-loaded serving member and drains it through the PR-7 gate;
    a member still busy at ``drain_timeout`` bounces the scale-down
    (``TimeoutError``) instead of failing its streams.

    When a ``registry`` is given, each member's metrics surface is
    registered under ``<name>.<member>`` on the way in and unregistered
    on the way out, so ``/metrics`` tracks live membership exactly
    (the PR-16 registry churn fix)."""

    def __init__(self, rset, factory: Callable[[], Any], *,
                 name: str = "pool", registry=None, warm: bool = True,
                 drain_timeout: float = 30.0):
        self.rset = rset
        self.factory = factory
        self.name = name
        self.registry = registry
        self.warm = bool(warm)
        self.drain_timeout = float(drain_timeout)
        if registry is not None:
            for r in rset._replicas:
                self._register_member(r.name, r.backend)

    # ------------------------------------------------- registry churn ----

    def _member_source(self, backend) -> Optional[Any]:
        if callable(getattr(backend, "snapshot", None)):
            return backend
        return getattr(backend, "metrics", None)

    def _register_member(self, member: str, backend) -> None:
        if self.registry is None:
            return
        src = self._member_source(backend)
        if src is not None:
            # replace=True: a crashed member may not have unregistered
            self.registry.register(f"{self.name}.{member}", src,
                                   replace=True)

    def _unregister_member(self, member: str) -> None:
        if self.registry is not None:
            self.registry.unregister(f"{self.name}.{member}")

    # ----------------------------------------------------- membership ----

    def size(self) -> int:
        """Members that count against the policy bounds: serving plus
        warming (a member mid-warmup already holds its slot — counting
        it prevents a double scale-up while it compiles)."""
        with self.rset._cond:
            return sum(1 for r in self.rset._replicas
                       if not r.draining and (r.healthy or r.warming))

    def scale_up(self) -> str:
        backend = self.factory()
        name = self.rset.add_replica(backend, warming=self.warm)
        if self.warm:
            try:
                backend.warmup()
            except Exception:
                # a backend that cannot even warm must not enter
                # rotation — drop it and let the next tick retry
                self.rset.remove_replica(name, force=True)
                raise
            self.rset.activate_replica(name)
        self._register_member(name, backend)
        return name

    def scale_down(self) -> str:
        with self.rset._cond:
            serving = [r for r in self.rset._replicas
                       if r.healthy and not r.draining and not r.warming]
            if len(serving) <= 1:
                raise ValueError(
                    f"pool {self.name!r}: refusing to drain the last "
                    f"serving member")
            # least-loaded first; newest (highest index) among ties, so
            # steady state converges back to the oldest members
            victim = min(serving, key=lambda r: (r.inflight, -r.index))
            name = victim.name
        self.rset.remove_replica(name, drain_timeout=self.drain_timeout)
        self._unregister_member(name)
        return name

    def heal(self) -> List[str]:
        """Replace members whose PROCESS is gone (a quarantined-but-
        alive backend stays on the probe/rejoin path — killing it would
        fight the prober). Returns the replacement member names."""
        with self.rset._cond:
            dead = [r.name for r in self.rset._replicas
                    if not r.healthy
                    and getattr(r.backend, "process_alive", True) is False]
        replaced = []
        for name in dead:
            self.rset.remove_replica(name, force=True)
            self._unregister_member(name)
            record_event("autoscale.heal", pool=self.name, dead=name)
            replaced.append(self.scale_up())
        return replaced

    def snapshot(self) -> Dict[str, Any]:
        return {"size": self.size(),
                "healthy": len(self.rset.healthy_replicas),
                "warming": len(self.rset.warming_replicas),
                "total": self.rset.n_replicas}


# ------------------------------------------------- disaggregated fleet ----


class _FleetStream(GenerationStream):
    """Front-door stream of one fleet request. Cancels forward to the
    prefill-role inner stream (so a cancel lands pre-handoff), and any
    terminal error that is not a request-scoped client error — a member
    died mid-stream — reaches the consumer as
    :class:`ReplicaUnavailable` with the member's failure chained, so
    the chaos contract ("the front door only ever raises
    Overloaded/ReplicaUnavailable") holds for in-flight streams too."""

    def __init__(self, fleet: "DisaggregatedFleet"):
        super().__init__()
        self._fleet = fleet
        self._inner: Optional[GenerationStream] = None

    def cancel(self) -> None:
        super().cancel()
        inner = self._inner
        if inner is not None:
            inner.cancel()

    def _finish(self, error: Optional[BaseException] = None,
                now: Optional[float] = None) -> None:
        if error is not None and not isinstance(error, _CLIENT_ERRORS) \
                and not isinstance(error, ReplicaUnavailable):
            wrapped = ReplicaUnavailable(self._fleet.name,
                                         self._fleet.member_names())
            wrapped.__cause__ = error
            error = wrapped
        super()._finish(error, now)


class _FleetMember:
    __slots__ = ("name", "role", "engine", "inflight", "healthy",
                 "draining", "warming")

    def __init__(self, name: str, role: str, engine):
        self.name = name
        self.role = role
        self.engine = engine
        self.inflight = 0
        self.healthy = True
        self.draining = False
        self.warming = False


class DisaggregatedFleet:
    """The PR-15 front door generalised to N prefill + M decode
    engines, with membership that changes while traffic flows.

    ``make_prefill`` / ``make_decode`` are zero-arg factories returning
    role engines (``role="prefill"`` / ``role="decode"`` —
    :class:`DisaggregatedEngine` semantics per member). Placement is
    least-loaded across the serving members of each pool; a member that
    rejects with ``Overloaded`` fails over to its siblings and the
    front door raises ``Overloaded`` only once EVERY serving member
    rejected. A member that dies (engine loop failure, injected chaos)
    is marked unhealthy, skipped by placement, and left for
    :meth:`heal` to replace; its in-flight streams end in
    :class:`ReplicaUnavailable`.

    The per-request handoff is the PR-15 device gather on the owning
    prefill member, dispatched to the least-loaded decode member's
    ``submit_prefilled`` — so KV pages move directly between the two
    pools involved and a scale-up on either side is immediately
    routable."""

    def __init__(self, make_prefill: Callable[[], Any],
                 make_decode: Callable[[], Any], *,
                 n_prefill: int = 1, n_decode: int = 1,
                 name: str = "fleet", warm: bool = False):
        if n_prefill < 1 or n_decode < 1:
            raise ValueError("a fleet needs at least one member per role")
        self.name = name
        self._make = {"prefill": make_prefill, "decode": make_decode}
        self._cond = threading.Condition()
        self._members: Dict[str, List[_FleetMember]] = {"prefill": [],
                                                        "decode": []}
        self._next = {"prefill": 0, "decode": 0}
        self._closed = False
        self.submitted = 0
        self.rejected = 0
        self.unavailable = 0
        for _ in range(n_prefill):
            self.add_member("prefill", warm=warm)
        for _ in range(n_decode):
            self.add_member("decode", warm=warm)

    # ----------------------------------------------------- membership ----

    def _serving(self, role: str) -> List[_FleetMember]:
        # caller holds self._cond
        return [m for m in self._members[role]
                if m.healthy and not m.draining and not m.warming]

    def member_names(self, role: Optional[str] = None) -> List[str]:
        with self._cond:
            roles = [role] if role else ["prefill", "decode"]
            return [m.name for r in roles for m in self._members[r]]

    def pool_size(self, role: str) -> int:
        """Members holding a slot against the policy bounds (serving or
        warming; draining and dead members are already on their way
        out)."""
        with self._cond:
            return sum(1 for m in self._members[role]
                       if not m.draining and (m.healthy or m.warming))

    def add_member(self, role: str, *, warm: bool = True) -> str:
        """Scale one role up: build the engine, expose it WARMING, warm
        it off the placement path, then activate. Returns the member
        name (``p3``/``d1`` — indices monotonic, never reused)."""
        engine = self._make[role]()
        with self._cond:
            if self._closed:
                engine.close(drain=False)
                raise RuntimeError("fleet is closed")
            member = _FleetMember(f"{role[0]}{self._next[role]}", role,
                                  engine)
            self._next[role] += 1
            member.warming = bool(warm)
            if role == "prefill":
                engine._handoff_cb = self._handoff_for(member)
            self._members[role].append(member)
        if warm:
            try:
                engine.warmup()
            except Exception:
                with self._cond:
                    self._members[role].remove(member)
                engine.close(drain=False)
                raise
            with self._cond:
                member.warming = False
                self._cond.notify_all()
        record_event("fleet.member_added", fleet=self.name, role=role,
                     member=member.name)
        log.info("fleet %s: %s member %s added", self.name, role,
                 member.name)
        return member.name

    def remove_member(self, role: str, name: Optional[str] = None, *,
                      drain_timeout: float = 30.0,
                      force: bool = False) -> str:
        """Scale one role down through the drain gate: stop placing on
        the member, wait out its in-flight requests, close it. Picks
        the least-loaded serving member when ``name`` is omitted.
        Refuses to shrink a role to zero and bounces (``TimeoutError``)
        rather than failing a stream if the member is still busy at the
        deadline. ``force=True`` skips both — the heal path for a
        member that is already dead."""
        with self._cond:
            pool = self._members[role]
            if name is None:
                serving = self._serving(role)
                if not serving:
                    raise ValueError(f"fleet {self.name!r}: no serving "
                                     f"{role} member to remove")
                member = min(serving, key=lambda m: (m.inflight, m.name))
            else:
                member = next((m for m in pool if m.name == name), None)
                if member is None:
                    raise KeyError(f"no {role} member named {name!r}")
            if not force and len(self._serving(role)) <= 1 \
                    and member in self._serving(role):
                raise ValueError(
                    f"fleet {self.name!r}: refusing to remove the last "
                    f"serving {role} member {member.name!r}")
            member.draining = True
            if not force:
                deadline = time.monotonic() + float(drain_timeout)
                while member.inflight > 0:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        member.draining = False
                        raise TimeoutError(
                            f"fleet {self.name!r}: {role} member "
                            f"{member.name!r} still has "
                            f"{member.inflight} in flight after "
                            f"{drain_timeout:.1f}s drain; not removed")
                    self._cond.wait(timeout=min(0.1, left))
            pool.remove(member)
        try:
            member.engine.close(drain=not force, timeout=drain_timeout)
        except Exception:
            log.exception("fleet %s: closing %s member %s failed",
                          self.name, role, member.name)
        record_event("fleet.member_removed", fleet=self.name, role=role,
                     member=member.name, forced=bool(force))
        log.info("fleet %s: %s member %s removed%s", self.name, role,
                 member.name, " (forced)" if force else " (drained)")
        return member.name

    def heal(self, role: str) -> List[Tuple[str, str]]:
        """Replace every dead member of ``role`` (engine loop failed —
        in-process chaos — or, for members probing a child process, the
        process is gone). Placement marks a member dead when traffic
        trips over it; the probe here catches the quiet case — a loop
        that died with no follow-up traffic to notice. Returns
        ``(dead, replacement)`` name pairs."""
        newly_dead: List[_FleetMember] = []
        with self._cond:
            for m in self._members[role]:
                if m.healthy and not m.warming \
                        and getattr(m.engine, "failed", None) is not None:
                    m.healthy = False
                    newly_dead.append(m)
            if newly_dead:
                self._cond.notify_all()
            dead = [m.name for m in self._members[role] if not m.healthy]
        for m in newly_dead:
            record_event("fleet.member_died", fleet=self.name,
                         role=m.role, member=m.name,
                         error=type(m.engine.failed).__name__)
            log.warning("fleet %s: %s member %s found dead by the heal "
                        "probe (%s)", self.name, m.role, m.name,
                        m.engine.failed)
        replaced = []
        for name in dead:
            self.remove_member(role, name, force=True)
            new = self.add_member(role)
            record_event("fleet.healed", fleet=self.name, role=role,
                         dead=name, replacement=new)
            replaced.append((name, new))
        return replaced

    # ------------------------------------------------------ front door ----

    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: Optional[int] = None,
               deadline: Optional[float] = None,
               temperature: float = 0.0, top_k: int = 0,
               top_p: float = 1.0,
               seed: Optional[int] = None) -> GenerationStream:
        """Monolithic-shaped submit with fleet placement. Raises only
        ``Overloaded`` (every serving prefill member rejected — healthy
        backpressure) or ``ReplicaUnavailable`` (no serving prefill
        member at all)."""
        stream = _FleetStream(self)
        ctx = {"stream": stream,
               "deadline": (None if deadline is None
                            else stream.t_submit + float(deadline)),
               "dispatched": False}
        tried: set = set()
        last_over: Optional[Overloaded] = None
        while True:
            with self._cond:
                if self._closed:
                    self.unavailable += 1
                    raise ReplicaUnavailable(self.name, [])
                cands = [m for m in self._serving("prefill")
                         if m.name not in tried]
                if not cands:
                    if last_over is not None:
                        self.rejected += 1
                        raise last_over
                    self.unavailable += 1
                    raise ReplicaUnavailable(
                        self.name, self.member_names("prefill"))
                member = min(cands, key=lambda m: (m.inflight, m.name))
                member.inflight += 1
            try:
                inner = member.engine.submit(
                    prompt, max_new_tokens=max_new_tokens,
                    deadline=deadline, temperature=temperature,
                    top_k=top_k, top_p=top_p, seed=seed, tag=ctx)
            except Overloaded as e:
                self._release(member)
                tried.add(member.name)
                last_over = e
                continue
            except (ValueError, TypeError):
                self._release(member)
                raise  # a malformed request fails on any member
            except Exception as e:
                # the member itself broke (closed/failed loop): out of
                # placement it goes, the heal pass replaces it
                self._fail_member(member, e)
                tried.add(member.name)
                continue
            break
        with self._cond:
            self.submitted += 1
        stream._inner = inner
        inner.add_done_callback(self._relay_for(ctx, member))
        return stream

    def generate(self, prompt: Sequence[int], *,
                 timeout: Optional[float] = None, **kw) -> List[int]:
        return self.submit(prompt, **kw).result(timeout)

    def _release(self, member: _FleetMember) -> None:
        with self._cond:
            member.inflight -= 1
            self._cond.notify_all()

    def _fail_member(self, member: _FleetMember,
                     error: BaseException) -> None:
        with self._cond:
            member.inflight = max(0, member.inflight - 1)
            fresh = member.healthy
            member.healthy = False
            self._cond.notify_all()
        if fresh:
            record_event("fleet.member_died", fleet=self.name,
                         role=member.role, member=member.name,
                         error=type(error).__name__)
            log.warning("fleet %s: %s member %s failed (%s); out of "
                        "placement until healed", self.name, member.role,
                        member.name, error)

    def _relay_for(self, ctx: dict, member: _FleetMember):
        """Done-callback on the prefill-role inner stream: release the
        member, forward a prefill-phase failure or a no-handoff finish
        (request retired AT its first token) to the front stream."""

        def relay(inner: GenerationStream) -> None:
            self._release(member)
            stream: GenerationStream = ctx["stream"]
            err = inner.error
            if err is not None:
                # ReplicaUnavailable here means the DECODE pool had no
                # one to adopt the handoff — not this member's fault
                if not isinstance(err, _CLIENT_ERRORS) \
                        and not isinstance(err, ReplicaUnavailable):
                    self._mark_dead(member, err)
                stream._finish(err)  # _FleetStream translates
                return
            if ctx["dispatched"]:
                return
            now = time.monotonic()
            for t in inner.tokens:
                stream._push(int(t), now)
            stream._finish(None, now)

        return relay

    def _mark_dead(self, member: _FleetMember,
                   error: BaseException) -> None:
        with self._cond:
            fresh = member.healthy
            member.healthy = False
            self._cond.notify_all()
        if fresh:
            record_event("fleet.member_died", fleet=self.name,
                         role=member.role, member=member.name,
                         error=type(error).__name__)
            log.warning("fleet %s: %s member %s failed mid-stream (%s)",
                        self.name, member.role, member.name, error)

    # --------------------------------------------------------- handoff ----

    def _handoff_for(self, member: _FleetMember):
        def on_handoff(payload: dict) -> None:
            # prefill loop thread, pages still owned by `member`
            payload["block"] = member.engine._mover.gather(
                member.engine._cache, payload["page_row"])
            self._dispatch(payload)

        return on_handoff

    def _dispatch(self, payload: dict) -> None:
        """Adopt one finished prefill into the least-loaded decode
        member. Failing members fail over; raising out of here lands in
        the prefill engine's abort path (pages released, inner stream
        failed, relay forwards to the front stream)."""
        ctx = payload.pop("tag")
        ctx["dispatched"] = True
        payload["deadline"] = ctx["deadline"]
        stream: GenerationStream = ctx["stream"]
        tried: set = set()
        last: Optional[BaseException] = None
        while True:
            with self._cond:
                cands = [m for m in self._serving("decode")
                         if m.name not in tried]
                if not cands:
                    err = last if isinstance(last, Overloaded) else \
                        ReplicaUnavailable(self.name,
                                           self.member_names("decode"))
                    if last is not None and err is not last:
                        err.__cause__ = last
                    stream._finish(err)
                    raise err
                member = min(cands, key=lambda m: (m.inflight, m.name))
                member.inflight += 1
            try:
                member.engine.submit_prefilled(payload, stream=stream)
            except Overloaded as e:
                self._release(member)
                tried.add(member.name)
                last = e
                continue
            except Exception as e:
                self._fail_member(member, e)
                tried.add(member.name)
                last = e
                continue
            stream.add_done_callback(lambda s, m=member: self._release(m))
            return

    # ------------------------------------------------------ lifecycle ----

    def warmup(self) -> None:
        with self._cond:
            members = [m for r in ("prefill", "decode")
                       for m in self._members[r]]
        for m in members:
            m.engine.warmup()
            with self._cond:
                m.warming = False
                self._cond.notify_all()

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Prefill members first (their drains flush pending handoffs
        into the decode queues), then decode members."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            members = list(self._members["prefill"]) \
                + list(self._members["decode"])
        for m in members:
            try:
                m.engine.close(drain=drain, timeout=timeout)
            except Exception:
                log.exception("fleet %s: closing member %s failed",
                              self.name, m.name)

    def __enter__(self) -> "DisaggregatedFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -------------------------------------------------------- queries ----

    def pages_in_use(self, role: Optional[str] = None) -> int:
        with self._cond:
            roles = [role] if role else ["prefill", "decode"]
            members = [m for r in roles for m in self._members[r]]
        return sum(m.engine.pages_in_use for m in members)

    def snapshot(self) -> Dict[str, Any]:
        """Fleet-level control gauges: per-role aggregates (the signals
        scaling rules key on — flat numeric leaves under stable keys)
        plus per-member detail. Latency control signals are the RECENT
        windows (a lifetime p99 stays breached long after the fleet
        absorbed the burst — steering on it can never see its own
        action land)."""
        with self._cond:
            members = {r: list(self._members[r])
                       for r in ("prefill", "decode")}
            counters = {"submitted": self.submitted,
                        "rejected": self.rejected,
                        "unavailable": self.unavailable}
        out: Dict[str, Any] = dict(counters)
        detail: Dict[str, Any] = {}
        for role in ("prefill", "decode"):
            size = queue = inflight = pages = pages_total = 0
            warming = dead = 0
            lat_key = "ttft_recent_ms" if role == "prefill" \
                else "itl_recent_ms"
            lat_p99: Optional[float] = None
            for m in members[role]:
                es = m.engine.metrics.snapshot()
                if not m.draining and (m.healthy or m.warming):
                    size += 1
                warming += m.warming
                dead += not m.healthy
                queue += es["queue_depth"]
                inflight += m.inflight
                pages += es["pages_in_use"]
                pages_total += es["pages_total"]
                recent = es.get(lat_key)
                if recent is not None:
                    p = recent.get("p99")
                    if p is not None:
                        lat_p99 = p if lat_p99 is None else max(lat_p99,
                                                                p)
                detail[m.name] = {
                    "role": role, "healthy": m.healthy,
                    "draining": m.draining, "warming": m.warming,
                    "inflight": m.inflight,
                    "queue_depth": es["queue_depth"],
                    "pages_in_use": es["pages_in_use"],
                }
            agg = {"size": size, "warming": warming, "dead": dead,
                   "inflight": inflight, "queue_depth": queue,
                   "pages_in_use": pages,
                   "page_occupancy": (pages / pages_total
                                      if pages_total else 0.0)}
            agg["ttft_recent_p99_ms" if role == "prefill"
                else "itl_recent_p99_ms"] = lat_p99
            out[role] = agg
        out["members"] = detail
        return out


class EnginePool:
    """Scalable-pool adapter over ONE role of a
    :class:`DisaggregatedFleet` — what gives the controller independent
    prefill and decode knobs over a single front door."""

    def __init__(self, fleet: DisaggregatedFleet, role: str, *,
                 drain_timeout: float = 30.0):
        if role not in ("prefill", "decode"):
            raise ValueError(f"unknown fleet role {role!r}")
        self.fleet = fleet
        self.role = role
        self.name = f"{fleet.name}.{role}"
        self.drain_timeout = float(drain_timeout)

    def size(self) -> int:
        return self.fleet.pool_size(self.role)

    def scale_up(self) -> str:
        return self.fleet.add_member(self.role)

    def scale_down(self) -> str:
        return self.fleet.remove_member(self.role,
                                        drain_timeout=self.drain_timeout)

    def heal(self) -> List[str]:
        return [new for _dead, new in self.fleet.heal(self.role)]

    def snapshot(self) -> Dict[str, Any]:
        return {"size": self.size()}


# -------------------------------------------------------- controller ----


class _PoolState:
    __slots__ = ("name", "pool", "policy", "up_streak", "down_streak",
                 "last_up", "last_down", "scale_ups", "scale_downs",
                 "heals", "bounced_downs")

    def __init__(self, name: str, pool, policy: ScalingPolicy):
        self.name = name
        self.pool = pool
        self.policy = policy
        self.up_streak = 0
        self.down_streak = 0
        self.last_up: Optional[float] = None
        self.last_down: Optional[float] = None
        self.scale_ups = 0
        self.scale_downs = 0
        self.heals = 0
        self.bounced_downs = 0


class AutoscaleController:
    """The poll loop closing the elasticity control loop.

    ``pools`` maps a pool name to ``(pool, ScalingPolicy)`` — any
    object with the pool protocol (``size``/``scale_up``/``scale_down``
    and optionally ``heal``): :class:`ReplicaPool`,
    :class:`EnginePool`, or a test stub. Each :meth:`poll_once`:

    1. **heals** — dead members are replaced before policy runs, so a
       SIGKILL never masquerades as scale-down headroom;
    2. samples the ``registry`` ONCE (every pool's rules see the same
       consistent tick);
    3. per pool: updates breach streaks, then applies at most one
       membership change, bounded and cooled per the policy. A bounced
       scale-down (drain timeout — the member was still busy) keeps its
       streak and retries next tick.

    ``start()`` runs it on a daemon thread every ``interval_s``;
    :meth:`poll_once` with an injected ``clock`` drives the same state
    machine deterministically for tests. The controller itself is a
    metrics source (``snapshot()``) and self-registers as
    ``autoscale`` when given a registry — its own decisions ride the
    same ``/metrics`` surface it steers by."""

    def __init__(self, pools: Dict[str, Tuple[Any, ScalingPolicy]], *,
                 registry=None, interval_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic,
                 register_as: Optional[str] = "autoscale"):
        if not pools:
            raise ValueError("at least one pool is required")
        self._pools = [_PoolState(n, p, pol)
                       for n, (p, pol) in pools.items()]
        self.registry = registry
        self.interval_s = float(interval_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.polls = 0
        #: bounded decision log: (t, pool, action, member)
        self.history: deque = deque(maxlen=256)
        #: bounded per-tick pool sizes: (t, {pool: size}) — the
        #: asymmetric-scaling record the fleet bench captures
        self.size_history: deque = deque(maxlen=4096)
        if registry is not None and register_as:
            registry.register(register_as, self, replace=True)

    # ----------------------------------------------------------- loop ----

    def poll_once(self, now: Optional[float] = None,
                  sample: Optional[Dict[str, Any]] = None) -> List[dict]:
        """One control tick; returns the actions taken. ``now`` and
        ``sample`` inject a clock value and a pre-collected metrics
        sample (tests drive hysteresis with these — no threads, no
        sleeps)."""
        if now is None:
            now = self._clock()
        if sample is None:
            sample = self.registry.collect() if self.registry else {}
        actions: List[dict] = []

        def act(st: _PoolState, action: str, member) -> None:
            entry = {"t": now, "pool": st.name, "action": action,
                     "member": member}
            with self._lock:
                self.history.append((now, st.name, action, member))
            actions.append(entry)
            record_event("autoscale.action", pool=st.name, action=action,
                         member=member)

        for st in self._pools:
            healed = []
            if callable(getattr(st.pool, "heal", None)):
                try:
                    healed = st.pool.heal()
                except Exception:
                    log.exception("autoscale: heal pass failed for pool "
                                  "%s", st.name)
            for member in healed:
                st.heals += 1
                # a heal is a scale-up in disguise: start the up
                # cooldown so policy doesn't immediately double down
                st.last_up = now
                act(st, "heal", member)

            pol = st.policy
            up = bool(pol.up_when(sample)) if pol.up_when else False
            down = bool(pol.down_when(sample)) if pol.down_when else False
            if up:
                down = False  # pressure wins over quiet in a tie
            st.up_streak = st.up_streak + 1 if up else 0
            st.down_streak = st.down_streak + 1 if down else 0

            size = st.pool.size()
            if up and st.up_streak >= pol.breach_up \
                    and size < pol.max_replicas \
                    and (st.last_up is None
                         or now - st.last_up >= pol.cooldown_up_s):
                try:
                    member = st.pool.scale_up()
                except Exception:
                    log.exception("autoscale: scale-up failed for pool "
                                  "%s", st.name)
                else:
                    st.scale_ups += 1
                    st.last_up = now
                    st.up_streak = 0
                    act(st, "scale_up", member)
            elif down and st.down_streak >= pol.breach_down \
                    and size > pol.min_replicas \
                    and self._down_cooled(st, now):
                try:
                    member = st.pool.scale_down()
                except TimeoutError:
                    # busy member bounced the drain — keep the streak,
                    # retry next tick (never fail a stream to shrink)
                    st.bounced_downs += 1
                    log.info("autoscale: scale-down of pool %s bounced "
                             "(member still busy)", st.name)
                except Exception:
                    log.exception("autoscale: scale-down failed for "
                                  "pool %s", st.name)
                else:
                    st.scale_downs += 1
                    st.last_down = now
                    st.down_streak = 0
                    act(st, "scale_down", member)

        with self._lock:
            self.polls += 1
            self.size_history.append(
                (now, {st.name: st.pool.size() for st in self._pools}))
        return actions

    def _down_cooled(self, st: _PoolState, now: float) -> bool:
        """Scale-down cools against the last action in EITHER
        direction: shrinking right after growing chases the quiet the
        new member just created."""
        for last in (st.last_up, st.last_down):
            if last is not None and now - last < st.policy.cooldown_down_s:
                return False
        return True

    def start(self) -> "AutoscaleController":
        if self._thread is not None:
            raise RuntimeError("controller already started")
        self._stop.clear()

        def loop() -> None:
            while not self._stop.is_set():
                try:
                    self.poll_once()
                except Exception:
                    log.exception("autoscale poll failed; continuing")
                self._stop.wait(self.interval_s)

        self._thread = threading.Thread(target=loop,
                                        name="bigdl-autoscale",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout: Optional[float] = 5.0) -> None:
        """Stop and join the poll thread (idempotent). The pools and
        their members stay up — the controller owns decisions, not
        engines."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout)
            self._thread = None

    close = stop

    def __enter__(self) -> "AutoscaleController":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -------------------------------------------------------- queries ----

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            polls = self.polls
        out: Dict[str, Any] = {"polls": polls}
        pools: Dict[str, Any] = {}
        for st in self._pools:
            pools[st.name] = {
                "size": st.pool.size(),
                "up_streak": st.up_streak,
                "down_streak": st.down_streak,
                "scale_ups": st.scale_ups,
                "scale_downs": st.scale_downs,
                "bounced_downs": st.bounced_downs,
                "heals": st.heals,
                "policy": st.policy.describe(),
            }
        out["pools"] = pools
        return out

    def format_table(self) -> str:
        snap = self.snapshot()
        lines = [f"{'pool':<16} {'size':>5} {'ups':>5} {'downs':>6} "
                 f"{'heals':>6} {'bounced':>8}"]
        for name in sorted(snap["pools"]):
            p = snap["pools"][name]
            lines.append(f"{name:<16} {p['size']:>5} {p['scale_ups']:>5} "
                         f"{p['scale_downs']:>6} {p['heals']:>6} "
                         f"{p['bounced_downs']:>8}")
        return "\n".join(lines)
