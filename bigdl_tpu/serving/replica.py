"""ReplicaSet — N serving backends for one model behind one front door.

The scale-OUT half of sharded + replicated serving: one model, N engine
replicas on disjoint device sets (build the meshes with
``parallel.mesh.serving_meshes``; each replica may itself be
tensor-parallel over ``tp`` chips). ``submit()`` keeps the exact backend
signature — a :class:`~bigdl_tpu.serving.router.ModelRouter` resolves a
model name to a ReplicaSet transparently (``register`` even auto-wraps a
list of backends) — and the set adds the cross-replica concerns:

- **least-loaded placement** — each request goes to the placeable
  replica with the fewest set-tracked in-flight requests (ties break by
  replica index, so placement is a pure function of the request/
  completion sequence — the skew test leans on this). A replica that
  rejects with :class:`Overloaded` is skipped for that request; only
  when EVERY placeable replica is saturated does the front door raise.
- **health / eviction / rejoin** — a replica whose submissions or
  streams fail with an engine error (not a client error: deadlines,
  cancels, overload and malformed requests never count) accrues
  consecutive failures; at ``max_failures`` it is quarantined and
  traffic fails over to its siblings instead of failing the front door.
  A quarantined replica rejoins only after a ``probe`` succeeds against
  it. The background prober paces itself on the shared
  :class:`~bigdl_tpu.faults.RetryPolicy` backoff: the first probe after
  an eviction comes at ``probe_interval``, and each quarantined pass
  without a rejoin doubles the wait (deterministic jitter, capped at
  ~30 s) so a long-dead backend is not hammered forever; a rejoin or a
  fresh eviction resets the schedule. ``probe_once()`` is the
  synchronous handle for tests and operators.
- **draining rolling reloads** — ``reload(params)`` sweeps the replicas
  ONE at a time: mark draining (no new placements), wait for in-flight
  work to finish, swap weights via the backend's atomic ``reload``,
  return it to service, move on. At most one replica is ever out of
  rotation, so a set of N never drops below N-1 serving replicas — and
  ``watch_checkpoints`` drives the whole roll from a training job's
  checkpoint manifest, because the set duck-types the ``reload``
  contract its members implement.

Backends are anything speaking the serving trio (``submit`` returning a
future/stream with ``add_done_callback``, ``metrics``, ``close``):
:class:`~bigdl_tpu.serving.engine.GenerationEngine`,
:class:`~bigdl_tpu.serving.service.InferenceService`, or stubs. When all
replicas share ONE :class:`ServingMetrics` (the recommended wiring — the
engines accept ``metrics=``), the set adopts it, so aggregate counters
and the replica gauges land in a single table.
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from concurrent.futures import CancelledError, Future
from typing import Any, Callable, Dict, List, Optional, Sequence

from bigdl_tpu import faults
from bigdl_tpu.faults import RetryPolicy
from bigdl_tpu.obs.recorder import record_event
from bigdl_tpu.serving.errors import (
    DeadlineExceeded,
    Overloaded,
    ReplicaUnavailable,
    StreamCancelled,
    UnknownModel,
)
from bigdl_tpu.serving.metrics import ServingMetrics

log = logging.getLogger("bigdl_tpu.serving")

# errors that indict the REQUEST (or its consumer), never the replica:
# a deadline miss, a cancel, healthy backpressure, or a malformed input
# would fail identically on every sibling
_CLIENT_ERRORS = (Overloaded, DeadlineExceeded, StreamCancelled,
                  UnknownModel, ValueError, TypeError, CancelledError)


class _HedgedHandle(Future):
    """Future-shaped first-wins wrapper over a primary dispatch and an
    optional tail-latency hedge. ``result()`` is the winner's result —
    for generation backends that is the WHOLE token list (the wrapper
    is not an iterator: with two candidate streams there is no single
    token sequence to stream until one wins)."""

    def __init__(self, request_id: str):
        super().__init__()
        self.request_id = request_id


class _Replica:
    """Host-side bookkeeping for one backend."""

    __slots__ = ("backend", "name", "index", "inflight", "healthy",
                 "draining", "warming", "failures", "served", "failed",
                 "weights_version")

    def __init__(self, backend, index: int):
        self.backend = backend
        self.name = f"r{index}"
        self.index = index
        self.inflight = 0       # set-tracked depth (the placement key)
        self.healthy = True
        self.draining = False   # rolling reload: excluded from placement
        self.warming = False    # added but not yet in rotation (scale-up)
        self.failures = 0       # CONSECUTIVE failures (reset on success)
        self.served = 0
        self.failed = 0
        self.weights_version = 0  # last rolling-reload sweep applied


class ReplicaSet:
    """N serving backends for one model behind one ``submit`` door.

    ``replicas`` is a non-empty sequence of backends (engines/services
    the set now OWNS — ``close()`` closes them). ``max_failures``
    consecutive engine failures quarantine a replica; ``probe(backend)``
    (raises on failure) lets it rejoin, paced by ``probe_backoff`` (a
    :class:`RetryPolicy`; default: base ``probe_interval``, doubling per
    fruitless pass, capped at 30 s with deterministic jitter, reset on
    rejoin/eviction). ``metrics`` defaults to the replicas' shared
    :class:`ServingMetrics` when they share one, else a fresh set-level
    instance; the replica gauges land there either way.
    """

    def __init__(self, replicas: Sequence[Any], *,
                 metrics: Optional[ServingMetrics] = None,
                 max_failures: int = 2,
                 probe: Optional[Callable[[Any], Any]] = None,
                 probe_interval: float = 2.0,
                 probe_backoff: Optional[RetryPolicy] = None,
                 hedge: bool = False,
                 hedge_delay: Optional[float] = None,
                 name: str = "replicas"):
        replicas = list(replicas)
        if not replicas:
            raise ValueError("ReplicaSet needs at least one replica")
        if max_failures < 1:
            raise ValueError("max_failures must be >= 1")
        self.name = name
        self.max_failures = int(max_failures)
        # tail-latency hedging (PR 14): when on, submit() returns a
        # future-shaped first-wins wrapper and a straggling primary is
        # re-dispatched to a second healthy replica after hedge_delay
        # (default: the live p99 latency), idempotent by request id
        self.hedge = bool(hedge)
        self.hedge_delay = hedge_delay
        self.hedges_launched = 0
        self.hedges_won = 0
        self._cond = threading.Condition()
        self._replicas = [_Replica(b, i) for i, b in enumerate(replicas)]
        self._next_index = len(self._replicas)  # names never reused
        if metrics is None:
            first = getattr(replicas[0], "metrics", None)
            shared = first is not None and all(
                getattr(b, "metrics", None) is first for b in replicas)
            metrics = first if shared else ServingMetrics()
        self.metrics = metrics
        self._probe_fn = probe
        self.probe_interval = float(probe_interval)
        # prober pacing: probe_interval is only the BASE of the shared
        # RetryPolicy backoff — each quarantined pass without a rejoin
        # doubles the wait (deterministic jitter, capped ~30 s), so a
        # long-dead backend is not hammered every 2 s forever; a rejoin
        # or a fresh eviction resets the schedule (and an eviction kicks
        # the prober awake so the first probe comes at base delay)
        self._probe_policy = probe_backoff or RetryPolicy.poll_schedule(
            self.probe_interval)
        self._probe_cond = threading.Condition()
        self._probe_attempt = 0
        self._probe_kick = False
        self._closed = False
        self._roll_lock = threading.Lock()  # one rolling reload at a time
        self._weights_version = 0           # bumped per reload() sweep
        self._latest_weights = None         # (params, state) of last sweep
        self._stop = threading.Event()
        self._prober: Optional[threading.Thread] = None
        self._update_gauges()
        if probe is not None and self.probe_interval > 0:
            self._prober = threading.Thread(
                target=self._probe_loop, name="bigdl-serving-replica-probe",
                daemon=True)
            self._prober.start()

    # ------------------------------------------------------ placement ----

    def _pick(self, tried: List[_Replica]) -> Optional[_Replica]:
        """Least-loaded placeable replica not yet tried for this request.
        Falls back to a DRAINING replica only when NO healthy replica is
        in rotation at all (a 1-replica set mid-reload keeps its door
        open — backend reloads are atomic between steps, so this is
        safe; the drain wait then relies on its timeout). When serving
        siblings exist but were tried (Overloaded), the answer is
        backpressure, NOT the draining replica — dumping overflow there
        would keep its in-flight count pinned and turn every swap of a
        loaded roll into a full drain_timeout wait."""
        with self._cond:
            serving = [r for r in self._replicas
                       if r.healthy and not r.draining and not r.warming]
            pool = [r for r in serving if r not in tried]
            if not serving:
                # a WARMING replica never falls back into placement —
                # unlike a draining one it cannot serve at all yet
                pool = [r for r in self._replicas
                        if r.healthy and not r.warming and r not in tried]
            if pool:
                return min(pool, key=lambda r: (r.inflight, r.index))
            return None

    def submit(self, x, **kwargs):
        """Place one request on the least-loaded healthy replica and
        return its handle (stream/future — exactly what the backend's
        ``submit`` returns). An :class:`Overloaded` replica is skipped; a
        replica that fails at submission is marked and skipped; raises
        :class:`Overloaded` only when every placeable replica is
        saturated, :class:`ReplicaUnavailable` when none is healthy.

        With ``hedge=True`` (and ≥ 2 replicas available) the return is a
        future-shaped first-wins wrapper instead: if the primary has not
        settled after the hedge delay, the same request is re-dispatched
        to a second replica and whichever finishes first wins."""
        with self._cond:
            if self._closed:
                raise RuntimeError("replica set is closed")
        if self.hedge and len(self._replicas) > 1:
            return self._submit_hedged(x, kwargs)
        _, handle = self._submit_once(x, kwargs)
        return handle

    def _submit_once(self, x, kwargs: Dict[str, Any],
                     tried: Optional[List[_Replica]] = None,
                     rid: Optional[str] = None):
        """One placement pass over the failover loop; returns
        ``(replica, handle)``. ``tried`` seeds the exclusion list (the
        hedge leg excludes the primary); ``rid`` is forwarded as
        ``request_id=`` to backends that advertise
        ``accepts_request_id`` (the RemoteReplica idempotency key)."""
        tried = list(tried or [])
        overload: Optional[Overloaded] = None
        while True:
            r = self._pick(tried)
            if r is None:
                if overload is not None:
                    raise overload
                raise ReplicaUnavailable(
                    self.name, [rr.name for rr in self._replicas])
            kw = kwargs
            if rid is not None and getattr(r.backend, "accepts_request_id",
                                           False):
                kw = dict(kwargs, request_id=rid)
            try:
                # fault site INSIDE the try: an armed failure routes
                # through the same classification as a real backend's
                # (client errors re-raise, engine errors mark + fail over)
                faults.fire("replica.submit", replica=r.backend, index=r.index)
                handle = r.backend.submit(x, **kw)
            except Overloaded as e:
                overload = e  # healthy backpressure, not a health event
                tried.append(r)
                continue
            except _CLIENT_ERRORS:
                raise  # would fail identically on every sibling
            except Exception as e:
                self._note_failure(r, e, where="submit")
                tried.append(r)
                continue
            tr = getattr(handle, "trace", None)
            if tr is not None:
                # the set stamps placement onto the backend's trace —
                # the context rides the handle across the layering
                tr.annotate(replica=r.name, replica_set=self.name)
            self._track(r, handle)
            return r, handle

    # -------------------------------------------------------- hedging ----

    def _hedge_delay_s(self) -> float:
        """How long to give the primary before launching the hedge:
        the configured ``hedge_delay``, else the live p99 latency (the
        canonical tail-hedging delay — only genuine stragglers pay the
        duplicate dispatch), else 50 ms before any latency history."""
        if self.hedge_delay is not None:
            return float(self.hedge_delay)
        lat = self.metrics.snapshot().get("latency_ms") or {}
        p99 = lat.get("p99") if isinstance(lat, dict) else None
        if p99:
            return float(p99) / 1e3
        return 0.05

    def _submit_hedged(self, x, kwargs: Dict[str, Any]) -> _HedgedHandle:
        """First-wins dispatch: place on the primary now, and if it has
        not settled after :meth:`_hedge_delay_s`, place the SAME request
        (same generated request id — remote backends dedupe on it) on a
        second replica. The loser is cancelled. An engine error on one
        leg while the other is still outstanding is absorbed — the
        wrapper fails only when no leg can still win (client errors
        settle immediately: they would fail identically everywhere)."""
        rid = uuid.uuid4().hex
        r0, h0 = self._submit_once(x, kwargs, rid=rid)
        wrapper = _HedgedHandle(rid)
        lock = threading.Lock()
        state = {"settled": False, "outstanding": 1, "hedge_pending": True,
                 "handles": [(r0, h0)], "last_err": None}

        def settle_with(r: _Replica, h, err: Optional[BaseException],
                        is_hedge: bool) -> None:
            timer.cancel()
            if err is None:
                try:
                    wrapper.set_result(h.result(timeout=0))
                except BaseException as e:  # result/error raced: fail legibly
                    wrapper.set_exception(e)
            else:
                wrapper.set_exception(err)
            if is_hedge and err is None:
                with self._cond:
                    self.hedges_won += 1
                win = getattr(r.backend, "record_hedge_win", None)
                if win is not None:
                    win()
                record_event("replica.hedge_won", set=self.name,
                             replica=r.name, request=rid)
            with lock:
                losers = [lh for _, lh in state["handles"] if lh is not h]
            for lh in losers:
                try:
                    lh.cancel()
                except Exception:
                    pass

        def on_done(r: _Replica, h, is_hedge: bool) -> None:
            err = self._handle_error(h)
            with lock:
                if state["settled"]:
                    return
                state["outstanding"] -= 1
                if err is not None and not isinstance(err, _CLIENT_ERRORS) \
                        and (state["outstanding"] > 0
                             or state["hedge_pending"]):
                    # the other leg (or the not-yet-launched hedge) can
                    # still win; remember the error in case it cannot
                    state["last_err"] = err
                    return
                state["settled"] = True
            settle_with(r, h, err, is_hedge)

        def launch() -> None:
            with lock:
                state["hedge_pending"] = False
                if state["settled"]:
                    return
            with self._cond:
                if self._closed:
                    return
            try:
                r1, h1 = self._submit_once(x, kwargs, tried=[r0], rid=rid)
            except (ReplicaUnavailable, Overloaded):
                # no second replica to hedge onto: primary-only. If the
                # primary already failed while we held the pending flag,
                # nothing else can win — fail the wrapper now
                with lock:
                    if state["settled"] or state["outstanding"] > 0:
                        return
                    state["settled"] = True
                    err = state["last_err"]
                wrapper.set_exception(
                    err or ReplicaUnavailable(
                        self.name, [rr.name for rr in self._replicas]))
                return
            except _CLIENT_ERRORS:
                return  # primary still owns the request
            with lock:
                if state["settled"]:
                    state["handles"].append((r1, h1))
                    late = True
                else:
                    state["outstanding"] += 1
                    state["handles"].append((r1, h1))
                    late = False
            if late:
                try:
                    h1.cancel()
                except Exception:
                    pass
                return
            with self._cond:
                self.hedges_launched += 1
            record_event("replica.hedge_launched", set=self.name,
                         replica=r1.name, request=rid)
            h1.add_done_callback(lambda h: on_done(r1, h, True))

        timer = threading.Timer(self._hedge_delay_s(), launch)
        timer.name = "bigdl-serving-hedge"
        timer.daemon = True
        timer.start()
        h0.add_done_callback(lambda h: on_done(r0, h, False))
        return wrapper

    def predict(self, x, timeout: Optional[float] = None, **kwargs):
        """Blocking convenience: ``submit(...).result(timeout)``."""
        return self.submit(x, **kwargs).result(timeout)

    def _track(self, r: _Replica, handle) -> None:
        with self._cond:
            r.inflight += 1
        self._update_gauges()
        released = [False]

        def done(h):
            # idempotent by construction: a handle whose callbacks fire
            # twice (or a close() racing a completion) releases the
            # in-flight slot exactly once
            with self._cond:
                if released[0]:
                    return
                released[0] = True
                r.inflight -= 1
                self._cond.notify_all()
            err = self._handle_error(h)
            if err is None:
                self._note_success(r)
            elif not isinstance(err, _CLIENT_ERRORS):
                self._note_failure(r, err, where="stream")
            # client outcomes (deadline, cancel, ...) are NEUTRAL: they
            # neither count as served nor reset the consecutive-failure
            # streak — otherwise interleaved deadline traffic could keep
            # an every-other-stream-failing replica below max_failures
            # forever
            self._update_gauges()

        try:
            handle.add_done_callback(done)
        except BaseException:
            done(handle)  # never strand the in-flight count
            raise

    @staticmethod
    def _handle_error(handle) -> Optional[BaseException]:
        err = getattr(handle, "error", None)
        if err is None and hasattr(handle, "exception"):
            try:
                err = handle.exception(timeout=0)
            except TypeError:
                err = handle.exception()
            except BaseException as e:  # CancelledError et al.
                err = e
        return err

    # --------------------------------------------------------- health ----

    def _note_failure(self, r: _Replica, error: BaseException,
                      where: str) -> None:
        with self._cond:
            if r not in self._replicas:
                # late failure from a member already scaled out (a
                # force-removed dead backend failing its last streams):
                # not an eviction, and not this set's gauges anymore
                return
            r.failures += 1
            r.failed += 1
            evict = r.healthy and r.failures >= self.max_failures
            if evict:
                r.healthy = False
        if evict:
            self.metrics.record_eviction()
            record_event("replica.evicted", set=self.name, replica=r.name,
                         failures=r.failures, where=where,
                         error=type(error).__name__)
            with self._probe_cond:
                # a FRESH eviction restarts the probe schedule from the
                # base interval (the capped backoff belongs to backends
                # that have been dead a while) and wakes a prober parked
                # on a long wait so the reset takes effect now
                self._probe_attempt = 0
                self._probe_kick = True
                self._probe_cond.notify_all()
            log.warning(
                "replica %s/%s quarantined after %d consecutive failures "
                "(last, at %s: %s); traffic fails over to siblings",
                self.name, r.name, r.failures, where, error)
        else:
            log.info("replica %s/%s failure at %s (%d/%d before eviction): "
                     "%s", self.name, r.name, where, r.failures,
                     self.max_failures, error)
        self._update_gauges()

    def _note_success(self, r: _Replica) -> None:
        with self._cond:
            r.served += 1
            r.failures = 0

    def _probe_wait(self, delay: float) -> str:
        """Block until ``delay`` elapses ("elapsed"), the schedule is
        reset by a fresh eviction ("kick" — re-wait from the new base
        delay), or the set closes ("stop"). Separated out so the backoff
        regression test can drive the schedule with a fake clock."""
        deadline = time.monotonic() + delay
        with self._probe_cond:
            while not self._stop.is_set() and not self._probe_kick:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._probe_cond.wait(left)
            if self._stop.is_set():
                return "stop"
            if self._probe_kick:
                self._probe_kick = False
                return "kick"
            return "elapsed"

    def _probe_loop(self) -> None:
        while True:
            with self._probe_cond:
                attempt = self._probe_attempt
            why = self._probe_wait(self._probe_policy.backoff(attempt))
            if why == "stop":
                return
            if why == "kick":
                continue  # schedule reset: wait the fresh base delay
            try:
                rejoined = self.probe_once()
            except Exception:
                log.exception("replica probe pass failed; will retry")
                rejoined = 0
            with self._cond:
                quarantined = any(not r.healthy for r in self._replicas)
            with self._probe_cond:
                if rejoined or not quarantined:
                    # progress (or a healthy fleet): the next quarantine
                    # era starts from the base interval again
                    self._probe_attempt = 0
                elif not self._probe_kick:  # don't outrun a fresh reset
                    self._probe_attempt += 1

    def probe_once(self) -> int:
        """Probe every quarantined replica once; rejoin the ones whose
        probe succeeds. Returns how many rejoined. (The background prober
        calls this every ``probe_interval``; tests and operators can call
        it synchronously.)"""
        if self._probe_fn is None:
            return 0
        rejoined = 0
        for r in list(self._replicas):
            with self._cond:
                if r.healthy or self._closed or r not in self._replicas:
                    continue
            try:
                self._probe_fn(r.backend)
            except Exception as e:
                log.info("replica %s/%s probe failed (stays quarantined): "
                         "%s", self.name, r.name, e)
                continue
            # a replica that missed a rolling reload while quarantined
            # must catch up BEFORE it rejoins — re-entering rotation on
            # the old checkpoint would serve mixed model versions forever
            # (the watcher's tip has already advanced, so nothing else
            # would ever retry the swap)
            with self._roll_lock:
                stale = r.weights_version != self._weights_version
                weights = self._latest_weights
                if stale and weights is not None:
                    params, state = weights
                    try:
                        if state is None:
                            r.backend.reload(params)
                        else:
                            r.backend.reload(params, state)
                    except Exception as e:
                        log.warning(
                            "replica %s/%s probe succeeded but the "
                            "missed-reload catch-up failed (stays "
                            "quarantined): %s", self.name, r.name, e)
                        continue
                    r.weights_version = self._weights_version
            with self._cond:
                r.healthy = True
                r.failures = 0
            rejoined += 1
            self.metrics.record_rejoin()
            record_event("replica.rejoined", set=self.name, replica=r.name)
            log.info("replica %s/%s rejoined after a successful probe",
                     self.name, r.name)
        if rejoined:
            self._update_gauges()
        return rejoined

    # --------------------------------------------------- rolling reload ----

    def reload(self, params, state: Any = None, *,
               drain_timeout: float = 30.0) -> None:
        """Rolling reload: drain and swap each replica IN TURN via its
        atomic ``reload``, so the set never drops below N-1 serving
        replicas (``watch_checkpoints`` on a ReplicaSet drives exactly
        this). A replica still busy after ``drain_timeout`` is reloaded
        anyway — backend reloads swap between steps/batches, so this
        trades per-stream params consistency for bounded roll time, with
        a warning. A HEALTHY replica rejecting the weights (signature
        mismatch = config error) aborts the roll loudly; already-swapped
        siblings keep the new weights. Quarantined replicas are still
        attempted (so a later rejoin serves fresh weights) but their
        failures only log."""
        with self._roll_lock:
            # remember the sweep: a quarantined replica that misses it
            # must catch up at probe-rejoin time, or it would re-enter
            # rotation serving the previous checkpoint
            self._weights_version += 1
            self._latest_weights = (params, state)
            version = self._weights_version
            for r in self._replicas:
                with self._cond:
                    if self._closed:
                        raise RuntimeError("replica set is closed")
                    healthy = r.healthy
                    r.draining = True
                    deadline = time.monotonic() + float(drain_timeout)
                    while r.inflight > 0:
                        left = deadline - time.monotonic()
                        if left <= 0:
                            break
                        self._cond.wait(timeout=min(0.1, left))
                    drained = r.inflight == 0
                if not drained:
                    log.warning(
                        "replica %s/%s still has %d request(s) in flight "
                        "after %.1fs drain; reloading anyway (the backend "
                        "swap is atomic between steps)",
                        self.name, r.name, r.inflight, drain_timeout)
                try:
                    if state is None:
                        r.backend.reload(params)
                    else:
                        r.backend.reload(params, state)
                except Exception as e:
                    with self._cond:
                        r.draining = False
                    self._update_gauges()
                    if healthy:
                        raise
                    log.warning("quarantined replica %s/%s reload failed "
                                "(retried at probe-rejoin): %s",
                                self.name, r.name, e)
                    continue
                r.weights_version = version
                with self._cond:
                    r.draining = False
                self._update_gauges()
            self.metrics.record_rolling_reload()
            record_event("replica.rolling_reload", set=self.name,
                         version=version)

    # --------------------------------------------- dynamic membership ----

    def _find(self, name: str) -> Optional[_Replica]:
        for r in self._replicas:
            if r.name == name:
                return r
        return None

    def add_replica(self, backend, *, warming: bool = False) -> str:
        """Grow the set by one backend (the scale-up half of the
        elastic fleet). The new member enters rotation immediately
        unless ``warming=True`` — then it is VISIBLE (gauges, snapshot,
        healthz ``total``) but unplaceable until
        :meth:`activate_replica`, so a mid-scale-up fleet neither
        routes traffic to a still-compiling engine nor reports itself
        degraded while it waits. Returns the member's name (``rN`` —
        indices are monotonic and never reused, so per-replica metric
        sources stay unambiguous across scale-down/up cycles)."""
        with self._roll_lock:
            with self._cond:
                if self._closed:
                    raise RuntimeError("replica set is closed")
                r = _Replica(backend, self._next_index)
                self._next_index += 1
                r.warming = bool(warming)
                # a member born after N rolling-reload sweeps was built
                # from the tip weights by its factory — stamp it current
                # so probe-rejoin never "catches it up" backwards
                r.weights_version = self._weights_version
                self._replicas.append(r)
                name = r.name
        self._update_gauges()
        record_event("replica.added", set=self.name, replica=name,
                     warming=bool(warming))
        log.info("replica %s/%s added to the set%s", self.name, name,
                 " (warming)" if warming else "")
        return name

    def activate_replica(self, name: str) -> None:
        """Flip a warming member into the serving rotation — call it
        after the backend's ``warmup()`` finished compiling."""
        with self._cond:
            r = self._find(name)
            if r is None:
                raise KeyError(f"no replica named {name!r}")
            r.warming = False
        self._update_gauges()
        record_event("replica.activated", set=self.name, replica=name)

    def remove_replica(self, name: str, *, drain_timeout: float = 30.0,
                       close: bool = True, force: bool = False):
        """Shrink the set by one member (the scale-down half) through
        the same drain machinery a rolling reload uses: mark draining
        (no new placements), wait for its in-flight work to finish,
        then detach and (by default) close it. The drain is a GATE, not
        a courtesy — a member still busy after ``drain_timeout`` is put
        back in rotation and ``TimeoutError`` raised, so a scale-down
        can never fail live streams or strand reserved KV pages.

        ``force=True`` skips the drain and the last-serving-replica
        check (the autoscaler's replace-a-SIGKILLed-member path: the
        backend is already dead, its streams already failed over).
        Refuses to remove the last serving replica otherwise. Returns
        the detached backend."""
        with self._roll_lock:
            with self._cond:
                if self._closed:
                    raise RuntimeError("replica set is closed")
                r = self._find(name)
                if r is None:
                    raise KeyError(f"no replica named {name!r}")
                serving = [x for x in self._replicas
                           if x.healthy and not x.draining
                           and not x.warming]
                if not force and r in serving and len(serving) <= 1:
                    raise ValueError(
                        f"refusing to remove {name!r}: it is the last "
                        f"serving replica of {self.name!r} (force=True "
                        f"overrides)")
                r.draining = True
            self._update_gauges()
            if not force:
                with self._cond:
                    deadline = time.monotonic() + float(drain_timeout)
                    while r.inflight > 0:
                        left = deadline - time.monotonic()
                        if left <= 0:
                            break
                        self._cond.wait(timeout=min(0.1, left))
                    drained = r.inflight == 0
                    if not drained:
                        inflight = r.inflight
                        r.draining = False
                if not drained:
                    self._update_gauges()
                    raise TimeoutError(
                        f"replica {self.name}/{name} still has "
                        f"{inflight} request(s) in flight after "
                        f"{drain_timeout:.1f}s drain; not removed")
            with self._cond:
                self._replicas.remove(r)
        self._update_gauges()
        record_event("replica.removed", set=self.name, replica=name,
                     forced=bool(force))
        log.info("replica %s/%s removed from the set%s", self.name, name,
                 " (forced)" if force else " (drained)")
        if close:
            try:
                r.backend.close(drain=not force, timeout=drain_timeout)
            except TypeError:
                r.backend.close(drain=not force)
            except Exception:
                log.exception("closing removed replica %s/%s failed",
                              self.name, name)
        return r.backend

    # ------------------------------------------------------ lifecycle ----

    def warmup(self, *args, **kwargs) -> None:
        """Forward ``warmup`` to every replica (compile before traffic)."""
        for r in list(self._replicas):
            r.backend.warmup(*args, **kwargs)

    def close(self, drain: bool = True,
              timeout: Optional[float] = None) -> None:
        """Stop the prober, refuse new traffic, close every replica
        (drained by default — the set owns its members)."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        with self._probe_cond:
            self._probe_cond.notify_all()  # wake a prober mid-backoff
        if self._prober is not None:
            self._prober.join(timeout)
        for r in list(self._replicas):
            try:
                r.backend.close(drain=drain, timeout=timeout)
            except TypeError:
                r.backend.close(drain=drain)
            except Exception:
                log.exception("closing replica %s/%s failed",
                              self.name, r.name)

    def __enter__(self) -> "ReplicaSet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -------------------------------------------------------- queries ----

    def _update_gauges(self) -> None:
        with self._cond:
            # a warming member is in the set but not yet serving — it
            # counts in total, never in healthy (healthz reads the gap
            # as quarantine, so warming must not widen it)
            healthy = sum(r.healthy and not r.warming
                          for r in self._replicas)
            inflight = {r.name: r.inflight for r in self._replicas}
        self.metrics.set_replicas(healthy, len(self._replicas), inflight)

    @property
    def replicas(self) -> List[Any]:
        return [r.backend for r in self._replicas]

    @property
    def n_replicas(self) -> int:
        return len(self._replicas)

    @property
    def healthy_replicas(self) -> List[str]:
        with self._cond:
            return [r.name for r in self._replicas
                    if r.healthy and not r.warming]

    @property
    def warming_replicas(self) -> List[str]:
        with self._cond:
            return [r.name for r in self._replicas if r.warming]

    def inflight(self, index: int) -> int:
        with self._cond:
            return self._replicas[index].inflight

    def snapshot(self) -> Dict[str, Any]:
        """Set-level view: health/placement per replica plus each
        replica's own metrics snapshot (``set`` holds the set-level
        :class:`ServingMetrics` — the one the router reads)."""
        out: Dict[str, Any] = {"set": self.metrics.snapshot(),
                               "replicas": {}}
        with self._cond:
            states = [(r.name, r.healthy, r.draining, r.warming, r.inflight,
                       r.served, r.failed, r.failures, r.backend)
                      for r in self._replicas]
            if self.hedge:
                out["hedging"] = {"launched": self.hedges_launched,
                                  "won": self.hedges_won}
        for name, healthy, draining, warming, inflight, served, failed, \
                fails, b in states:
            entry = {"healthy": healthy, "draining": draining,
                     "warming": warming, "inflight": inflight,
                     "served": served, "failed": failed,
                     "consecutive_failures": fails}
            m = getattr(b, "metrics", None)
            if m is not None and m is not self.metrics:
                entry["metrics"] = m.snapshot()
            # remote replicas carry their transport gauges (reconnects,
            # deadline misses, hedge wins, breaker state) — purely local
            # reads, never a network call from inside snapshot()
            t = getattr(b, "transport_snapshot", None)
            if t is not None:
                entry["transport"] = t()
            out["replicas"][name] = entry
        return out

    def format_table(self) -> str:
        """One row per replica, in the style of the metrics tables."""
        snap = self.snapshot()
        lines = [f"{'replica':<10} {'state':<12} {'inflight':>8} "
                 f"{'served':>8} {'failed':>8}"]
        for name in sorted(snap["replicas"]):
            r = snap["replicas"][name]
            state = ("draining" if r["draining"]
                     else "warming" if r.get("warming")
                     else "healthy" if r["healthy"] else "quarantined")
            lines.append(f"{name:<10} {state:<12} {r['inflight']:>8} "
                         f"{r['served']:>8} {r['failed']:>8}")
        return "\n".join(lines)
