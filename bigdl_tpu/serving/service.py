"""InferenceService — the serving front door.

``submit(x, deadline=None) -> Future`` (or the blocking ``predict``)
feeds a :class:`~bigdl_tpu.serving.batcher.DynamicBatcher`; concurrent
callers are aggregated into hardware-sized micro-batches behind one
jitted forward. Robustness is built in, not bolted on:

- **admission control** — a bounded queue; at the bound ``submit``
  raises :class:`~bigdl_tpu.serving.errors.Overloaded` immediately
  (shed load at the door, don't buffer into an ever-growing tail);
- **deadlines** — per-request, in seconds from submit; an expired
  request is dropped before wasting a forward slot and its future fails
  with :class:`~bigdl_tpu.serving.errors.DeadlineExceeded`;
- **warmup** — pre-compile every batch bucket before traffic arrives,
  so no live request pays a compile;
- **graceful close** — stop admitting, drain in-flight work, join the
  worker.

Metrics (:class:`~bigdl_tpu.serving.metrics.ServingMetrics`) track
served/rejected/expired counts, batch-size and latency distributions,
and padding waste — the numbers ``bench.py --mode serving`` reports.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future
from typing import Optional, Sequence

import jax
import numpy as np

from bigdl_tpu.obs.trace import submit_trace
from bigdl_tpu.serving.batcher import DynamicBatcher, _Request
from bigdl_tpu.serving.metrics import ServingMetrics


def _model_forward(model):
    def forward(params, state, x):
        out, _ = model.apply(params, x, state=state, training=False)
        return out
    return forward


def tree_signature(tree):
    """(treedef, per-leaf (shape, dtype)) — the compile signature of a
    pytree as jit sees it: two trees with equal signatures hit the same
    compiled executable."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return treedef, tuple(
        (tuple(np.shape(l)), np.result_type(l).str) for l in leaves)


def require_matching_signature(kind: str, old, new) -> None:
    """Raise ``ValueError`` unless ``new`` has the exact tree structure
    and per-leaf shapes/dtypes of ``old`` — the hot-reload contract:
    matching signatures guarantee the jitted forward is NOT recompiled
    (weights are traced arguments, only their shapes are baked in)."""
    old_sig, new_sig = tree_signature(old), tree_signature(new)
    if old_sig != new_sig:
        raise ValueError(
            f"reload {kind} signature mismatch: structure or leaf "
            f"shapes/dtypes differ from the serving tree (a different "
            f"model/config cannot be hot-swapped into a running service)")


class InferenceService:
    """Dynamic-batching inference over one model / one input signature.

    ``forward_fn`` (signature ``(params, state, batched_x) -> batched
    out``) overrides the default jitted ``model.apply`` — tests use it to
    count compilations; production can pass an AOT-compiled executable.
    """

    def __init__(self, model, params, state=None, *,
                 max_batch_size: int = 8, max_wait_ms: float = 2.0,
                 max_queue: int = 64,
                 metrics: Optional[ServingMetrics] = None,
                 forward_fn=None, mesh=None, param_pspecs=None,
                 quantize: Optional[str] = None,
                 tracer=None):
        # int8 post-training quantization at the door (the reference's
        # AbstractModule.quantize() applied to serving): the module tree
        # is rewritten once (Linear/conv -> int8 twins, nn.quantized),
        # reloads re-run the params transform against the ORIGINAL float
        # module so checkpoint watchers keep feeding float trees — the
        # quantized tree's shapes are a pure function of the float tree,
        # so reload never recompiles.
        if quantize not in (None, "int8"):
            raise ValueError(f"quantize must be None or 'int8', "
                             f"got {quantize!r}")
        self.quantize = quantize
        self._quantize_params = None
        if quantize == "int8":
            from bigdl_tpu.nn.quantized import (
                count_executed_gemms,
                quantize as _quantize_tree,
            )

            float_model = model
            model, params = _quantize_tree(float_model, params)
            self._quantize_params = (
                lambda p: _quantize_tree(float_model, p)[1])
            metrics = metrics or ServingMetrics()
            # count from the MODULE tree, not the param tree: quantized
            # convs default to executing as float (BIGDL_INT8_CONV) and
            # must not inflate the "GEMMs running int8" gauge
            metrics.set_quantized_gemms(count_executed_gemms(model))
        self.model = model
        state = state or {}
        # sharded (tensor-parallel) mode: with a mesh, params are placed
        # per their PartitionSpecs (``param_pspecs`` overrides the
        # model's own ``param_pspecs()`` annotations; unannotated leaves
        # replicate) and the jitted forward becomes pjit — GSPMD derives
        # the collectives from the weight shardings. State (BN stats
        # etc.) replicates: it is elementwise per-feature and tiny.
        self.mesh = mesh
        self._param_shardings = None
        self._state_shardings = None
        if mesh is not None:
            from bigdl_tpu.parallel.mesh import tree_shardings

            if param_pspecs is None:
                param_pspecs = (model.param_pspecs()
                                if hasattr(model, "param_pspecs") else {})
            self._param_shardings = tree_shardings(mesh, params, param_pspecs)
            params = jax.device_put(params, self._param_shardings)
            if state:
                self._state_shardings = tree_shardings(mesh, state, None)
                state = jax.device_put(state, self._state_shardings)
        # params+state live in ONE tuple so a reload is a single atomic
        # reference swap: a batch reads the tuple once and always sees a
        # matched pair, never one new half and one old (test-enforced)
        self._weights = (params, state)
        self.metrics = metrics or ServingMetrics()
        # jit a closure over the MODEL, never a bound method: a jitted
        # bound method puts the service in a cycle through the C++ pjit
        # object, which the GC cannot break — an unclosed service would
        # leak itself plus params forever
        self._fwd = forward_fn if forward_fn is not None else jax.jit(
            _model_forward(model))
        self._signature = None  # (treedef, leaf shapes/dtypes) of request 1
        self._sig_lock = threading.Lock()  # check-and-set must be atomic
        # per-request tracing (obs.Tracer); None is free — one `is
        # None` test on the submit path, the disarmed-fault-site budget
        self.tracer = tracer
        self.batcher = DynamicBatcher(
            self._forward_batch, max_batch_size=max_batch_size,
            max_wait_ms=max_wait_ms, max_queue=max_queue,
            metrics=self.metrics)

    def _forward_batch(self, batched_x):
        params, state = self._weights  # one read: reload can't tear a batch
        return self._fwd(params, state, batched_x)

    @property
    def params(self):
        return self._weights[0]

    @property
    def state(self):
        return self._weights[1]

    def reload(self, params, state=None) -> None:
        """Hot-swap serving weights atomically between batches — the
        training-to-serving handoff without restart. The new trees are
        signature-checked against the serving ones (same structure, leaf
        shapes and dtypes), which guarantees the jitted forward is NOT
        recompiled; a mismatch (different model/config) raises
        ``ValueError`` and the old weights keep serving. A batch already
        in flight finishes on the weights it started with; the next batch
        sees the new pair — never a torn mix (test-enforced)."""
        if self._quantize_params is not None:
            # a quantized service reloads from FLOAT checkpoints; the
            # deterministic transform keeps the serving signature, so
            # the jitted forward is not recompiled
            params = self._quantize_params(params)
        old_params, old_state = self._weights
        require_matching_signature("params", old_params, params)
        if state is not None:
            require_matching_signature("state", old_state, state)
        # device_put once at reload: host arrays (e.g. a deserialized
        # checkpoint) would otherwise re-transfer per batch AND miss the
        # jit cache (an uncommitted arg keys a different executable). A
        # sharded service re-places with the ORIGINAL shardings so the
        # pjit executable is reused, not recompiled.
        params = (jax.device_put(params, self._param_shardings)
                  if self._param_shardings is not None
                  else jax.device_put(params))
        if state is None:
            state = old_state
        elif self._state_shardings is not None:
            state = jax.device_put(state, self._state_shardings)
        else:
            state = jax.device_put(state)
        self._weights = (params, state)
        self.metrics.record_reload()

    # ------------------------------------------------------ submission ----

    def submit(self, x, deadline: Optional[float] = None) -> Future:
        """Enqueue one UNBATCHED feature tree; returns the future of its
        unbatched output tree. ``deadline`` is seconds from now; raises
        :class:`Overloaded` when the queue is at its bound."""
        x = jax.tree_util.tree_map(np.asarray, x)
        self._check_signature(x)
        now = time.monotonic()
        fut: Future = Future()
        tr = submit_trace(self.tracer, "predict")
        if tr is not None:
            # the trace context rides the future, like the engine's
            # stream — routers/replica sets annotate it downstream
            fut.trace = tr
            tr.event("submit")
        req = _Request(x, fut, now,
                       None if deadline is None else now + float(deadline))
        try:
            self.batcher.submit(req)  # raises Overloaded / closed
        except BaseException:
            if tr is not None:
                tr.finish(outcome="rejected")
            raise
        if tr is not None:
            fut.add_done_callback(self._finish_trace)
        return fut

    @staticmethod
    def _finish_trace(fut) -> None:
        tr = getattr(fut, "trace", None)
        if tr is None or tr.done:
            return
        if fut.cancelled():
            tr.finish(outcome="cancelled")
            return
        err = fut.exception()
        tr.finish(outcome="done" if err is None else "failed",
                  **({} if err is None else {"error": type(err).__name__}))

    def _check_signature(self, x) -> None:
        """One service serves one input signature (structure + per-leaf
        shape/dtype, fixed by the first request or warmup): mismatches are
        rejected at the door, before they can poison a batch."""
        leaves, treedef = jax.tree_util.tree_flatten(x)
        sig = (treedef, tuple((l.shape, l.dtype.str) for l in leaves))
        with self._sig_lock:
            if self._signature is None:
                self._signature = sig
            elif sig != self._signature:
                raise ValueError(
                    f"request feature signature {sig[1]} does not match "
                    f"this service's signature {self._signature[1]}; one "
                    "InferenceService serves one input signature")

    def predict(self, x, timeout: Optional[float] = None,
                deadline: Optional[float] = None):
        """Blocking convenience wrapper: ``submit(...).result(timeout)``."""
        return self.submit(x, deadline=deadline).result(timeout)

    # -------------------------------------------------------- lifecycle ----

    def warmup(self, example_x, buckets: Optional[Sequence[int]] = None) -> None:
        """Compile every bucket shape BEFORE traffic arrives: one forward
        per bucket size, built by tiling one example feature tree. Live
        requests then never pay a compile (the reference warms its model
        pool by cloning; here the pool is the executable cache)."""
        example_x = jax.tree_util.tree_map(np.asarray, example_x)
        self._check_signature(example_x)
        for b in (buckets or self.batcher.bucket_sizes):
            batched = jax.tree_util.tree_map(
                lambda a: np.stack([a] * b), example_x)
            jax.block_until_ready(self._forward_batch(batched))

    def close(self, drain: bool = True, timeout: Optional[float] = None) -> None:
        """Stop admitting new requests and (by default) drain queued ones."""
        self.batcher.close(drain=drain, timeout=timeout)

    def __enter__(self) -> "InferenceService":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
