"""TensorBoard event-file writer, dependency-free.

Reference: ``DL/visualization/tensorboard/`` — ``FileWriter``/``EventWriter``
(async event-file writer), ``RecordWriter`` (CRC-framed TF ``Event``
protos), with the proto classes generated under ``DLJ/org/tensorflow`` and
the masked CRC in ``DLJ/netty/Crc32c.java``. Here the tiny subset of the
``Event``/``Summary`` protobuf wire format is hand-encoded (scalars +
histograms need only varint/fixed64/length-delimited fields), and the
masked CRC32C framing is implemented in Python (optionally accelerated by
the native helper in ``bigdl_tpu/native`` when built).

File format per record: len(8 LE) | masked_crc32c(len) (4 LE) | data |
masked_crc32c(data) (4 LE).
"""

from __future__ import annotations

import os
import struct
import threading
import time
from typing import Iterable, List, Optional, Tuple

# ---------------------------------------------------------------- crc32c ---

_CRC_TABLE = []


def _make_table():
    poly = 0x82F63B78
    for n in range(256):
        c = n
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        _CRC_TABLE.append(c)


_make_table()


def crc32c(data: bytes) -> int:
    try:
        from bigdl_tpu.native import crc32c as native_crc32c  # C accelerated

        return native_crc32c(data)
    except Exception:
        crc = 0xFFFFFFFF
        for b in data:
            crc = _CRC_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
        return crc ^ 0xFFFFFFFF


def masked_crc32c(data: bytes) -> int:
    crc = crc32c(data)
    return ((crc >> 15) | (crc << 17)) + 0xA282EAD8 & 0xFFFFFFFF


# ------------------------------------------------------- protobuf encoding ---


def _varint(n: int) -> bytes:
    out = b""
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out += bytes([b | 0x80])
        else:
            out += bytes([b])
            return out


def _field(num: int, wire: int) -> bytes:
    return _varint((num << 3) | wire)


def _f_double(num: int, v: float) -> bytes:
    return _field(num, 1) + struct.pack("<d", v)


def _f_float(num: int, v: float) -> bytes:
    return _field(num, 5) + struct.pack("<f", v)


def _f_int(num: int, v: int) -> bytes:
    return _field(num, 0) + _varint(v)


def _f_bytes(num: int, b: bytes) -> bytes:
    return _field(num, 2) + _varint(len(b)) + b


def _f_str(num: int, s: str) -> bytes:
    return _f_bytes(num, s.encode("utf-8"))


def encode_scalar_summary(tag: str, value: float) -> bytes:
    # Summary{ value: [Summary.Value{ tag=1, simple_value=2 }] }
    v = _f_str(1, tag) + _f_float(2, value)
    return _f_bytes(1, v)


def encode_histogram_summary(tag: str, values) -> bytes:
    """Summary.Value{ tag, histo: HistogramProto } — HistogramProto fields:
    min=1, max=2, num=3, sum=4, sum_squares=5, bucket_limit=6 (packed),
    bucket=7 (packed)."""
    import numpy as np

    arr = np.asarray(values, np.float64).ravel()
    if arr.size == 0:
        arr = np.zeros(1)
    counts, edges = np.histogram(arr, bins=30)
    histo = (
        _f_double(1, float(arr.min()))
        + _f_double(2, float(arr.max()))
        + _f_double(3, float(arr.size))
        + _f_double(4, float(arr.sum()))
        + _f_double(5, float((arr * arr).sum()))
    )
    limits = b"".join(struct.pack("<d", float(e)) for e in edges[1:])
    buckets = b"".join(struct.pack("<d", float(c)) for c in counts)
    histo += _f_bytes(6, limits) + _f_bytes(7, buckets)
    v = _f_str(1, tag) + _f_bytes(7, histo)  # Value.histo = field 7
    return _f_bytes(1, v)


def encode_event(
    step: int, wall_time: Optional[float] = None, summary: Optional[bytes] = None,
    file_version: Optional[str] = None,
) -> bytes:
    # Event{ wall_time=1(double), step=2(int64), file_version=3, summary=5 }
    out = _f_double(1, wall_time if wall_time is not None else time.time())
    if step:
        out += _f_int(2, step)
    if file_version is not None:
        out += _f_str(3, file_version)
    if summary is not None:
        out += _f_bytes(5, summary)
    return out


# ------------------------------------------------------------- file writer ---


class EventWriter:
    """Append CRC-framed events to a tfevents file (reference:
    ``EventWriter.scala`` — async flush thread; here: buffered + lock)."""

    def __init__(self, log_dir: str, suffix: str = ""):
        os.makedirs(log_dir, exist_ok=True)
        fname = f"events.out.tfevents.{int(time.time())}.bigdl_tpu{suffix}"
        self.path = os.path.join(log_dir, fname)
        self._fh = open(self.path, "ab")
        self._lock = threading.Lock()
        self.write_event(encode_event(0, file_version="brain.Event:2"))

    def write_event(self, event: bytes) -> None:
        header = struct.pack("<Q", len(event))
        rec = (
            header
            + struct.pack("<I", masked_crc32c(header))
            + event
            + struct.pack("<I", masked_crc32c(event))
        )
        with self._lock:
            self._fh.write(rec)
            self._fh.flush()

    def close(self) -> None:
        with self._lock:
            self._fh.close()


def read_events(path: str) -> List[Tuple[float, int, List[Tuple[str, float]]]]:
    """Minimal reader for round-trip tests (reference: ``FileReader.scala``).
    Returns [(wall_time, step, [(tag, simple_value)])]."""
    out = []
    with open(path, "rb") as fh:
        data = fh.read()
    pos = 0
    while pos + 12 <= len(data):
        (length,) = struct.unpack_from("<Q", data, pos)
        pos += 12  # len + len-crc
        event = data[pos : pos + length]
        pos += length + 4  # data + data-crc
        out.append(_decode_event(event))
    return out


def _decode_event(buf: bytes):
    wall, step, scalars = 0.0, 0, []

    def walk(buf, handlers):
        pos = 0
        while pos < len(buf):
            key, pos = _read_varint(buf, pos)
            num, wire = key >> 3, key & 7
            if wire == 0:
                val, pos = _read_varint(buf, pos)
            elif wire == 1:
                val = struct.unpack_from("<d", buf, pos)[0]
                pos += 8
            elif wire == 5:
                val = struct.unpack_from("<f", buf, pos)[0]
                pos += 4
            elif wire == 2:
                ln, pos = _read_varint(buf, pos)
                val = buf[pos : pos + ln]
                pos += ln
            else:
                raise ValueError(f"wire type {wire}")
            handlers.get(num, lambda v: None)(val)

    def on_summary(sbuf):
        def on_value(vbuf):
            tag = [None]
            sv = [None]
            walk(vbuf, {1: lambda v: tag.__setitem__(0, v.decode()), 2: lambda v: sv.__setitem__(0, v)})
            if tag[0] is not None and sv[0] is not None:
                scalars.append((tag[0], sv[0]))

        walk(sbuf, {1: on_value})

    holder = {"wall": 0.0, "step": 0}
    walk(
        buf,
        {
            1: lambda v: holder.__setitem__("wall", v),
            2: lambda v: holder.__setitem__("step", v),
            5: on_summary,
        },
    )
    return holder["wall"], holder["step"], scalars


def _read_varint(buf: bytes, pos: int):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
