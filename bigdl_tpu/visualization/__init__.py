from bigdl_tpu.visualization.summary import Summary, TrainSummary, ValidationSummary
from bigdl_tpu.visualization.events import EventWriter, read_events
