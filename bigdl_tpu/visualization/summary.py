"""Training/validation summaries (TensorBoard-compatible).

Reference: ``DL/visualization/Summary.scala:32`` (``addScalar``:44,
``addHistogram``:61), ``TrainSummary.scala`` (Loss/Throughput/LearningRate
+ opt-in Parameters histograms), ``ValidationSummary.scala``; readable back
via ``FileReader`` / ``TrainSummary.readScalar``.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

from bigdl_tpu.visualization.events import (
    EventWriter,
    encode_event,
    encode_histogram_summary,
    encode_scalar_summary,
    read_events,
)


class Summary:
    def __init__(self, log_dir: str, app_name: str, tag_suffix: str = ""):
        self.log_dir = os.path.join(log_dir, app_name + tag_suffix)
        self._writer = EventWriter(self.log_dir)

    def add_scalar(self, tag: str, value: float, step: int) -> "Summary":
        self._writer.write_event(encode_event(step, summary=encode_scalar_summary(tag, float(value))))
        return self

    def add_histogram(self, tag: str, values, step: int) -> "Summary":
        self._writer.write_event(
            encode_event(step, summary=encode_histogram_summary(tag, values))
        )
        return self

    def read_scalar(self, tag: str) -> List[Tuple[int, float]]:
        """(step, value) series for a tag (reference: ``readScalar``)."""
        out = []
        for name in sorted(os.listdir(self.log_dir)):
            if "tfevents" not in name:
                continue
            for _, step, scalars in read_events(os.path.join(self.log_dir, name)):
                for t, v in scalars:
                    if t == tag:
                        out.append((step, v))
        return out

    def close(self) -> None:
        self._writer.close()


class TrainSummary(Summary):
    """Reference: ``TrainSummary.scala`` — default scalar triggers for
    Loss/Throughput/LearningRate; ``set_summary_trigger("Parameters", ...)``
    opts into weight histograms."""

    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name, "/train")
        self.triggers: Dict[str, object] = {}

    def set_summary_trigger(self, name: str, trigger) -> "TrainSummary":
        self.triggers[name] = trigger
        return self


class ValidationSummary(Summary):
    def __init__(self, log_dir: str, app_name: str):
        super().__init__(log_dir, app_name, "/validation")
