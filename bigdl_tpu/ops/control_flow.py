"""Control-flow ops.

Reference: ``DL/nn/tf/ControlOps.scala`` — TF-style dataflow control flow
(``Switch``/``Merge``/``Enter``/``Exit``/``NextIteration``) executed by a
dynamic ``Scheduler`` with ``FrameManager`` frames
(``DL/nn/Scheduler.scala``, ``FrameManager.scala``), plus
``StateOps.scala`` (Variable/Assign) and ``DataFlowOps.scala``
(TensorArray).

TPU-native redesign: under XLA there is no dynamic scheduler — control flow
must be structured so the compiler sees a single static program. The
Switch/Merge dataflow pair therefore collapses into :class:`Cond`
(``lax.cond``), the Enter/Exit/NextIteration loop frame into :class:`While`
(``lax.while_loop``), and TensorArray into :class:`TensorArrayScan`
(``lax.scan`` with a preallocated output). Mutable ``Variable``/``Assign``
state ops functionalize into the module state mechanism (``ctx.put_state``).

State inside traced control flow: ``While`` and ``TensorArrayScan`` thread
their body's state updates through the loop carry (so ``AssignTo``/BN-stats
inside the loop behave like the reference's per-iteration mutation);
``Cond`` branches must be stateless — a branch state write is rejected at
trace time with a clear error, because the two branches generally have
different state structures and XLA cannot select between them.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp
from jax import lax

from bigdl_tpu.nn.module import Context, Module, _merge_updates


def _sub_context(ctx: Context, name: str, state):
    """Isolated child context whose updates do NOT leak into ctx (needed
    inside lax-traced functions, where writes to the shared updates dict
    would escape the trace as tracers)."""
    return Context(
        ctx.params.get(name, {}),
        state,
        ctx.training,
        ctx._rng,
        ctx.path + (name,),
        updates={},
        rng_count=[ctx._rng_count[0]],
    )


def _relative_updates(ctx: Context, name: str, updates):
    """Absolute-path updates from a sub context -> paths relative to it."""
    base = len(ctx.path) + 1
    return {p[base:]: kv for p, kv in updates.items()}


def _record_state(ctx: Context, name: str, st, base=()):
    """Write a (possibly nested) state tree into ctx's update channel."""
    for k, v in st.items():
        if isinstance(v, dict):
            _record_state(ctx, name, v, base + (k,))
        else:
            ctx._updates.setdefault(ctx.path + (name,) + base, {})[k] = v


class Cond(Module):
    """Structured Switch/Merge (reference ``ControlOps.scala`` switch/merge
    pattern): ``Cond(then_module, else_module)`` applied to (pred, x).

    Both branches see the same input and must produce identically-shaped
    outputs (XLA requirement; the reference's dynamic graph skipped the
    untaken branch at runtime instead). Branches must be stateless."""

    def __init__(self, then_branch: Module, else_branch: Module):
        super().__init__()
        self.then_branch = then_branch
        self.else_branch = else_branch

    def forward(self, ctx: Context, x):
        pred, data = x

        def make_branch(mod, name):
            def fn(d):
                sub = _sub_context(ctx, name, ctx.state.get(name, {}))
                out = mod.forward(sub, d)
                if sub.updates:
                    raise NotImplementedError(
                        f"stateful module inside Cond branch '{name}' "
                        f"(state write at {next(iter(sub.updates))}): branch "
                        f"state cannot be selected under XLA — hoist the "
                        f"stateful module out of the Cond"
                    )
                return out
            return fn

        return lax.cond(
            pred,
            make_branch(self.then_branch, "then_branch"),
            make_branch(self.else_branch, "else_branch"),
            data,
        )


class While(Module):
    """Structured Enter/NextIteration/Exit loop frame
    (reference ``ControlOps.scala``): ``While(cond_fn, body_module)``
    iterates ``state = body(state)`` while ``cond_fn(state)`` holds.
    Body-module state (Variable/BN stats) threads through the loop carry;
    its structure must not change across iterations (XLA carry contract)."""

    def __init__(self, cond_fn: Callable[[Any], jax.Array], body: Module,
                 max_iterations: Optional[int] = None):
        super().__init__()
        self.cond_fn = cond_fn
        self.body = body
        self.max_iterations = max_iterations

    def forward(self, ctx: Context, x):
        init_state = ctx.state.get("body", {})

        def body_fn(carry):
            data, st = carry
            sub = _sub_context(ctx, "body", st)
            out = self.body.forward(sub, data)
            new_st = _merge_updates(st, _relative_updates(ctx, "body", sub.updates))
            return out, new_st

        if self.max_iterations is None:
            out, final_st = lax.while_loop(
                lambda c: self.cond_fn(c[0]), body_fn, (x, init_state)
            )
        else:
            # bounded variant keeps reverse-mode autodiff available
            # (while_loop is not reverse-differentiable; fori over a static
            # bound is)
            def step(i, carry):
                return lax.cond(self.cond_fn(carry[0]), body_fn,
                                lambda c: c, carry)
            out, final_st = lax.fori_loop(0, self.max_iterations, step,
                                          (x, init_state))
        if final_st:
            _record_state(ctx, "body", final_st)
        return out


class TensorArrayScan(Module):
    """TensorArray write-in-a-loop (reference ``DataFlowOps.scala``
    TensorArray + scatter/gather ops): applies ``body`` to each timestep
    and stacks results — the XLA-native equivalent of ``TensorArray.write``
    inside a while frame. Body state threads through the scan carry."""

    def __init__(self, body: Module, axis: int = 1):
        super().__init__()
        self.body = body
        self.axis = axis

    def forward(self, ctx: Context, x):
        init_state = ctx.state.get("body", {})
        xs = jnp.moveaxis(x, self.axis, 0)

        def step(st, x_t):
            sub = _sub_context(ctx, "body", st)
            y = self.body.forward(sub, x_t)
            new_st = _merge_updates(st, _relative_updates(ctx, "body", sub.updates))
            return new_st, y

        final_st, ys = lax.scan(step, init_state, xs)
        if final_st:
            _record_state(ctx, "body", final_st)
        return jnp.moveaxis(ys, 0, self.axis)


class Variable(Module):
    """Functionalized mutable state (reference ``StateOps.scala``
    Variable/Assign): holds a buffer in module state; ``forward`` returns
    the current value; assignment goes through :class:`AssignTo`."""

    def __init__(self, shape: Sequence[int], dtype=jnp.float32, init_value: float = 0.0):
        super().__init__()
        self.shape = tuple(shape)
        self.dtype = dtype
        self.init_value = init_value

    def build_state(self):
        return {"value": jnp.full(self.shape, self.init_value, self.dtype)}

    def forward(self, ctx: Context, x=None):
        return ctx.get_state("value")


class AssignTo(Module):
    """Bound assign (reference ``StateOps.scala`` Assign): owns the Variable
    as child 'var'; ``forward(x)`` writes x into it and returns x. The state
    update propagates through ``apply``'s state tree like BN running stats."""

    def __init__(self, shape: Sequence[int], dtype=jnp.float32, init_value: float = 0.0):
        super().__init__()
        self.var = Variable(shape, dtype, init_value)

    def forward(self, ctx: Context, x):
        var_ctx = ctx.child("var")
        var_ctx.put_state("value", x)
        return x

    def read(self, ctx: Context):
        return self.run_child(ctx, "var", None)
