"""Flash attention as a Pallas TPU kernel.

The reference computes attention as unfused matmul/softmax/matmul modules
(``DL/nn/Attention.scala:35`` builds a Graph of MM + SoftMax + CMulTable);
at sequence length S that materialises the (S, S) score matrix in memory.
On TPU the memory-bound softmax traffic dominates HBM bandwidth, so the
TPU-native design is the online-softmax (flash) formulation: stream K/V
blocks through VMEM, keep running max/sum statistics, never materialise the
score matrix. Forward is a Pallas kernel; backward recomputes attention
(rematerialisation — FLOPs are cheap on the MXU, HBM is not) with a plain
XLA implementation under ``jax.custom_vjp``.

Shapes follow (batch, heads, seq, head_dim) throughout.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_MIN_LANE = 128


def _xla_attention(q, k, v, bias, sm_scale, causal,
                   dropout_rate=0.0, dropout_rng=None):
    """Reference XLA path (also the recompute used by the flash backward).

    Causal convention (shared with the kernel): END-aligned — query row i
    attends key cols j with ``j <= i + (klen - qlen)``, i.e. queries are the
    LAST ``qlen`` positions of the key sequence (the decode-time case; for
    qlen == klen this is the ordinary lower triangle).
    """
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * sm_scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        qlen, klen = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((qlen, klen), bool), k=klen - qlen)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    if dropout_rate > 0.0:
        keep = jax.random.bernoulli(dropout_rng, 1.0 - dropout_rate, p.shape)
        p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", p.astype(v.dtype), v)


def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, o_ref, acc_ref, m_ref, l_ref,
                *, sm_scale, causal, block_q, block_k, n_k, causal_offset):
    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    should_run = True
    if causal:
        # end-aligned: row i may see cols <= i + causal_offset
        should_run = qi * block_q + block_q - 1 + causal_offset >= ki * block_k

    @pl.when(should_run)
    def _compute():
        q = q_ref[0].astype(jnp.float32)          # (block_q, d)
        k = k_ref[0].astype(jnp.float32)          # (block_k, d)
        v = v_ref[0].astype(jnp.float32)          # (block_k, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * sm_scale                               # (block_q, block_k)
        if bias_ref is not None:
            s = s + bias_ref[0].astype(jnp.float32)
        if causal:
            rows = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
            cols = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
            s = jnp.where(rows + causal_offset >= cols, s, _NEG_INF)

        m_prev = m_ref[:, :1]                      # (block_q, 1)
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)  # (block_q, 1)
        m_next = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_next)
        p = jnp.exp(s - m_next)                    # (block_q, block_k)
        l_next = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[...] = jnp.broadcast_to(m_next, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_next, l_ref.shape)

    @pl.when(ki == n_k - 1)
    def _finalize():
        l = l_ref[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)            # fully-masked rows -> 0 output
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


def _flash_fwd(q, k, v, bias, sm_scale, causal, block_q, block_k, interpret):
    b, h, sq, d = q.shape
    sk = k.shape[2]
    block_q = min(block_q, sq)
    block_k = min(block_k, sk)
    if sq % block_q or sk % block_k:
        raise ValueError(f"seq lens ({sq},{sk}) not divisible by blocks ({block_q},{block_k})")
    n_q, n_k = sq // block_q, sk // block_k

    qr = q.reshape(b * h, sq, d)
    kr = k.reshape(b * h, sk, d)
    vr = v.reshape(b * h, sk, d)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, qi, ki: (bh, ki, 0)),
    ]
    args = [qr, kr, vr]
    if bias is not None:
        bias = jnp.broadcast_to(bias, (b, h, sq, sk)).reshape(b * h, sq, sk)
        in_specs.append(
            pl.BlockSpec((1, block_q, block_k), lambda bh, qi, ki: (bh, qi, ki))
        )
        args.append(bias)
        kernel = functools.partial(
            _fwd_kernel, sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, n_k=n_k, causal_offset=sk - sq,
        )
    else:
        kernel = functools.partial(
            lambda qf, kf, vf, o, acc, m, l, **kw: _fwd_kernel(
                qf, kf, vf, None, o, acc, m, l, **kw),
            sm_scale=sm_scale, causal=causal,
            block_q=block_q, block_k=block_k, n_k=n_k, causal_offset=sk - sq,
        )

    out = pl.pallas_call(
        kernel,
        grid=(b * h, n_q, n_k),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, qi, ki: (bh, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _MIN_LANE), jnp.float32),
            pltpu.VMEM((block_q, _MIN_LANE), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return out.reshape(b, h, sq, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def flash_attention(q, k, v, bias=None, sm_scale: Optional[float] = None,
                    causal: bool = False, block_q: int = 128,
                    block_k: int = 128, interpret: bool = False):
    """Fused online-softmax attention. q/k/v: (B, H, S, D)."""
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5
    return _flash_fwd(q, k, v, bias, scale, causal, block_q, block_k, interpret)


def _vjp_fwd(q, k, v, bias, sm_scale, causal, block_q, block_k, interpret):
    out = flash_attention(q, k, v, bias, sm_scale, causal, block_q, block_k, interpret)
    return out, (q, k, v, bias)


def _vjp_bwd(sm_scale, causal, block_q, block_k, interpret, res, g):
    q, k, v, bias = res
    scale = sm_scale if sm_scale is not None else q.shape[-1] ** -0.5

    def ref(q, k, v, bias):
        if bias is None:
            return _xla_attention(q, k, v, None, scale, causal)
        return _xla_attention(q, k, v, bias, scale, causal)

    if bias is None:
        _, vjp = jax.vjp(lambda q, k, v: ref(q, k, v, None), q, k, v)
        dq, dk, dv = vjp(g)
        return dq, dk, dv, None
    _, vjp = jax.vjp(ref, q, k, v, bias)
    return vjp(g)


flash_attention.defvjp(_vjp_fwd, _vjp_bwd)
